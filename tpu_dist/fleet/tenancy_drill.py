"""The tenancy drill — ``make tenancy-drill`` /
``python -m tpu_dist.fleet.tenancy_drill``.

The end-to-end proof of SLO-aware train+serve co-scheduling
(docs/resilience.md "Multi-tenant pod"), self-contained on CPU. One
recorded diurnal day — off-peak → load spike → recovery → off-peak —
is replayed through the kind-aware :class:`~tpu_dist.fleet.scheduler.
FleetScheduler` in three phases:

**Phase policy** (fast, tier-1) — the deterministic replay on a manual
tick clock: every tick writes the RECORDED serve exposition (real
``ServeStats`` windows through the real SLO alert engine — the spike
windows genuinely fire ``slo_*`` rules), genuinely scrapes it back
through the pod telemetry hub (ONE :class:`~tpu_dist.obs.hub.
TelemetryHub` aggregation pass fed to ``signals_from_hub`` — the same
single fan-in a production arbiter uses), and steps the scheduler.
Asserted exactly: the preempt-donate fires at
``spike_tick + serve_breach_ticks - 1``, the chips land one tick later
(the documented preemption-latency bound), the donate and its
completion grant share ONE ``decision_id`` (``chained``), availability
recovers over threshold, the off-peak release + grow-back land at
their tick-arithmetic positions, the federated hub page round-trips
with per-run labels and the ``pod.last_decision_id`` rollup, and the
chip-second conservation identity holds **exactly**
(``audit_chip_seconds`` over the per-tick ``tenancy`` snapshots:
per-run bucket sums ∪ free ∪ pending == pod chip-seconds, integer
chip-ticks, no float slack).

**Phase cycle** (slow) — the same day against a REAL trainer: a golden
uninterrupted run first, then the co-scheduled run driven by the real
``elastic/supervisor.py`` loop + capacity probe over the scheduler's
allocation file. The spike preempts the trainer (allocation shrinks →
probe → SIGTERM → emergency save → exit 75 → relaunch smaller), the
serve run is granted the chips, the recovery windows turn healthy, and
off-peak the two-phase donate/grant reclaims the chips (allocation
grows → probe → checkpoint → relaunch at full size). Verified: a
shrink AND a grow resume record, every epoch's loss within the golden
trajectory tolerance, the scraped availability back over threshold,
the wall-clock SIGTERM latency, and the exact conservation identity —
plus the full causal chain (``make hub-drill`` surface): the
preempt-donate's ``decision_id`` must reappear, verbatim, in the
scheduler's ``fleet`` ledger records, the allocation file's metadata
tokens (stamped into the relaunch env by ``stamp_decision_env``), the
shrunken trainer's ``resume`` record (with ``decision_cause ==
"serve_breach"``), its per-round flight ring, and the hub's federated
``pod.last_decision_id`` rollup, with the serve-preempt gap charged to
the ``preempt_for_serve_s`` goodput bucket and the bucket partition
still summing to wall-clock exactly.

**Phase replica** (slow) — the serving half of robustness: a real
supervised replica process is SIGKILL'd mid-serve; the
:class:`~tpu_dist.serve.supervisor.ReplicaSupervisor` detects the
crash, postmortem-bundles the evidence dirs BEFORE relaunching, and
the relaunch restores through the CRC-verified ladder — proven
bit-exact (equal weights digests across incarnations) with zero
post-warmup retraces, then drains gracefully on SIGTERM.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional, Sequence

from tpu_dist.fleet.drill import LOSS_RTOL, _epoch_losses, _load, _train_env
from tpu_dist.fleet.scheduler import (
    FleetPolicy,
    FleetScheduler,
    RunSpec,
    audit_chip_seconds,
    signals_from_hub,
)
from tpu_dist.obs import export as export_lib
from tpu_dist.obs import hub as hub_lib

#: The recorded diurnal day the policy phase replays, one profile per
#: scheduler tick. With the default policy (serve_breach_ticks=2,
#: serve_release_ticks=3, move_cooldown=2) the arbitration events MUST
#: land at: preempt-donate @3, preempt-grant @4, off-peak release @8,
#: trainer grow-back grant @9.
DIURNAL_TRACE = (
    "idle",        # 1: off-peak — trainer soaks the pod
    "spike",       # 2: the load spike arrives (queue growth + slo_* fire)
    "spike",       # 3: sustained -> breach streak == serve_breach_ticks
    "spike",       # 4: pending matures -> the chips land
    "recovering",  # 5: latency back under SLO, backlog still draining
    "idle",        # 6: healthy reading 1
    "idle",        # 7: healthy reading 2
    "idle",        # 8: healthy reading 3 -> off-peak release
    "idle",        # 9: released chips mature -> trainer grows back
    "idle",        # 10: steady state again
)
SPIKE_TICK = 1 + DIURNAL_TRACE.index("spike")  # ticks are 1-based

#: One drill tick in seconds — the manual clock the policy phase stamps
#: records with, and the chip-second unit of the conservation report.
TICK_SECONDS = 1.0


def _say(msg: str) -> None:
    # tpu-dist: ignore[TD002,TD007] — single-process CLI; stdout is the report
    print(f"tenancy-drill: {msg}", flush=True)


def _pod_scheduler(fleet_dir: Optional[str], devices: int, shrink_to: int):
    """The drill pod: one trainer soaking most of the chips, one serve
    run at its off-peak size, one chip vacant — 11 chips total at the
    defaults. Both phases use the SAME shape so the policy phase's tick
    arithmetic transfers to the real-trainer cycle."""
    return FleetScheduler(
        [
            RunSpec("trainer", devices, min_procs=shrink_to, kind="train"),
            RunSpec("svc", shrink_to, min_procs=1, kind="serve"),
        ],
        policy=FleetPolicy(),
        fleet_dir=fleet_dir,
        allocations={"trainer": devices, "svc": shrink_to // 2},
        total_chips=devices + shrink_to // 2 + 1,
    )


# -- the recorded serve windows ----------------------------------------------


def _serve_window_stats(profile: str, k: int = 0):
    """One recorded serving window. ``spike`` blows the 500 ms p99
    ceiling and the 50 ms deadline with a queue exploding tick over
    tick (``k`` = spike tick index); ``recovering`` is back under every
    ceiling but still draining backlog (not release-eligible);
    ``idle`` is the off-peak window."""
    from tpu_dist.serve import slo as slo_lib

    stats = slo_lib.ServeStats(deadline_s=0.05)
    if profile == "spike":
        for _ in range(4):
            stats.on_batch(3, 4)
            stats.on_request_done(
                0.6, 0.45, {p: 0.1 for p in slo_lib.PHASES}
            )
        stats.set_queue_depth(4 + 3 * k)
    elif profile == "recovering":
        for _ in range(4):
            stats.on_batch(4, 4)
            stats.on_request_done(
                0.02, 0.01, {p: 0.004 for p in slo_lib.PHASES}
            )
        stats.set_queue_depth(2)
    else:  # idle
        for _ in range(2):
            stats.on_batch(1, 1)
            stats.on_request_done(
                0.02, 0.01, {p: 0.004 for p in slo_lib.PHASES}
            )
        stats.set_queue_depth(0)
    return stats


def _write_serve_exposition(path: str, engine, profile: str, k: int) -> dict:
    """Render one recorded window through the PERSISTENT SLO alert
    engine (exactly what a replica's exporter publishes: the ``slo_*``
    rules fire on the spike windows and clear on the clean ones) and
    write it atomically. Returns the window scalars."""
    stats = _serve_window_stats(profile, k)
    window = stats.scalars(window_s=1.0, completed_in_window=stats.completed)
    engine.observe(window)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(export_lib.render(
            window,
            {"alert_active": engine.active()},
            histograms=stats.histogram_families(),
        ))
    os.replace(tmp, path)
    return window


def _write_trainer_exposition(path: str, stall: float = 0.02) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(export_lib.render({
            "train.data_stall_frac": stall,
            "goodput.goodput_frac": 0.93,
            "train.mfu": 0.52,
            "train.epoch": 1,
        }))
    os.replace(tmp, path)


def _report_conservation(records: List[dict]) -> bool:
    audit = audit_chip_seconds(records, tick_s=TICK_SECONDS)
    per_run = ", ".join(
        f"{run}={cs:g}" for run, cs in audit["per_run"].items()
    )
    _say(
        f"chip-seconds over {audit['n_ticks']} tick(s) x "
        f"{audit['total_chips']} chip(s): {per_run}, "
        f"free={audit['free_chip_s']:g}, pending={audit['pending_chip_s']:g} "
        f"-> accounted {audit['accounted_chip_s']:g} of "
        f"{audit['pod_chip_s']:g} pod chip-seconds"
    )
    if not audit["conserved"]:
        _say(f"FAIL: chip-second conservation VIOLATED: "
             f"{audit['violations'] or 'totals diverge'}")
        return False
    _say("chip-second conservation identity holds EXACTLY")
    return True


# -- phase policy ------------------------------------------------------------


def run_policy_phase(args) -> int:
    """The recorded diurnal replay on the manual tick clock — pure host
    arithmetic (no jax, no subprocesses), every signal genuinely
    scraped off disk."""
    from tpu_dist.serve import slo as slo_lib

    fleet_dir = os.path.join(args.workdir, "policy_fleet")
    sched = _pod_scheduler(fleet_dir, args.devices, args.shrink_to)
    policy = sched.policy
    slo_engine = slo_lib.make_slo_engine(slo_lib.load_slo_rules("default"))
    svc_prom = os.path.join(fleet_dir, "svc", "metrics.prom")
    trainer_prom = os.path.join(fleet_dir, "trainer", "metrics.prom")
    fleet_prom = os.path.join(fleet_dir, "fleet.prom")
    os.makedirs(os.path.dirname(svc_prom), exist_ok=True)
    _write_trainer_exposition(trainer_prom)
    # the ONE scrape fan-in: the arbiter reads every signal off one hub
    # aggregation pass — exactly the production shape (obs/hub.py)
    hub = hub_lib.TelemetryHub(
        [
            hub_lib.RunSource("trainer", metrics_file=trainer_prom,
                              kind="train"),
            hub_lib.RunSource("svc", metrics_file=svc_prom, kind="serve"),
        ],
        fleet_exposition=fleet_prom,
    )

    by_tick: dict = {}
    spike_k = 0
    recovered_at: Optional[int] = None
    for tick, profile in enumerate(DIURNAL_TRACE, start=1):
        window = _write_serve_exposition(
            svc_prom, slo_engine, profile, spike_k
        )
        if profile == "spike":
            spike_k += 1
        sched.write_exposition(fleet_prom)
        sig = signals_from_hub(hub.collect())
        if sig["svc"].queue_depth != window["serve.queue_depth"]:
            _say(f"FAIL: tick {tick}: hub scrape did not round-trip "
                 "the queue")
            return 1
        for d in sched.step(tick, sig, ts=tick * TICK_SECONDS):
            by_tick[tick] = d
            _say(f"tick {tick}: {d['action']}"
                 f"{' [SLO preemption]' if d.get('preempt') else ''} — "
                 f"{d['reason']}")
        if (
            recovered_at is None
            and tick > SPIKE_TICK
            and (sig["svc"].availability or 0.0)
            >= policy.serve_ok_availability
        ):
            recovered_at = tick
            _say(f"tick {tick}: availability "
                 f"{sig['svc'].availability:.1%} — recovered over "
                 f"{policy.serve_ok_availability:.1%}")

    donate_tick = SPIKE_TICK + policy.serve_breach_ticks - 1
    grant_tick = donate_tick + 1
    checks = (
        ("preempt-donate at the documented bound",
         by_tick.get(donate_tick, {}).get("action") == "donate"
         and by_tick[donate_tick].get("preempt") is True
         and by_tick[donate_tick].get("donor") == "trainer"),
        ("preempt-grant one tick later",
         by_tick.get(grant_tick, {}).get("action") == "grant"
         and by_tick[grant_tick].get("preempt") is True
         and by_tick[grant_tick].get("recipient") == "svc"),
        ("availability recovered after the chips landed",
         recovered_at is not None and recovered_at > grant_tick),
        ("off-peak release fired",
         any(d.get("action") == "donate" and d.get("donor") == "svc"
             and not d.get("preempt") for d in by_tick.values())),
        ("trainer grew back to its original size",
         sched.alloc["trainer"] == args.devices),
        ("both preemption moves counted",
         sched.preemptions == 2),
        # causal tracing (schema v15): the donation and the grant that
        # consumes its matured chips are ONE arbitration under one id
        ("donate and its completion grant share ONE decision_id",
         by_tick.get(donate_tick, {}).get("decision_id") is not None
         and by_tick[donate_tick].get("decision_id")
         == by_tick.get(grant_tick, {}).get("decision_id")
         and by_tick[grant_tick].get("chained") is True),
        ("the hub aggregated every tick with zero drops",
         hub.drops_total == {"torn": 0, "dead": 0, "absent": 0}),
    )
    ok = True
    for what, passed in checks:
        if not passed:
            _say(f"FAIL: {what}")
            ok = False
    if not ok:
        return 1
    _say(
        f"preemption latency: SIGTERM'd the trainer at tick {donate_tick} "
        f"(= spike tick {SPIKE_TICK} + serve_breach_ticks "
        f"{policy.serve_breach_ticks} - 1), chips landed at tick "
        f"{grant_tick}"
    )
    sched.write_exposition(fleet_prom)
    page = hub.federated()
    if not (
        page.endswith("# EOF\n")
        and 'run="svc"' in page
        and "tpu_dist_pod_last_decision_id" in page
        and "tpu_dist_pod_runs_aggregated 2" in page
    ):
        _say("FAIL: the federated hub page lost its per-run labels or "
             "pod rollups")
        return 1
    _say(
        "hub: federated page carries per-run labels + pod rollups "
        f"(last decision #{sched.last_decision_id})"
    )
    if not _report_conservation(_load(sched.history_path())):
        return 1
    _say("PASS policy: recorded diurnal replay reproduced every "
         "arbitration event at its documented tick")
    return 0


# -- phase cycle -------------------------------------------------------------


class _DiurnalDriver:
    """The cycle phase's signal source: the same recorded profiles, but
    paced against the REAL trainer — the spike starts once the trainer
    has banked an epoch and HOLDS until the serve run has its chips
    (the breach must stay sustained through the donor's vacate window),
    the recovery holds until the SHRUNKEN trainer has resumed and
    banked an epoch of its own, then the day goes idle (the off-peak
    reclaim window)."""

    def __init__(self, sched: FleetScheduler, elastic_log: str,
                 shrink_to: int):
        self.sched = sched
        self.elastic_log = elastic_log
        self.shrink_to = shrink_to
        self.tick = 0
        self.spike_k = 0
        self.spike_tick: Optional[int] = None
        self.donate_tick: Optional[int] = None
        self.donated_at_s: Optional[float] = None
        self.grant_tick: Optional[int] = None
        self.recovered = False
        self.decisions: List[dict] = []
        self._log_size = -1
        self._records: List[dict] = []
        from tpu_dist.serve import slo as slo_lib

        self.slo_engine = slo_lib.make_slo_engine(
            slo_lib.load_slo_rules("default")
        )
        self.svc_prom = os.path.join(sched.fleet_dir, "svc", "metrics.prom")
        self.trainer_prom = os.path.join(
            sched.fleet_dir, "trainer", "metrics.prom"
        )
        self.fleet_prom = os.path.join(sched.fleet_dir, "fleet.prom")
        os.makedirs(os.path.dirname(self.svc_prom), exist_ok=True)
        _write_trainer_exposition(self.trainer_prom)
        # the cycle phase arbitrates off the SAME single hub fan-in the
        # policy phase proved — no drill-private scrape path
        self.hub = hub_lib.TelemetryHub(
            [
                hub_lib.RunSource("trainer", metrics_file=self.trainer_prom,
                                  kind="train"),
                hub_lib.RunSource("svc", metrics_file=self.svc_prom,
                                  kind="serve"),
            ],
            fleet_exposition=self.fleet_prom,
        )

    def _log(self) -> List[dict]:
        try:
            size = os.path.getsize(self.elastic_log)
        except OSError:
            return self._records
        if size != self._log_size:  # re-parse only on growth
            self._log_size = size
            self._records = _load(self.elastic_log)
        return self._records

    def _any_epoch_banked(self) -> bool:
        return any(r.get("kind") == "train_epoch" for r in self._log())

    def _shrunken_epoch_banked(self) -> bool:
        """True once the log shows a shrink resume record FOLLOWED by a
        completed epoch — the off-peak reclaim must not start before
        the preempted trainer has proven it resumed and made progress
        at the smaller size."""
        recs = self._log()
        for i, r in enumerate(recs):
            if r.get("kind") == "resume" and r.get("dp") == self.shrink_to:
                return any(
                    x.get("kind") == "train_epoch" for x in recs[i + 1:]
                )
        return False

    def profile(self) -> str:
        if self.grant_tick is None:
            # pre-grant: off-peak until the trainer banks an epoch,
            # then the spike holds until the chips land
            if self.spike_tick is None and not self._any_epoch_banked():
                return "idle"
            return "spike"
        if self.sched.alloc["svc"] == self.sched.specs["svc"].original:
            # peak allocation held: recover, then idle once the
            # shrunken trainer banked its epoch
            return (
                "idle" if self._shrunken_epoch_banked() else "recovering"
            )
        return "idle"  # reclaimed — the day stays off-peak

    def step(self) -> None:
        self.tick += 1
        profile = self.profile()
        if profile == "spike" and self.spike_tick is None:
            self.spike_tick = self.tick
            _say(f"tick {self.tick}: the recorded load spike begins")
        _write_serve_exposition(
            self.svc_prom, self.slo_engine, profile, self.spike_k
        )
        if profile == "spike":
            self.spike_k += 1
        self.sched.write_exposition(self.fleet_prom)
        sig = signals_from_hub(self.hub.collect())
        for d in self.sched.step(self.tick, sig, ts=time.time()):
            self.decisions.append(d)
            _say(f"tick {self.tick}: {d['action']}"
                 f"{' [SLO preemption]' if d.get('preempt') else ''} — "
                 f"{d['reason']}")
            if d.get("preempt") and d["action"] == "donate":
                self.donate_tick = self.tick
                self.donated_at_s = time.monotonic()
            if d.get("preempt") and d["action"] == "grant":
                self.grant_tick = self.tick
        if (
            self.grant_tick is not None
            and not self.recovered
            and (sig["svc"].availability or 0.0)
            >= self.sched.policy.serve_ok_availability
        ):
            self.recovered = True
            _say(f"tick {self.tick}: serving availability "
                 f"{sig['svc'].availability:.1%} — recovered")


def run_cycle_phase(args) -> int:
    from tpu_dist.elastic.supervisor import (
        CapacityProbe,
        RoundResult,
        stamp_decision_env,
        supervise,
    )
    from tpu_dist.fleet import capacity as capacity_lib
    from tpu_dist.resilience.preemption import PREEMPTION_EXIT_CODE

    golden_log = os.path.join(args.workdir, "golden.jsonl")
    elastic_log = os.path.join(args.workdir, "elastic.jsonl")
    base = [
        "--dataset", "synthetic", "--model", args.model,
        "--num_classes", "10", "--synthetic_n", "256",
        "--batch_size", str(args.batch_size),
        "--epochs", str(args.epochs),
        "--steps_per_epoch", str(args.steps_per_epoch),
        "--eval_every", "0", "--save_every", "1", "--log_every", "50",
        "--seed", "0", "--shard_weight_update",
    ]
    _say(f"phase golden: {args.devices} device(s), uninterrupted")
    rc = subprocess.call(
        [sys.executable, "-m", "tpu_dist.cli.train"] + base
        + ["--ckpt_dir", os.path.join(args.workdir, "ck_golden"),
           "--log_file", golden_log],
        env=_train_env(args.devices),
    )
    if rc != 0:
        _say(f"FAIL: golden run exited {rc}")
        return 1

    fleet_dir = os.path.join(args.workdir, "cycle_fleet")
    sched = _pod_scheduler(fleet_dir, args.devices, args.shrink_to)
    driver = _DiurnalDriver(sched, elastic_log, shrink_to=args.shrink_to)
    probe = CapacityProbe(
        capacity_lib.make_census(sched.allocation_path("trainer")),
        original=args.devices,
        min_procs=args.shrink_to,
        interval=0.3,
    )
    elastic_ck = os.path.join(args.workdir, "ck_elastic")
    preempt_latency = [None]

    crash_base = os.path.join(args.workdir, "crash")

    def round_fn(n: int, round_idx: int) -> RoundResult:
        child = [sys.executable, "-m", "tpu_dist.cli.train"] + base + [
            "--ckpt_dir", elastic_ck, "--log_file", elastic_log,
            # one flight-ring dir per ROUND: the chain check reads the
            # shrunken incarnation's ring after later rounds re-arm
            "--crash_dir", os.path.join(crash_base, f"round{round_idx}"),
        ]
        if round_idx:
            child += ["--resume"]
        env = _train_env(n)
        env["TPU_DIST_ELASTIC_RESTARTS"] = str(round_idx)
        # propagate the active arbitration into the relaunch env — the
        # trainer stamps it into its resume record and flight ring
        meta = stamp_decision_env(env, sched.allocation_path("trainer"))
        if meta["decision_id"] is not None:
            _say(f"round {round_idx}: relaunch actuates fleet decision "
                 f"#{meta['decision_id']} ({meta['cause']})")
        _say(f"round {round_idx}: trainer at {n} device(s)")
        proc = subprocess.Popen(child, env=env)
        probe.reset_timer()
        resize: Optional[int] = None
        last_tick = time.monotonic()
        while proc.poll() is None:
            time.sleep(0.1)
            if time.monotonic() - last_tick >= args.tick_s:
                last_tick = time.monotonic()
                driver.step()
            if resize is None:
                target = probe.poll(n)
                if target is not None and target != n:
                    _say(
                        f"probe: census wants {target} (running {n}) — "
                        "checkpointing this round for the resize"
                    )
                    resize = target
                    proc.send_signal(signal.SIGTERM)
        rc = proc.returncode
        _say(f"round {round_idx}: exit {rc}")
        if (
            rc == PREEMPTION_EXIT_CODE
            and resize is not None
            and resize < n
            and preempt_latency[0] is None
            and driver.donated_at_s is not None
        ):
            preempt_latency[0] = time.monotonic() - driver.donated_at_s
        return RoundResult(rc, {0: rc}, resize)

    rc = supervise(
        round_fn,
        nproc=args.devices,
        min_procs=args.shrink_to,
        max_restarts=4,
        backoff_base=0.01,
        announce=lambda m: _say(f"supervisor: {m}"),
        probe=probe,
    )
    if rc != 0:
        _say(f"FAIL: supervised co-scheduled run exited {rc}")
        return 1

    recs = _load(elastic_log)
    resumes = [r for r in recs if r.get("kind") == "resume"]
    shrinks = [
        r for r in resumes
        if r.get("prev_dp") == args.devices and r.get("dp") == args.shrink_to
    ]
    grows = [
        r for r in resumes
        if r.get("prev_dp") == args.shrink_to and r.get("dp") == args.devices
    ]
    policy = sched.policy
    checks = (
        ("a preempt-shrink resume record", bool(shrinks)),
        ("an off-peak grow resume record", bool(grows)),
        ("the preempt-donate decision fired",
         driver.donate_tick is not None and driver.spike_tick is not None),
        ("the serve run got its chips one tick later",
         driver.grant_tick == (driver.donate_tick or 0) + 1),
        ("SIGTERM within the tick bound",
         driver.donate_tick is not None
         and driver.donate_tick - driver.spike_tick + 1
         == policy.serve_breach_ticks),
        ("serving availability recovered", driver.recovered),
        ("trainer back at full size",
         sched.alloc["trainer"] == args.devices),
        ("preemption wall latency measured",
         preempt_latency[0] is not None and preempt_latency[0] < 60.0),
    )
    ok = True
    for what, passed in checks:
        if not passed:
            _say(f"FAIL: {what}")
            ok = False
    if not ok:
        return 1
    _say(
        f"preemption latency: donate at tick {driver.donate_tick} "
        f"(spike at {driver.spike_tick}, bound serve_breach_ticks="
        f"{policy.serve_breach_ticks}); SIGTERM->exit-75 in "
        f"{preempt_latency[0]:.1f}s of wall clock"
    )
    golden = _epoch_losses(_load(golden_log))
    elastic = _epoch_losses(recs)
    for epoch, want in sorted(golden.items()):
        got = elastic.get(epoch)
        if got is None:
            _say(f"FAIL: co-scheduled run has no epoch {epoch}")
            return 1
        rel = abs(got - want) / max(abs(want), 1e-12)
        _say(
            f"epoch {epoch}: golden loss {want:.6f}, co-scheduled "
            f"{got:.6f} (rel {rel:.2e})"
        )
        if rel > LOSS_RTOL:
            _say(f"FAIL: loss diverged past rtol {LOSS_RTOL}")
            return 1
    if not _report_conservation(_load(sched.history_path())):
        return 1

    # -- the full causal chain (make hub-drill): ONE decision_id spans
    # scheduler ledger -> allocation file/relaunch env -> resume record
    # -> donor flight ring -> hub exposition, and the goodput ledger
    # charges the serve-preempt gap to its own bucket, partition exact
    from tpu_dist.obs import flight as flight_lib
    from tpu_dist.obs import goodput as goodput_lib

    donates = [
        d for d in driver.decisions
        if d.get("preempt") and d["action"] == "donate"
    ]
    did = donates[0].get("decision_id") if donates else None
    ledger_ids = {
        r.get("decision_id")
        for r in _load(sched.history_path())
        if r.get("kind") == "fleet"
    }
    shrink = shrinks[0] if shrinks else {}
    ring_resumes: List[dict] = []
    try:
        ring = flight_lib.decode(os.path.join(
            crash_base, f"round{shrink.get('restarts')}",
            flight_lib.RING_NAME,
        ))
        ring_resumes = [
            r for r in ring["records"]
            if r.get("kind") == "resume" and r.get("decision_id") == did
        ]
    except OSError as e:
        # Tolerated: the "flight ring stamped it" chain check below fails
        # loudly on an empty ring_resumes, naming the missing link.
        _say(f"note: donor flight ring unreadable ({e!r})")
    sched.write_exposition(driver.fleet_prom)
    rollup = driver.hub.collect()["rollup"]
    gp = goodput_lib.run_ledger(recs) or {}
    bucket_sum = sum(
        gp.get(f"{b}_s", 0.0) for b in goodput_lib.ALL_BUCKETS
    )
    chain_checks = (
        ("the preempt-donate carried a decision_id",
         isinstance(did, int)),
        ("the scheduler ledger stamped it", did in ledger_ids),
        ("the shrink resume record propagated it",
         shrink.get("decision_id") == did
         and shrink.get("decision_cause") == "serve_breach"),
        ("the donor's flight ring stamped it", bool(ring_resumes)),
        ("the hub exposition rolled it up",
         isinstance(rollup.get("last_decision_id"), float)
         and rollup["last_decision_id"] >= (did or 1)),
        ("the serve-preempt gap landed in preempt_for_serve_s",
         gp.get("preempt_for_serve_s", 0.0) > 0.0),
        # run_ledger rounds each bucket (and elapsed) to 4 decimals for
        # rendering — the unrounded partition is exact, so the rounded
        # sum can drift by at most 5e-5 per term (10 terms => 5e-4)
        ("the goodput bucket partition stayed exact",
         abs(bucket_sum - gp.get("elapsed_s", -1.0)) < 1e-3),
    )
    ok = True
    for what, passed in chain_checks:
        if not passed:
            _say(f"FAIL: {what}")
            ok = False
    if not ok:
        return 1
    _say(
        f"causal chain: decision #{did} spans scheduler ledger -> "
        "relaunch env -> resume record -> donor flight ring -> hub "
        f"exposition; preempt_for_serve_s={gp['preempt_for_serve_s']:.1f}s "
        "with the bucket partition exact"
    )
    _say(
        "PASS cycle: spike preempted the trainer losslessly, serving "
        "recovered, off-peak reclaimed the chips, books balanced"
    )
    return 0


# -- phase replica -----------------------------------------------------------

_MAKE_CKPT = """
import sys
from tpu_dist.serve.drill import _drill_model, write_training_ckpt
write_training_ckpt(sys.argv[1], _drill_model())
"""


def run_replica_phase(args, timeout_s: float = 180.0) -> int:
    """SIGKILL a real supervised serving replica and prove the
    crash→bundle→relaunch→bit-exact-restore loop."""
    from tpu_dist.serve.supervisor import ReplicaPolicy, ReplicaSupervisor

    rdir = os.path.join(args.workdir, "replica")
    ckpt_dir = os.path.join(rdir, "ck")
    os.makedirs(rdir, exist_ok=True)
    status = os.path.join(rdir, "status.jsonl")
    rc = subprocess.call(
        [sys.executable, "-c", _MAKE_CKPT, ckpt_dir], env=_train_env(4)
    )
    if rc != 0:
        _say(f"FAIL: checkpoint writer exited {rc}")
        return 1

    def spawn(incarnation: int):
        _say(f"spawning replica incarnation {incarnation}")
        return subprocess.Popen(
            [
                sys.executable, "-m", "tpu_dist.serve", "replica",
                "--ckpt", ckpt_dir, "--workdir", rdir,
                "--status_file", status, "--pace_s", "0.02",
            ],
            env=_train_env(1),
        )

    sup = ReplicaSupervisor(
        spawn,
        heartbeat_file=os.path.join(rdir, "hb.json"),
        policy=ReplicaPolicy(max_restarts=2, backoff_base_s=0.01),
        postmortem_dirs=[rdir],
    )

    def readys() -> List[dict]:
        if not os.path.exists(status):
            return []
        with open(status) as f:
            return [
                json.loads(ln) for ln in f
                if ln.strip() and json.loads(ln).get("event") == "ready"
            ]

    def wait(what, cond, deadline) -> bool:
        while time.monotonic() < deadline:
            if cond():
                return True
            time.sleep(0.2)
        _say(f"FAIL: timed out waiting for {what}")
        return False

    deadline = time.monotonic() + timeout_s
    sup.start()
    try:
        if not wait("incarnation 1 ready", lambda: len(readys()) >= 1,
                    deadline):
            return 1
        first = readys()[0]
        _say(f"incarnation 1 ready: digest {first['weights_digest']}, "
             f"{first['warmup_compiles']} warmup compile(s)")

        _say(f"SIGKILL pid {sup.proc.pid} (the crash under test)")
        os.kill(sup.proc.pid, signal.SIGKILL)
        if not wait("the kill to land", lambda: sup.proc.poll() is not None,
                    deadline):
            return 1
        verdict = sup.poll_once()
        if verdict != "crash":
            _say(f"FAIL: supervisor verdict {verdict!r}, wanted 'crash'")
            return 1
        bundles = [e for e in sup.events if e["event"] == "postmortem"]
        if not bundles:
            _say("FAIL: crash was not postmortem-bundled before relaunch")
            return 1
        _say(f"crash detected (rc {sup.last_rc}), bundled: "
             f"{bundles[-1]['bundle']}")

        if not wait("incarnation 2 ready", lambda: len(readys()) >= 2,
                    deadline):
            return 1
        second = readys()[1]
        if second["weights_digest"] != first["weights_digest"]:
            _say(f"FAIL: relaunch digest {second['weights_digest']} != "
                 f"{first['weights_digest']} — restore not bit-exact")
            return 1
        _say("relaunch restored BIT-EXACT weights "
             f"(digest {second['weights_digest']})")

        sup.proc.send_signal(signal.SIGTERM)  # graceful vacate
        if not wait("the graceful drain", lambda: sup.proc.poll() is not None,
                    deadline):
            return 1
        if sup.poll_once() != "exit" or not sup.done:
            _say(f"FAIL: expected a clean exit, got rc {sup.last_rc}")
            return 1
        with open(status) as f:
            drained = [
                json.loads(ln) for ln in f
                if ln.strip() and json.loads(ln).get("event") == "drained"
            ]
        if not drained or drained[-1].get("retraces") != 0:
            _say(f"FAIL: post-warmup retraces in the relaunched replica: "
                 f"{drained and drained[-1].get('retraces')}")
            return 1
        _say("PASS replica: SIGKILL detected, bundled, relaunched "
             "bit-exact, drained with 0 post-warmup retraces")
        return 0
    finally:
        if sup.proc is not None and sup.proc.poll() is None:
            sup.proc.kill()
            sup.proc.wait()


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tpu_dist.fleet.tenancy_drill",
        description="SLO-aware train+serve co-scheduling drill (CPU)",
    )
    p.add_argument("--workdir", required=True)
    p.add_argument("--devices", type=int, default=8)
    p.add_argument("--shrink_to", type=int, default=4)
    p.add_argument("--model", default="vit_tiny")
    p.add_argument("--epochs", type=int, default=4)
    p.add_argument("--steps_per_epoch", type=int, default=8)
    p.add_argument("--batch_size", type=int, default=32)
    p.add_argument("--tick_s", type=float, default=0.25,
                   help="cycle phase: wall seconds per scheduler tick")
    p.add_argument(
        "--phase", choices=("all", "policy", "cycle", "replica", "hub"),
        default="all",
        help="'policy' = the recorded diurnal replay (pure, fast); "
             "'cycle' = the same day against a real trainer (jax "
             "subprocesses, slow); 'replica' = SIGKILL a supervised "
             "serving replica (jax subprocess); 'hub' = policy + cycle "
             "(the make hub-drill surface: the hub fan-in and the full "
             "decision_id chain); 'all' = every phase",
    )
    args = p.parse_args(argv)
    os.makedirs(args.workdir, exist_ok=True)
    if args.phase in ("all", "policy", "hub"):
        rc = run_policy_phase(args)
        if rc != 0:
            return rc
    if args.phase in ("all", "cycle", "hub"):
        rc = run_cycle_phase(args)
        if rc != 0:
            return rc
    if args.phase in ("all", "replica"):
        rc = run_replica_phase(args)
        if rc != 0:
            return rc
    _say("PASS: all requested phases")
    return 0


if __name__ == "__main__":
    sys.exit(main())
