"""The capacity census — what the elastic supervisor's probe reads
(docs/resilience.md "Scale-up & fleet scheduling").

A run's *allocation* is the number of processes' worth of chips it may
use right now. The channel is deliberately dumb: one small text file per
run holding one integer, written atomically (tmp + ``os.replace``, the
heartbeat discipline) by whoever owns capacity — the fleet scheduler
(``tpu_dist/fleet/scheduler.py``), an external orchestrator, or a human
with ``echo``. The launcher's :class:`~tpu_dist.elastic.supervisor.
CapacityProbe` polls it; a change in either direction rides the proven
elastic path (graceful SIGTERM → checkpoint → relaunch ``--resume`` at
the new size).

Census resolution order (:func:`make_census`):

1. the allocation file (``--elastic_capacity_file``) when given,
2. the ``TPU_DIST_AVAILABLE_PROCS`` environment variable (set by an
   orchestrator that can't write files into the run's tree),
3. the static default — the original launch size: on a dedicated host
   the preempted chips "return" as soon as the preemption ends, so an
   unconstrained run always wants to grow back to what it was asked for.

Stdlib-only and jax-free: the launcher imports this before any backend
exists, and the scheduler runs on machines that only see the files.
"""

from __future__ import annotations

import os
import re
from typing import Callable, Optional

#: Environment override an orchestrator can set for a whole launcher
#: process tree (resolution order 2 — see module docstring).
CAPACITY_ENV = "TPU_DIST_AVAILABLE_PROCS"


def read_allocation(path: str) -> Optional[int]:
    """The census read: the integer in ``path``, or None when the file is
    absent, empty, or torn (an atomic writer makes torn rare; a missing
    file means nobody constrains this run yet). Never raises — the probe
    must degrade to "no answer", not kill the supervisor."""
    try:
        with open(path) as f:
            text = f.read().strip()
    except OSError:
        return None
    if not text:
        return None
    try:
        return int(text.split()[0])
    except ValueError:
        return None


def read_allocation_meta(path: str) -> dict:
    """The causal-tracing tokens riding behind the allocation integer:
    ``{"decision_id": int|None, "cause": str|None}`` (both None when
    the file is absent/torn or was written by a pre-tracing writer —
    the channel stays readable in both directions). Never raises."""
    out = {"decision_id": None, "cause": None}
    try:
        with open(path) as f:
            tokens = f.read().split()
    except OSError:
        return out
    for tok in tokens[1:]:
        if tok.startswith("decision="):
            val = tok[len("decision="):]
            if re.fullmatch(r"[0-9]+", val):
                out["decision_id"] = int(val)
        elif tok.startswith("cause="):
            out["cause"] = tok[len("cause="):] or None
    return out


def write_allocation(
    path: str, n: int,
    decision_id: Optional[int] = None, cause: Optional[str] = None,
) -> None:
    """Atomically publish allocation ``n`` (tmp + ``os.replace`` — a
    concurrent :func:`read_allocation` sees the old value or the new one,
    never a torn write). ``decision_id``/``cause`` append the causal-
    tracing tokens (``N decision=7 cause=serve_breach``) —
    :func:`read_allocation` only parses the leading integer, so every
    pre-tracing reader keeps working; :func:`read_allocation_meta` and
    the elastic supervisor's env stamping read the tokens back."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    line = f"{int(n)}"
    if decision_id is not None:
        line += f" decision={int(decision_id)}"
        if cause:
            line += f" cause={cause}"
    with open(tmp, "w") as f:
        f.write(line + "\n")
    os.replace(tmp, path)


def make_census(
    capacity_file: Optional[str] = None,
    *,
    default: Optional[int] = None,
    env: Optional[dict] = None,
) -> Callable[[], Optional[int]]:
    """Build the probe's census callable (module docstring for the
    resolution order). ``env`` is injectable for tests; ``default`` is
    the launcher's original ``--nproc``."""
    environ = env if env is not None else os.environ

    def census() -> Optional[int]:
        if capacity_file:
            n = read_allocation(capacity_file)
            if n is not None:
                return n
        raw = (environ.get(CAPACITY_ENV) or "").strip()
        # strict ASCII-integer match: `"--4".lstrip("+-").isdigit()`-style
        # checks pass values int() then rejects, and a garbage env var
        # must degrade down the chain, never crash the launcher's probe
        if re.fullmatch(r"[+-]?[0-9]+", raw):
            return int(raw)
        return default

    return census
