"""The fleet drill — ``make fleet-drill`` / ``python -m tpu_dist.fleet.drill``.

The end-to-end proof of elastic scale-up + fleet arbitration
(docs/resilience.md "Scale-up & fleet scheduling"), self-contained on
CPU-emulated devices. Two phases:

**Phase grow** — the full elastic round trip, driven by the REAL
supervisor loop (``elastic/supervisor.py::supervise`` + a real
``CapacityProbe`` over a real allocation file):

1. **Golden** — an uninterrupted run at ``--devices`` (ZeRO-1 state so
   the dp-dependent layouts are real).
2. **Preempt** — round 0 with a deterministic ``sigterm@epoch=E:step=S``
   fault exits 75; the drill marks the preempted chips gone (allocation
   file → ``--shrink_to``), and the supervisor's failure relaunch is
   CAPPED BY THE CENSUS: it resumes at ``--shrink_to`` devices, state
   remapped onto the smaller extent.
3. **Grow** — when the shrunken world finishes an epoch, the drill
   returns the chips (allocation file → ``--devices``); the probe
   notices, the round checkpoints itself (SIGTERM → 75), and the
   supervisor relaunches at full size — the restore ladder grows the
   state back (TD112's remap path).
4. **Verify** — exit codes (75, 75, 0), a shrink resume record
   (``prev_dp=devices → dp=shrink_to``) AND a grow resume record
   (``prev_dp=shrink_to → dp=devices``) in the JSONL, the
   ``elastic.grows`` counter, and every epoch's loss within the
   golden-trajectory tolerance of the uninterrupted run.

Each round is a subprocess with its own
``--xla_force_host_platform_device_count`` (a process cannot change its
device count after the backend initializes), so "world size" here is
the emulated device count — the same state-remap path a multi-host
resize takes, without needing cross-process collectives on CPU.

**Phase fleet** — two REAL supervised launcher runs (stub children, no
jax) share one chip pool; the scheduler scrapes each run's OpenMetrics
textfile, decides the stalled run donates to the compute-bound one,
writes the allocation files — and both launchers act on it through
their capacity probes (donor: SIGTERM → 75 → relaunch smaller;
recipient: probe → grow). Verified: the auditable ``fleet`` decision
record (with its scraped inputs) and each run's observed world-size
sequence.
"""

from __future__ import annotations

import argparse
import os
import re
import signal
import subprocess
import sys
import time
from typing import List, Optional, Sequence

from tpu_dist.elastic.supervisor import CapacityProbe, RoundResult, supervise
from tpu_dist.fleet import capacity as capacity_lib
from tpu_dist.fleet.scheduler import (
    FleetPolicy,
    FleetScheduler,
    RunSpec,
    read_signals,
)
from tpu_dist.obs import export as export_lib
from tpu_dist.resilience.preemption import PREEMPTION_EXIT_CODE

#: Same golden-trajectory bound the elastic drill gates at: resumed
#: segments reduce over different device counts, so float order differs
#: while the math is the same.
LOSS_RTOL = 2e-3


def _say(msg: str) -> None:
    # tpu-dist: ignore[TD002,TD007] — single-process CLI; stdout is the report
    print(f"fleet-drill: {msg}", flush=True)


def _train_env(devices: int) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""  # skip TPU plugin registration
    inherited = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "",
        env.get("XLA_FLAGS", ""),
    )
    env["XLA_FLAGS"] = (
        inherited + f" --xla_force_host_platform_device_count={devices}"
    ).strip()
    return env


def _load(log_path: str) -> List[dict]:
    from tpu_dist.obs.summarize import load_records  # one JSONL reader

    records, _bad = load_records(log_path)
    return records


def _epoch_losses(records: List[dict]) -> dict:
    return {
        rec.get("epoch"): rec["loss"]  # last segment wins
        for rec in records
        if rec.get("kind") == "train_epoch"
        and isinstance(rec.get("loss"), (int, float))
    }


# -- phase grow --------------------------------------------------------------


def run_grow_phase(args) -> int:
    golden_log = os.path.join(args.workdir, "golden.jsonl")
    elastic_log = os.path.join(args.workdir, "elastic.jsonl")
    cap_file = os.path.join(args.workdir, "allocation")
    base = [
        "--dataset", "synthetic", "--model", args.model,
        "--num_classes", "10", "--synthetic_n", "256",
        "--batch_size", str(args.batch_size),
        "--epochs", str(args.epochs),
        "--steps_per_epoch", str(args.steps_per_epoch),
        "--eval_every", "0", "--save_every", "1", "--log_every", "50",
        "--seed", "0", "--shard_weight_update",
    ]

    _say(f"phase golden: {args.devices} device(s), uninterrupted")
    rc = subprocess.call(
        [sys.executable, "-m", "tpu_dist.cli.train"] + base
        + ["--ckpt_dir", os.path.join(args.workdir, "ck_golden"),
           "--log_file", golden_log],
        env=_train_env(args.devices),
    )
    if rc != 0:
        _say(f"FAIL: golden run exited {rc}")
        return 1

    # the elastic run, driven by the REAL supervisor + capacity probe:
    # the allocation file starts at full capacity; the preemption takes
    # chips away, finishing an epoch at the shrunken size brings them back
    capacity_lib.write_allocation(cap_file, args.devices)
    probe = CapacityProbe(
        capacity_lib.make_census(cap_file),
        original=args.devices,
        min_procs=args.shrink_to,
        interval=0.3,
    )
    elastic_ck = os.path.join(args.workdir, "ck_elastic")
    capacity_returned = [False]
    seen_size = [0]  # re-parse the log only when it actually grew

    def shrunk_finished_an_epoch() -> bool:
        try:
            size = os.path.getsize(elastic_log)
        except OSError:
            return False
        if size == seen_size[0]:
            return False  # nothing new — don't re-parse the whole file
        seen_size[0] = size
        return any(
            r.get("kind") == "train_epoch"
            and r.get("epoch") == args.kill_epoch
            for r in _load(elastic_log)
        )

    def round_fn(n: int, round_idx: int) -> RoundResult:
        child = [sys.executable, "-m", "tpu_dist.cli.train"] + base + [
            "--ckpt_dir", elastic_ck, "--log_file", elastic_log,
        ]
        if round_idx == 0:
            child += [
                "--fault_plan",
                f"sigterm@epoch={args.kill_epoch}:step={args.kill_step}",
            ]
        else:
            child += ["--resume"]
        env = _train_env(n)
        env["TPU_DIST_ELASTIC_RESTARTS"] = str(round_idx)
        _say(f"round {round_idx}: {n} device(s)")
        proc = subprocess.Popen(child, env=env)
        probe.reset_timer()
        resize: Optional[int] = None
        while proc.poll() is None:
            time.sleep(0.2)
            if (
                not capacity_returned[0]
                and n == args.shrink_to
                and shrunk_finished_an_epoch()
            ):
                # the preempted chips came back — exactly the scale-up
                # trigger the probe exists to notice
                _say(f"capacity returns: allocation -> {args.devices}")
                capacity_lib.write_allocation(cap_file, args.devices)
                capacity_returned[0] = True
            if resize is None:
                target = probe.poll(n)
                if target is not None and target != n:
                    _say(
                        f"probe: census wants {target} (running {n}) — "
                        "checkpointing this round for the resize"
                    )
                    resize = target
                    proc.send_signal(signal.SIGTERM)
        rc = proc.returncode
        _say(f"round {round_idx}: exit {rc}")
        if round_idx == 0 and rc == PREEMPTION_EXIT_CODE:
            # the preemption took the chips with it: the supervisor's
            # failure relaunch must be capped by the census
            capacity_lib.write_allocation(cap_file, args.shrink_to)
        return RoundResult(rc, {0: rc}, resize)

    rc = supervise(
        round_fn,
        nproc=args.devices,
        min_procs=args.shrink_to,
        max_restarts=3,
        backoff_base=0.01,
        announce=lambda m: _say(f"supervisor: {m}"),
        probe=probe,
    )
    if rc != 0:
        _say(f"FAIL: supervised elastic run exited {rc}")
        return 1

    recs = _load(elastic_log)
    resumes = [r for r in recs if r.get("kind") == "resume"]
    shrinks = [
        r for r in resumes
        if r.get("prev_dp") == args.devices and r.get("dp") == args.shrink_to
    ]
    grows = [
        r for r in resumes
        if r.get("prev_dp") == args.shrink_to and r.get("dp") == args.devices
    ]
    if not shrinks:
        _say(f"FAIL: no shrink resume record ({args.devices}->{args.shrink_to})")
        return 1
    if not grows:
        _say(f"FAIL: no grow resume record ({args.shrink_to}->{args.devices})")
        return 1
    _say(
        f"resume records: shrank dp {args.devices}->{args.shrink_to}, "
        f"grew dp {args.shrink_to}->{args.devices}"
    )
    counters = [
        r.get("counters") for r in recs
        if isinstance(r.get("counters"), dict)
    ]
    if not any(c.get("elastic.grows") for c in counters):
        _say("FAIL: elastic.grows counter never observed in the history")
        return 1

    golden = _epoch_losses(_load(golden_log))
    elastic = _epoch_losses(recs)
    for epoch, want in sorted(golden.items()):
        got = elastic.get(epoch)
        if got is None:
            _say(f"FAIL: elastic run has no epoch {epoch}")
            return 1
        rel = abs(got - want) / max(abs(want), 1e-12)
        _say(
            f"epoch {epoch}: golden loss {want:.6f}, elastic {got:.6f} "
            f"(rel {rel:.2e})"
        )
        if rel > LOSS_RTOL:
            _say(f"FAIL: loss diverged past rtol {LOSS_RTOL}")
            return 1
    _say(
        f"PASS grow: preempt-shrink {args.devices}->{args.shrink_to}, "
        f"probe-grow back to {args.devices}, trajectory within golden "
        "tolerance"
    )
    return 0


# -- phase fleet -------------------------------------------------------------

_STUB_CHILD = """
import os, signal, sys, time
argv = sys.argv
n = int(argv[argv.index('--num_processes') + 1])
rank = int(argv[argv.index('--process_id') + 1])
if rank == 0:
    with open(os.environ['DRILL_MARKER'], 'a') as f:
        f.write(f"{n} resume={'--resume' in argv}\\n")
signal.signal(signal.SIGTERM, lambda *a: sys.exit(75))
time.sleep(120)
"""


def _await(deadline: float, what: str, cond) -> bool:
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.2)
    _say(f"FAIL: timed out waiting for {what}")
    return False


def _worlds(marker: str) -> List[int]:
    if not os.path.exists(marker):
        return []
    return [int(ln.split()[0]) for ln in open(marker) if ln.strip()]


def run_fleet_phase(args, timeout_s: float = 90.0) -> int:
    """Two supervised stub runs on one pool; the scheduler moves chips
    from the deliberately stalled one to the compute-bound one based on
    genuinely scraped OpenMetrics textfiles."""
    fleet_dir = os.path.join(args.workdir, "fleet")
    scheduler = FleetScheduler(
        [RunSpec("stalled", 4, min_procs=1),
         RunSpec("compute", 4, min_procs=1)],
        policy=FleetPolicy(),
        fleet_dir=fleet_dir,
        allocations={"stalled": 4, "compute": 2},
        total_chips=6,
    )
    launchers = {}
    markers = {}
    try:
        for run in ("stalled", "compute"):
            marker = os.path.join(fleet_dir, run, "worlds.txt")
            markers[run] = marker
            env = dict(os.environ)
            env["DRILL_MARKER"] = marker
            launchers[run] = subprocess.Popen(
                [
                    sys.executable, "-m", "tpu_dist.cli.launch",
                    "--nproc", "4", "--elastic_min_procs", "1",
                    "--elastic_max_restarts", "3",
                    "--elastic_backoff", "0.01",
                    "--elastic_probe_interval", "0.3",
                    "--elastic_capacity_file",
                    scheduler.allocation_path(run),
                    "--", sys.executable, "-c", _STUB_CHILD,
                ],
                env=env,
            )
        deadline = time.monotonic() + timeout_s
        # both runs settle at their scheduler-granted allocations first
        # ("compute" launches at 4 and is shrunk to its allocation of 2 by
        # the census — the allocation file is authoritative from birth)
        if not _await(
            deadline, "runs to settle at allocations (4, 2)",
            lambda: _worlds(markers["stalled"])[-1:] == [4]
            and _worlds(markers["compute"])[-1:] == [2],
        ):
            return 1
        _say("both runs settled: stalled@4, compute@2")

        # each run's exporter textfile — written here the way the trainer
        # writes them, then GENUINELY scraped back by the scheduler
        sig = {}
        for run, stall, goodput, mfu in (
            ("stalled", 0.62, 0.35, 0.08),
            ("compute", 0.02, 0.93, 0.52),
        ):
            prom = os.path.join(fleet_dir, run, "metrics.prom")
            with open(prom, "w") as f:
                f.write(export_lib.render({
                    "train.data_stall_frac": stall,
                    "goodput.goodput_frac": goodput,
                    "train.mfu": mfu,
                    "train.epoch": 1,
                }))
            sig[run] = read_signals(run, prom)
            if sig[run].data_stall_frac != stall:
                _say(f"FAIL: scrape of {prom} did not round-trip")
                return 1
        # tick 0: the pool is dry, so the stalled run DONATES — its chips
        # bank as pending (the donor needs its checkpoint/relaunch window
        # to vacate them; granting now would oversubscribe the pool)
        decisions = scheduler.step(0, sig, ts=time.time())
        if not decisions or decisions[0].get("action") != "donate":
            _say(f"FAIL: expected a donation at tick 0, got {decisions}")
            return 1
        d = decisions[0]
        _say(f"decision: {d['reason']} — alloc {d['alloc_before']} -> "
             f"{d['alloc_after']}")
        if d["donor"] != "stalled" or d.get("for_run") != "compute":
            _say(f"FAIL: wrong donation {d}")
            return 1
        if not _await(
            deadline, "the donor to vacate (stalled->2)",
            lambda: _worlds(markers["stalled"])[-1:] == [2],
        ):
            return 1
        # tick 1: the banked chips mature into the free pool and the
        # compute-bound run is granted them
        decisions = scheduler.step(1, sig, ts=time.time())
        if not decisions or decisions[0].get("action") != "grant":
            _say(f"FAIL: expected a grant at tick 1, got {decisions}")
            return 1
        g = decisions[0]
        _say(f"decision: {g['reason']} — alloc {g['alloc_before']} -> "
             f"{g['alloc_after']}")
        if g["recipient"] != "compute" or g["donor"] is not None:
            _say(f"FAIL: wrong grant {g}")
            return 1
        if not _await(
            deadline, "the recipient to grow (compute->4)",
            lambda: _worlds(markers["compute"])[-1:] == [4],
        ):
            return 1
        hist = _load(scheduler.history_path())
        audited = [
            r for r in hist if r.get("kind") == "fleet" and r.get("inputs")
        ]
        if len(audited) != 2:
            _say(f"FAIL: expected 2 auditable fleet records, got {len(audited)}")
            return 1
        _say(
            "PASS fleet: stalled run donated 2 chips (worlds "
            f"{_worlds(markers['stalled'])}), compute-bound run was "
            f"granted them one tick later (worlds "
            f"{_worlds(markers['compute'])}); both decisions audited "
            "with their scraped inputs"
        )
        return 0
    finally:
        for proc in launchers.values():
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        for proc in launchers.values():
            try:
                proc.wait(timeout=20)
            except subprocess.TimeoutExpired:
                proc.kill()


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tpu_dist.fleet.drill",
        description="preempt-shrink -> probe-grow -> fleet arbitration "
                    "drill (CPU)",
    )
    p.add_argument("--workdir", required=True, help="scratch dir for ckpts/logs")
    p.add_argument("--devices", type=int, default=8)
    p.add_argument("--shrink_to", type=int, default=4)
    p.add_argument("--model", default="vit_tiny")
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--steps_per_epoch", type=int, default=3)
    p.add_argument("--batch_size", type=int, default=32)
    p.add_argument("--kill_epoch", type=int, default=1)
    p.add_argument("--kill_step", type=int, default=1)
    p.add_argument(
        "--phase", choices=("all", "grow", "fleet"), default="all",
        help="'grow' = golden + preempt-shrink + probe-grow parity (jax "
             "subprocesses, slow); 'fleet' = the two-run arbitration "
             "drill (stub children, fast); 'all' = both",
    )
    args = p.parse_args(argv)
    os.makedirs(args.workdir, exist_ok=True)
    if args.phase in ("all", "grow"):
        rc = run_grow_phase(args)
        if rc != 0:
            return rc
    if args.phase in ("all", "fleet"):
        rc = run_fleet_phase(args)
        if rc != 0:
            return rc
    _say("PASS: all requested phases")
    return 0


if __name__ == "__main__":
    sys.exit(main())
