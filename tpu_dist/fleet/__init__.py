"""Pod-level fleet control (docs/resilience.md "Scale-up & fleet
scheduling").

PR 6-9 built the sensors (goodput ledger, OpenMetrics export, pod
aggregation) and the actuator (the elastic supervisor's
checkpoint-remap-relaunch path); this package is the control loop that
connects them:

* :mod:`tpu_dist.fleet.capacity` — the capacity census: per-run
  allocation files the scheduler owns and the launcher's
  :class:`~tpu_dist.elastic.supervisor.CapacityProbe` reads. The file is
  the single communication channel between the arbiter and a run's
  supervisor — no sockets, no shared state, auditable with ``cat``.
* :mod:`tpu_dist.fleet.scheduler` — the goodput-aware arbiter:
  gang-schedules N runs on one pod and reallocates chips at epoch-grain
  decision points from the signals the obs stack already exports per run
  (data-stall fraction, goodput, MFU, active alerts, heartbeat
  liveness). Every decision is an auditable ``fleet`` history record
  carrying the inputs that justified it.
* :mod:`tpu_dist.fleet.drill` — ``make fleet-drill``: the end-to-end
  proof (preempt-shrink, probe-grow with loss parity, then a
  metrics-driven chip move between two live supervised runs).
"""
