"""Goodput-aware chip arbitration across runs sharing one pod
(docs/resilience.md "Scale-up & fleet scheduling").

Multiple workloads time-sharing the same chips is the expected
production shape (PAPERS.md: Gemma fine-tune + serve on one pod), and at
pod scale worker churn is routine (PAPERS.md: Concurrency on Google
TPUs) — so chips should sit where they buy goodput, not where the
original submission happened to put them. This module is the arbiter:

* **Sensors** — the per-run signals the obs stack already exports:
  each run's OpenMetrics exposition carries data-stall fraction,
  goodput fraction, MFU, the serving gauges (queue depth, availability,
  p99 latency bound) and the active-alert gauges, and its heartbeat
  file answers liveness. The scrape itself lives in the pod telemetry
  hub (``obs/hub.py::sample_run`` — ONE fan-in for the arbiter, the
  federated ``/metrics``, and the watchdog alike); this module only
  TYPES the sample into :class:`RunSignals`. Nothing here instruments
  a run — the scheduler is a pure reader of artifacts that exist
  anyway, and it never opens a metrics file itself (regression-pinned
  by ``tests/test_hub.py``).
* **Policy** (:meth:`FleetScheduler.decide`) — the pod is
  multi-tenant: each :class:`RunSpec` carries a ``kind`` (``train`` or
  ``serve``) and the policy is deliberately **asymmetric**. Training
  runs trade chips on goodput: at epoch-grain decision points (integer
  ``tick``), a run data-stalled past ``donate_stall_frac`` donates
  chips toward a compute-bound one under ``receive_stall_frac``.
  Serving runs trade chips on SLO: a serving SLO breach sustained for
  ``serve_breach_ticks`` readings (any active ``slo_*`` alert, or
  queue-depth growth across consecutive readings) **preempts** training
  chips — the breached replica set is granted from the free pool when
  chips are vacant, otherwise a training donor is shrunk *regardless of
  its stall fraction* (the SLO outranks goodput; ``min_procs`` floors
  and shrink feasibility still hold) — and once the breach clears for
  ``serve_release_ticks`` readings the serve run donates its surplus
  back so training soaks everything idle off-peak. Donated chips are
  **pending until the next tick**: the donor needs its
  checkpoint→relaunch window to actually vacate them, so granting in
  the same instant would transiently oversubscribe the pool — the
  recipient is granted from the FREE pool only, one tick later.
  Hysteresis (a run that just received must breach the donate
  threshold by an extra margin before donating back, and vice versa;
  the serve breach/release streaks play the same role for serve runs)
  plus a per-run move cooldown keep allocations from thrashing; a run
  with active alerts or a stale heartbeat is vetoed from receiving —
  except that on a SERVE run the ``slo_*`` alerts are the *demand
  signal*, not sickness, so only non-SLO alerts (e.g.
  ``serve_retrace``) veto a serve grant; a donor never drops below its
  ``min_procs`` floor. The function is pure: (state, tick, signals) →
  decisions, no clock — every decision is reproducible from its
  recorded inputs (the breach/release streak state is derived
  deterministically from the signal sequence by
  :meth:`FleetScheduler.note_signals`).
* **Actuator** — a decision writes the runs' allocation files
  (``fleet/capacity.py``); each run's elastic supervisor probe picks the
  change up and rides the proven path (donor: SIGTERM → checkpoint →
  exit 75 → relaunch smaller; recipient: probe → grow-resume). The
  scheduler never signals a training process directly.
* **Causal tracing** — every decision carries a monotonic
  ``decision_id`` and a ``cause`` (``serve_breach`` for SLO
  preemptions, ``serve_release`` for the off-peak reclaim, ``goodput``
  for stall-market moves). The id is written into the allocation file
  as trailing metadata tokens (``fleet/capacity.py`` — old readers
  still parse the leading integer), so the donor's relaunch env, its
  resume record, its flight-ring slot and its goodput window can all
  name WHICH arbitration moved them — and the preempt-grant that
  consumes chips matured out of a donation REUSES the donation's id,
  so one ``decision_id`` spans the whole
  donate→SIGTERM→exit-75→relaunch→grant chain (``obs pod`` renders
  it; ``make tenancy-drill`` asserts it on real processes).
* **Audit** — every decision appends a ``fleet`` history record
  (schema-additive; ``obs summarize``/``pod`` render it) carrying the
  allocations before/after AND the full signal inputs that justified
  the move, plus ``fleet.allocation.<run>`` gauges / ``fleet.decisions``
  counter and an optional OpenMetrics exposition
  (``tpu_dist_fleet_allocation{run="..."}``). Additionally every
  :meth:`FleetScheduler.step` appends one ``tenancy`` record — a
  per-tick snapshot of every run's allocation plus the free and
  pending pools — so chip-second accounting is **exact by
  construction**: at every tick ``sum(alloc) + free + pending ==
  total_chips`` (a scheduler invariant), hence summed over N ticks the
  per-run buckets ∪ the scheduler's own free/pending audit equal
  ``total_chips × N`` exactly (:func:`audit_chip_seconds`).

Stdlib-only (no jax): the arbiter runs wherever the metrics files are
visible — the pod's controller VM, a laptop over a mount.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Dict, List, Optional, Tuple

from tpu_dist.elastic.supervisor import (
    feasible_sizes,
    grow_target,
    shrink_target,
)
from tpu_dist.fleet import capacity as capacity_lib
from tpu_dist.obs import counters as counters_lib
from tpu_dist.obs import export as export_lib
from tpu_dist.obs import hub as hub_lib

# Heartbeat-staleness threshold — re-exported from its ONE home in the
# hub (obs/hub.py) for the existing importers of
# ``scheduler.STALE_AFTER_S``.
from tpu_dist.obs.hub import STALE_AFTER_S  # noqa: F401  (re-export)

#: ``fleet``/``tenancy`` records stamp the CURRENT history schema
#: (metrics/history.py — v15 after the additive ``decision_id``/
#: ``decision_cause`` tracing fields). Kept as a literal so this module
#: stays jax-free; ``tests/test_fleet.py`` pins it to the real
#: SCHEMA_VERSION so the two can never drift silently.
FLEET_SCHEMA_VERSION = 15

#: The run classes the arbiter understands (``RunSpec.kind``).
RUN_KINDS = ("train", "serve")

#: The causal tags a decision can carry — WHY the chips moved.
DECISION_CAUSES = ("serve_breach", "serve_release", "goodput")


@dataclasses.dataclass(frozen=True)
class RunSpec:
    """One gang-scheduled run: its name, the size it was submitted at
    (``original`` — also its ceiling: the arbiter never grows a run past
    what it asked for), its floor, and its class. ``kind`` selects the
    policy half that governs it: ``train`` runs trade chips on goodput
    (stall fractions), ``serve`` runs on SLO state (breach/release
    streaks)."""

    name: str
    original: int
    min_procs: int = 1
    kind: str = "train"

    def __post_init__(self):
        if self.original <= 0:
            raise ValueError(f"{self.name}: original size must be positive")
        if not 1 <= self.min_procs <= self.original:
            raise ValueError(
                f"{self.name}: min_procs {self.min_procs} outside "
                f"[1, {self.original}]"
            )
        if self.kind not in RUN_KINDS:
            raise ValueError(
                f"{self.name}: kind {self.kind!r} not in {RUN_KINDS}"
            )


@dataclasses.dataclass(frozen=True)
class RunSignals:
    """One run's scraped sensor readings at a decision point. ``None``
    means the signal is absent (run not exporting yet) — absent signals
    make a run ineligible for moves in either direction rather than
    defaulting to a number."""

    run: str
    data_stall_frac: Optional[float] = None
    goodput_frac: Optional[float] = None
    mfu: Optional[float] = None
    active_alerts: Tuple[str, ...] = ()
    heartbeat_age_s: Optional[float] = None
    alive: Optional[bool] = None  # None = no liveness source configured
    epoch: Optional[float] = None
    # the serving sensor triplet (serve/slo.py scalars — published by
    # ServingEngine.record_window): demand, health, and the p99 bound
    queue_depth: Optional[float] = None
    availability: Optional[float] = None
    latency_p99_ms: Optional[float] = None

    def to_record(self) -> dict:
        out = {
            k: v
            for k, v in dataclasses.asdict(self).items()
            if k != "run" and v is not None and v != ()
        }
        if self.active_alerts:
            out["active_alerts"] = list(self.active_alerts)
        return out


def signals_from_sample(sample: dict) -> RunSignals:
    """Type one hub sample (``obs/hub.py::sample_run`` — or one entry
    of a :meth:`TelemetryHub.collect` snapshot's ``runs``) into
    :class:`RunSignals`. The ONE place the arbiter's gauge vocabulary
    lives — the scheduler never parses an exposition itself."""
    vals = sample.get("values") or {}

    def gauge(raw: str) -> Optional[float]:
        return vals.get(export_lib.metric_name(raw))

    return RunSignals(
        run=sample["run"],
        data_stall_frac=gauge("train.data_stall_frac"),
        goodput_frac=gauge("goodput.goodput_frac"),
        mfu=gauge("train.mfu"),
        active_alerts=tuple(export_lib.active_labels(vals)),
        heartbeat_age_s=sample.get("heartbeat_age_s"),
        alive=sample.get("alive"),
        epoch=gauge("train.epoch"),
        queue_depth=gauge("serve.queue_depth"),
        availability=gauge("serve.availability"),
        latency_p99_ms=gauge("serve.latency_p99_ms"),
    )


def read_signals(
    run: str,
    metrics_file: str,
    heartbeat_file: Optional[str] = None,
    now: Optional[float] = None,
) -> RunSignals:
    """One run's :class:`RunSignals`, scraped **via the hub's sample
    primitive** (``obs/hub.py::sample_run`` — the one scrape fan-in; an
    absent or torn exposition degrades to all-None signals, a stale or
    garbage heartbeat fails closed to ``alive=False``, never raises).
    Kept as the per-run convenience entry point; a pod-scale arbiter
    feeds a whole hub snapshot through :func:`signals_from_hub`
    instead of calling this N times."""
    return signals_from_sample(hub_lib.sample_run(
        run,
        metrics_file=metrics_file,
        heartbeat_file=heartbeat_file,
        now=now,
    ))


def signals_from_hub(snapshot: dict) -> Dict[str, RunSignals]:
    """Every run's :class:`RunSignals` out of ONE hub aggregation pass
    (:meth:`TelemetryHub.collect`) — the pod-scale fan-in: one snapshot
    feeds the whole ``decide`` call instead of N per-run scrapes."""
    return {
        run: signals_from_sample(sample)
        for run, sample in snapshot.get("runs", {}).items()
    }


@dataclasses.dataclass(frozen=True)
class FleetPolicy:
    """The arbitration thresholds (docs/resilience.md for semantics)."""

    donate_stall_frac: float = 0.40   # a run stalled past this donates
    receive_stall_frac: float = 0.10  # a recipient must be under this
    hysteresis: float = 0.05          # extra margin to reverse a move
    move_cooldown: int = 2            # ticks a moved run sits out
    # -- the serve half of the asymmetric policy ----------------------------
    # a serving SLO breach must be SUSTAINED this many consecutive
    # readings before it preempts training chips (one noisy window must
    # not SIGTERM a trainer) — the documented preemption-latency bound
    # is serve_breach_ticks ticks to the donor's SIGTERM (its probe
    # fires within one interval of the allocation-file shrink) plus two
    # ticks (pending maturation + grant) to the chips landing
    serve_breach_ticks: int = 2
    # ...and must stay CLEAR this many readings before the serve run
    # releases its surplus back to training (the off-peak reclaim) —
    # the serve-side hysteresis against diurnal-edge thrash
    serve_release_ticks: int = 3
    # queue-depth growth of at least this much across consecutive
    # readings counts as a breach signal even before an slo_* alert
    # fires (the queue explodes faster than a p99 histogram converges)
    serve_queue_growth: float = 1.0
    # a serve run is "healthy" (release-streak eligible) only while its
    # queue is at most this deep and availability is at least this high
    serve_idle_queue: float = 1.0
    serve_ok_availability: float = 0.99

    def __post_init__(self):
        if not 0.0 <= self.receive_stall_frac < self.donate_stall_frac <= 1.0:
            raise ValueError(
                "need 0 <= receive_stall_frac < donate_stall_frac <= 1 "
                f"(got {self.receive_stall_frac} / {self.donate_stall_frac})"
            )
        if self.hysteresis < 0 or self.move_cooldown < 0:
            raise ValueError("hysteresis and move_cooldown must be >= 0")
        if self.serve_breach_ticks < 1 or self.serve_release_ticks < 1:
            raise ValueError(
                "serve_breach_ticks and serve_release_ticks must be >= 1"
            )
        if (
            self.serve_queue_growth <= 0
            or self.serve_idle_queue < 0
            or not 0.0 <= self.serve_ok_availability <= 1.0
        ):
            raise ValueError(
                "need serve_queue_growth > 0, serve_idle_queue >= 0, "
                "serve_ok_availability in [0, 1]"
            )


class FleetScheduler:
    """Gang-schedule N runs on one pod and arbitrate their chips.

    ``fleet_dir`` (optional) is where the actuator lives: each run's
    allocation file at ``<fleet_dir>/<run>/allocation`` and the audit
    log at ``<fleet_dir>/fleet.jsonl``. Constructed without it, the
    scheduler is a pure policy object (the unit-test mode).
    """

    def __init__(
        self,
        runs: List[RunSpec],
        *,
        policy: Optional[FleetPolicy] = None,
        fleet_dir: Optional[str] = None,
        total_chips: Optional[int] = None,
        allocations: Optional[Dict[str, int]] = None,
    ):
        if not runs:
            raise ValueError("a fleet needs at least one run")
        names = [r.name for r in runs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate run names: {names}")
        self.specs: Dict[str, RunSpec] = {r.name: r for r in runs}
        self.policy = policy or FleetPolicy()
        self.fleet_dir = fleet_dir
        self.alloc: Dict[str, int] = {}
        for r in runs:
            a = (allocations or {}).get(r.name, r.original)
            if a not in feasible_sizes(r.original) or a < r.min_procs:
                raise ValueError(
                    f"{r.name}: allocation {a} is not a feasible size of "
                    f"{r.original} (or under min_procs {r.min_procs})"
                )
            self.alloc[r.name] = a
        allocated = sum(self.alloc.values())
        self.total_chips = (
            int(total_chips) if total_chips is not None else allocated
        )
        if self.total_chips < allocated:
            raise ValueError(
                f"total_chips {self.total_chips} < initial allocations "
                f"{allocated}"
            )
        self.free = self.total_chips - allocated
        # chips freed by a donation are PENDING until the next tick: the
        # donor needs its SIGTERM->checkpoint->relaunch window to actually
        # vacate them, and granting in the same instant would transiently
        # oversubscribe the pool (the recipient's probe can fire first
        # and relaunch onto chips the donor still holds). Decision points
        # are epoch-grain and the donor's resize completes within a probe
        # interval, so one-tick maturation closes the window.
        self.pending = 0
        self._pending_since: Optional[int] = None
        self._last_move_tick: Dict[str, int] = {}
        self._last_move_dir: Dict[str, str] = {}  # 'donated' | 'received'
        self.decisions = 0
        self.preemptions = 0
        # causal arbitration tracing: every decision carries a monotonic
        # decision_id. decide() stays pure — it READS the next id (and
        # the matured-donation id below); apply() advances the counter.
        self._next_decision_id = 1
        self.last_decision_id = 0
        # the donation currently maturing in the pending pool, and the
        # matured donation whose chips now sit in the free pool: the
        # FIRST grant after maturation reuses that id — the grant is the
        # completion leg of arbitration N, not a new arbitration — so
        # one decision_id spans donate→SIGTERM→exit-75→relaunch→grant
        self._pending_decision_id: Optional[int] = None
        self._matured_decision_id: Optional[int] = None
        # the serve-policy streak state — derived DETERMINISTICALLY from
        # the signal sequence by note_signals (step drives it), so a
        # replay of the recorded inputs reproduces every decision
        self._breach_streak: Dict[str, int] = {}
        self._healthy_streak: Dict[str, int] = {}
        self._last_queue_depth: Dict[str, float] = {}
        if fleet_dir:
            os.makedirs(fleet_dir, exist_ok=True)
            for name, a in self.alloc.items():
                capacity_lib.write_allocation(self.allocation_path(name), a)
        self._publish_gauges()

    # -- paths ---------------------------------------------------------------

    def allocation_path(self, run: str) -> str:
        if not self.fleet_dir:
            raise ValueError("scheduler constructed without a fleet_dir")
        return os.path.join(self.fleet_dir, run, "allocation")

    def history_path(self) -> str:
        if not self.fleet_dir:
            raise ValueError("scheduler constructed without a fleet_dir")
        return os.path.join(self.fleet_dir, "fleet.jsonl")

    # -- policy --------------------------------------------------------------

    def _in_cooldown(self, run: str, tick: int) -> bool:
        last = self._last_move_tick.get(run)
        return last is not None and tick - last <= self.policy.move_cooldown

    # -- the serve half: breach/release streaks ------------------------------

    def _serve_breached(self, run: str, sig: RunSignals) -> bool:
        """One reading's breach verdict: any active ``slo_*`` alert
        (serve/slo.py SLO_BUILTINS — p99/p50/TTFB/availability/rps/
        queue), or the queue growing across consecutive readings (the
        early-warning signal — a queue explodes faster than a p99
        histogram converges)."""
        if any(a.startswith("slo_") for a in sig.active_alerts):
            return True
        q, last = sig.queue_depth, self._last_queue_depth.get(run)
        return (
            q is not None and last is not None
            and q - last >= self.policy.serve_queue_growth
        )

    def _serve_healthy(self, run: str, sig: RunSignals) -> bool:
        """One reading's release-eligibility verdict: no breach signal,
        queue at idle depth, availability over the bar (an absent
        availability — no completed requests yet in the window — reads
        as healthy only alongside an idle queue)."""
        if self._serve_breached(run, sig):
            return False
        if sig.queue_depth is None or sig.queue_depth > self.policy.serve_idle_queue:
            return False
        return (
            sig.availability is None
            or sig.availability >= self.policy.serve_ok_availability
        )

    def note_signals(self, signals: Dict[str, RunSignals]) -> None:
        """Advance each serve run's breach/release streaks from one
        reading. :meth:`step` calls this before :meth:`decide`; drive it
        yourself (in signal order) when replaying recorded inputs
        through :meth:`decide` directly. A run with no reading holds
        its streaks — absent evidence neither escalates nor clears."""
        for run, spec in self.specs.items():
            if spec.kind != "serve":
                continue
            sig = signals.get(run)
            if sig is None:
                continue
            if self._serve_breached(run, sig):
                self._breach_streak[run] = self._breach_streak.get(run, 0) + 1
                self._healthy_streak[run] = 0
            elif self._serve_healthy(run, sig):
                self._healthy_streak[run] = (
                    self._healthy_streak.get(run, 0) + 1
                )
                self._breach_streak[run] = 0
            else:
                # neither breached nor idle-healthy (e.g. busy but
                # within SLO): both streaks reset — no escalation, no
                # release
                self._breach_streak[run] = 0
                self._healthy_streak[run] = 0
            if sig.queue_depth is not None:
                self._last_queue_depth[run] = sig.queue_depth

    def _serve_wants_chips(self, run: str, sig: Optional[RunSignals],
                           tick: int) -> bool:
        """A serve run whose breach streak crossed the sustained bar and
        that can still grow. Deliberately NOT cooldown-gated: the
        breach streak is itself the thrash guard, and the preemption-
        latency contract cannot hide a cooldown inside it."""
        spec = self.specs[run]
        if spec.kind != "serve" or self.alloc[run] >= spec.original:
            return False
        if sig is None or sig.alive is False:
            return False
        if any(not a.startswith("slo_") for a in sig.active_alerts):
            # asymmetric alert veto: slo_* alerts ARE the demand signal,
            # but a non-SLO alert (serve_retrace, heartbeat_stale...)
            # means the replica is sick — chips won't fix that
            return False
        return (
            self._breach_streak.get(run, 0) >= self.policy.serve_breach_ticks
        )

    def _serve_can_release(self, run: str, sig: Optional[RunSignals],
                           tick: int) -> bool:
        """A serve run healthy long enough to hand its surplus back."""
        spec = self.specs[run]
        if spec.kind != "serve" or self.alloc[run] <= spec.min_procs:
            return False
        if shrink_target(
            spec.original, self.alloc[run], self.alloc[run] - 1, spec.min_procs
        ) is None:
            return False
        if self._in_cooldown(run, tick):
            return False
        if sig is None or sig.alive is False:
            return False
        return (
            self._healthy_streak.get(run, 0) >= self.policy.serve_release_ticks
        )

    # -- the train half: stall-fraction thresholds ---------------------------

    def _donor_ok(self, run: str, sig: Optional[RunSignals], tick: int) -> bool:
        spec = self.specs[run]
        if spec.kind == "serve":
            # a serve run donates on its release streak, not on stall
            return self._serve_can_release(run, sig, tick)
        if self.alloc[run] <= spec.min_procs:
            return False
        if shrink_target(
            spec.original, self.alloc[run], self.alloc[run] - 1, spec.min_procs
        ) is None:
            return False
        if self._in_cooldown(run, tick):
            return False
        if sig is None or sig.alive is False:
            return False
        stall = sig.data_stall_frac
        if stall is None:
            return False
        threshold = self.policy.donate_stall_frac
        if self._last_move_dir.get(run) == "received":
            # hysteresis: reversing a receive needs extra conviction
            threshold += self.policy.hysteresis
        return stall >= threshold

    def _recipient_ok(self, run: str, sig: Optional[RunSignals], tick: int) -> bool:
        spec = self.specs[run]
        if spec.kind == "serve":
            return False  # serve runs grow only through the breach path
        if self.alloc[run] >= spec.original:
            return False
        if self._in_cooldown(run, tick):
            return False
        if sig is None or sig.alive is False:
            return False
        if sig.active_alerts:
            return False  # alert-veto: never feed chips to a sick run
        stall = sig.data_stall_frac
        if stall is None:
            return False
        threshold = self.policy.receive_stall_frac
        if self._last_move_dir.get(run) == "donated":
            threshold -= self.policy.hysteresis
        return stall <= threshold

    def _preempt_donor(self, recipient: str, signals: Dict[str, RunSignals],
                       tick: int) -> Optional[Tuple[str, int]]:
        """Pick the training run to shrink for a breached serve run:
        prefer the most data-stalled (its chips buy the least), but —
        unlike the goodput path — a compute-bound trainer is preempted
        too when it is all there is: the SLO outranks goodput. Floors,
        shrink feasibility and liveness still hold; the donor cooldown
        does NOT (it would add unbounded ticks to the preemption-latency
        contract). Returns ``(donor, target_size)`` or None."""
        rspec = self.specs[recipient]
        rcur = self.alloc[recipient]
        candidates = sorted(
            (r for r, s in self.specs.items() if s.kind == "train"),
            key=lambda r: (
                -(signals[r].data_stall_frac or 0.0)
                if r in signals and signals[r] is not None else 0.0,
                r,
            ),
        )
        for donor in candidates:
            sig = signals.get(donor)
            if sig is None or sig.alive is False:
                continue
            dspec = self.specs[donor]
            dcur = self.alloc[donor]
            # smallest sufficient shrink: walk the donor's feasible
            # sizes largest-first and take the first whose freed chips
            # make the serve grow reachable — a preemption must actually
            # buy the replica set its next bucket, not just wound the
            # trainer
            for dtarget in sorted(
                (s for s in feasible_sizes(dspec.original)
                 if dspec.min_procs <= s < dcur),
                reverse=True,
            ):
                if grow_target(
                    rspec.original, rcur,
                    rcur + self.free + self.pending + (dcur - dtarget),
                    rspec.original,
                ) is not None:
                    return donor, dtarget
        return None

    def mature_pending(self, tick: int) -> None:
        """Fold chips a donor freed at an EARLIER tick into the grantable
        pool — by the next epoch-grain decision point the donor's probe
        has long since relaunched it at the smaller size, so the chips
        are genuinely vacant. :meth:`step` calls this; drive it yourself
        when using :meth:`decide`/:meth:`apply` directly."""
        if self._pending_since is not None and tick > self._pending_since:
            self.free += self.pending
            self.pending = 0
            self._pending_since = None
            # the donation's id rides with its chips into the free pool:
            # the next grant completes that arbitration under the same id
            self._matured_decision_id = self._pending_decision_id
            self._pending_decision_id = None
            self._publish_gauges()

    def decide(
        self, tick: int, signals: Dict[str, RunSignals]
    ) -> List[dict]:
        """One decision point: pure policy over the scraped signals (no
        state mutated — :meth:`step` applies + audits). At most one
        decision per tick (epoch-grain pacing; the cooldown makes more
        pointless anyway): a **grant** grows the best compute-bound
        recipient from the FREE pool; when the pool is empty a
        **donation** shrinks the worst stalled donor, banking its chips
        as pending until the next tick — never both at once, so the
        allocations on disk never sum past the chips that are actually
        vacant (the donor needs its checkpoint/relaunch window to vacate
        them).

        Serve-breach arbitration runs FIRST: a serve run whose breach
        streak crossed ``serve_breach_ticks`` is granted from the free
        pool when chips are vacant, else a training donor is preempted
        (shrunk regardless of stall) — SLO demand outranks every
        goodput move. Off-peak the release streak turns the serve run
        into an ordinary donor and the existing recipient-driven
        donate/grant discipline reclaims the chips for training."""
        # -- priority 1: a sustained serving SLO breach claims chips ----
        breached = sorted(
            (r for r in self.specs
             if self._serve_wants_chips(r, signals.get(r), tick)),
            key=lambda r: (-self._breach_streak.get(r, 0), r),
        )
        for run in breached:
            spec = self.specs[run]
            cur = self.alloc[run]
            target = grow_target(
                spec.original, cur, cur + self.free, spec.original
            )
            if target is not None:
                return [self._grant_decision(
                    tick, signals, run, target, preempt=True
                )]
            picked = self._preempt_donor(run, signals, tick)
            if picked is not None:
                donor, dtarget = picked
                return [self._donate_decision(
                    tick, signals, donor, dtarget, for_run=run, preempt=True
                )]
        # -- priority 2: the goodput market (train↔train, plus serve
        # runs releasing surplus off-peak via _donor_ok) ----------------
        donors = sorted(
            (r for r in self.specs if self._donor_ok(r, signals.get(r), tick)),
            key=lambda r: (-(signals[r].data_stall_frac or 0.0), r),
        )
        recipients = sorted(
            (r for r in self.specs
             if self._recipient_ok(r, signals.get(r), tick)),
            key=lambda r: (signals[r].data_stall_frac or 0.0, r),
        )
        recipients = [r for r in recipients if r not in donors]
        for recipient in recipients:
            spec = self.specs[recipient]
            cur = self.alloc[recipient]
            target = grow_target(
                spec.original, cur, cur + self.free, spec.original
            )
            if target is not None:
                return [self._grant_decision(
                    tick, signals, recipient, target
                )]
            # the recipient is starved and the pool is dry: bank the
            # worst donor's chips for the NEXT tick (a donation without
            # demand never happens — chips would just idle)
            for donor in donors:
                dspec = self.specs[donor]
                dcur = self.alloc[donor]
                if dspec.kind == "serve":
                    # an off-peak release may need more than one
                    # feasible step at once (the trainer's next size up
                    # can be far away) — take the smallest sufficient
                    # shrink, largest target first
                    targets = sorted(
                        (s for s in feasible_sizes(dspec.original)
                         if dspec.min_procs <= s < dcur),
                        reverse=True,
                    )
                else:
                    one = shrink_target(
                        dspec.original, dcur, dcur - 1, dspec.min_procs
                    )
                    targets = [one] if one is not None else []
                for dtarget in targets:
                    freed = dcur - dtarget
                    if grow_target(
                        spec.original, cur,
                        cur + self.free + self.pending + freed, spec.original,
                    ) is None:
                        continue  # would never reach a feasible grow
                    return [self._donate_decision(
                        tick, signals, donor, dtarget, for_run=recipient
                    )]
        return []

    def _base_record(self, tick: int, signals: Dict[str, RunSignals]) -> dict:
        rec = {
            "kind": "fleet",
            "schema_version": FLEET_SCHEMA_VERSION,
            "tick": int(tick),
            "inputs": {
                r: signals[r].to_record() for r in sorted(signals)
            },
            "policy": dataclasses.asdict(self.policy),
        }
        streaks = {
            r: {
                "breach": self._breach_streak.get(r, 0),
                "healthy": self._healthy_streak.get(r, 0),
            }
            for r, s in sorted(self.specs.items()) if s.kind == "serve"
        }
        if streaks:
            rec["serve_streaks"] = streaks
        return rec

    def _grant_decision(
        self, tick: int, signals: Dict[str, RunSignals],
        recipient: str, recipient_to: int, preempt: bool = False,
    ) -> dict:
        before = dict(self.alloc)
        after = dict(before)
        after[recipient] = recipient_to
        moved = recipient_to - before[recipient]
        rsig = signals.get(recipient)
        if preempt:
            reason = (
                f"sustained SLO breach "
                f"({self._breach_streak.get(recipient, 0)} reading(s)) — "
                f"free pool staffs breached serve run {recipient}"
                + (
                    f" (queue {rsig.queue_depth:g})"
                    if rsig is not None and rsig.queue_depth is not None
                    else ""
                )
            )
        else:
            reason = "free pool staffs compute-bound " + recipient + (
                f" (stall {rsig.data_stall_frac:.0%})"
                if rsig is not None and rsig.data_stall_frac is not None
                else ""
            )
        # a grant that consumes chips matured out of a donation is the
        # COMPLETION of that arbitration: reuse its id (one decision_id
        # spans the whole donate→…→grant chain); a grant from original
        # free-pool slack is its own fresh arbitration
        chained = self._matured_decision_id is not None
        return {
            **self._base_record(tick, signals),
            "action": "grant",
            "decision_id": (
                self._matured_decision_id if chained
                else self._next_decision_id
            ),
            "cause": "serve_breach" if preempt else "goodput",
            "chained": chained,
            "donor": None,
            "recipient": recipient,
            "chips": int(moved),
            "preempt": bool(preempt),
            "alloc_before": before,
            "alloc_after": after,
            "free_before": self.free,
            "free_after": self.free - moved,
            "pending_after": self.pending,
            "reason": reason,
        }

    def _donate_decision(
        self, tick: int, signals: Dict[str, RunSignals],
        donor: str, donor_to: int, for_run: str, preempt: bool = False,
    ) -> dict:
        before = dict(self.alloc)
        after = dict(before)
        after[donor] = int(donor_to)
        freed = before[donor] - after[donor]
        dsig = signals.get(donor)
        fsig = signals.get(for_run)
        if preempt:
            reason = (
                f"sustained SLO breach on {for_run} "
                f"({self._breach_streak.get(for_run, 0)} reading(s)) "
                f"preempts {freed} chip(s) from trainer {donor} "
                "(SIGTERM→emergency-save→exit-75) — grantable next tick"
            )
        elif self.specs[donor].kind == "serve":
            reason = (
                f"serve run {donor} healthy "
                f"{self._healthy_streak.get(donor, 0)} reading(s) releases "
                f"{freed} chip(s) toward compute-bound {for_run}"
                + (
                    f" (stall {fsig.data_stall_frac:.0%})"
                    if fsig is not None and fsig.data_stall_frac is not None
                    else ""
                )
                + " — grantable next tick"
            )
        else:
            reason = (
                f"{donor} "
                + (
                    f"{dsig.data_stall_frac:.0%} "
                    if dsig is not None and dsig.data_stall_frac is not None
                    else ""
                )
                + f"data-stalled donates {freed} chip(s) toward "
                f"compute-bound {for_run}"
                + (
                    f" (stall {fsig.data_stall_frac:.0%})"
                    if fsig is not None and fsig.data_stall_frac is not None
                    else ""
                )
                + " — grantable next tick"
            )
        if preempt:
            cause = "serve_breach"
        elif self.specs[donor].kind == "serve":
            cause = "serve_release"
        else:
            cause = "goodput"
        return {
            **self._base_record(tick, signals),
            "action": "donate",
            "decision_id": self._next_decision_id,
            "cause": cause,
            "chained": False,
            "donor": donor,
            "recipient": None,
            "for_run": for_run,
            "chips": int(freed),
            "preempt": bool(preempt),
            "alloc_before": before,
            "alloc_after": after,
            "free_before": self.free,
            "free_after": self.free,
            "pending_after": self.pending + freed,
            "reason": reason,
        }

    # -- actuation + audit ---------------------------------------------------

    def apply(self, decision: dict, tick: int) -> None:
        """Commit one decision: allocations, cooldown/hysteresis state,
        pending/free pools, decision-id bookkeeping, gauges, allocation
        files (written WITH the decision metadata tokens — the donor's
        supervisor reads them back into the relaunch env, which is how
        the id crosses the process boundary)."""
        after = decision["alloc_after"]
        did = int(decision.get("decision_id") or self._next_decision_id)
        cause = decision.get("cause")
        for run in self.specs:
            if after[run] != self.alloc[run]:
                self._last_move_tick[run] = tick
                self._last_move_dir[run] = (
                    "donated" if after[run] < self.alloc[run] else "received"
                )
                self.alloc[run] = after[run]
                if self.fleet_dir:
                    capacity_lib.write_allocation(
                        self.allocation_path(run), after[run],
                        decision_id=did, cause=cause,
                    )
        self.free = decision["free_after"]
        if decision.get("action") == "donate":
            self.pending = decision["pending_after"]
            self._pending_since = tick
            self._pending_decision_id = did
        elif did == self._matured_decision_id:
            # the matured donation's completion grant just fired — the
            # chain is closed, the next grant is a fresh arbitration
            self._matured_decision_id = None
        self._next_decision_id = max(self._next_decision_id, did + 1)
        self.last_decision_id = did
        self.decisions += 1
        counters_lib.inc("fleet.decisions")
        if decision.get("preempt"):
            self.preemptions += 1
            counters_lib.inc("fleet.preemptions")
        self._publish_gauges()

    def tenancy_record(self, tick: int) -> dict:
        """One per-tick chip-accounting snapshot (``tenancy`` history
        kind, schema v15): every run's allocation plus the free and
        pending pools, stamped with the id of the LAST arbitration that
        shaped them (``decision_id`` — 0 until the first move; the
        ``obs pod`` chip-ownership Gantt reads the ticks off these).
        ``sum(alloc) + free + pending == total_chips`` holds at every
        tick (the pools are conserved by construction), which is what
        makes :func:`audit_chip_seconds` exact rather than
        approximate."""
        return {
            "kind": "tenancy",
            "schema_version": FLEET_SCHEMA_VERSION,
            "tick": int(tick),
            "alloc": dict(self.alloc),
            "free": int(self.free),
            "pending": int(self.pending),
            "total_chips": int(self.total_chips),
            "run_kinds": {r: s.kind for r, s in sorted(self.specs.items())},
            "decision_id": int(self.last_decision_id),
        }

    def step(
        self,
        tick: int,
        signals: Dict[str, RunSignals],
        ts: Optional[float] = None,
    ) -> List[dict]:
        """mature pending → note serve streaks → decide → apply → audit
        (every decision PLUS one per-tick ``tenancy`` snapshot). ``ts``
        annotates the records for humans and cross-run joins; the
        POLICY never reads it (reproducibility contract)."""
        self.mature_pending(tick)
        self.note_signals(signals)
        decisions = self.decide(tick, signals)
        now = time.time() if ts is None else ts
        for d in decisions:
            self.apply(d, tick)
            if self.fleet_dir:
                rec = dict(d)
                rec["ts"] = now
                with open(self.history_path(), "a") as f:
                    f.write(json.dumps(rec) + "\n")
        if self.fleet_dir:
            rec = self.tenancy_record(tick)
            rec["ts"] = now
            with open(self.history_path(), "a") as f:
                f.write(json.dumps(rec) + "\n")
        return decisions

    def _publish_gauges(self) -> None:
        for run, a in self.alloc.items():
            counters_lib.set_gauge(f"fleet.allocation.{run}", a)
        counters_lib.set_gauge("fleet.free_chips", self.free)
        counters_lib.set_gauge("fleet.pending_chips", self.pending)

    def exposition(self) -> str:
        """The scheduler's own OpenMetrics exposition:
        ``tpu_dist_fleet_allocation{run="..."}`` samples plus the
        decision counter — scrape-able next to the runs it arbitrates."""
        return export_lib.render(
            {
                "fleet.decisions": self.decisions,
                "fleet.preemptions": self.preemptions,
                "fleet.free_chips": self.free,
                "fleet.pending_chips": self.pending,
                # the hub's chip rollups and the pod-level decision
                # cursor read these two off the scraped ledger
                "fleet.total_chips": self.total_chips,
                "fleet.last_decision_id": self.last_decision_id,
            },
            labeled={"fleet_allocation": dict(self.alloc)},
            label_keys={"fleet_allocation": "run"},
        )

    def write_exposition(self, path: str) -> None:
        tmp = f"{path}.tmp.{os.getpid()}"
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(tmp, "w") as f:
            f.write(self.exposition())
        os.replace(tmp, path)


# -- chip-second accounting ---------------------------------------------------


def audit_chip_seconds(
    records: List[dict], tick_s: float = 1.0
) -> dict:
    """The conservation audit over a run's ``tenancy`` snapshots: the
    per-run chip-second buckets ∪ the scheduler's own free/pending
    account must equal the pod's chip-seconds **exactly** — integer
    chip-ticks scaled by ``tick_s``, no float accumulation in the
    identity itself.

    ``records`` is any iterable of history records (non-``tenancy``
    kinds are ignored — pass a whole parsed ``fleet.jsonl``). Returns::

        {"n_ticks", "total_chips", "tick_s",
         "per_run": {run: chip_seconds}, "free_chip_s", "pending_chip_s",
         "accounted_chip_s", "pod_chip_s", "conserved", "violations"}

    ``conserved`` is the exact identity over the whole window;
    ``violations`` lists any single tick where
    ``sum(alloc) + free + pending != total_chips`` (none can occur for
    snapshots a :class:`FleetScheduler` wrote — the pools are conserved
    by construction — so a violation means the log was edited or mixed
    from two schedulers)."""
    snaps = [r for r in records if r.get("kind") == "tenancy"]
    per_run_ticks: Dict[str, int] = {}
    free_ticks = 0
    pending_ticks = 0
    total_chips = 0
    violations: List[dict] = []
    for r in snaps:
        alloc = r.get("alloc") or {}
        free = int(r.get("free") or 0)
        pending = int(r.get("pending") or 0)
        total_chips = int(r.get("total_chips") or 0)
        for run, a in alloc.items():
            per_run_ticks[run] = per_run_ticks.get(run, 0) + int(a)
        free_ticks += free
        pending_ticks += pending
        if sum(int(a) for a in alloc.values()) + free + pending != total_chips:
            violations.append({
                "tick": r.get("tick"), "alloc": dict(alloc),
                "free": free, "pending": pending,
                "total_chips": total_chips,
            })
    n_ticks = len(snaps)
    accounted_ticks = sum(per_run_ticks.values()) + free_ticks + pending_ticks
    pod_ticks = total_chips * n_ticks
    return {
        "n_ticks": n_ticks,
        "total_chips": total_chips,
        "tick_s": tick_s,
        "per_run": {
            run: t * tick_s for run, t in sorted(per_run_ticks.items())
        },
        "free_chip_s": free_ticks * tick_s,
        "pending_chip_s": pending_ticks * tick_s,
        "accounted_chip_s": accounted_ticks * tick_s,
        "pod_chip_s": pod_ticks * tick_s,
        "conserved": accounted_ticks == pod_ticks and not violations,
        "violations": violations,
    }
