"""Goodput-aware chip arbitration across runs sharing one pod
(docs/resilience.md "Scale-up & fleet scheduling").

Multiple workloads time-sharing the same chips is the expected
production shape (PAPERS.md: Gemma fine-tune + serve on one pod), and at
pod scale worker churn is routine (PAPERS.md: Concurrency on Google
TPUs) — so chips should sit where they buy goodput, not where the
original submission happened to put them. This module is the arbiter:

* **Sensors** — the per-run signals the obs stack already exports:
  each run's OpenMetrics textfile (``--metrics_file``; scraped with
  ``obs/export.py::scrape``) carries data-stall fraction, goodput
  fraction, MFU and the active-alert gauges, and its heartbeat file
  answers liveness. Nothing here instruments a run — the scheduler is a
  pure reader of artifacts that exist anyway.
* **Policy** (:meth:`FleetScheduler.decide`) — at epoch-grain decision
  points (integer ``tick``), a run data-stalled past
  ``donate_stall_frac`` donates chips toward a compute-bound one under
  ``receive_stall_frac``. Donated chips are **pending until the next
  tick**: the donor needs its checkpoint→relaunch window to actually
  vacate them, so granting in the same instant would transiently
  oversubscribe the pool — the recipient is granted from the FREE pool
  only, one tick later. Hysteresis (a run that just received must
  breach the donate threshold by an extra margin before donating back,
  and vice versa) plus a per-run move cooldown keep allocations from
  thrashing; a run with active alerts or a stale heartbeat is vetoed
  from receiving; a donor never drops below its ``min_procs`` floor.
  The function is pure: (state, tick, signals) → decisions, no clock —
  every decision is reproducible from its recorded inputs.
* **Actuator** — a decision writes the runs' allocation files
  (``fleet/capacity.py``); each run's elastic supervisor probe picks the
  change up and rides the proven path (donor: SIGTERM → checkpoint →
  exit 75 → relaunch smaller; recipient: probe → grow-resume). The
  scheduler never signals a training process directly.
* **Audit** — every decision appends a ``fleet`` history record
  (schema-additive; ``obs summarize``/``pod`` render it) carrying the
  allocations before/after AND the full signal inputs that justified
  the move, plus ``fleet.allocation.<run>`` gauges / ``fleet.decisions``
  counter and an optional OpenMetrics exposition
  (``tpu_dist_fleet_allocation{run="..."}``).

Stdlib-only (no jax): the arbiter runs wherever the metrics files are
visible — the pod's controller VM, a laptop over a mount.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Dict, List, Optional, Tuple

from tpu_dist.elastic.supervisor import (
    feasible_sizes,
    grow_target,
    shrink_target,
)
from tpu_dist.fleet import capacity as capacity_lib
from tpu_dist.obs import counters as counters_lib
from tpu_dist.obs import export as export_lib

#: ``fleet`` records stamp the CURRENT history schema (metrics/
#: history.py — v13 after the additive ``tune`` kind). Kept as a
#: literal so this module stays jax-free; ``tests/test_fleet.py`` pins
#: it to the real SCHEMA_VERSION so the two can never drift silently.
FLEET_SCHEMA_VERSION = 13

#: Heartbeat older than this reads as a dead/wedged run (matches the
#: ``obs tail`` STALE threshold and the builtin heartbeat_stale rule).
STALE_AFTER_S = 60.0


@dataclasses.dataclass(frozen=True)
class RunSpec:
    """One gang-scheduled run: its name, the size it was submitted at
    (``original`` — also its ceiling: the arbiter never grows a run past
    what it asked for), and its floor."""

    name: str
    original: int
    min_procs: int = 1

    def __post_init__(self):
        if self.original <= 0:
            raise ValueError(f"{self.name}: original size must be positive")
        if not 1 <= self.min_procs <= self.original:
            raise ValueError(
                f"{self.name}: min_procs {self.min_procs} outside "
                f"[1, {self.original}]"
            )


@dataclasses.dataclass(frozen=True)
class RunSignals:
    """One run's scraped sensor readings at a decision point. ``None``
    means the signal is absent (run not exporting yet) — absent signals
    make a run ineligible for moves in either direction rather than
    defaulting to a number."""

    run: str
    data_stall_frac: Optional[float] = None
    goodput_frac: Optional[float] = None
    mfu: Optional[float] = None
    active_alerts: Tuple[str, ...] = ()
    heartbeat_age_s: Optional[float] = None
    alive: Optional[bool] = None  # None = no liveness source configured
    epoch: Optional[float] = None

    def to_record(self) -> dict:
        out = {
            k: v
            for k, v in dataclasses.asdict(self).items()
            if k != "run" and v is not None and v != ()
        }
        if self.active_alerts:
            out["active_alerts"] = list(self.active_alerts)
        return out


def read_signals(
    run: str,
    metrics_file: str,
    heartbeat_file: Optional[str] = None,
    now: Optional[float] = None,
) -> RunSignals:
    """Scrape one run's last OpenMetrics exposition (and optionally its
    heartbeat) into :class:`RunSignals`. Pure file reads — an absent or
    torn exposition degrades to all-None signals, never raises."""
    vals = export_lib.scrape(textfile=metrics_file) or {}

    def gauge(raw: str) -> Optional[float]:
        return vals.get(export_lib.metric_name(raw))

    alerts = tuple(export_lib.active_labels(vals))
    age = None
    alive: Optional[bool] = None
    if heartbeat_file is not None:
        from tpu_dist.obs import heartbeat as heartbeat_lib  # stdlib-only

        rec = heartbeat_lib.read(heartbeat_file)
        if rec is None:
            alive = False  # absent beat on a run we were told beats
        else:
            ts = rec.get("ts")
            if isinstance(ts, (int, float)):
                age = (time.time() if now is None else now) - float(ts)
                alive = age <= STALE_AFTER_S
    return RunSignals(
        run=run,
        data_stall_frac=gauge("train.data_stall_frac"),
        goodput_frac=gauge("goodput.goodput_frac"),
        mfu=gauge("train.mfu"),
        active_alerts=alerts,
        heartbeat_age_s=round(age, 1) if age is not None else None,
        alive=alive,
        epoch=gauge("train.epoch"),
    )


@dataclasses.dataclass(frozen=True)
class FleetPolicy:
    """The arbitration thresholds (docs/resilience.md for semantics)."""

    donate_stall_frac: float = 0.40   # a run stalled past this donates
    receive_stall_frac: float = 0.10  # a recipient must be under this
    hysteresis: float = 0.05          # extra margin to reverse a move
    move_cooldown: int = 2            # ticks a moved run sits out

    def __post_init__(self):
        if not 0.0 <= self.receive_stall_frac < self.donate_stall_frac <= 1.0:
            raise ValueError(
                "need 0 <= receive_stall_frac < donate_stall_frac <= 1 "
                f"(got {self.receive_stall_frac} / {self.donate_stall_frac})"
            )
        if self.hysteresis < 0 or self.move_cooldown < 0:
            raise ValueError("hysteresis and move_cooldown must be >= 0")


class FleetScheduler:
    """Gang-schedule N runs on one pod and arbitrate their chips.

    ``fleet_dir`` (optional) is where the actuator lives: each run's
    allocation file at ``<fleet_dir>/<run>/allocation`` and the audit
    log at ``<fleet_dir>/fleet.jsonl``. Constructed without it, the
    scheduler is a pure policy object (the unit-test mode).
    """

    def __init__(
        self,
        runs: List[RunSpec],
        *,
        policy: Optional[FleetPolicy] = None,
        fleet_dir: Optional[str] = None,
        total_chips: Optional[int] = None,
        allocations: Optional[Dict[str, int]] = None,
    ):
        if not runs:
            raise ValueError("a fleet needs at least one run")
        names = [r.name for r in runs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate run names: {names}")
        self.specs: Dict[str, RunSpec] = {r.name: r for r in runs}
        self.policy = policy or FleetPolicy()
        self.fleet_dir = fleet_dir
        self.alloc: Dict[str, int] = {}
        for r in runs:
            a = (allocations or {}).get(r.name, r.original)
            if a not in feasible_sizes(r.original) or a < r.min_procs:
                raise ValueError(
                    f"{r.name}: allocation {a} is not a feasible size of "
                    f"{r.original} (or under min_procs {r.min_procs})"
                )
            self.alloc[r.name] = a
        allocated = sum(self.alloc.values())
        self.total_chips = (
            int(total_chips) if total_chips is not None else allocated
        )
        if self.total_chips < allocated:
            raise ValueError(
                f"total_chips {self.total_chips} < initial allocations "
                f"{allocated}"
            )
        self.free = self.total_chips - allocated
        # chips freed by a donation are PENDING until the next tick: the
        # donor needs its SIGTERM->checkpoint->relaunch window to actually
        # vacate them, and granting in the same instant would transiently
        # oversubscribe the pool (the recipient's probe can fire first
        # and relaunch onto chips the donor still holds). Decision points
        # are epoch-grain and the donor's resize completes within a probe
        # interval, so one-tick maturation closes the window.
        self.pending = 0
        self._pending_since: Optional[int] = None
        self._last_move_tick: Dict[str, int] = {}
        self._last_move_dir: Dict[str, str] = {}  # 'donated' | 'received'
        self.decisions = 0
        if fleet_dir:
            os.makedirs(fleet_dir, exist_ok=True)
            for name, a in self.alloc.items():
                capacity_lib.write_allocation(self.allocation_path(name), a)
        self._publish_gauges()

    # -- paths ---------------------------------------------------------------

    def allocation_path(self, run: str) -> str:
        if not self.fleet_dir:
            raise ValueError("scheduler constructed without a fleet_dir")
        return os.path.join(self.fleet_dir, run, "allocation")

    def history_path(self) -> str:
        if not self.fleet_dir:
            raise ValueError("scheduler constructed without a fleet_dir")
        return os.path.join(self.fleet_dir, "fleet.jsonl")

    # -- policy --------------------------------------------------------------

    def _in_cooldown(self, run: str, tick: int) -> bool:
        last = self._last_move_tick.get(run)
        return last is not None and tick - last <= self.policy.move_cooldown

    def _donor_ok(self, run: str, sig: Optional[RunSignals], tick: int) -> bool:
        spec = self.specs[run]
        if self.alloc[run] <= spec.min_procs:
            return False
        if shrink_target(
            spec.original, self.alloc[run], self.alloc[run] - 1, spec.min_procs
        ) is None:
            return False
        if self._in_cooldown(run, tick):
            return False
        if sig is None or sig.alive is False:
            return False
        stall = sig.data_stall_frac
        if stall is None:
            return False
        threshold = self.policy.donate_stall_frac
        if self._last_move_dir.get(run) == "received":
            # hysteresis: reversing a receive needs extra conviction
            threshold += self.policy.hysteresis
        return stall >= threshold

    def _recipient_ok(self, run: str, sig: Optional[RunSignals], tick: int) -> bool:
        spec = self.specs[run]
        if self.alloc[run] >= spec.original:
            return False
        if self._in_cooldown(run, tick):
            return False
        if sig is None or sig.alive is False:
            return False
        if sig.active_alerts:
            return False  # alert-veto: never feed chips to a sick run
        stall = sig.data_stall_frac
        if stall is None:
            return False
        threshold = self.policy.receive_stall_frac
        if self._last_move_dir.get(run) == "donated":
            threshold -= self.policy.hysteresis
        return stall <= threshold

    def mature_pending(self, tick: int) -> None:
        """Fold chips a donor freed at an EARLIER tick into the grantable
        pool — by the next epoch-grain decision point the donor's probe
        has long since relaunched it at the smaller size, so the chips
        are genuinely vacant. :meth:`step` calls this; drive it yourself
        when using :meth:`decide`/:meth:`apply` directly."""
        if self._pending_since is not None and tick > self._pending_since:
            self.free += self.pending
            self.pending = 0
            self._pending_since = None
            self._publish_gauges()

    def decide(
        self, tick: int, signals: Dict[str, RunSignals]
    ) -> List[dict]:
        """One decision point: pure policy over the scraped signals (no
        state mutated — :meth:`step` applies + audits). At most one
        decision per tick (epoch-grain pacing; the cooldown makes more
        pointless anyway): a **grant** grows the best compute-bound
        recipient from the FREE pool; when the pool is empty a
        **donation** shrinks the worst stalled donor, banking its chips
        as pending until the next tick — never both at once, so the
        allocations on disk never sum past the chips that are actually
        vacant (the donor needs its checkpoint/relaunch window to vacate
        them)."""
        donors = sorted(
            (r for r in self.specs if self._donor_ok(r, signals.get(r), tick)),
            key=lambda r: (-(signals[r].data_stall_frac or 0.0), r),
        )
        recipients = sorted(
            (r for r in self.specs
             if self._recipient_ok(r, signals.get(r), tick)),
            key=lambda r: (signals[r].data_stall_frac or 0.0, r),
        )
        recipients = [r for r in recipients if r not in donors]
        for recipient in recipients:
            spec = self.specs[recipient]
            cur = self.alloc[recipient]
            target = grow_target(
                spec.original, cur, cur + self.free, spec.original
            )
            if target is not None:
                return [self._grant_decision(
                    tick, signals, recipient, target
                )]
            # the recipient is starved and the pool is dry: bank the
            # worst donor's chips for the NEXT tick (a donation without
            # demand never happens — chips would just idle)
            for donor in donors:
                dspec = self.specs[donor]
                dcur = self.alloc[donor]
                dtarget = shrink_target(
                    dspec.original, dcur, dcur - 1, dspec.min_procs
                )
                if dtarget is None:
                    continue
                freed = dcur - dtarget
                if grow_target(
                    spec.original, cur,
                    cur + self.free + self.pending + freed, spec.original,
                ) is None:
                    continue  # the donation would never reach a feasible grow
                return [self._donate_decision(
                    tick, signals, donor, dtarget, for_run=recipient
                )]
        return []

    def _base_record(self, tick: int, signals: Dict[str, RunSignals]) -> dict:
        return {
            "kind": "fleet",
            "schema_version": FLEET_SCHEMA_VERSION,
            "tick": int(tick),
            "inputs": {
                r: signals[r].to_record() for r in sorted(signals)
            },
            "policy": dataclasses.asdict(self.policy),
        }

    def _grant_decision(
        self, tick: int, signals: Dict[str, RunSignals],
        recipient: str, recipient_to: int,
    ) -> dict:
        before = dict(self.alloc)
        after = dict(before)
        after[recipient] = recipient_to
        moved = recipient_to - before[recipient]
        rsig = signals.get(recipient)
        return {
            **self._base_record(tick, signals),
            "action": "grant",
            "donor": None,
            "recipient": recipient,
            "chips": int(moved),
            "alloc_before": before,
            "alloc_after": after,
            "free_before": self.free,
            "free_after": self.free - moved,
            "pending_after": self.pending,
            "reason": "free pool staffs compute-bound "
            + recipient
            + (
                f" (stall {rsig.data_stall_frac:.0%})"
                if rsig is not None and rsig.data_stall_frac is not None
                else ""
            ),
        }

    def _donate_decision(
        self, tick: int, signals: Dict[str, RunSignals],
        donor: str, donor_to: int, for_run: str,
    ) -> dict:
        before = dict(self.alloc)
        after = dict(before)
        after[donor] = int(donor_to)
        freed = before[donor] - after[donor]
        dsig = signals.get(donor)
        fsig = signals.get(for_run)
        return {
            **self._base_record(tick, signals),
            "action": "donate",
            "donor": donor,
            "recipient": None,
            "for_run": for_run,
            "chips": int(freed),
            "alloc_before": before,
            "alloc_after": after,
            "free_before": self.free,
            "free_after": self.free,
            "pending_after": self.pending + freed,
            "reason": (
                f"{donor} "
                + (
                    f"{dsig.data_stall_frac:.0%} "
                    if dsig is not None and dsig.data_stall_frac is not None
                    else ""
                )
                + f"data-stalled donates {freed} chip(s) toward "
                f"compute-bound {for_run}"
                + (
                    f" (stall {fsig.data_stall_frac:.0%})"
                    if fsig is not None and fsig.data_stall_frac is not None
                    else ""
                )
                + " — grantable next tick"
            ),
        }

    # -- actuation + audit ---------------------------------------------------

    def apply(self, decision: dict, tick: int) -> None:
        """Commit one decision: allocations, cooldown/hysteresis state,
        pending/free pools, gauges, allocation files."""
        after = decision["alloc_after"]
        for run in self.specs:
            if after[run] != self.alloc[run]:
                self._last_move_tick[run] = tick
                self._last_move_dir[run] = (
                    "donated" if after[run] < self.alloc[run] else "received"
                )
                self.alloc[run] = after[run]
                if self.fleet_dir:
                    capacity_lib.write_allocation(
                        self.allocation_path(run), after[run]
                    )
        self.free = decision["free_after"]
        if decision.get("action") == "donate":
            self.pending = decision["pending_after"]
            self._pending_since = tick
        self.decisions += 1
        counters_lib.inc("fleet.decisions")
        self._publish_gauges()

    def step(
        self,
        tick: int,
        signals: Dict[str, RunSignals],
        ts: Optional[float] = None,
    ) -> List[dict]:
        """mature pending → decide → apply → audit. ``ts`` annotates the
        record for humans and cross-run joins; the POLICY never reads it
        (reproducibility contract)."""
        self.mature_pending(tick)
        decisions = self.decide(tick, signals)
        for d in decisions:
            self.apply(d, tick)
            if self.fleet_dir:
                rec = dict(d)
                rec["ts"] = time.time() if ts is None else ts
                with open(self.history_path(), "a") as f:
                    f.write(json.dumps(rec) + "\n")
        return decisions

    def _publish_gauges(self) -> None:
        for run, a in self.alloc.items():
            counters_lib.set_gauge(f"fleet.allocation.{run}", a)
        counters_lib.set_gauge("fleet.free_chips", self.free)
        counters_lib.set_gauge("fleet.pending_chips", self.pending)

    def exposition(self) -> str:
        """The scheduler's own OpenMetrics exposition:
        ``tpu_dist_fleet_allocation{run="..."}`` samples plus the
        decision counter — scrape-able next to the runs it arbitrates."""
        return export_lib.render(
            {
                "fleet.decisions": self.decisions,
                "fleet.free_chips": self.free,
                "fleet.pending_chips": self.pending,
            },
            labeled={"fleet_allocation": dict(self.alloc)},
            label_keys={"fleet_allocation": "run"},
        )

    def write_exposition(self, path: str) -> None:
        tmp = f"{path}.tmp.{os.getpid()}"
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(tmp, "w") as f:
            f.write(self.exposition())
        os.replace(tmp, path)
