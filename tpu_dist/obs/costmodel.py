"""Device cost & efficiency accounting — flops, bytes, peak HBM, MFU.

ONE home for reading XLA's cost/memory analysis out of a train step and
turning it into efficiency numbers, shared by the trainer (first-dispatch
gauges + the per-epoch MFU in every summary/history record) and
``bench.py`` (which previously kept its own private copy of the chip-peak
table and the cost-analysis plumbing).

Everything here is host-side: ``Lowered.cost_analysis()`` runs XLA's
``HloCostAnalysis`` over the traced module without compiling or touching a
device, ``Compiled.cost_analysis()``/``memory_analysis()`` read numbers
XLA already produced while compiling, and :func:`device_memory_stats`
reads the allocator's live counters. Arming any of it adds zero device
work — the TD106/TD107 jaxpr gates pin that.

MFU methodology (``docs/observability.md``): the numerator is the total
FLOPs XLA counts in ONE compiled step (the real fwd+bwd+update HLO, not an
analytic guess — inner ``scan`` bodies are counted once, so callers pass
``loop_trips`` for grad-accumulation/fused-epoch loops); the denominator
is wall seconds per step × the aggregate peak dense-matmul FLOP/s of the
visible chips (:data:`CHIP_PEAK_FLOPS`, public spec-sheet bf16 numbers).
Unknown chip kinds — including CPU emulation — yield ``mfu=None`` rather
than a made-up figure.
"""

from __future__ import annotations

from typing import Optional

from tpu_dist.obs import counters as counters_lib

# Peak dense matmul FLOP/s per chip (bf16), the MFU denominator. Public
# spec-sheet numbers; longest-prefix matched against ``device_kind``.
CHIP_PEAK_FLOPS = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}

_GIB = 1024 ** 3
# HBM bytes per jax device (public spec-sheet numbers; a "device" is one
# core on v2/v3 and one megacore chip from v4 on — exactly what
# ``jax.devices()`` enumerates, so the budget divides the way shardings
# do). Longest-prefix matched like the FLOP table; the pre-flight memory
# lint (``obs/memory.py::preflight_check``) prices configs against this.
CHIP_HBM_BYTES = {
    "TPU v2": 8 * _GIB,
    "TPU v3": 16 * _GIB,
    "TPU v4": 32 * _GIB,
    "TPU v5 lite": 16 * _GIB,
    "TPU v5e": 16 * _GIB,
    "TPU v5p": 95 * _GIB,
    "TPU v5": 95 * _GIB,
    "TPU v6 lite": 32 * _GIB,
    "TPU v6e": 32 * _GIB,
}


def _chip_lookup(table: dict, kind: Optional[str]):
    if kind is None:
        import jax  # noqa: PLC0415

        kind = jax.devices()[0].device_kind
    for name, val in sorted(table.items(), key=lambda kv: -len(kv[0])):
        if kind.startswith(name):
            return val
    return None


def chip_peak_flops(kind: Optional[str] = None) -> Optional[float]:
    """Peak FLOP/s for ``kind`` (default: the first visible device's
    ``device_kind``); None for unknown kinds — CPU emulation above all."""
    return _chip_lookup(CHIP_PEAK_FLOPS, kind)


def chip_hbm_bytes(kind: Optional[str] = None) -> Optional[int]:
    """Per-device HBM budget for ``kind`` (default: the first visible
    device); None for unknown kinds — the memory lint then declines to
    guess rather than refuse a run on a made-up budget."""
    return _chip_lookup(CHIP_HBM_BYTES, kind)


def _cost_dict(obj) -> dict:
    """``cost_analysis()`` of a Lowered/Compiled, normalized to one dict
    (older jax returns a one-element list per device)."""
    ca = obj.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca) if ca else {}


def step_cost(obj, loop_trips: int = 1) -> dict:
    """``{"flops_per_step", "bytes_per_step"}`` of one compiled/lowered
    step (either may be None when XLA reports nothing useful).

    ``loop_trips``: XLA counts a while/scan body ONCE, so steps built
    around an inner loop (grad-accumulation scan, fused-epoch step scan)
    pass the trip count; the body dominates the program, so multiplying
    the whole count errs by at most the loop-external ops (a few %,
    overestimating trips-1 copies of them)."""
    try:
        ca = _cost_dict(obj)
    except Exception:
        return {"flops_per_step": None, "bytes_per_step": None}

    def scaled(key):
        v = ca.get(key)
        return float(v) * loop_trips if v and v > 0 else None

    return {
        "flops_per_step": scaled("flops"),
        "bytes_per_step": scaled("bytes accessed"),
    }


def mfu(
    flops_per_step: Optional[float],
    step_seconds: float,
    n_devices: int,
    peak: Optional[float] = None,
) -> Optional[float]:
    """Model FLOPs utilization: achieved FLOP/s over aggregate chip peak.
    ``peak`` overrides the per-chip table lookup (tests, exotic parts)."""
    if peak is None:
        peak = chip_peak_flops()
    if flops_per_step is None or peak is None or step_seconds <= 0:
        return None
    return round(flops_per_step / step_seconds / (peak * n_devices), 4)


def memory_analysis_bytes(compiled) -> Optional[dict]:
    """Peak-HBM estimate from a Compiled's ``memory_analysis()``: XLA's
    own accounting of argument/output/temp/code bytes for the executable
    (``peak_bytes`` = their sum less buffer aliasing). None when the
    backend does not implement it."""
    try:
        ma = compiled.memory_analysis()
        arg = int(getattr(ma, "argument_size_in_bytes", 0) or 0)
        out = int(getattr(ma, "output_size_in_bytes", 0) or 0)
        tmp = int(getattr(ma, "temp_size_in_bytes", 0) or 0)
        code = int(getattr(ma, "generated_code_size_in_bytes", 0) or 0)
        alias = int(getattr(ma, "alias_size_in_bytes", 0) or 0)
    except Exception:
        return None
    return {
        "argument_bytes": arg,
        "output_bytes": out,
        "temp_bytes": tmp,
        "generated_code_bytes": code,
        "peak_bytes": max(arg + out + tmp + code - alias, 0),
    }


def device_memory_stats() -> Optional[dict]:
    """Live allocator counters across ALL local devices — the TRUE
    peak-HBM gauges on TPU/GPU, updated by the runtime itself. None
    where no backend device keeps stats (CPU).

    The scalar keys (``bytes_in_use`` / ``peak_bytes_in_use`` /
    ``bytes_limit``) report the WORST chip — the max across local
    devices, because HBM is a per-chip constraint and the hottest chip
    is the one that OOMs. (The previous device-0-only read hid exactly
    the failure this exists to surface: an unbalanced sharding whose hot
    chip was any device but 0.) Multi-device processes additionally get
    ``*_min`` floors, ``bytes_in_use_skew`` (max - min, the imbalance
    gauge), and ``mem_devices_reporting``."""
    try:
        import jax  # noqa: PLC0415

        devices = jax.local_devices()
    except Exception:
        return None
    per: list = []
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats:
            per.append(stats)
    if not per:
        return None
    out = {}
    for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
        vals = [
            int(s[key]) for s in per
            if isinstance(s.get(key), (int, float))
        ]
        if not vals:
            continue
        out[key] = max(vals)
        if len(vals) > 1:
            out[f"{key}_min"] = min(vals)
    if "bytes_in_use_min" in out:
        out["bytes_in_use_skew"] = (
            out["bytes_in_use"] - out["bytes_in_use_min"]
        )
    if out:
        out["mem_devices_reporting"] = len(per)
    return out or None


# One AOT lower+compile per (step, abstract signature), shared by
# ANALYSIS consumers that re-read the same executable's artifacts — the
# shardlint HLO text + cost analysis + memory waterfall
# (tpu_dist/analysis/shardlint.py) all read ONE compile instead of
# paying three. Long-lived training processes must NOT route their
# one-shot probes through here (see memory_analysis_jitted): values hold
# a strong ref to the jitted wrapper so the id() key cannot be recycled,
# which pins the executable until eviction. Bounded by
# :data:`_COMPILE_CACHE_MAX` (FIFO — the cache exists to dedupe within
# one analysis pass, not to live forever).
_COMPILE_CACHE: dict = {}
_COMPILE_CACHE_MAX = 32


def _aot_key(jitted, args) -> tuple:
    import jax  # noqa: PLC0415

    leaves = jax.tree_util.tree_leaves(args)
    sig = tuple(
        # arrays key on (shape, dtype); non-array leaves (python scalars,
        # static args) key on their VALUE — two lowers of the same jitted
        # fn with different static args must not collide on one executable
        (tuple(x.shape), str(x.dtype))
        if hasattr(x, "shape") and hasattr(x, "dtype")
        else ("val", repr(x)[:128])
        for x in leaves
    )
    return (id(jitted), sig)


def lower_and_compile(jitted, *args):
    """``(Lowered, Compiled)`` of a jitted step at ``args``' abstract
    signature, cached — the lower-and-cache seam every static analysis
    shares. Raises whatever lowering/compiling raises (callers that want
    degradation wrap it; the analyzers want the real error)."""
    key = _aot_key(jitted, args)
    hit = _COMPILE_CACHE.get(key)
    if hit is not None:
        return hit[1], hit[2]
    lowered = jitted.lower(*args)
    compiled = lowered.compile()
    if len(_COMPILE_CACHE) >= _COMPILE_CACHE_MAX:
        _COMPILE_CACHE.pop(next(iter(_COMPILE_CACHE)))
    _COMPILE_CACHE[key] = (jitted, lowered, compiled)
    return lowered, compiled


def clear_compile_cache() -> None:
    _COMPILE_CACHE.clear()


def memory_analysis_jitted(jitted, *args) -> Optional[dict]:
    """:func:`memory_analysis_bytes` of a ``jax.jit``-wrapped step: an
    AOT ``lower(...).compile()`` pass purely to read XLA's memory
    waterfall — jax exposes no handle to the executable the first
    dispatch already cached, so this pays ONE extra host-side backend
    compile (the ``jax.monitoring`` listener books it into
    ``compile.seconds``, where the goodput ledger attributes it). The
    trainer therefore captures it once per run and only when telemetry
    consumers exist — and deliberately does NOT go through the
    :func:`lower_and_compile` cache: pinning a second full executable of
    the TRAIN step for the rest of a run would raise steady-state host
    memory on exactly the memory-constrained runs this instruments (the
    cache is for analysis passes that re-read one executable's
    artifacts, e.g. shardlint). None when lowering/compiling is
    unavailable — callers degrade to the ledger without the waterfall,
    never to an error."""
    try:
        compiled = jitted.lower(*args).compile()
    except Exception:
        return None
    return memory_analysis_bytes(compiled)


def analyze_jitted(jitted, *args, loop_trips: int = 1) -> Optional[dict]:
    """Cost-analyze a ``jax.jit``-wrapped step WITHOUT compiling it twice:
    ``jitted.lower(*args)`` re-traces abstractly (host-only, no device
    dispatch, no XLA compile) and ``Lowered.cost_analysis()`` runs the HLO
    cost model over the traced module. Returns :func:`step_cost`'s dict,
    or None when lowering/analysis is unavailable — callers degrade to
    "no MFU", never to an error."""
    try:
        lowered = jitted.lower(*args)
    except Exception:
        return None
    return step_cost(lowered, loop_trips)


class CompileWatcher:
    """Turn a jitted step's executable-cache growth into compile telemetry.

    jax keeps one compiled executable per (shape, dtype, static-arg)
    signature; the cache growing past the expected warmup mid-run means
    the step RETRACED — usually shape/dtype drift in the input pipeline,
    and on a pod each retrace is a full XLA compile stall on every host.
    Callers invoke :meth:`observe` once per step (one C++ attribute
    read — no device work, no sync): every growth increments
    ``compile.events``; growth after the first dispatch (or after
    :meth:`baseline`) additionally increments ``compile.retraces``,
    prints the rank-0 warning, and returns True. The warning and the
    counters live HERE — the trainer, the serving engine, and any future
    caller get the same surfacing for free; ``obs summarize`` reports
    the per-epoch retrace delta.

    Multi-signature callers (the serving engine compiles one executable
    per batch bucket at warmup) call :meth:`baseline` after their warmup
    pass: the compiles so far are absorbed as expected (counted into
    ``compile.events``, never as retraces) and EVERY later growth is a
    retrace.

    Degrades to a permanent no-op when the callable has no
    ``_cache_size`` (a non-jit wrapper, or a jax that dropped the
    private API) — observation must never break the step loop."""

    def __init__(self, jitted, name: str = "train step", warn: bool = True):
        self._size_fn = getattr(jitted, "_cache_size", None)
        self._seen = 0
        self._baselined = False
        self.name = name
        self.warn = warn

    def _size(self) -> Optional[int]:
        if self._size_fn is None:
            return None
        try:
            return int(self._size_fn())
        except Exception:
            self._size_fn = None
            return None

    def baseline(self) -> int:
        """Absorb every compile so far as expected warmup: counts them
        into ``compile.events`` but never as retraces, and marks the
        watcher so ANY later growth is one. Returns the absorbed count."""
        size = self._size()
        if size is None:
            return 0
        grew = max(size - self._seen, 0)
        if grew:
            counters_lib.inc("compile.events", grew)
        self._seen = max(size, self._seen)
        self._baselined = True
        return grew

    def observe(self, context: str = "") -> bool:
        """Record any new compiles; True when one was a mid-run retrace.
        On a retrace the watcher itself prints the rank-0 warning
        (``warn=False`` to suppress); ``context`` names the position
        (``"epoch 3 step 12"``) in it."""
        size = self._size()
        if size is None or size <= self._seen:
            return False
        grew = size - self._seen
        first = self._seen == 0 and not self._baselined
        self._seen = size
        counters_lib.inc("compile.events", grew)
        retraces = grew - 1 if first else grew
        if retraces > 0:
            counters_lib.inc("compile.retraces", retraces)
            if self.warn:
                from tpu_dist.metrics.logging import rank0_print  # noqa: PLC0415

                rank0_print(
                    f"WARNING: {self.name} RECOMPILED"
                    + (f" at {context}" if context else "")
                    + " — input shape/dtype drift? (compile.retraces="
                    f"{counters_lib.get('compile.retraces'):g})"
                )
            return True
        return False


_LISTENER_INSTALLED = False


def install_compile_listener() -> bool:
    """Accumulate XLA's own backend-compile wall time into the
    ``compile.seconds`` counter via ``jax.monitoring`` (fires for every
    compile in the process — train step, eval step, fused paths alike).
    Idempotent; jax offers no unregistration, so ONE process-lifetime
    listener feeds the process-global counter registry. Returns whether
    the listener is (now) installed; False on a jax without the API."""
    global _LISTENER_INSTALLED
    if _LISTENER_INSTALLED:
        return True
    try:
        from jax import monitoring  # noqa: PLC0415

        def _on_event(event: str, duration: float, **kw) -> None:
            # backend_compile ONLY: one jit compile also fires nested
            # jaxpr_trace / jaxpr_to_mlir_module duration events whose
            # wall times overlap it — summing every "compile"-ish event
            # would over-count real elapsed time severalfold
            if "backend_compile" in event:
                counters_lib.inc("compile.seconds", round(float(duration), 3))

        monitoring.register_event_duration_secs_listener(_on_event)
    except Exception:
        return False
    _LISTENER_INSTALLED = True
    return True


def _sig(v: float, digits: int = 4) -> float:
    """Round to significant digits — calibration rates span 1e3..1e15."""
    return float(f"{v:.{digits}g}")


def calibration(
    cost: Optional[dict],
    analysis: Optional[dict],
    *,
    steps: Optional[int] = None,
    n_devices: int = 1,
    peak: Optional[float] = None,
) -> dict:
    """Calibrate the static cost model against a measured capture: divide
    the xprof attribution's measured category seconds into the predicted
    per-step FLOPs/bytes (``step_cost``) and return achieved-rate /
    drift gauges, keyed by their registry names:

    * ``cost.calibration_flops_per_s`` — AGGREGATE achieved FLOP/s over
      the capture's COMPUTE seconds only (matmul/conv + fusion), i.e.
      what the hardware sustains when it is actually computing — the
      number an ``--auto_shard`` planner should price compute with,
      where MFU (whole-step wall over chip peak) prices nothing.
    * ``cost.calibration_compute_frac`` — that rate over the AGGREGATE
      chip peak (``peak × n_devices``, :func:`mfu`'s denominator —
      ``flops_per_step`` is treated as the step's total across devices,
      the SAME convention ``mfu`` applies to the same ``step_cost``
      dict, so the two published efficiency numbers always agree);
      omitted on unknown chips (CPU emulation).
    * ``cost.calibration_bytes_per_s`` — aggregate achieved bytes/s:
      the cost model's per-step byte count over measured busy seconds.
    * ``cost.calibration_collective_frac`` / ``_overlap_frac`` — the
      capture's collective share of device busy time and comm/compute
      overlap fraction, the two schedule-quality drift signals.
    * ``cost.calibration_steps`` — steps the capture covered (the
      normalization the rates used).

    ``analysis`` is the compact xprof record; ``steps`` the step count
    the capture covered (rate gauges need it; the fraction gauges work
    without). Returns {} when nothing is computable — callers publish
    whatever comes back and never fail a capture on a thin one."""
    out: dict = {}
    if not analysis:
        return out
    cf = analysis.get("collective_frac")
    if isinstance(cf, (int, float)):
        out["cost.calibration_collective_frac"] = cf
    ov = analysis.get("overlap_frac")
    if ov is None and isinstance(analysis.get("overlap"), dict):
        ov = analysis["overlap"].get("overlap_frac")
    if isinstance(ov, (int, float)):
        out["cost.calibration_overlap_frac"] = ov
    busy = analysis.get("device_busy_s")
    cats = analysis.get("categories") or {}
    if not steps or not isinstance(busy, (int, float)) or busy <= 0:
        return out
    out["cost.calibration_steps"] = int(steps)
    n_devices = max(int(n_devices), 1)
    cost = cost or {}
    # measured seconds are SUMMED across the capture's devices, so the
    # concurrent-wall compute time per step is compute_s/steps/n_devices;
    # flops_per_step is the step's aggregate count (the mfu convention),
    # so the ratio is the aggregate achieved rate
    compute_s = (
        float(cats.get("matmul_conv", 0.0)) + float(cats.get("fusion_other", 0.0))
    )
    flops = cost.get("flops_per_step")
    if isinstance(flops, (int, float)) and flops > 0 and compute_s > 0:
        achieved = flops / (compute_s / steps / n_devices)
        out["cost.calibration_flops_per_s"] = _sig(achieved)
        if peak is None:
            peak = chip_peak_flops()
        if peak:
            out["cost.calibration_compute_frac"] = round(
                achieved / (peak * n_devices), 4
            )
    byts = cost.get("bytes_per_step")
    if isinstance(byts, (int, float)) and byts > 0:
        out["cost.calibration_bytes_per_s"] = _sig(
            byts / (busy / steps / n_devices)
        )
    return out


def publish_calibration(gauges: dict) -> None:
    """Stamp :func:`calibration`'s gauges into the telemetry registry —
    every later history record and OpenMetrics exposition carries them
    (``counters.snapshot`` feeds both)."""
    for name, v in gauges.items():
        counters_lib.set_gauge(name, v)


def predicted_step_time(
    cost: Optional[dict],
    *,
    wire_bytes: Optional[int] = None,
    n_devices: int = 1,
    gauges: Optional[dict] = None,
    peak: Optional[float] = None,
) -> dict:
    """Static step-time prediction, corrected by the latest measured
    ``cost.calibration_*`` gauges — the scalar an ``--auto_shard`` planner
    ranks mesh layouts with (ROADMAP item 3; the shard report stamps it
    per config family).

    Model (documented, deliberately simple): compute time is the step's
    FLOPs over the ACHIEVED FLOP/s from the last calibrated capture
    (falling back to the spec-sheet chip peak when no capture exists —
    ``source`` says which); memory time is XLA's bytes-accessed over the
    achieved bytes/s; communication time is the HLO wire bytes over the
    same achieved bytes/s (a proxy until an ICI-rate gauge exists —
    recorded as such). Compute and memory overlap perfectly inside a
    fused step (``max``); communication hides behind compute by the
    measured ``overlap_frac`` (0 when never measured). Returns ``{}``
    when there is nothing to price (no flops and no bytes)."""
    gauges = gauges if gauges is not None else counters_lib.snapshot()
    cost = cost or {}
    flops = cost.get("flops_per_step")
    byts = cost.get("bytes_per_step")
    flops_rate = gauges.get("cost.calibration_flops_per_s")
    bytes_rate = gauges.get("cost.calibration_bytes_per_s")
    overlap = gauges.get("cost.calibration_overlap_frac") or 0.0
    source = "calibrated"
    if not isinstance(flops_rate, (int, float)) or flops_rate <= 0:
        if peak is None:
            peak = chip_peak_flops()
        flops_rate = peak * n_devices if peak else None
        source = "spec_peak"
    out: dict = {}
    t_compute = (
        flops / flops_rate
        if isinstance(flops, (int, float)) and flops > 0 and flops_rate
        else None
    )
    t_mem = (
        byts / bytes_rate
        if isinstance(byts, (int, float)) and byts > 0
        and isinstance(bytes_rate, (int, float)) and bytes_rate > 0
        else None
    )
    t_comm = (
        wire_bytes / bytes_rate
        if isinstance(wire_bytes, (int, float)) and wire_bytes > 0
        and isinstance(bytes_rate, (int, float)) and bytes_rate > 0
        else None
    )
    if t_compute is None and t_mem is None:
        return out
    busy = max(t for t in (t_compute, t_mem) if t is not None)
    exposed_comm = (t_comm or 0.0) * (1.0 - min(max(overlap, 0.0), 1.0))
    out = {
        "predicted_step_s": _sig(busy + exposed_comm),
        "compute_s": _sig(t_compute) if t_compute is not None else None,
        "memory_s": _sig(t_mem) if t_mem is not None else None,
        "comm_s": _sig(t_comm) if t_comm is not None else None,
        "overlap_frac_applied": round(float(overlap), 4),
        "rate_source": source,
    }
    return out


def planner_error_frac(
    predicted_s: Optional[float], achieved_s: Optional[float],
) -> Optional[float]:
    """The TD119 drift scalar: ``|predicted - achieved| / achieved`` of
    one step's wall time — how far the ``--auto_shard`` planner's priced
    step time sits from what the hardware measured. Lands in history as
    ``planner_error_frac`` (``plan`` records, schema v12) and gates
    through ``obs compare`` METRIC_DIRECTIONS (lower is better), so a
    cost-model regression fails CI like a throughput one. None — a
    skipped gate row, never a fake zero — when either side is missing
    or non-positive."""
    if (
        not isinstance(predicted_s, (int, float)) or predicted_s <= 0
        or not isinstance(achieved_s, (int, float)) or achieved_s <= 0
    ):
        return None
    return round(abs(float(predicted_s) - float(achieved_s)) / float(achieved_s), 4)


def publish(cost: Optional[dict]) -> None:
    """Stamp a step-cost dict into the telemetry gauges
    (``device.flops_per_step`` / ``device.bytes_per_step``) so every
    history record carries the numbers next to the throughput they
    explain."""
    if not cost:
        return
    for key, gauge in (
        ("flops_per_step", "device.flops_per_step"),
        ("bytes_per_step", "device.bytes_per_step"),
    ):
        v = cost.get(key)
        if v is not None:
            counters_lib.set_gauge(gauge, v)
