"""``obs tail`` — follow a live run from another terminal
(``docs/observability.md``).

``summarize`` reads a finished log; this module watches a GROWING one:
it tails the ``--log_file`` JSONL (and optionally the heartbeat file)
the way ``tail -f`` would, but schema-aware — a rolling per-epoch table
of throughput / step p50 / stall / MFU / goodput fraction, the latest
alert / anomaly / straggler / profile lines, and a heartbeat liveness
row with its staleness age.  Torn tails are first-class: the writer is
line-buffered but a poll can still land mid-line, so the follower only
consumes COMPLETE lines and leaves the partial tail for the next poll
(the same tolerance ``summarize`` has for a killed writer, applied
incrementally).

Pure stdlib + file reads — runs on any machine the log is visible
from; it never touches jax or the training process.  The CLI lives in
``obs/__main__.py`` (``python -m tpu_dist.obs tail run.jsonl``);
``make monitor LOG=run.jsonl`` wraps it.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional, TextIO

from tpu_dist.obs import heartbeat as heartbeat_lib
from tpu_dist.obs.goodput import fleet_move_phrase, resume_direction

#: epochs shown in the rolling table (older rows scroll off — the full
#: history is what ``summarize`` is for)
DEFAULT_ROWS = 10
#: event lines (alert/anomaly/straggler/profile) kept on screen
DEFAULT_EVENTS = 8
#: heartbeat age above which the liveness row flags STALE (matches the
#: built-in ``heartbeat_stale`` alert rule's threshold)
STALE_AFTER_S = 60.0


class LogFollower:
    """Incremental JSONL reader: each :meth:`poll` returns the records
    appended since the last one, consuming only complete lines.  A
    shrunken file (rotation / a fresh run reusing the path) resets the
    cursor to the start rather than silently reading garbage."""

    def __init__(self, path: str):
        self.path = path
        self._pos = 0
        self.bad_lines = 0

    def poll(self) -> List[dict]:
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return []
        if size < self._pos:  # truncated/rotated: start over
            self._pos = 0
        if size == self._pos:
            return []
        with open(self.path, "rb") as f:
            f.seek(self._pos)
            chunk = f.read(size - self._pos)
        # consume complete lines only; a torn tail stays on disk for the
        # next poll (the writer will finish it — or never, in which case
        # it is exactly the torn trailing line summarize tolerates)
        end = chunk.rfind(b"\n")
        if end < 0:
            return []
        self._pos += end + 1
        out: List[dict] = []
        for line in chunk[: end + 1].splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                self.bad_lines += 1
                continue
            if isinstance(rec, dict):
                out.append(rec)
        return out


class TailState:
    """Folds a stream of history records into the rolling dashboard
    state; :meth:`render` draws it.  Deterministic given the records and
    the clock inputs — the golden test drives it directly."""

    def __init__(self, rows: int = DEFAULT_ROWS, events: int = DEFAULT_EVENTS):
        self.rows = rows
        self.max_events = events
        self.run_id: Optional[str] = None
        self.schema: Optional[int] = None
        self.n_records = 0
        self.epochs: Dict[int, dict] = {}
        self.events: List[str] = []
        self.alerts_fired = 0
        self.finished = False  # run-end goodput totals record seen
        self.crashed = False   # postmortem crash bundle seen (schema v9)
        self.bundle: Optional[str] = None  # the bundle's path, when known

    def add(self, records: List[dict]) -> None:
        for rec in records:
            self.n_records += 1
            rid = rec.get("run_id")
            if rid is not None and rid != self.run_id:
                if self.run_id is not None:
                    self._event(f"— resumed: new segment {rid} —")
                    self.finished = False
                self.run_id = rid
            sv = rec.get("schema_version")
            self.schema = sv if isinstance(sv, int) else self.schema
            kind = rec.get("kind")
            ep = rec.get("epoch")
            if kind == "train_epoch" and isinstance(ep, int):
                row = self.epochs.setdefault(ep, {})
                row.update({
                    k: rec.get(k)
                    for k in ("images_per_sec", "step_time_p50",
                              "data_stall_frac", "mfu", "loss")
                })
            elif kind == "eval" and isinstance(ep, int):
                self.epochs.setdefault(ep, {})["val_top1"] = rec.get("top1")
            elif kind == "goodput" and rec.get("final"):
                self.finished = True
                gp = rec.get("goodput_frac")
                self._event(
                    f"run ended: goodput {gp:.1%} of "
                    f"{rec.get('elapsed_s', 0):.1f}s wall-clock"
                    if isinstance(gp, (int, float))
                    else "run ended"
                )
            elif kind == "goodput" and isinstance(ep, int) and not rec.get("tail"):
                w = rec.get("window_s")
                p = rec.get("productive_s")
                if isinstance(w, (int, float)) and w > 0 and isinstance(p, (int, float)):
                    self.epochs.setdefault(ep, {})["goodput_frac"] = p / w
            elif kind == "alert":
                self.alerts_fired += 1
                self._event(
                    f"ALERT {rec.get('rule')}: {rec.get('metric')} "
                    f"{rec.get('value')} {rec.get('op')} {rec.get('threshold')} "
                    f"(sustained {rec.get('sustained')} window(s), epoch {ep}"
                    + (f" step {rec.get('step')}" if rec.get("step") is not None else "")
                    + ")"
                )
            elif kind == "anomaly":
                self._event(
                    f"anomaly {rec.get('anomaly')} at epoch {ep} step "
                    f"{rec.get('step')}: value {rec.get('value')}"
                )
            elif kind == "straggler":
                self._event(
                    f"straggler: process {rec.get('worst_rank')} at "
                    f"{rec.get('skew')}x median (epoch {ep})"
                )
            elif kind == "profile":
                evt = rec.get("event")
                if evt == "start":
                    self._event(
                        f"profile capture started ({rec.get('reason')}) "
                        f"at epoch {ep}"
                    )
                elif evt == "stop":
                    self._event(
                        f"profile captured {rec.get('steps')} step(s) "
                        f"({rec.get('reason')})"
                    )
            elif kind == "profile_analysis":
                # one-line device-time attribution per closed capture
                # (obs/xprof.py auto-analysis, schema v6)
                if rec.get("error"):
                    self._event(
                        f"capture analysis FAILED ({rec.get('reason')}): "
                        f"{rec.get('error')}"
                    )
                else:
                    busy = rec.get("device_busy_s")
                    cf = rec.get("collective_frac")
                    ov = rec.get("overlap_frac")
                    fmt = lambda v, s: (  # noqa: E731
                        format(v, s) if isinstance(v, (int, float)) else "-"
                    )
                    self._event(
                        f"capture analysis ({rec.get('reason')}): device "
                        f"busy {fmt(busy, '.3f')}s, collectives "
                        f"{fmt(cf, '.0%')}, overlap {fmt(ov, '.0%')}, "
                        f"infeed stall "
                        f"{fmt(rec.get('infeed_stall_s'), '.3f')}s"
                    )
            elif kind == "auto_recover":
                self._event(
                    f"auto-recover at epoch {ep} (lr_scale "
                    f"{rec.get('lr_scale')})"
                )
            elif kind == "resume":
                # segment boundary with world-size context (schema v7):
                # the host set is not fixed — say which world this
                # segment runs at and which DIRECTION the resize went
                # (GROWN = scale-up/fleet receipt, RESHARDED = shrink;
                # one shared classifier: goodput.resume_direction)
                direction = resume_direction(rec)
                self._event(
                    f"resumed epoch {ep} on {rec.get('world')} "
                    f"process(es), dp={rec.get('dp')}"
                    + (
                        f" — {'GROWN' if direction == 'grown' else 'RESHARDED'}"
                        f" from dp={rec.get('prev_dp')} (elastic)"
                        if direction else ""
                    )
                    + (
                        f", restart #{rec.get('restarts')}"
                        if rec.get("restarts") else ""
                    )
                    + (
                        # causal tracing (schema v15): a resume actuating
                        # a fleet decision names it — live view shows the
                        # same chain the pod report renders offline
                        f" [decision #{rec.get('decision_id')}]"
                        if rec.get("decision_id") is not None else ""
                    )
                )
            elif kind == "fleet":
                # a scheduler decision (schema v8): chips moved between
                # runs sharing this pod — say who paid and who gained
                self._event(
                    "fleet: " + fleet_move_phrase(rec)
                    + (
                        f": {rec.get('reason')}"
                        if rec.get("reason") else ""
                    )
                )
            elif kind == "tenancy":
                # a per-tick chip-accounting snapshot (schema v14):
                # silent while the books balance — every tick would be
                # noise — but a conservation violation is front-page
                alloc = rec.get("alloc") or {}
                accounted = (
                    sum(int(a) for a in alloc.values())
                    + int(rec.get("free") or 0)
                    + int(rec.get("pending") or 0)
                )
                total = int(rec.get("total_chips") or 0)
                if accounted != total:
                    self._event(
                        f"tenancy VIOLATION: tick {rec.get('tick')} "
                        f"accounts {accounted} of {total} chip(s) "
                        f"(alloc {alloc}, free {rec.get('free')}, "
                        f"pending {rec.get('pending')})"
                    )
            elif kind == "serve":
                # a serving SLO window (schema v10) or a mid-serve event
                # (retrace) — one line each, the serving analogue of the
                # epoch row
                if rec.get("event") == "retrace":
                    self._event(
                        f"serve RETRACE: bucket-{rec.get('bucket')} batch "
                        f"({rec.get('n_real')} real) recompiled mid-serve"
                    )
                else:
                    fmt = lambda v, s: (  # noqa: E731
                        format(v, s) if isinstance(v, (int, float)) else "-"
                    )
                    self._event(
                        f"serve: {fmt(rec.get('requests_per_s'), '.1f')} "
                        f"req/s, p50 {fmt(rec.get('latency_p50_ms'), '.2f')} "
                        f"ms, p99 {fmt(rec.get('latency_p99_ms'), '.2f')} ms, "
                        f"avail {fmt(rec.get('availability'), '.3f')}, "
                        f"occupancy {fmt(rec.get('batch_occupancy'), '.2f')}, "
                        f"queue≤{fmt(rec.get('queue_depth_max'), 'g')}"
                        + (
                            f" — {rec['retraces']:g} RETRACE(S)"
                            if rec.get("retraces") else ""
                        )
                    )
            elif kind == "memory":
                # an HBM-ledger snapshot (schema v11) or an OOM event —
                # through the shared obs/memory.py formatters, so tail,
                # summarize, and the pod report render identically
                from tpu_dist.obs import memory as memory_lib

                if rec.get("event") == "oom":
                    oom = rec.get("oom")
                    self._event(
                        memory_lib.oom_summary_line(oom)
                        if isinstance(oom, dict)
                        else "OOM: RESOURCE_EXHAUSTED (unparsed)"
                    )
                else:
                    self._event(memory_lib.summary_line(rec))
            elif kind == "postmortem":
                # a crash bundle landed (schema v9, the watchdog's
                # auto-invoke): the run did NOT end cleanly — render the
                # per-rank fatal/wedge findings and stop following (no
                # goodput-final record is coming from a dead writer)
                self.crashed = True
                self.finished = True
                self.bundle = rec.get("bundle") or self.bundle
                self._event(
                    f"POSTMORTEM: crash bundle over {rec.get('n_ranks')} "
                    "rank(s)"
                    + (f" — {rec['bundle']}" if rec.get("bundle") else "")
                )
                from tpu_dist.obs.postmortem import sorted_ranks

                verdicts = rec.get("verdicts") or {}
                stuck = rec.get("stuck_frames") or {}
                fatal = rec.get("fatal") or {}
                oom = rec.get("oom") or {}
                for rank in sorted_ranks(verdicts):
                    if rank in oom:
                        self._event(f"rank {rank}: {oom[rank]}")
                    elif rank in fatal:
                        self._event(
                            f"fatal on rank {rank}: {fatal[rank]}"
                        )
                    elif rank in stuck:
                        self._event(
                            f"rank {rank} wedged — stuck in {stuck[rank]}"
                        )
                    elif verdicts[rank] not in ("clean", "preempted"):
                        self._event(
                            f"rank {rank}: {verdicts[rank]}"
                        )

    def _event(self, line: str) -> None:
        self.events.append(line)
        del self.events[: -self.max_events]

    def render(
        self,
        heartbeat: Optional[dict] = None,
        *,
        now_wall: Optional[float] = None,
        bad_lines: int = 0,
    ) -> str:
        """One full dashboard frame as text.  ``heartbeat`` is the parsed
        per-rank file (or None); ``now_wall`` the wall clock used for its
        age — injectable so the golden test is deterministic."""
        lines = [
            f"run {self.run_id or '<no run_id>'} — {self.n_records} "
            f"record(s), {len(self.epochs)} epoch(s)"
            + (f", {self.alerts_fired} alert(s) fired" if self.alerts_fired else "")
            + (f", {bad_lines} torn line(s) skipped" if bad_lines else "")
        ]
        lines.append(
            f"{'epoch':>5} {'img/s':>9} {'p50_ms':>8} {'stall%':>7} "
            f"{'mfu':>6} {'goodput':>8} {'loss':>9} {'val_top1':>9}"
        )

        def fmt(v, spec, width):
            return (format(v, spec) if isinstance(v, (int, float)) else "-").rjust(width)

        for ep in sorted(self.epochs)[-self.rows:]:
            r = self.epochs[ep]
            p50 = r.get("step_time_p50")
            stall = r.get("data_stall_frac")
            lines.append(
                f"{ep:>5} {fmt(r.get('images_per_sec'), '.1f', 9)} "
                f"{fmt(p50 * 1e3 if isinstance(p50, (int, float)) else None, '.1f', 8)} "
                f"{fmt(stall * 100 if isinstance(stall, (int, float)) else None, '.1f', 7)} "
                f"{fmt(r.get('mfu'), '.3f', 6)} "
                f"{fmt(r.get('goodput_frac'), '.1%', 8)} "
                f"{fmt(r.get('loss'), '.4f', 9)} "
                f"{fmt(r.get('val_top1'), '.2f', 9)}"
            )
        for ev in self.events:
            lines.append(f"  {ev}")
        if heartbeat is not None:
            now = time.time() if now_wall is None else now_wall
            ts = heartbeat.get("ts")
            age = now - float(ts) if isinstance(ts, (int, float)) else None
            stale = isinstance(age, float) and age > STALE_AFTER_S
            lines.append(
                f"heartbeat: #{heartbeat.get('counter')} epoch "
                f"{heartbeat.get('epoch')} step {heartbeat.get('step')} "
                f"phase {heartbeat.get('phase')!r}"
                + (f", age {age:.1f}s" if age is not None else "")
                + (" — STALE" if stale else "")
            )
        elif self.finished and not self.crashed:
            lines.append("heartbeat: swept (clean exit)")
        if self.finished:
            # the exit line says HOW it ended: a clean run swept its
            # heartbeat and wrote its goodput totals; a crashed one left
            # a postmortem bundle behind instead
            lines.append(
                "run: CRASHED — postmortem bundle left behind"
                + (f" ({self.bundle})" if self.bundle else "")
                if self.crashed else "run: clean exit"
            )
        return "\n".join(lines)


def run_tail(
    log: str,
    *,
    heartbeat: Optional[str] = None,
    interval: float = 2.0,
    once: bool = False,
    rows: int = DEFAULT_ROWS,
    stream: Optional[TextIO] = None,
) -> int:
    """The ``obs tail`` loop: poll the log, redraw on growth, exit 0 when
    the run-end totals record lands (or on Ctrl-C).  ``once`` renders the
    current state and returns immediately (scripting / the golden CLI
    test).  Returns the process exit code."""
    import sys

    out = stream if stream is not None else sys.stdout
    follower = LogFollower(log)
    state = TailState(rows=rows)
    tty = hasattr(out, "isatty") and out.isatty()

    def frame() -> None:
        hb = heartbeat_lib.read(heartbeat) if heartbeat else None
        text = state.render(hb, bad_lines=follower.bad_lines)
        if tty:
            out.write("\x1b[2J\x1b[H")  # clear + home: a live dashboard
        out.write(text + "\n")
        out.flush()

    state.add(follower.poll())
    if once:
        frame()
        return 0 if state.n_records else 1
    frame()
    try:
        while not state.finished:
            time.sleep(interval)
            fresh = follower.poll()
            if fresh:
                state.add(fresh)
            frame()  # heartbeat age moves even when the log does not
    except KeyboardInterrupt:
        return 0  # the operator detached from the dashboard — clean exit
    return 0
