"""The pod telemetry hub — ONE federated ``/metrics`` for N runs
(``docs/observability.md`` "Pod telemetry hub").

Every run already publishes its own OpenMetrics exposition (per-rank
textfiles and/or a rank-0 HTTP endpoint — ``obs/export.py``) and its
heartbeat; the fleet scheduler used to walk those N textfiles itself.
That fan-out does not scale past a handful of runs and gives a pod
operator no single place to point a scraper. This module is the
controller-side fix:

* :func:`sample_run` — the ONE scrape primitive: one run's exposition
  (textfile preferred, HTTP fallback) plus its heartbeat verdict, as a
  plain dict. ``fleet/scheduler.py::read_signals`` consumes THIS — the
  scheduler no longer opens metrics files itself (the regression pin in
  ``tests/test_hub.py`` keeps it that way).
* :class:`TelemetryHub` — the pull-aggregator: scrape every registered
  :class:`RunSource`, tolerate the real-world failure modes **with
  counted drops** (a torn mid-rename exposition serves the last good
  parse and counts ``torn``; a stale/absent heartbeat marks the run
  **dead with its last-seen age** — never silently dropped; a run that
  has not published yet counts ``absent``), and render ONE federated
  exposition: every sample re-labeled ``{run="<name>"}``, hub health
  gauges, and the pod rollups (total/free/pending chips from the
  capacity ledger's own exposition, per-class goodput, worst-run stall,
  breach count, the last arbitration ``decision_id``).
* ``python -m tpu_dist.obs hub`` — the CLI: one-shot or looped
  aggregation to a textfile and/or an HTTP ``/metrics`` endpoint
  (the same snapshot-under-lock discipline as ``MetricsExporter``).

Cost contract: the hub is pure host-side string/file work — jaxpr rule
**TD123** proves the traced train AND serve steps are byte-identical
with the hub armed and scraped mid-audit (vacuity-guarded: a hub that
aggregated zero runs is itself a violation).

Stdlib-only on purpose: the hub runs on the pod's controller VM where
no jax exists.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from tpu_dist.obs import export as export_lib
from tpu_dist.obs import heartbeat as heartbeat_lib

#: Heartbeat older than this reads as a dead/wedged run — ONE home for
#: the threshold (``fleet/scheduler.py`` and ``obs tail`` import it).
STALE_AFTER_S = 60.0

#: The run classes the rollups aggregate by (mirrors RunSpec.kind).
RUN_KINDS = ("train", "serve")


@dataclasses.dataclass(frozen=True)
class RunSource:
    """One run the hub scrapes: its exposition (textfile and/or rank-0
    HTTP port — textfile preferred, HTTP the fallback when the file is
    unreadable), its heartbeat file, and its class (``kind`` buckets the
    per-class goodput rollup)."""

    run: str
    metrics_file: Optional[str] = None
    port: Optional[int] = None
    heartbeat_file: Optional[str] = None
    kind: str = "train"

    def __post_init__(self):
        if not self.run:
            raise ValueError("a RunSource needs a run name")
        if self.metrics_file is None and self.port is None:
            raise ValueError(f"{self.run}: need a metrics_file or a port")
        if self.kind not in RUN_KINDS:
            raise ValueError(f"{self.run}: kind {self.kind!r} not in {RUN_KINDS}")


def sample_run(
    run: str,
    *,
    metrics_file: Optional[str] = None,
    port: Optional[int] = None,
    heartbeat_file: Optional[str] = None,
    now: Optional[float] = None,
    stale_after_s: float = STALE_AFTER_S,
) -> dict:
    """Scrape ONE run: its latest exposition plus its heartbeat verdict.

    Pure file/socket reads, never raises — an absent or unreadable
    exposition degrades to empty ``values``. Returns::

        {"run", "values": {name_or_name{labels}: float},
         "scraped": bool, "source": "textfile"|"http"|None,
         "alive": True|False|None, "heartbeat_age_s": float|None}

    ``alive`` is None when no heartbeat source was configured (liveness
    unknowable), False on an absent/stale/garbage beat — the same
    fail-closed verdicts ``read_signals`` always gave.
    """
    values: Dict[str, float] = {}
    source: Optional[str] = None
    if metrics_file is not None:
        got = export_lib.scrape(textfile=metrics_file)
        if got is not None:
            values, source = got, "textfile"
    if source is None and port is not None:
        got = export_lib.scrape(port=port)
        if got is not None:
            values, source = got, "http"
    age: Optional[float] = None
    alive: Optional[bool] = None
    if heartbeat_file is not None:
        rec = heartbeat_lib.read(heartbeat_file)
        if rec is None:
            alive = False  # absent beat on a run we were told beats
        else:
            ts = rec.get("ts")
            if isinstance(ts, (int, float)) and not isinstance(ts, bool):
                age = (time.time() if now is None else now) - float(ts)
                alive = age <= stale_after_s
            else:
                # a beat that parsed but carries no usable timestamp is
                # as dead as a stale one — fail closed, never None
                alive = False
    return {
        "run": run,
        "values": values,
        "scraped": source is not None,
        "source": source,
        "alive": alive,
        "heartbeat_age_s": round(age, 1) if age is not None else None,
    }


def _gauge(values: Dict[str, float], raw: str) -> Optional[float]:
    return values.get(export_lib.metric_name(raw))


class TelemetryHub:
    """Pull-aggregate N :class:`RunSource` expositions into one.

    ``fleet_exposition`` (optional) is the path the fleet scheduler's
    :meth:`~tpu_dist.fleet.scheduler.FleetScheduler.write_exposition`
    publishes — the capacity ledger the chip rollups come from
    (total/free/pending chips, decision/preemption counters, the last
    ``decision_id``). Without it the chip rollups are simply absent.

    Drop accounting is cumulative across :meth:`collect` calls (the
    hub's own ``hub.drops_total{reason=...}`` family) AND per-snapshot
    (``snapshot["drops"]``): a torn exposition, a dead run, an absent
    one — every degraded scrape is counted, never silent.
    """

    def __init__(
        self,
        sources: List[RunSource],
        *,
        fleet_exposition: Optional[str] = None,
        stale_after_s: float = STALE_AFTER_S,
    ):
        if not sources:
            raise ValueError("a hub needs at least one RunSource")
        names = [s.run for s in sources]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate run names: {names}")
        self.sources = list(sources)
        self.fleet_exposition = fleet_exposition
        self.stale_after_s = stale_after_s
        self.scrapes = 0
        self.drops_total = {"torn": 0, "dead": 0, "absent": 0}
        # last-good cache per run (the heartbeat _LAST_GOOD discipline):
        # a torn mid-rename exposition must serve the previous parse,
        # not a hole — and be COUNTED doing it
        self._last_good: Dict[str, Dict[str, float]] = {}

    # -- scraping ------------------------------------------------------------

    def _scrape_one(self, src: RunSource, now: Optional[float]) -> dict:
        """One run's hub view: :func:`sample_run` hardened with torn
        detection (an exposition that does not end in ``# EOF`` was
        caught mid-write by a non-atomic publisher — serve the last
        good parse) and the dead/absent classification."""
        torn = False
        if src.metrics_file is not None:
            try:
                with open(src.metrics_file) as f:
                    text = f.read()
            except OSError:
                text = None
            if text is not None and not text.rstrip().endswith("# EOF"):
                torn = True
        sample = sample_run(
            src.run,
            metrics_file=src.metrics_file,
            port=src.port,
            heartbeat_file=src.heartbeat_file,
            now=now,
            stale_after_s=self.stale_after_s,
        )
        sample["kind"] = src.kind
        if torn and sample["source"] == "textfile":
            # mid-rename tear: whatever parsed is suspect — fall back
            sample["values"] = dict(self._last_good.get(src.run, {}))
            sample["torn"] = True
        else:
            sample["torn"] = False
            if sample["values"]:
                self._last_good[src.run] = dict(sample["values"])
        sample["dead"] = sample["alive"] is False
        sample["absent"] = not sample["values"] and not sample["torn"]
        return sample

    def collect(self, now: Optional[float] = None) -> dict:
        """One aggregation pass: every source scraped, drops counted,
        rollups computed. Returns the snapshot dict :meth:`federated`
        renders (``runs`` keeps EVERY registered run — a dead run is
        marked dead with its last-seen age, never removed)."""
        self.scrapes += 1
        runs: Dict[str, dict] = {}
        drops = {"torn": 0, "dead": 0, "absent": 0}
        for src in self.sources:
            sample = self._scrape_one(src, now)
            runs[src.run] = sample
            for reason in drops:
                if sample.get(reason):
                    drops[reason] += 1
                    self.drops_total[reason] += 1
        fleet: Dict[str, float] = {}
        if self.fleet_exposition:
            fleet = export_lib.scrape(textfile=self.fleet_exposition) or {}
        return {
            "runs": runs,
            "drops": drops,
            "drops_total": dict(self.drops_total),
            "fleet": fleet,
            "rollup": self._rollup(runs, fleet),
            "scrapes": self.scrapes,
        }

    def _rollup(self, runs: Dict[str, dict], fleet: Dict[str, float]) -> dict:
        """The pod-level gauges: chips from the capacity ledger's own
        exposition, per-class goodput means, the worst stall, and how
        many serve runs currently fire an ``slo_*`` alert."""
        out: dict = {
            "runs_aggregated": sum(1 for s in runs.values() if s["values"]),
            "runs_dead": sum(1 for s in runs.values() if s["dead"]),
        }
        for raw, name in (
            ("fleet.total_chips", "total_chips"),
            ("fleet.free_chips", "free_chips"),
            ("fleet.pending_chips", "pending_chips"),
            ("fleet.decisions", "decisions"),
            ("fleet.preemptions", "preemptions"),
            ("fleet.last_decision_id", "last_decision_id"),
        ):
            v = _gauge(fleet, raw)
            if v is not None:
                out[name] = v
        goodput: Dict[str, List[float]] = {}
        worst_stall: Optional[Tuple[float, str]] = None
        breaches = 0
        for name, s in runs.items():
            vals = s["values"]
            g = _gauge(vals, "goodput.goodput_frac")
            if g is not None:
                goodput.setdefault(s["kind"], []).append(g)
            stall = _gauge(vals, "train.data_stall_frac")
            if stall is not None and (
                worst_stall is None or stall > worst_stall[0]
            ):
                worst_stall = (stall, name)
            if any(
                a.startswith("slo_")
                for a in export_lib.active_labels(vals)
            ):
                breaches += 1
        out["goodput_by_kind"] = {
            kind: round(sum(v) / len(v), 4) for kind, v in sorted(goodput.items())
        }
        if worst_stall is not None:
            out["worst_stall_frac"] = worst_stall[0]
            out["worst_stall_run"] = worst_stall[1]
        out["breach_count"] = breaches
        return out

    # -- federation ----------------------------------------------------------

    @staticmethod
    def _labeled(name: str, run: str) -> str:
        """Inject the ``run`` label into a scraped sample name —
        ``tpu_dist_x`` → ``tpu_dist_x{run="r"}``, and an already-labeled
        ``tpu_dist_alert_active{rule="y"}`` keeps its label:
        ``tpu_dist_alert_active{rule="y",run="r"}``."""
        safe = run.replace("\\", "\\\\").replace('"', '\\"')
        if name.endswith("}") and "{" in name:
            return f'{name[:-1]},run="{safe}"}}'
        return f'{name}{{run="{safe}"}}'

    def federated(self, snapshot: Optional[dict] = None) -> str:
        """Render one snapshot as THE pod exposition: every run's
        samples re-labeled ``{run=...}``, the hub's own health/drop
        gauges, and the ``pod.*`` rollups. Ends with ``# EOF``."""
        snap = snapshot if snapshot is not None else self.collect()
        lines: List[str] = []
        rollup = snap["rollup"]
        pod_values = {
            "pod.runs_aggregated": rollup.get("runs_aggregated", 0),
            "pod.runs_dead": rollup.get("runs_dead", 0),
            "pod.breach_count": rollup.get("breach_count", 0),
            "hub.scrapes_total": snap.get("scrapes", self.scrapes),
        }
        for name in (
            "total_chips", "free_chips", "pending_chips",
            "decisions", "preemptions", "last_decision_id",
            "worst_stall_frac",
        ):
            if rollup.get(name) is not None:
                pod_values[f"pod.{name}"] = rollup[name]
        for raw in sorted(pod_values):
            name = export_lib.metric_name(raw)
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {export_lib._fmt_value(pod_values[raw])}")
        drops_name = export_lib.metric_name("hub.drops_total")
        lines.append(f"# TYPE {drops_name} gauge")
        for reason in sorted(snap["drops_total"]):
            lines.append(
                f'{drops_name}{{reason="{reason}"}} '
                f'{export_lib._fmt_value(snap["drops_total"][reason])}'
            )
        gpk = rollup.get("goodput_by_kind") or {}
        if gpk:
            name = export_lib.metric_name("pod.goodput_frac")
            lines.append(f"# TYPE {name} gauge")
            for kind in sorted(gpk):
                lines.append(
                    f'{name}{{kind="{kind}"}} {export_lib._fmt_value(gpk[kind])}'
                )
        up_name = export_lib.metric_name("hub.run_up")
        age_name = export_lib.metric_name("hub.run_heartbeat_age_s")
        lines.append(f"# TYPE {up_name} gauge")
        for run in sorted(snap["runs"]):
            s = snap["runs"][run]
            up = 0 if s["dead"] else 1
            lines.append(f'{self._labeled(up_name, run)} {up}')
        if any(
            s["heartbeat_age_s"] is not None for s in snap["runs"].values()
        ):
            lines.append(f"# TYPE {age_name} gauge")
        for run in sorted(snap["runs"]):
            s = snap["runs"][run]
            if s["heartbeat_age_s"] is not None:
                lines.append(
                    f'{self._labeled(age_name, run)} '
                    f'{export_lib._fmt_value(s["heartbeat_age_s"])}'
                )
        for run in sorted(snap["runs"]):
            for name in sorted(snap["runs"][run]["values"]):
                v = snap["runs"][run]["values"][name]
                lines.append(
                    f"{self._labeled(name, run)} {export_lib._fmt_value(v)}"
                )
        lines.append("# EOF")
        return "\n".join(lines) + "\n"

    def write(self, path: str, snapshot: Optional[dict] = None) -> None:
        """Atomically publish the federated exposition (tmp +
        ``os.replace`` — a scraper never sees a torn hub)."""
        text = self.federated(snapshot)
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        # tpu-dist: ignore[TD002,TD007] — the hub is a single controller
        # process by construction; there is exactly one writer per path
        with open(tmp, "w") as f:
            f.write(text)
        os.replace(tmp, path)


class HubServer:
    """The hub's HTTP half: serve the LAST PUBLISHED federated snapshot
    at ``GET /metrics`` (bytes under a lock — the handler thread never
    scrapes, so a slow source can never stall a scrape of the hub
    itself; the ``MetricsExporter`` snapshot discipline)."""

    def __init__(self, port: int):
        from http.server import ThreadingHTTPServer

        self._lock = threading.Lock()
        self._body = b"# EOF\n"
        srv = ThreadingHTTPServer(("", port), export_lib._Handler)
        srv.daemon_threads = True
        srv.exporter_body = self._snapshot  # type: ignore[attr-defined]
        self._server = srv
        self.port = srv.server_address[1]  # resolves port=0 requests
        self._thread = threading.Thread(
            target=srv.serve_forever, name="telemetry-hub", daemon=True
        )
        self._thread.start()

    def _snapshot(self) -> bytes:
        with self._lock:
            return self._body

    def publish(self, text: str) -> None:
        with self._lock:
            self._body = text.encode()

    def close(self) -> None:
        if self._server is not None:
            srv, self._server = self._server, None
            srv.shutdown()
            srv.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "HubServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def parse_source(spec: str) -> RunSource:
    """CLI grammar for one ``--run``: ``name=metrics_path`` with optional
    ``,hb=<heartbeat>`` / ``,port=<p>`` / ``,kind=<train|serve>`` parts,
    e.g. ``svc=/pod/svc/metrics.prom,hb=/pod/svc/hb.json,kind=serve``.
    A bare ``name=port:9100`` registers an HTTP-only source."""
    if "=" not in spec:
        raise ValueError(f"--run {spec!r}: want name=metrics_path[,...]")
    run, rest = spec.split("=", 1)
    parts = rest.split(",")
    kw: dict = {"run": run}
    head = parts[0]
    if head.startswith("port:"):
        kw["port"] = int(head[len("port:"):])
    elif head:
        kw["metrics_file"] = head
    for part in parts[1:]:
        if "=" not in part:
            raise ValueError(f"--run {spec!r}: bad part {part!r}")
        k, v = part.split("=", 1)
        if k == "hb":
            kw["heartbeat_file"] = v
        elif k == "port":
            kw["port"] = int(v)
        elif k == "kind":
            kw["kind"] = v
        else:
            raise ValueError(f"--run {spec!r}: unknown key {k!r}")
    return RunSource(**kw)
