"""Flight recorder — a SIGKILL-surviving per-rank event ring, plus the
on-demand stack-capture arming (``docs/observability.md`` "Crash
forensics").

Everything else in ``tpu_dist/obs`` assumes the process gets to say
goodbye: the history JSONL is line-buffered, the heartbeat is swept on
clean exit, the goodput ledger writes its totals in a ``finally``. A
rank that is SIGKILLed (watchdog escalation, OOM killer, a preemption
that skipped the grace period) leaves none of that — the dominant
debugging cost at pod scale (PAPERS.md "Exploring the limits of
Concurrency in ML Training on Google TPUs"). This module is the part of
the telemetry stack designed around NOT getting to say goodbye:

* :class:`FlightRecorder` — a **fixed-slot ring file**: ``n_slots``
  slots of ``slot_size`` bytes after a fixed-size header, each record
  written with ONE ``os.pwrite`` into slot ``seq % n_slots``. The file
  never grows, there is no buffer to flush, and the most a hard kill
  can tear is the single slot being written — which the decoder detects
  by its per-slot CRC32 and reports as torn instead of raising. The
  last ``n_slots`` events of the run are always readable from the
  corpse of the file.
* **Fatal slots** — :meth:`FlightRecorder.install_excepthooks` wraps
  ``sys.excepthook`` and ``threading.excepthook`` so an UNHANDLED
  exception (main thread or a worker like the loader producer) stamps a
  final ``fatal`` record — exception type, message, innermost frames —
  before the interpreter dies. The previous hooks still run.
* **Stack capture** — :func:`arm_faulthandler` points the stdlib
  ``faulthandler`` at a per-rank crash file (hard faults: SIGSEGV/
  SIGABRT tracebacks land there instead of a lost stderr) and registers
  ``SIGUSR1`` as an on-demand **all-threads dump**: the launcher
  watchdog signals a live-but-frozen rank and reads back WHERE it is
  stuck (loader ``get``, collective dispatch, checkpoint write) before
  escalating to SIGTERM/SIGKILL. :func:`parse_stack_dump` turns the
  faulthandler text back into structured frames.

Cost contract (audited by TD113): everything here is host-side file I/O
on the step boundary — arming the recorder, the excepthooks, and the
faulthandler changes NOTHING inside the traced train step.

This module must not import jax: the decoder runs on any machine the
ring can be copied to, and the excepthook path runs while the
interpreter is dying.
"""

from __future__ import annotations

import io
import json
import os
import re
import sys
import threading
import time
import traceback
import zlib
from typing import Dict, List, Optional, Tuple

from tpu_dist.obs import counters as counters_lib

#: Ring geometry defaults: 256 slots x 512 B = a 128 KiB file holding the
#: last ~256 events — at one step record per step plus sparse events,
#: minutes of context on a fast loop, hours on a slow one.
DEFAULT_SLOT_SIZE = 512
DEFAULT_N_SLOTS = 256
#: Fixed-size header region before slot 0. The header is itself
#: CRC-free JSON — a run killed before the first slot still identifies
#: itself; a torn header degrades the decode to the geometry defaults.
HEADER_SIZE = 256
_MAGIC = b"TDFR1 "

#: Canonical per-rank artifact names inside a ``--crash_dir`` (rank 0
#: bare, rank k ``.h<k>`` — ``heartbeat.per_rank_path``, the ONE naming
#: scheme every forensic reader shares).
RING_NAME = "flight.ring"
STACKS_NAME = "stacks.txt"


def _encode_slot(payload: str, slot_size: int) -> Optional[bytes]:
    """``crc32-hex SP payload NL`` padded with NULs; None when it cannot
    fit (caller shrinks the payload and retries)."""
    body = payload.encode("utf-8", "replace")
    raw = b"%08x %s\n" % (zlib.crc32(body), body)
    if len(raw) > slot_size:
        return None
    return raw + b"\0" * (slot_size - len(raw))


class FlightRecorder:
    """One writer per ring file (the trainer derives one path per rank).

    Every mutation is a single ``pwrite`` into a preallocated region —
    no append, no flush discipline, no growth. ``record`` NEVER raises:
    forensics must not be able to kill the training step it documents
    (failed writes are counted, ``flight.write_errors``)."""

    def __init__(
        self,
        path: str,
        *,
        slot_size: int = DEFAULT_SLOT_SIZE,
        n_slots: int = DEFAULT_N_SLOTS,
        run_id: Optional[str] = None,
        rank: Optional[int] = None,
    ):
        if slot_size < 64 or n_slots < 2:
            raise ValueError(
                f"ring needs slot_size >= 64 and n_slots >= 2, got "
                f"{slot_size}/{n_slots}"
            )
        self.path = path
        self.slot_size = slot_size
        self.n_slots = n_slots
        self.run_id = run_id
        self.rank = rank
        self.seq = 0
        self._lock = threading.Lock()
        self._last_counters: Dict[str, object] = {}
        self._prev_sys_hook = None
        self._prev_thread_hook = None
        self._sys_wrapper = None
        self._thread_wrapper = None
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        # tpu-dist: ignore[TD002] — deliberately per-process I/O: each
        # rank owns its own derived ring path (per_rank_path), so this
        # never needs the rank-0 guard the lint looks for
        self._fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
        # start from an EMPTY ring: truncate away any previous process's
        # slots first (an elastic relaunch reuses the same --crash_dir
        # path, and stale slots carry valid CRCs — decode would sort the
        # old run's records into this run's tail and a hard kill could
        # read as the previous round's clean 'preempt'), then extend to
        # the full geometry (sparse zeros decode as empty slots)
        os.ftruncate(self._fd, 0)
        os.ftruncate(self._fd, HEADER_SIZE + slot_size * n_slots)
        header = {
            "slot_size": slot_size, "n_slots": n_slots,
            "pid": os.getpid(), "ts": round(time.time(), 3),
        }
        if run_id:
            header["run_id"] = str(run_id)[:64]
        if rank is not None:
            header["rank"] = rank
        raw = _MAGIC + json.dumps(header).encode() + b"\n"
        os.pwrite(self._fd, raw[:HEADER_SIZE].ljust(HEADER_SIZE, b"\0"), 0)

    # -- writing ----------------------------------------------------------

    def record(self, kind: str, **fields) -> bool:
        """Stamp one slot. Oversized records shed their bulk (the
        ``counters`` delta first, then long strings) rather than fail —
        a slot ALWAYS lands unless the filesystem itself refuses."""
        rec = {"seq": None, "t": round(time.time(), 3), "kind": kind}
        rec.update(fields)
        try:
            with self._lock:
                self.seq += 1
                rec["seq"] = self.seq
                raw = self._fit(rec)
                off = HEADER_SIZE + ((self.seq - 1) % self.n_slots) * self.slot_size
                os.pwrite(self._fd, raw, off)
            return True
        except (OSError, ValueError, TypeError):
            # ValueError: fd closed under us (interpreter teardown);
            # TypeError: unserializable field — shed everything but the kind
            counters_lib.inc("flight.write_errors")
            return False

    def _fit(self, rec: dict) -> bytes:
        raw = _encode_slot(json.dumps(rec, default=str), self.slot_size)
        if raw is not None:
            return raw
        slim = dict(rec)
        slim.pop("counters", None)  # the usual bulk: shed it first
        raw = _encode_slot(json.dumps(slim, default=str), self.slot_size)
        if raw is not None:
            return raw
        for k, v in list(slim.items()):  # long strings/lists next
            if isinstance(v, str) and len(v) > 80:
                slim[k] = v[:80]
            elif isinstance(v, (list, tuple)) and len(v) > 4:
                slim[k] = list(v)[:4]
        slim["overflow"] = True
        raw = _encode_slot(json.dumps(slim, default=str), self.slot_size)
        if raw is not None:
            return raw
        return _encode_slot(
            json.dumps({"seq": rec["seq"], "t": rec["t"],
                        "kind": rec["kind"], "overflow": True}),
            self.slot_size,
        )

    def step(self, epoch: int, step: int) -> bool:
        """The step-boundary record: position plus the counter registry's
        numeric delta since the previous step record — the last slots of
        a killed run read as 'step 412: +1 ckpt write, +3 batches, then
        nothing', which is the whole forensic point."""
        cur = counters_lib.snapshot()
        delta = counters_lib.delta(self._last_counters, cur)
        self._last_counters = cur
        return self.record(
            "step", epoch=epoch, step=step,
            **({"counters": delta} if delta else {}),
        )

    def span_open(self, name: str, args: Optional[dict] = None) -> None:
        """``spans.set_open_listener`` target: every host span OPEN (ckpt
        write, restore ladder, loader produce, eval) stamps a slot — the
        ring then shows which host operation was in flight at death."""
        self.record("span", name=name)

    def fatal(self, exc_type, exc, tb, thread: Optional[str] = None) -> bool:
        """The last-words slot: type, message, innermost frames."""
        frames: List[str] = []
        try:
            for fr in traceback.extract_tb(tb)[-6:]:
                frames.append(f"{fr.filename}:{fr.lineno}:{fr.name}")
        except Exception:  # tpu-dist: ignore[TD006] — a broken traceback
            pass  # object must not lose the fatal record itself
        return self.record(
            "fatal",
            error=getattr(exc_type, "__name__", str(exc_type)),
            message=str(exc)[:200],
            frames=frames,
            **({"thread": thread} if thread else {}),
        )

    def close(self, kind: str = "exit", **fields) -> None:
        """Stamp a terminal record and release the fd. A ring whose last
        record is ``exit``/``preempt`` ended on its own terms; one that
        just stops is the signature of a hard kill."""
        self.record(kind, **fields)
        with self._lock:
            fd, self._fd = self._fd, -1
            if fd >= 0:
                try:
                    os.close(fd)
                except OSError:  # tpu-dist: ignore[TD006] — already closed
                    pass

    # -- excepthooks ------------------------------------------------------

    def install_excepthooks(self) -> None:
        """Wrap ``sys.excepthook`` + ``threading.excepthook`` so an
        unhandled exception anywhere stamps a ``fatal`` slot, then chain
        to the previous hooks (their output still appears)."""
        if self._prev_sys_hook is not None:
            return  # already installed

        prev_sys = sys.excepthook
        prev_thread = threading.excepthook

        def _sys_hook(exc_type, exc, tb):
            self.fatal(exc_type, exc, tb)
            prev_sys(exc_type, exc, tb)

        def _thread_hook(hook_args):
            self.fatal(
                hook_args.exc_type, hook_args.exc_value,
                hook_args.exc_traceback,
                thread=getattr(hook_args.thread, "name", None),
            )
            prev_thread(hook_args)

        self._prev_sys_hook = prev_sys
        self._prev_thread_hook = prev_thread
        self._sys_wrapper = _sys_hook
        self._thread_wrapper = _thread_hook
        sys.excepthook = _sys_hook
        threading.excepthook = _thread_hook

    def uninstall_excepthooks(self) -> None:
        """Restore the chained hooks. Idempotent, and unwinds our layer
        ONLY when it is still on top: if someone wrapped the hooks after
        us, blindly restoring ``_prev_*`` would drop their layer for the
        rest of the process — instead we leave the chain intact (the
        newer wrapper keeps chaining through ours, which goes quiet once
        the ring closes)."""
        if self._prev_sys_hook is not None:
            if sys.excepthook is self._sys_wrapper:
                sys.excepthook = self._prev_sys_hook
            self._prev_sys_hook = None
        if self._prev_thread_hook is not None:
            if threading.excepthook is self._thread_wrapper:
                threading.excepthook = self._prev_thread_hook
            self._prev_thread_hook = None


# --------------------------------------------------------------------------
# Decoding — torn-tail tolerant by construction.
# --------------------------------------------------------------------------


def decode(path: str) -> dict:
    """Read a ring back: ``{"header", "records", "torn_slots",
    "empty_slots", "last"}`` with records ordered by ``seq``.

    NEVER raises on content: a torn header falls back to the geometry
    defaults, a torn slot (the SIGKILL-mid-pwrite case) is counted in
    ``torn_slots``, an all-zero slot counts as empty. Only a genuinely
    unreadable file raises ``OSError`` — the caller decides whether
    absence means 'never armed' or 'lost'."""
    with open(path, "rb") as f:
        data = f.read()
    header = None
    torn_header = False
    head = data[:HEADER_SIZE]
    if head.startswith(_MAGIC):
        try:
            header = json.loads(head[len(_MAGIC):].split(b"\0", 1)[0])
        except (json.JSONDecodeError, UnicodeDecodeError):
            torn_header = True
    else:
        torn_header = bool(head.strip(b"\0"))
    slot_size = (
        int(header["slot_size"])
        if isinstance(header, dict)
        and isinstance(header.get("slot_size"), int)
        and header["slot_size"] >= 64
        else DEFAULT_SLOT_SIZE
    )
    records: List[dict] = []
    torn = 0
    empty = 0
    body = data[HEADER_SIZE:]
    for i in range(0, len(body), slot_size):
        chunk = body[i:i + slot_size].rstrip(b"\0")
        if not chunk:
            empty += 1
            continue
        if chunk.endswith(b"\n"):
            chunk = chunk[:-1]
        m = re.match(rb"([0-9a-f]{8}) (.*)$", chunk, re.DOTALL)
        if not m:
            torn += 1
            continue
        crc, payload = m.group(1), m.group(2)
        if zlib.crc32(payload) != int(crc, 16):
            torn += 1
            continue
        try:
            rec = json.loads(payload)
        except (json.JSONDecodeError, UnicodeDecodeError):
            torn += 1
            continue
        if isinstance(rec, dict) and isinstance(rec.get("seq"), int):
            records.append(rec)
        else:
            torn += 1
    records.sort(key=lambda r: r["seq"])
    return {
        "header": header,
        "torn_header": torn_header,
        "records": records,
        "torn_slots": torn,
        "empty_slots": empty,
        "last": records[-1] if records else None,
    }


def last_step(decoded: dict) -> Optional[dict]:
    """The newest ``step`` record of a decoded ring — where the run was
    when it stopped writing."""
    for rec in reversed(decoded.get("records") or []):
        if rec.get("kind") == "step":
            return rec
    return None


def fatal_records(decoded: dict) -> List[dict]:
    return [
        r for r in (decoded.get("records") or []) if r.get("kind") == "fatal"
    ]


# --------------------------------------------------------------------------
# faulthandler arming — hard-fault tracebacks + SIGUSR1 all-threads dump.
# --------------------------------------------------------------------------


class _FaulthandlerHandle:
    """What :func:`arm_faulthandler` returns; :func:`disarm_faulthandler`
    needs the open file plus the prior-state bookkeeping."""

    def __init__(self, path: str, f: io.IOBase, was_enabled: bool,
                 registered: bool):
        self.path = path
        self.file = f
        self.was_enabled = was_enabled
        self.registered = registered


def arm_faulthandler(path: str) -> Optional[_FaulthandlerHandle]:
    """Point ``faulthandler`` at ``path`` (append mode — dumps
    accumulate) for hard faults AND register ``SIGUSR1`` as an
    on-demand all-threads dump. Returns a handle for
    :func:`disarm_faulthandler`, or None when the platform refuses
    (no SIGUSR1 on Windows; arming is then skipped, never fatal)."""
    import faulthandler
    import signal

    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    # tpu-dist: ignore[TD002] — per-rank crash file by design (the rank
    # derives its own path); line-unbuffered so a dump survives a kill
    f = open(path, "a", buffering=1)
    was_enabled = faulthandler.is_enabled()
    faulthandler.enable(file=f, all_threads=True)
    registered = False
    try:
        faulthandler.register(
            signal.SIGUSR1, file=f, all_threads=True, chain=False
        )
        registered = True
    # tpu-dist: ignore[TD006] — degraded arming is the contract: no
    # SIGUSR1 on this platform means hard-fault capture alone is armed
    except (AttributeError, ValueError, OSError):
        pass
    return _FaulthandlerHandle(path, f, was_enabled, registered)


def disarm_faulthandler(handle: Optional[_FaulthandlerHandle]) -> None:
    """Undo :func:`arm_faulthandler`: unregister the SIGUSR1 dump, and
    restore faulthandler to its prior disposition (back onto stderr when
    something else — e.g. pytest — had it enabled, off otherwise)."""
    if handle is None:
        return
    import faulthandler
    import signal

    if handle.registered:
        try:
            faulthandler.unregister(signal.SIGUSR1)
        # tpu-dist: ignore[TD006] — already unregistered / no SIGUSR1
        except (AttributeError, ValueError):
            pass
    if handle.was_enabled:
        faulthandler.enable()  # back to stderr, the pre-arm owner
    else:
        faulthandler.disable()
    try:
        handle.file.close()
    except OSError:  # tpu-dist: ignore[TD006] — best-effort teardown
        pass


# --------------------------------------------------------------------------
# Stack-dump parsing — faulthandler text back into frames.
# --------------------------------------------------------------------------

_THREAD_RE = re.compile(
    r"^(Current thread|Thread) (0x[0-9a-fA-F]+)(?: \(([^)]*)\))?"
)
_FRAME_RE = re.compile(r'^  File "([^"]+)", line (\d+) in (.+)$')


def parse_stack_dump(text: str) -> dict:
    """Structure a faulthandler dump file: ``{"threads": [...],
    "current": {...}|None, "n_dumps": k}``.

    The file accumulates (SIGUSR1 appends) so threads are grouped into
    dumps — a new dump starts whenever a ``Current thread``/``Thread``
    header follows a frame or fatal line of a previous block's current
    thread; in practice every dump ends with the current thread, so the
    LAST dump is what the accessors report. Each thread entry:
    ``{"thread", "name", "current", "frames": [[file, line, func],
    ...]}`` with frames most-recent-first (the faulthandler order)."""
    dumps: List[List[dict]] = []
    cur_dump: List[dict] = []
    cur_thread: Optional[dict] = None
    for line in text.splitlines():
        m = _THREAD_RE.match(line)
        if m:
            if cur_dump and any(t["current"] for t in cur_dump):
                # a previous dump already closed with its current thread:
                # this header opens a NEW dump
                dumps.append(cur_dump)
                cur_dump = []
            cur_thread = {
                "thread": m.group(2),
                "name": m.group(3),
                "current": m.group(1) == "Current thread",
                "frames": [],
            }
            cur_dump.append(cur_thread)
            continue
        fm = _FRAME_RE.match(line)
        if fm and cur_thread is not None:
            cur_thread["frames"].append(
                [fm.group(1), int(fm.group(2)), fm.group(3)]
            )
    if cur_dump:
        dumps.append(cur_dump)
    last = dumps[-1] if dumps else []
    # the current thread's position inside a dump is interpreter-order,
    # not guaranteed last — take the LAST current-thread block anywhere
    # (the newest dump's, however the blocks were grouped)
    all_blocks = [t for d in dumps for t in d]
    current = next((t for t in reversed(all_blocks) if t["current"]), None)
    return {"threads": last, "current": current, "n_dumps": len(dumps)}


def stuck_frame(parsed: dict) -> Optional[str]:
    """One human line naming WHERE the dumped process was: the top
    (most recent) frame of the last dump's current thread —
    ``'get (tpu_dist/data/loader.py:118)'``."""
    cur = parsed.get("current")
    if not cur or not cur.get("frames"):
        return None
    fname, lineno, func = cur["frames"][0]
    return f"{func} ({fname}:{lineno})"


def read_stack_dump(path: str, offset: int = 0) -> Optional[dict]:
    """Parse the dump file (from ``offset`` — the watchdog passes the
    pre-signal size so it reads only ITS dump). None when absent/empty."""
    try:
        with open(path, "r", errors="replace") as f:
            f.seek(offset)
            text = f.read()
    except OSError:
        return None
    if not text.strip():
        return None
    return parse_stack_dump(text)
