"""Offline analysis of a run's JSONL history (``--log_file``) — the engine
behind ``python -m tpu_dist.obs summarize`` / ``export-trace``.

Pure host-side file crunching: this module itself never touches jax, so
the report runs anywhere the package imports (a laptop holding a pod
run's log). Input is the :class:`MetricsHistory` JSONL
schema (``docs/observability.md``): one object per line, ``kind`` keyed —
``train_epoch`` (throughput, step-time percentiles, stall fraction, MFU,
a counter-registry snapshot), ``eval``, ``straggler``, ``device_stats``
(the per-step ``--device_metrics`` scalars, aggregated per epoch here),
``anomaly`` (loss-spike / grad-explosion findings), ``alert`` (a
declarative threshold rule fired — ``obs/alerts.py``), ``spans``
(drained Chrome trace events), ``auto_recover``. A torn trailing line (the process
died mid-write) is tolerated and reported, not fatal. The regression-gate
half of the CLI (``compare``) lives in ``obs/compare.py`` and consumes
:func:`summarize`'s report.
"""

from __future__ import annotations

import json
from typing import List, Optional, Tuple

from tpu_dist.obs import counters as counters_lib
from tpu_dist.obs import goodput as goodput_lib
from tpu_dist.obs import memory as memory_lib

#: Newest history schema this reader fully understands
#: (``metrics/history.py``). Records stamped newer still have their KNOWN
#: kinds summarized; their unknown kinds are skipped with a count — the
#: forward-compat contract that lets v3 tooling read v4 logs and vice
#: versa (every schema bump is additive).
SUPPORTED_SCHEMA = 15

#: Record kinds this reader folds into the report. Anything else is
#: counted into ``skipped_kinds`` — never an error, never silent.
KNOWN_KINDS = frozenset((
    "train_epoch", "eval", "straggler", "anomaly", "device_stats",
    "auto_recover", "spans", "goodput", "profile", "alert",
    "profile_analysis", "resume", "fleet", "postmortem", "serve",
    "memory", "plan", "tune", "tenancy",
))


def load_records(path: str) -> Tuple[List[dict], int]:
    """Parse the JSONL; returns ``(records, n_bad_lines)``."""
    records: List[dict] = []
    bad = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                bad += 1  # torn tail from a killed writer — report, keep going
                continue
            if isinstance(rec, dict):
                records.append(rec)
            else:
                bad += 1
    return records, bad


def capture_stamp(path: str) -> dict:
    """The history log's capture identity — the history-side analogue of
    the bench capture fingerprint (PR 7): a content hash of the log
    itself, so two ingests of the same physical log dedupe and a
    re-emitted copy is recognizable as the SAME capture rather than a
    fresh run. Content-based on purpose: re-summarizing the identical
    log on another host must produce the identical fingerprint."""
    import hashlib  # noqa: PLC0415
    import os  # noqa: PLC0415

    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 16), b""):
            h.update(chunk)
    return {
        "fingerprint": h.hexdigest()[:16],
        "source_log": os.path.abspath(path),
    }


def stamp_capture(report: dict, path: str) -> dict:
    """Stamp :func:`capture_stamp` into a summarize report's header
    (``obs summarize --format json`` does this; archive ingest reads
    it back for dedupe). Returns the report for chaining."""
    stamp = capture_stamp(path)
    report["source_log"] = stamp["source_log"]
    report["capture"] = {
        "fingerprint": stamp["fingerprint"],
        "run_id": report.get("run_id"),
    }
    return report


def _tenancy_audit(snapshots: List[dict]) -> dict:
    """The exact chip-second conservation audit over the ``tenancy``
    snapshots (fleet/scheduler.py owns the arithmetic; imported lazily —
    both modules are jax-free but obs must not import fleet at module
    load)."""
    from tpu_dist.fleet.scheduler import audit_chip_seconds

    return audit_chip_seconds(
        [{**s, "kind": "tenancy"} for s in snapshots]
    )


def summarize(records: List[dict], bad_lines: int = 0) -> dict:
    """The per-epoch report: throughput, step-time percentiles, data-stall
    fraction, MFU, counter deltas (vs the previous epoch's snapshot), eval,
    device-stats, anomaly, and straggler results merged in by epoch."""
    epochs: List[dict] = []
    evals = {}
    stragglers = []
    anomalies: List[dict] = []
    alerts: List[dict] = []
    profiles: List[dict] = []
    profile_analyses: List[dict] = []
    goodput_epochs: List[dict] = []
    resumes: List[dict] = []  # segment boundaries (world size, reshard)
    world_sizes: List[int] = []  # distinct dp extents, in order of appearance
    fleet_decisions: List[dict] = []  # scheduler chip moves (schema v8)
    postmortems: List[dict] = []  # crash bundles (schema v9)
    serve_windows: List[dict] = []  # serving SLO windows (schema v10)
    serve_events: List[dict] = []   # serving events (mid-serve retraces)
    memory_records: List[dict] = []  # HBM-ledger snapshots (schema v11)
    oom_events: List[dict] = []      # parsed RESOURCE_EXHAUSTED crashes
    plan_records: List[dict] = []    # --auto_shard plan / TD119 drift (v12)
    tune_records: List[dict] = []    # --tune_report knob application (v13)
    tenancy_snapshots: List[dict] = []  # per-tick chip accounting (v14)
    dstats: dict = {}  # epoch -> per-epoch device_stats aggregate
    recoveries = 0
    prev_counters: Optional[dict] = None
    prev_run_id = None
    final_counters: Optional[dict] = None
    run_id = None
    schema = None
    skipped_kinds: dict = {}       # unknown kind -> count (never silent)
    newer_schema_records = 0       # records stamped past SUPPORTED_SCHEMA
    for rec in records:
        kind = rec.get("kind")
        run_id = rec.get("run_id", run_id)
        sv = rec.get("schema_version")
        if isinstance(sv, int) and sv > SUPPORTED_SCHEMA:
            newer_schema_records += 1
        schema = sv if sv is not None else schema
        if kind not in KNOWN_KINDS:
            # a future schema's kind (or a foreign line): skip WITH a
            # count — the v3 kind set must not be a parsing assumption
            skipped_kinds[str(kind)] = skipped_kinds.get(str(kind), 0) + 1
            continue
        rid = rec.get("run_id")
        if rid is not None and rid != prev_run_id:
            # resume boundary (same --log_file, fresh process + counter
            # registry): deltas across it would go negative/meaningless
            prev_counters = None
            prev_run_id = rid
        if kind == "eval":
            evals[rec.get("epoch")] = rec
        elif kind == "straggler":
            stragglers.append(
                {k: rec.get(k) for k in ("epoch", "skew", "worst_rank", "max_s", "median_s")}
            )
        elif kind == "alert":
            alerts.append({
                k: rec.get(k)
                for k in ("epoch", "step", "rule", "metric", "value",
                          "threshold", "op", "sustained")
                if rec.get(k) is not None
            })
        elif kind == "anomaly":
            anomalies.append({
                k: rec.get(k)
                for k in ("epoch", "step", "anomaly", "value", "median", "ratio")
            })
        elif kind == "device_stats":
            # per-epoch rollup of the per-step scalars: last value tracks
            # where the run ended up, max grad_norm catches the spike the
            # last sample may have missed
            d = dstats.setdefault(rec.get("epoch"), {"samples": 0})
            d["samples"] += 1
            g = rec.get("grad_norm")
            if isinstance(g, (int, float)):
                d["grad_norm_last"] = g
                d["grad_norm_max"] = max(d.get("grad_norm_max", g), g)
            for key in ("update_ratio", "param_norm"):
                v = rec.get(key)
                if isinstance(v, (int, float)):
                    d[f"{key}_last"] = v
        elif kind == "auto_recover":
            recoveries += 1
        elif kind == "resume":
            # segment boundary (schema v7): the host set is NOT fixed —
            # an elastic relaunch changes the world size mid-log, and the
            # report must say so instead of silently merging segments
            resumes.append({
                k: rec.get(k)
                for k in ("epoch", "world", "dp", "prev_dp", "prev_procs",
                          "resharded", "restarts", "mid_epoch_step",
                          "examples_offset", "decision_id",
                          "decision_cause")
                if rec.get(k) is not None
            })
            # the FIRST segment logs no resume record (fresh starts
            # don't), so seed the world-size history from the resumed
            # checkpoint's stamped previous extent — otherwise the
            # canonical single shrink would read as one world size and
            # the change banner would never render
            prev_dp = rec.get("prev_dp")
            if not world_sizes and isinstance(prev_dp, int):
                world_sizes.append(prev_dp)
            dp = rec.get("dp")
            if isinstance(dp, int) and (
                not world_sizes or world_sizes[-1] != dp
            ):
                world_sizes.append(dp)
        elif kind == "fleet":
            # a fleet-scheduler decision (schema v8): auditable chip move
            # between runs sharing the pod — keep the justification AND
            # the allocations so the report replays the arbitration
            fleet_decisions.append({
                k: rec.get(k)
                for k in ("tick", "action", "donor", "recipient", "for_run",
                          "chips", "alloc_before", "alloc_after",
                          "pending_after", "reason", "inputs",
                          "decision_id", "cause", "chained", "preempt")
                if rec.get(k) is not None
            })
        elif kind == "tenancy":
            # a per-tick chip-accounting snapshot (schema v14,
            # fleet/scheduler.py): every run's allocation + the free and
            # pending pools — the raw material of the exact chip-second
            # conservation audit
            tenancy_snapshots.append({
                k: rec.get(k)
                for k in ("tick", "alloc", "free", "pending",
                          "total_chips", "run_kinds", "decision_id")
                if rec.get(k) is not None
            })
        elif kind == "postmortem":
            # a crash bundle (schema v9): the watchdog/CLI assembler's
            # after-the-fact record of how the run DIED — verdicts per
            # rank, stuck frames, where each flight ring stopped
            postmortems.append({
                k: rec.get(k)
                for k in ("bundle", "n_ranks", "verdicts", "stuck_frames",
                          "fatal", "last_steps")
                if rec.get(k) is not None
            })
        elif kind == "serve":
            # a serving SLO window (schema v10, serve/engine.py): latency
            # percentile bounds, request rate, availability, batching
            # efficiency — or a mid-serve event (retrace) stamped by the
            # engine's pump
            if rec.get("event"):
                serve_events.append({
                    k: rec.get(k)
                    for k in ("event", "bucket", "n_real")
                    if rec.get(k) is not None
                })
            else:
                serve_windows.append({
                    k: rec.get(k)
                    for k in ("window_s", "requests", "completed",
                              "requests_per_s", "latency_p50_ms",
                              "latency_p95_ms", "latency_p99_ms",
                              "ttfb_p50_ms", "ttfb_p99_ms",
                              "availability", "batch_occupancy",
                              "batches", "queue_depth",
                              "queue_depth_max", "retraces", "phase_s")
                    if rec.get(k) is not None
                })
        elif kind == "memory":
            # an HBM-ledger snapshot (schema v11, obs/memory.py): the
            # first-dispatch static/census/allocator reconciliation, or
            # an event:"oom" crash record with the parsed allocation
            # report + the ledger that was live at the time
            if rec.get("event") == "oom":
                oom_events.append({
                    k: rec.get(k) for k in ("epoch", "oom", "ledger")
                    if rec.get(k) is not None
                })
            else:
                memory_records.append({
                    k: rec.get(k)
                    for k in ("epoch", "static", "xla", "census",
                              "reconciliation", "allocator", "feasibility")
                    if rec.get(k) is not None
                })
        elif kind == "plan":
            # an --auto_shard plan (schema v12, analysis/planner.py):
            # the chosen family + its priced step time at fit() start,
            # and — after a profiled run — the TD119 predicted-vs-
            # achieved drift record
            plan_records.append({
                k: rec.get(k)
                for k in ("epoch", "family", "mode", "applied",
                          "predicted_step_s", "achieved_step_s",
                          "planner_error_frac", "gauge_source",
                          "n_candidates", "n_refused")
                if rec.get(k) is not None
            })
        elif kind == "tune":
            # a --tune_report application (schema v13, analysis/overlap.py):
            # which schedule knobs the run trains with, which the user
            # kept, and the tuner objective they were chosen under
            tune_records.append({
                k: rec.get(k)
                for k in ("epoch", "family", "report", "objective",
                          "applied", "user_overrides")
                if rec.get(k) is not None
            })
        elif kind == "profile":
            profiles.append({
                k: rec.get(k)
                for k in ("epoch", "event", "reason", "start_step",
                          "stop_step", "steps", "dir", "error")
                if rec.get(k) is not None
            })
        elif kind == "profile_analysis":
            # the capture read back (obs/xprof.py, schema v6): category
            # attribution + overlap + calibration per capture
            profile_analyses.append({
                k: rec.get(k)
                for k in ("epoch", "reason", "dir", "steps",
                          "device_busy_s", "categories", "collectives",
                          "collective_frac", "overlap_frac",
                          "infeed_stall_s", "top_ops", "calibration",
                          "dropped", "error")
                if rec.get(k) is not None
            })
        elif kind == "goodput" and not rec.get("final"):
            goodput_epochs.append({
                "epoch": rec.get("epoch"),
                **({"tail": True} if rec.get("tail") else {}),
                **{
                    k: rec.get(k)
                    for k in (
                        [f"{b}_s" for b in goodput_lib.ALL_BUCKETS]
                        + ["window_s"]
                    )
                    if isinstance(rec.get(k), (int, float))
                },
            })
        if isinstance(rec.get("counters"), dict):
            final_counters = rec["counters"]
        if kind != "train_epoch":
            continue
        cur_counters = rec.get("counters") if isinstance(rec.get("counters"), dict) else None
        row = {
            "epoch": rec.get("epoch"),
            "images_per_sec": rec.get("images_per_sec"),
            "epoch_time_s": rec.get("epoch_time"),
            "step_time_p50_s": rec.get("step_time_p50"),
            "step_time_p95_s": rec.get("step_time_p95"),
            "step_time_p99_s": rec.get("step_time_p99"),
            "data_stall_frac": rec.get("data_stall_frac"),
            "loss": rec.get("loss"),
            "mfu": rec.get("mfu"),
        }
        if cur_counters is not None:
            deltas = counters_lib.delta(prev_counters, cur_counters)
            row["counter_deltas"] = deltas
            # mid-run retraces are a first-class health signal, not just a
            # counter line: surface the per-epoch delta explicitly
            if deltas.get("compile.retraces"):
                row["retraces"] = deltas["compile.retraces"]
            prev_counters = cur_counters
        epochs.append(row)
    attached = set()
    for row in epochs:
        ev = evals.get(row["epoch"])
        if ev is not None:
            row["val_top1"] = ev.get("top1")
        ds = dstats.get(row["epoch"])
        if ds is not None:
            row["device_stats"] = ds
            attached.add(row["epoch"])
    # device_stats of epochs with NO train_epoch record — the run died
    # mid-epoch (exactly the torn-tail case this report tolerates), and
    # the health data explaining the crash must not vanish with it
    partial = [
        {"epoch": e, **d}
        for e, d in sorted(dstats.items(), key=lambda kv: (kv[0] is None, kv[0]))
        if e not in attached
    ]
    times = [r["epoch_time_s"] for r in epochs if r.get("epoch_time_s")]
    ips = [r["images_per_sec"] for r in epochs if r.get("images_per_sec")]
    mfus = [r["mfu"] for r in epochs if isinstance(r.get("mfu"), (int, float))]
    # the single gating scalar of the memory layer: the worst observed
    # peak HBM — ledger snapshots first (allocator peak > xla estimate >
    # census), the epoch-grain mem.* gauge series as the running floor
    peak_hbm: Optional[int] = None
    for mr in memory_records:
        p = memory_lib.record_peak_hbm(mr)
        if p is not None:
            peak_hbm = max(peak_hbm or 0, p)
    for rec in records:
        cnt = rec.get("counters")
        if isinstance(cnt, dict):
            v = cnt.get("mem.peak_bytes_in_use")
            if isinstance(v, (int, float)) and v > 0:
                peak_hbm = max(peak_hbm or 0, int(v))
    out = {
        "run_id": run_id,
        "schema_version": schema,
        "n_records": len(records),
        "bad_lines": bad_lines,
        "skipped_kinds": skipped_kinds,
        "newer_schema_records": newer_schema_records,
        "epochs": epochs,
        "partial_epoch_device_stats": partial,
        "resumes": resumes,
        "world_sizes": world_sizes,
        "fleet_decisions": fleet_decisions,
        "postmortems": postmortems,
        "serve_windows": serve_windows,
        "serve_events": serve_events,
        "memory_records": memory_records,
        "oom_events": oom_events,
        "memory": (
            {"peak_hbm_bytes": peak_hbm, "oom_events": len(oom_events)}
            if (peak_hbm is not None or oom_events or memory_records)
            else None
        ),
        "plan_records": plan_records,
        "plan": (
            # the gating view of the planner layer: the last plan record
            # wins (the post-profile TD119 drift record supersedes the
            # fit()-start announcement, which carries no achieved time)
            {
                k: plan_records[-1].get(k)
                for k in ("family", "mode", "applied", "predicted_step_s",
                          "achieved_step_s", "planner_error_frac",
                          "gauge_source")
                if plan_records[-1].get(k) is not None
            }
            if plan_records else None
        ),
        "tenancy_snapshots": tenancy_snapshots,
        "tenancy": (
            # the gating view of the multi-tenant pod: the exact
            # chip-second conservation audit over every snapshot seen
            _tenancy_audit(tenancy_snapshots)
            if tenancy_snapshots else None
        ),
        "tune_records": tune_records,
        "tune": (
            # the gating view of the tuner layer: the last application
            # wins (a resume re-applies and re-announces)
            {
                k: tune_records[-1].get(k)
                for k in ("family", "objective", "applied",
                          "user_overrides")
                if tune_records[-1].get(k) is not None
            }
            if tune_records else None
        ),
        "stragglers": stragglers,
        "anomalies": anomalies,
        "alerts": alerts,
        "profiles": profiles,
        "profile_analyses": profile_analyses,
        "goodput_epochs": goodput_epochs,
        # run-level goodput ledger: resumed segments folded, restart gaps
        # attributed to preempt_s (None on a goodput-less / pre-v4 log)
        "goodput": goodput_lib.run_ledger(records),
        "auto_recoveries": recoveries,
        "totals": {
            "n_epochs": len(epochs),
            "total_train_time_s": round(sum(times), 3) if times else 0.0,
            "images_per_sec_mean": round(sum(ips) / len(ips), 1) if ips else None,
            "mfu_mean": round(sum(mfus) / len(mfus), 4) if mfus else None,
            "counters": final_counters or {},
        },
    }
    return out


def _fmt(v, spec: str, width: int) -> str:
    return (format(v, spec) if v is not None else "-").rjust(width)


def format_text(report: dict) -> str:
    """Human-readable rendering of :func:`summarize`'s report."""
    lines = []
    rid = report.get("run_id")
    lines.append(
        f"run {rid or '<no run_id>'} — {report['totals']['n_epochs']} epoch(s), "
        f"{report['n_records']} record(s)"
        + (f", {report['bad_lines']} unparsable line(s)" if report["bad_lines"] else "")
    )
    skipped = report.get("skipped_kinds") or {}
    if skipped:
        body = ", ".join(f"{k}×{v}" for k, v in sorted(skipped.items()))
        lines.append(
            f"skipped {sum(skipped.values())} record(s) of unknown kind(s): "
            f"{body}"
        )
    if report.get("newer_schema_records"):
        lines.append(
            f"NOTE: {report['newer_schema_records']} record(s) carry a "
            f"schema version newer than this reader supports "
            f"({SUPPORTED_SCHEMA}) — known kinds are summarized, the rest "
            "skipped above"
        )
    ws = report.get("world_sizes") or []
    if len(ws) > 1:
        lines.append(
            "world size changed mid-run (elastic): dp "
            + " -> ".join(str(w) for w in ws)
            + " — epoch rows below span DIFFERENT host/device sets"
        )
    for rs in report.get("resumes", []):
        pos = (
            f" at step {rs['mid_epoch_step']}" if rs.get("mid_epoch_step")
            else f" at example offset {rs['examples_offset']}"
            if rs.get("examples_offset") else ""
        )
        # world-size INCREASE (scale-up / fleet receipt) labeled
        # distinctly from the preemption-shrink reshard — one shared
        # classifier: goodput.resume_direction
        direction = goodput_lib.resume_direction(rs)
        lines.append(
            f"segment: resumed epoch {rs.get('epoch')}{pos} on "
            f"{rs.get('world')} process(es), dp={rs.get('dp')}"
            + (
                f" ({'GROWN' if direction == 'grown' else 'RESHARDED'}"
                f" from dp={rs.get('prev_dp')})"
                if direction else ""
            )
            + (
                f" — elastic restart #{rs['restarts']}"
                if rs.get("restarts") else ""
            )
            + (
                # causal tracing (schema v15): a fleet-initiated resize
                # names its arbitration; a chip-loss one carries none
                f" [decision #{rs['decision_id']}"
                + (f": {rs['decision_cause']}" if rs.get("decision_cause")
                   else "")
                + "]"
                if rs.get("decision_id") is not None else ""
            )
        )
    for fd in report.get("fleet_decisions", []):
        lines.append(
            f"fleet: tick {fd.get('tick')}: "
            + goodput_lib.fleet_move_phrase(fd)
            + (f" — {fd['reason']}" if fd.get("reason") else "")
            + (
                " [alloc "
                + ", ".join(
                    f"{r}:{fd['alloc_before'][r]}->{fd['alloc_after'][r]}"
                    for r in sorted(fd["alloc_before"])
                )
                + "]"
                if fd.get("alloc_before") and fd.get("alloc_after") else ""
            )
        )
    ten = report.get("tenancy")
    if ten:
        lines.append(
            f"tenancy: {ten['n_ticks']} tick(s) × {ten['total_chips']} "
            "chip(s) — "
            + (
                "chip-seconds conserved exactly"
                if ten.get("conserved")
                else "CHIP-SECOND CONSERVATION VIOLATED"
            )
            + " ["
            + ", ".join(
                f"{r}:{v:g}" for r, v in (ten.get("per_run") or {}).items()
            )
            + f", free:{ten.get('free_chip_s', 0):g}"
            + f", pending:{ten.get('pending_chip_s', 0):g}]"
        )
    hdr = (
        f"{'epoch':>5} {'img/s':>9} {'epoch_s':>8} {'p50_ms':>8} "
        f"{'p95_ms':>8} {'p99_ms':>8} {'stall%':>7} {'mfu':>6} "
        f"{'loss':>9} {'val_top1':>9}"
    )
    lines.append(hdr)
    for r in report["epochs"]:
        ms = lambda v: v * 1e3 if v is not None else None  # noqa: E731
        lines.append(
            f"{_fmt(r['epoch'], 'd', 5)} {_fmt(r['images_per_sec'], '.1f', 9)} "
            f"{_fmt(r['epoch_time_s'], '.2f', 8)} {_fmt(ms(r['step_time_p50_s']), '.1f', 8)} "
            f"{_fmt(ms(r['step_time_p95_s']), '.1f', 8)} {_fmt(ms(r['step_time_p99_s']), '.1f', 8)} "
            f"{_fmt(r['data_stall_frac'] * 100 if r['data_stall_frac'] is not None else None, '.1f', 7)} "
            f"{_fmt(r.get('mfu'), '.3f', 6)} "
            f"{_fmt(r['loss'], '.4f', 9)} {_fmt(r.get('val_top1'), '.2f', 9)}"
        )
        ds = r.get("device_stats")
        if ds:
            lines.append(
                "      device: grad_norm last "
                f"{_fmt(ds.get('grad_norm_last'), '.4g', 0).strip()} / max "
                f"{_fmt(ds.get('grad_norm_max'), '.4g', 0).strip()}, "
                "update_ratio "
                f"{_fmt(ds.get('update_ratio_last'), '.3g', 0).strip()} "
                f"({ds['samples']} sample(s))"
            )
        if r.get("retraces"):
            lines.append(
                f"      WARNING: {r['retraces']:g} mid-run retrace(s) — the "
                "train step recompiled after step 0 (shape/dtype drift)"
            )
        deltas = r.get("counter_deltas") or {}
        if deltas:
            body = ", ".join(f"{k}+{v:g}" for k, v in sorted(deltas.items()))
            lines.append(f"      counters: {body}")
    for ds in report.get("partial_epoch_device_stats", []):
        lines.append(
            f"partial epoch {ds.get('epoch')} (no epoch summary — run died "
            "mid-epoch): grad_norm last "
            f"{_fmt(ds.get('grad_norm_last'), '.4g', 0).strip()} / max "
            f"{_fmt(ds.get('grad_norm_max'), '.4g', 0).strip()}, "
            "update_ratio "
            f"{_fmt(ds.get('update_ratio_last'), '.3g', 0).strip()} "
            f"({ds.get('samples')} sample(s))"
        )
    for pm in report.get("postmortems", []):
        # per-rank lines through the ONE shared formatter (obs/
        # postmortem.py — jax-free): summarize/tail/pod can never drift
        from tpu_dist.obs.postmortem import rank_summary, sorted_ranks

        lines.append(
            f"POSTMORTEM: crash bundle over {pm.get('n_ranks')} rank(s)"
            + (f" — {pm['bundle']}" if pm.get("bundle") else "")
        )
        for rank in sorted_ranks(pm.get("verdicts") or {}):
            lines.append(f"  rank {rank}: {rank_summary(pm, rank)}")
    for a in report.get("alerts", []):
        lines.append(
            f"alert: {a.get('rule')} fired at epoch {a.get('epoch')}"
            + (f" step {a.get('step')}" if a.get("step") is not None else "")
            + f" — {a.get('metric')} {a.get('value')} {a.get('op')} "
            f"threshold {a.get('threshold')} "
            f"(sustained {a.get('sustained')} window(s))"
        )
    for a in report.get("anomalies", []):
        lines.append(
            f"anomaly: epoch {a.get('epoch')} step {a.get('step')} "
            f"{a.get('anomaly')} value {a.get('value')}"
            + (
                f" ({a.get('ratio')}x rolling median {a.get('median')})"
                if a.get("ratio") is not None
                else ""
            )
        )
    for s in report["stragglers"]:
        lines.append(
            f"straggler: epoch {s.get('epoch')} process {s.get('worst_rank')} "
            f"at {s.get('skew')}x median ({s.get('max_s')}s vs {s.get('median_s')}s)"
        )
    for pr in report.get("profiles", []):
        if pr.get("event") == "stop":
            lines.append(
                f"profile: captured {pr.get('steps')} step(s) from global "
                f"step {pr.get('start_step')} ({pr.get('reason')}) → "
                f"{pr.get('dir')}"
            )
        elif pr.get("event") == "error":
            lines.append(
                f"profile: capture FAILED ({pr.get('reason')}): "
                f"{pr.get('error')}"
            )
    pas = report.get("profile_analyses") or []
    if pas:
        from tpu_dist.obs import xprof as xprof_lib  # stdlib-only

        lines.append("capture attribution (device seconds, obs/xprof.py):")
        cats = list(xprof_lib.CATEGORIES)
        lines.append(
            f"{'epoch':>5} {'reason':>16} {'busy_s':>9} "
            + " ".join(f"{c[:10]:>10}" for c in cats)
            + f" {'overlap':>8} {'infeed_s':>9}"
        )
        for pa in pas:
            if pa.get("error"):
                lines.append(
                    f"  epoch {pa.get('epoch')} ({pa.get('reason')}): "
                    f"analysis FAILED: {pa['error']}"
                )
                continue
            pc = pa.get("categories") or {}
            lines.append(
                f"{_fmt(pa.get('epoch'), 'd', 5)} "
                f"{str(pa.get('reason') or '-')[:16]:>16} "
                f"{_fmt(pa.get('device_busy_s'), '.4f', 9)} "
                + " ".join(_fmt(pc.get(c), ".4f", 10) for c in cats)
                + f" {_fmt(pa.get('overlap_frac'), '.1%', 8)}"
                + f" {_fmt(pa.get('infeed_stall_s'), '.4f', 9)}"
            )
            cal = pa.get("calibration") or {}
            if cal:
                body = ", ".join(
                    f"{k.split('calibration_', 1)[-1]}={v:g}"
                    if isinstance(v, (int, float)) else f"{k}={v}"
                    for k, v in sorted(cal.items())
                )
                lines.append(f"      calibration: {body}")
            if pa.get("dropped"):
                n = sum(pa["dropped"].values())
                lines.append(
                    f"      WARNING: {n} trace file(s) dropped during "
                    f"analysis ({pa['dropped']})"
                )
    sw = report.get("serve_windows") or []
    if sw:
        # the table through the ONE shared renderer (serve/slo.py —
        # jax-free): the offline serve report and this view can never
        # drift column by column
        from tpu_dist.serve.slo import window_table_lines

        lines.append("serving SLO windows (serve/slo.py, schema v10):")
        lines.extend(window_table_lines(sw))
    for ev in report.get("serve_events") or []:
        if ev.get("event") == "retrace":
            lines.append(
                f"serve: RETRACE on a bucket-{ev.get('bucket')} batch "
                f"({ev.get('n_real')} real request(s)) — the compiled "
                "forward saw a new shape mid-serve"
            )
    for mr in report.get("memory_records") or []:
        # the full ledger through the ONE shared renderer (obs/memory.py
        # — jax-free): summarize and the `obs memory` CLI cannot drift
        lines.append(memory_lib.format_ledger_text(mr))
    for o in report.get("oom_events") or []:
        lines.append(
            "OOM"
            + (f" at epoch {o['epoch']}" if o.get("epoch") is not None else "")
            + ": "
            + (
                memory_lib.oom_summary_line(o["oom"])
                if isinstance(o.get("oom"), dict) else "RESOURCE_EXHAUSTED"
            )
        )
    mem = report.get("memory")
    if mem and mem.get("peak_hbm_bytes") is not None:
        lines.append(
            f"peak HBM: {memory_lib.fmt_bytes(mem['peak_hbm_bytes'])} "
            "(worst chip — the compare gate's memory scalar)"
        )
    plan = report.get("plan")
    if plan:
        bits = [f"plan: {plan.get('family', '?')}"]
        if plan.get("mode"):
            bits.append(f"mode={plan['mode']}")
        if plan.get("predicted_step_s") is not None:
            bits.append(f"predicted {plan['predicted_step_s'] * 1e3:.3g} ms/step")
        if plan.get("achieved_step_s") is not None:
            bits.append(f"achieved {plan['achieved_step_s'] * 1e3:.3g} ms/step")
        if plan.get("planner_error_frac") is not None:
            bits.append(
                f"planner_error_frac={plan['planner_error_frac']:.4f}"
                " (TD119 — the compare gate's planner scalar)"
            )
        lines.append("  ".join(bits))
    gp_epochs = report.get("goodput_epochs") or []
    if gp_epochs:
        lines.append("goodput (seconds per window):")
        cols = [b for b in goodput_lib.ALL_BUCKETS]
        lines.append(
            f"{'epoch':>5} {'window':>8} "
            + " ".join(f"{c[:10]:>10}" for c in cols)
        )
        any_tail = False
        for g in gp_epochs:
            ep = g.get("epoch")
            tail = bool(g.get("tail"))
            any_tail = any_tail or tail
            ep_cell = (
                f"{_fmt(ep, 'd', 4)}*" if isinstance(ep, int) and tail
                else f"{_fmt(ep, 'd', 5)}" if isinstance(ep, int)
                else "    -"
            )
            lines.append(
                f"{ep_cell} "
                f"{_fmt(g.get('window_s'), '.2f', 8)} "
                + " ".join(_fmt(g.get(f"{c}_s"), ".2f", 10) for c in cols)
            )
        if any_tail:
            lines.append(
                "  (* run-end tail window: final save / writer drain / "
                "teardown, not an epoch)"
            )
    gp = report.get("goodput")
    if gp:
        lines.append(goodput_lib.ledger_line(gp))
    if report["auto_recoveries"]:
        lines.append(f"auto-recoveries: {report['auto_recoveries']}")
    t = report["totals"]
    lines.append(
        f"total: {t['total_train_time_s']}s train"
        + (f", mean {t['images_per_sec_mean']} img/s" if t["images_per_sec_mean"] else "")
        + (f", mean MFU {t['mfu_mean']}" if t.get("mfu_mean") else "")
    )
    cnt = t.get("counters") or {}
    if cnt:
        lines.append("final counters:")
        for k in sorted(cnt):
            lines.append(f"  {k} = {cnt[k]}")
    return "\n".join(lines)


def export_trace(records: List[dict]) -> dict:
    """Chrome trace-event JSON from a run's history: the ``spans`` records'
    drained events, plus synthesized epoch/eval bars (from each record's
    monotonic ``rel_s``) so even a span-less log yields a loadable
    timeline.

    Resumed runs append to the same log with a fresh ``run_id`` AND a
    restarted clock (``rel_s`` and the span recorder both re-zero in the
    new process), so each run segment is shifted to start where the
    previous one ended — Perfetto shows sequential segments, not two runs
    overlapping at ts≈0."""
    events: List[dict] = []
    offset_s = 0.0   # where the current segment's clock-zero sits globally
    seg_end_s = 0.0  # furthest global timestamp seen so far
    seen_run = False
    cur_run = None
    for rec in records:
        rid = rec.get("run_id")
        if not seen_run or rid != cur_run:
            if seen_run:
                offset_s = seg_end_s  # resume boundary: new clock origin
            cur_run, seen_run = rid, True
        kind = rec.get("kind")
        rel = rec.get("rel_s")
        if rel is not None:
            seg_end_s = max(seg_end_s, offset_s + float(rel))
        if kind == "spans" and isinstance(rec.get("events"), list):
            for e in rec["events"]:
                if not isinstance(e, dict):
                    continue
                e = {**e, "ts": round(float(e.get("ts", 0)) + offset_s * 1e6, 1)}
                events.append(e)
                seg_end_s = max(
                    seg_end_s, (e["ts"] + float(e.get("dur", 0))) / 1e6
                )
        if kind in ("train_epoch", "eval") and rel is not None:
            dur = float(rec.get("epoch_time") or 0.0) if kind == "train_epoch" else 0.0
            # the record is stamped at the END of the region
            ts = (offset_s + float(rel) - dur) * 1e6
            events.append(
                {
                    "name": f"{kind}/{rec.get('epoch')}",
                    "ph": "X",
                    "ts": round(max(ts, offset_s * 1e6), 1),
                    "dur": round(dur * 1e6, 1),
                    "pid": 0,
                    "tid": 0,
                    "args": {"kind": kind, "epoch": rec.get("epoch")},
                }
            )
    return {"traceEvents": events, "displayTimeUnit": "ms"}
