"""Pod-wide telemetry aggregation — ``python -m tpu_dist.obs pod``.

Everything else in ``tpu_dist/obs`` reports one process at a time; pod
debugging is a CROSS-host exercise (arXiv:1909.09756: MLPerf-scale TPU
debugging lives or dies on cross-host timeline correlation). This module
merges per-host artifacts into one view:

* **Report** (:func:`pod_report`): each host's ``--log_file`` JSONL is
  folded through ``summarize``; the pod report puts the per-host goodput
  ledgers side by side, computes per-epoch cross-host skew from the
  epoch times the logs already carry, and attributes each straggling
  epoch to a phase — a host slow WITH a high data-stall fraction is an
  input-pipeline problem, one slow WITHOUT it is compute/other (exactly
  the triage rule the in-run straggler warning prints). Heartbeat files
  (``--heartbeat``) add a liveness row per host: position, phase, and
  how stale the last beat is.
* **Trace** (:func:`pod_trace`): per-host Chrome traces (spans + epoch
  bars, via ``summarize.export_trace``) merged into ONE Perfetto
  timeline — one ``pid`` track per host, named by a metadata event, and
  aligned on the shared clock: each host's wall origin is recovered as
  ``ts - rel_s`` of its first record, and every host is shifted by its
  offset from the earliest origin, so skew between hosts renders as real
  horizontal displacement instead of every track pretending to start at
  zero. When any log carries ``tenancy`` snapshots (the fleet
  scheduler's per-tick chip accounting, schema v14+), one extra
  **chip-ownership Gantt track** is synthesized beside the host
  tracks: one row per chip, one bar per ownership stretch (run name /
  ``free`` / ``pending``), each bar stamped with the ``decision_id``
  that created it — the pod's chip timeline at a glance.
* **Decision chains** (:func:`decision_chains`): every artifact
  carrying the same ``decision_id`` (schema v15 causal tracing) —
  the scheduler's donate and its completion grant, the tenancy ticks
  it shaped, the donor's/recipient's relaunch ``resume`` records —
  folded into one end-to-end chain, so "why did run A shrink at tick
  42" is one rendered line, not a four-file join.

Per-host logs come from ``--per_host_log`` (each process writes
``<log_file>.h<rank>``; rank 0 keeps the bare path) or from any N
separately-collected ``--log_file`` JSONLs of the same logical run.
Pure host-side file crunching — no jax, runs anywhere the logs can be
copied to.
"""

from __future__ import annotations

import time
from statistics import median
from typing import List, Optional, Tuple

from tpu_dist.obs import goodput as goodput_lib
from tpu_dist.obs import summarize as summ

#: A straggling epoch is attributed to the input pipeline when the slow
#: host's data-stall fraction exceeds the other hosts' median by this
#: many absolute points.
_STALL_ATTRIBUTION_MARGIN = 0.1


def _wall_origin(records: List[dict]) -> Optional[float]:
    """The host's clock-zero (Trainer construction) on the wall clock:
    ``ts - rel_s`` of the first record carrying both."""
    for rec in records:
        ts, rel = rec.get("ts"), rec.get("rel_s")
        if isinstance(ts, (int, float)) and isinstance(rel, (int, float)):
            return float(ts) - float(rel)
    return None


def epoch_skew_rows(hosts: List[Tuple[str, dict]]) -> List[dict]:
    """Cross-host skew per epoch, with phase attribution. ``hosts`` is
    ``[(name, summarize_report), ...]``."""
    by_epoch: dict = {}
    for name, rep in hosts:
        for row in rep.get("epochs", []):
            e = row.get("epoch")
            t = row.get("epoch_time_s")
            if e is None or not isinstance(t, (int, float)):
                continue
            by_epoch.setdefault(e, []).append((name, row))
    out: List[dict] = []
    for e in sorted(by_epoch):
        entries = by_epoch[e]
        if len(entries) < 2:
            continue
        times = [row["epoch_time_s"] for _, row in entries]
        med = median(times)
        worst_i = max(range(len(entries)), key=lambda i: times[i])
        worst_name, worst_row = entries[worst_i]
        skew = times[worst_i] / med if med > 0 else 1.0
        stalls = [
            row.get("data_stall_frac")
            for i, (_, row) in enumerate(entries)
            if i != worst_i and isinstance(row.get("data_stall_frac"), (int, float))
        ]
        worst_stall = worst_row.get("data_stall_frac")
        phase = "unknown"
        if isinstance(worst_stall, (int, float)) and stalls:
            phase = (
                "data_stall"
                if worst_stall - median(stalls) > _STALL_ATTRIBUTION_MARGIN
                else "compute/other"
            )
        out.append({
            "epoch": e,
            "hosts": len(entries),
            "median_s": round(med, 4),
            "max_s": round(times[worst_i], 4),
            "skew": round(skew, 4),
            "worst_host": worst_name,
            "worst_stall_frac": worst_stall,
            "attribution": phase,
        })
    return out


def heartbeat_rows(
    paths: List[str], now: Optional[float] = None
) -> List[dict]:
    """Liveness row per heartbeat file (``obs/heartbeat.py`` format):
    position + beat age. An absent file reads as a clean exit — that is
    the heartbeat contract, not an error."""
    from tpu_dist.obs import heartbeat as heartbeat_lib  # stdlib-only

    now = time.time() if now is None else now
    out = []
    for path in paths:
        rec = heartbeat_lib.read(path)
        if rec is None:
            out.append({"file": path, "status": "absent (clean exit or not started)"})
            continue
        age = now - rec["ts"] if isinstance(rec.get("ts"), (int, float)) else None
        out.append({
            "file": path,
            "status": "present",
            "counter": rec.get("counter"),
            "epoch": rec.get("epoch"),
            "step": rec.get("step"),
            "phase": rec.get("phase"),
            "beat_age_s": round(age, 1) if age is not None else None,
        })
    return out


def decision_chains(hosts: List[dict]) -> List[dict]:
    """Fold every artifact stamped with the same ``decision_id`` (schema
    v15) into one causal chain: the scheduler's chip moves (a donate and
    its completion grant SHARE the id) joined with the relaunch
    ``resume`` records the decision caused on donor and recipient.
    ``hosts`` is the per-host dict list :func:`pod_report` builds. A
    chain with moves but no observed resume is surfaced as incomplete —
    that is exactly the "decision fired but nobody relaunched" bug the
    tracing exists to catch, so it must not be dropped."""
    chains: dict = {}

    def chain(did: int, cause: Optional[str]) -> dict:
        c = chains.setdefault(did, {
            "decision_id": did, "cause": None, "moves": [], "resumes": [],
        })
        if cause and not c["cause"]:
            c["cause"] = cause
        return c

    for h in hosts:
        for fd in h.get("fleet_decisions", []):
            did = fd.get("decision_id")
            if did is None:
                continue
            chain(did, fd.get("cause"))["moves"].append(
                {"host": h["host"], **fd}
            )
        for rs in h.get("resumes", []):
            did = rs.get("decision_id")
            if did is None:
                continue
            chain(did, rs.get("decision_cause"))["resumes"].append(
                {"host": h["host"], **rs}
            )
    out = []
    for did in sorted(chains):
        c = chains[did]
        c["moves"].sort(key=lambda m: (m.get("tick") or 0))
        c["complete"] = bool(c["moves"]) and bool(c["resumes"])
        out.append(c)
    return out


def pod_report(
    host_records: List[Tuple[str, List[dict]]],
    heartbeats: Optional[List[str]] = None,
) -> dict:
    """The merged cross-host report over ``[(host_name, records), ...]``."""
    hosts = []
    reports = []
    for name, records in host_records:
        rep = summ.summarize(records)
        reports.append((name, rep))
        gp = rep.get("goodput")
        hosts.append({
            "host": name,
            "run_id": rep.get("run_id"),
            "n_epochs": rep["totals"]["n_epochs"],
            "images_per_sec_mean": rep["totals"].get("images_per_sec_mean"),
            "goodput": gp,
            "stragglers": rep.get("stragglers", []),
            "anomalies": len(rep.get("anomalies", [])),
            "profiles": rep.get("profiles", []),
            "profile_analyses": rep.get("profile_analyses", []),
            "skipped_kinds": rep.get("skipped_kinds", {}),
            # elastic segment boundaries (schema v7): the pod's host set
            # is NOT fixed across segments — surface world-size changes
            "resumes": rep.get("resumes", []),
            "world_sizes": rep.get("world_sizes", []),
            # fleet-scheduler chip moves (schema v8) found in this log
            "fleet_decisions": rep.get("fleet_decisions", []),
            # per-tick chip accounting (schema v14) — the chip-ownership
            # Gantt's raw material
            "tenancy_snapshots": rep.get("tenancy_snapshots", []),
            # crash bundles (schema v9): how this host's run DIED
            "postmortems": rep.get("postmortems", []),
            # serving SLO windows (schema v10): this host's serving
            # latency/rate rollup — last window is the current state
            "serve_windows": rep.get("serve_windows", []),
            "serve_events": rep.get("serve_events", []),
            # the memory layer (schema v11): the host's peak-HBM rollup
            # + OOM events — the pod view's per-host memory skew input
            "memory": rep.get("memory"),
            "oom_events": rep.get("oom_events", []),
        })
    fracs = [
        h["goodput"]["goodput_frac"] for h in hosts
        if h.get("goodput") and isinstance(
            h["goodput"].get("goodput_frac"), (int, float)
        )
    ]
    worst = min(
        (h for h in hosts if h.get("goodput")),
        key=lambda h: h["goodput"].get("goodput_frac", 1.0),
        default=None,
    )
    peaks = [
        (h["host"], h["memory"]["peak_hbm_bytes"]) for h in hosts
        if h.get("memory")
        and isinstance(h["memory"].get("peak_hbm_bytes"), (int, float))
    ]
    return {
        "n_hosts": len(hosts),
        "hosts": hosts,
        "decision_chains": decision_chains(hosts),
        "epoch_skew": epoch_skew_rows(reports),
        "heartbeats": heartbeat_rows(heartbeats) if heartbeats else [],
        "pod": {
            "goodput_frac_min": min(fracs) if fracs else None,
            "goodput_frac_mean": (
                round(sum(fracs) / len(fracs), 4) if fracs else None
            ),
            "worst_goodput_host": worst["host"] if worst else None,
            # cross-host peak-HBM spread: one hot HOST (after the
            # per-chip skew inside each) is the pod's OOM risk
            "peak_hbm_bytes_max": max((p for _, p in peaks), default=None),
            "peak_hbm_bytes_min": min((p for _, p in peaks), default=None),
            "worst_hbm_host": (
                max(peaks, key=lambda hp: hp[1])[0] if peaks else None
            ),
        },
    }


def _chip_ownership_events(
    host_records: List[Tuple[str, List[dict]]],
    base: Optional[float],
    pid: int,
) -> List[dict]:
    """The per-chip ownership Gantt track, synthesized from the raw
    ``tenancy`` snapshots found in any host's log: one ``tid`` row per
    chip, one ``X`` bar per ownership stretch (a run's name, ``free``,
    or ``pending``), each bar stamped with the ``decision_id`` active at
    the tick that started it. Chips inside a tick are laid out
    deterministically — runs in name order, then free, then pending —
    so the SAME layout renders on every machine; a bar ends where the
    next tick's layout disagrees, and the last tick extends by the
    median tick interval so it is visible at all."""
    snaps: dict = {}
    for _, records in host_records:
        for rec in records:
            # dedup by tick — the same scheduler tick may be mirrored
            # into several hosts' logs
            if rec.get("kind") != "tenancy" or rec.get("tick") is None:
                continue
            snaps.setdefault(rec["tick"], rec)
    if not snaps:
        return []
    ordered = [snaps[t] for t in sorted(snaps)]
    times = [
        float(rec["ts"]) if isinstance(rec.get("ts"), (int, float)) else None
        for rec in ordered
    ]
    if any(t is None for t in times):
        # no wall clock on the snapshots (foreign tooling): render ticks
        # as seconds so the track still has shape
        ref = 0.0 if base is None else base
        times = [ref + float(rec.get("tick", i)) for i, rec in enumerate(ordered)]
    ref = min(times) if base is None else base
    deltas = sorted(b - a for a, b in zip(times, times[1:]) if b > a)
    tail = median(deltas) if deltas else 1.0
    total = max(int(rec.get("total_chips") or 0) for rec in ordered)
    if total <= 0:
        return []

    def layout(rec: dict) -> List[str]:
        lane: List[str] = []
        alloc = rec.get("alloc") or {}
        for run in sorted(alloc):
            lane += [run] * int(alloc[run])
        lane += ["free"] * int(rec.get("free") or 0)
        lane += ["pending"] * int(rec.get("pending") or 0)
        return (lane + ["?"] * total)[:total]

    layouts = [layout(rec) for rec in ordered]
    events: List[dict] = [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": "chip ownership (tenancy)"},
    }]
    for chip in range(total):
        events.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": chip,
            "args": {"name": f"chip {chip}"},
        })
    n = len(ordered)
    for chip in range(total):
        i = 0
        while i < n:
            j = i
            while j + 1 < n and layouts[j + 1][chip] == layouts[i][chip]:
                j += 1
            t0 = times[i]
            t1 = times[j + 1] if j + 1 < n else times[j] + tail
            args = {"tick": ordered[i].get("tick")}
            if ordered[i].get("decision_id") is not None:
                args["decision_id"] = ordered[i]["decision_id"]
            events.append({
                "name": layouts[i][chip], "ph": "X", "cat": "tenancy",
                "pid": pid, "tid": chip,
                "ts": round((t0 - ref) * 1e6, 1),
                "dur": round(max(t1 - t0, 0.0) * 1e6, 1),
                "args": args,
            })
            i = j + 1
    return events


def pod_trace(host_records: List[Tuple[str, List[dict]]]) -> dict:
    """One Perfetto timeline with a track per host. Host i's events keep
    their own layout but move to ``pid=i``; tracks are aligned on the
    shared wall clock via each host's recovered origin so cross-host
    skew is visible as displacement. A final synthetic track renders the
    per-chip ownership Gantt whenever tenancy snapshots exist."""
    events: List[dict] = []
    origins = [
        _wall_origin(records) for _, records in host_records
    ]
    known = [o for o in origins if o is not None]
    base = min(known) if known else 0.0
    for i, (name, records) in enumerate(host_records):
        offset_us = ((origins[i] - base) if origins[i] is not None else 0.0) * 1e6
        events.append({
            "name": "process_name", "ph": "M", "pid": i, "tid": 0,
            "args": {"name": name},
        })
        for e in summ.export_trace(records)["traceEvents"]:
            events.append({
                **e,
                "pid": i,
                "ts": round(float(e.get("ts", 0.0)) + offset_us, 1),
            })
    events.extend(_chip_ownership_events(
        host_records,
        base if known else None,
        pid=len(host_records),
    ))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def format_text(report: dict) -> str:
    lines = [f"pod report — {report['n_hosts']} host(s)"]
    w = max([len(h["host"]) for h in report["hosts"]] + [4])
    cols = [b for b in goodput_lib.ALL_BUCKETS]
    lines.append("per-host goodput ledgers:")
    lines.append(
        f"  {'host'.ljust(w)} {'goodput':>8} {'elapsed':>9} "
        + " ".join(f"{c[:9]:>9}" for c in cols)
        + f" {'img/s':>9} {'seg':>4}"
    )

    def cell(v, spec, width):
        return (format(v, spec) if isinstance(v, (int, float)) else "-").rjust(width)

    for h in report["hosts"]:
        gp = h.get("goodput") or {}
        lines.append(
            f"  {h['host'].ljust(w)} "
            f"{cell(gp.get('goodput_frac'), '.1%', 8)} "
            f"{cell(gp.get('elapsed_s'), '.1f', 9)} "
            + " ".join(cell(gp.get(f"{c}_s"), ".1f", 9) for c in cols)
            + f" {cell(h.get('images_per_sec_mean'), '.1f', 9)}"
            + f" {cell(gp.get('n_segments'), 'd', 4)}"
        )
    # elastic segments: a world-size change mid-log means later epoch
    # rows ran on a DIFFERENT host/device set — the skew table and the
    # per-host ledgers must be read per segment, so say so explicitly
    for h in report["hosts"]:
        ws = h.get("world_sizes") or []
        if len(ws) > 1:
            resumes = h.get("resumes", [])
            grows = sum(
                1 for r in resumes
                if goodput_lib.resume_direction(r) == "grown"
            )
            lines.append(
                f"elastic on {h['host']}: world size dp "
                + " -> ".join(str(x) for x in ws)
                + " ("
                + str(sum(1 for r in resumes if r.get("resharded")))
                + " resharded resume(s)"
                + (f", {grows} grow(s)" if grows else "")
                + ") — host set not fixed across segments"
            )
    # fleet-scheduler decisions: chips moved BETWEEN runs on this pod —
    # the arbitration audit trail, rendered next to the runs it moved
    for h in report["hosts"]:
        for fd in h.get("fleet_decisions", []):
            lines.append(
                f"fleet ({h['host']}) tick {fd.get('tick')}: "
                + goodput_lib.fleet_move_phrase(fd)
                + (f" — {fd['reason']}" if fd.get("reason") else "")
            )
    # causal chains (schema v15): one line per decision_id answering
    # "why did run A shrink at tick N" end to end — the chip moves the
    # scheduler made under that id, then the relaunches it caused. The
    # per-move phrase drops its own [decision #N] suffix: the chain
    # header already names it.
    for c in report.get("decision_chains", []):
        steps = []
        for m in c.get("moves", []):
            steps.append(
                f"tick {m.get('tick')} {m.get('action')}: "
                + goodput_lib.fleet_move_phrase({**m, "decision_id": None})
            )
        for rs in c.get("resumes", []):
            step = f"{rs['host']} resumed dp={rs.get('dp')}"
            if rs.get("restarts") is not None:
                step += f" (restart #{rs['restarts']})"
            steps.append(step)
        lines.append(
            f"decision #{c['decision_id']}"
            + (f" [{c['cause']}]" if c.get("cause") else "")
            + (": " + " -> ".join(steps) if steps else "")
            + ("" if c.get("complete")
               else "  <-- no resume observed: chain INCOMPLETE")
        )
    # crash forensics (schema v9): a postmortem bundle in a host's log
    # means that run DIED hard — the pod view must lead with who crashed
    # and where it was stuck, not bury it under throughput rows
    for h in report["hosts"]:
        for pm in h.get("postmortems", []):
            from tpu_dist.obs.postmortem import rank_summary, sorted_ranks

            lines.append(
                f"POSTMORTEM on {h['host']}: crash bundle over "
                f"{pm.get('n_ranks')} rank(s)"
                + (f" — {pm['bundle']}" if pm.get("bundle") else "")
            )
            for rank in sorted_ranks(pm.get("verdicts") or {}):
                lines.append(f"  rank {rank}: {rank_summary(pm, rank)}")
    # the memory layer (schema v11): per-host peak HBM + OOMs, and the
    # pod-level spread — the hottest host is the pod's OOM risk even
    # when every mean looks healthy
    mem_hosts = [
        h for h in report["hosts"]
        if h.get("memory") or h.get("oom_events")
    ]
    if mem_hosts:
        from tpu_dist.obs import memory as memory_lib

        lines.append("per-host peak HBM (worst chip):")
        for h in mem_hosts:
            mem = h.get("memory") or {}
            ooms = h.get("oom_events") or []
            lines.append(
                f"  {h['host'].ljust(w)} "
                f"{memory_lib.fmt_bytes(mem.get('peak_hbm_bytes')):>10}"
                + (f"  {len(ooms)} OOM event(s)" if ooms else "")
            )
        pod = report.get("pod") or {}
        if isinstance(pod.get("peak_hbm_bytes_max"), (int, float)):
            spread = pod["peak_hbm_bytes_max"] - (
                pod.get("peak_hbm_bytes_min") or pod["peak_hbm_bytes_max"]
            )
            lines.append(
                f"  pod: max {memory_lib.fmt_bytes(pod['peak_hbm_bytes_max'])}"
                f" on {pod.get('worst_hbm_host')}"
                + (
                    f", cross-host spread {memory_lib.fmt_bytes(spread)}"
                    if spread else ""
                )
            )
    # per-host profiler captures: paths + the xprof analysis rollup, so
    # the pod view answers WHERE each capture lives and WHAT it said —
    # not just who heartbeats and who straggles
    for h in report["hosts"]:
        caps = [p for p in h.get("profiles", []) if p.get("event") == "stop"]
        analyses = {
            pa.get("dir"): pa for pa in h.get("profile_analyses", [])
        }
        fails = [p for p in h.get("profiles", []) if p.get("event") == "error"]
        if not caps and not fails:
            continue
        lines.append(f"captures on {h['host']}:")
        for p in caps:
            lines.append(
                f"  epoch {p.get('epoch')} ({p.get('reason')}): "
                f"{p.get('steps')} step(s) → {p.get('dir')}"
            )
            pa = analyses.get(p.get("dir"))
            if pa and not pa.get("error"):
                lines.append(
                    "    busy "
                    f"{cell(pa.get('device_busy_s'), '.3f', 0).strip()}s, "
                    "collectives "
                    f"{cell(pa.get('collective_frac'), '.0%', 0).strip()}, "
                    "overlap "
                    f"{cell(pa.get('overlap_frac'), '.0%', 0).strip()}, "
                    "infeed stall "
                    f"{cell(pa.get('infeed_stall_s'), '.3f', 0).strip()}s"
                )
            elif pa:
                lines.append(f"    analysis FAILED: {pa['error']}")
        for p in fails:
            lines.append(
                f"  epoch {p.get('epoch')} ({p.get('reason')}): capture "
                f"FAILED: {p.get('error')}"
            )
    # per-host serving rollup (schema v10): the LAST window is the
    # host's current SLO state; mid-serve retraces are called out
    for h in report["hosts"]:
        sw = h.get("serve_windows") or []
        if not sw:
            continue
        last = sw[-1]
        retraces = sum(
            1 for e in h.get("serve_events") or []
            if e.get("event") == "retrace"
        )
        lines.append(
            f"serving on {h['host']}: {len(sw)} window(s), last "
            f"{cell(last.get('requests_per_s'), '.1f', 0).strip()} req/s, "
            f"p99 {cell(last.get('latency_p99_ms'), '.2f', 0).strip()} ms, "
            "avail "
            f"{cell(last.get('availability'), '.3f', 0).strip()}"
            + (f" — {retraces} mid-serve RETRACE(S)" if retraces else "")
        )
    for s in report.get("epoch_skew", []):
        mark = " <-- STRAGGLER" if s["skew"] > 1.5 else ""
        lines.append(
            f"epoch {s['epoch']}: max/median skew {s['skew']}x "
            f"(worst {s['worst_host']}: {s['max_s']}s vs median "
            f"{s['median_s']}s, attribution: {s['attribution']}){mark}"
        )
    for hb in report.get("heartbeats", []):
        if hb.get("status") == "present":
            lines.append(
                f"heartbeat {hb['file']}: beat {hb.get('counter')} at epoch "
                f"{hb.get('epoch')} step {hb.get('step')} phase "
                f"{hb.get('phase')}, {hb.get('beat_age_s')}s old"
            )
        else:
            lines.append(f"heartbeat {hb['file']}: {hb['status']}")
    pod = report.get("pod", {})
    if pod.get("goodput_frac_mean") is not None:
        lines.append(
            f"pod goodput: mean {pod['goodput_frac_mean']:.1%}, min "
            f"{pod['goodput_frac_min']:.1%} "
            f"({pod['worst_goodput_host']})"
        )
    return "\n".join(lines)
