"""The longitudinal run archive — the stack's missing TIME axis.

Every regression gate before this module was pairwise (``obs compare
a b`` against ONE baseline), so a noisy baseline flaked the gate and a
slow multi-PR drift was invisible by construction. This module is the
fix, in three pieces behind ``python -m tpu_dist.obs {archive,trend}``
and ``obs compare --against-archive``:

* **ingest** — fold any run artifact into ONE append-only
  ``archive.jsonl`` of schema-pinned ``archive_record_v1`` lines:
  bench JSONLs (``bench.py`` output, ``LAST_GOOD_BENCH.json``), the
  driver's ``BENCH_*.json`` / ``MULTICHIP_*.json`` wrappers (a failed
  probe archives as an empty STALE record — the empty trajectory is
  itself evidence), ``--log_file`` histories (via the summarize
  report), and the schema-pinned analysis reports
  (``shard_report`` / ``plan_report`` / ``tune_report``). Each record
  carries a deterministic **fingerprint** (the bench capture identity
  when present, a content hash otherwise) and ingest is idempotent by
  it: re-ingesting an artifact appends nothing. A record that
  self-declares ``stale: true`` or re-emits an already-archived
  capture fingerprint (the PR 7 staleness discipline — the r03–r05
  failure mode) is archived **flagged STALE** and excluded from every
  band. Scalars flow through :data:`compare.METRIC_DIRECTIONS` — only
  metrics with a registered (or suffix-derivable) direction are
  gateable; the rest are counted, never silently dropped. The loader
  follows the house discipline: torn tail tolerated with a count,
  newer ``archive_record_v*`` schemas read by their known fields with
  a count, foreign lines skipped with a count.

* **band gating** — :func:`gate_candidate`: a candidate is gated
  against the rolling ``median ± max(k·MAD, rel_floor·|median|) +
  slack`` band of the last N non-stale archived records per metric.
  Direction-aware (a better-than-band candidate is NEVER flagged),
  and the relative floor keeps a young band honest: one archived
  record has MAD 0, and without the floor any wobble would flag. A
  gate whose every band is stale compares nothing — the CLI maps that
  to exit 2, never a silent pass.

* **trend + blame** — :func:`trend_report`: per-metric series in
  archive order with an offline CUSUM changepoint detector (stdlib
  arithmetic only — max |cumulative deviation| split, accepted when
  the segment-mean shift clears the MAD noise scale), and ``--blame``
  names the first archived record AFTER the shift (fingerprint +
  run_id + source path — i.e. which PR's artifact moved the metric).

* **probe** — :func:`inject_probe` (TD124 ``archive-gate-not-vacuous``):
  a synthetic worse-than-band candidate MUST come back REGRESSED, a
  better one MUST come back clean, and an injected step in a synthetic
  series MUST be localized to the exact record. A dead detector is
  exit 2 — the same injected-fault discipline as TD105/TD118/TD120.

Pure host-side file crunching — no jax, runs anywhere the package
imports. Formatters return strings; printing and exit codes belong to
``obs/__main__.py``. The whole kit is host-side by contract: TD124
(``analysis/jaxpr_audit.py``) proves arming it leaves the traced train
step byte-identical.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Optional, Tuple

from tpu_dist.obs import compare as compare_lib
from tpu_dist.obs import summarize as summ

#: Schema tag every archived line carries; bumps are additive (a reader
#: of v1 reads a v2 line's known fields and counts it ``newer_schema``).
SCHEMA = "archive_record_v1"
SCHEMA_VERSION = 1

#: Rolling band: the last N non-stale records per (label, metric).
DEFAULT_WINDOW = 20

#: Band half-width in MADs (median absolute deviation).
DEFAULT_K = 3.0

#: The band is never narrower than this fraction of |median| — a young
#: archive (one fresh record per metric is exactly the seeded state) has
#: MAD 0, and a zero-width band would flag noise as regression.
REL_FLOOR = 0.05

#: CUSUM acceptance: the segment-mean shift must clear this many MADs of
#: the within-segment residual noise AND this fraction of |before-mean|.
CUSUM_Z = 4.0
CUSUM_REL_MIN = 0.01
CUSUM_MIN_SEG = 3


def _sha(text: str) -> str:
    return hashlib.sha256(text.encode("utf-8", "replace")).hexdigest()


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def _mad(vals: List[float], med: Optional[float] = None) -> float:
    m = _median(vals) if med is None else med
    return _median([abs(v - m) for v in vals])


def _registered_scalars(rec: dict) -> Tuple[Dict[str, float], int]:
    """The record's gateable scalars: numeric fields whose name has a
    direction in :data:`compare.METRIC_DIRECTIONS` (or a suffix
    default). Everything else is counted, never silently dropped."""
    out: Dict[str, float] = {}
    unregistered = 0
    for key, val in rec.items():
        if isinstance(val, bool) or not isinstance(val, (int, float)):
            continue
        try:
            compare_lib.direction_of(key)
        except KeyError:
            unregistered += 1
            continue
        out[key] = val
    return out, unregistered


def _record(
    label: str, metrics: Dict[str, float], fingerprint: str, *,
    source: str, source_path: str, stale: bool = False,
    run_id: Optional[str] = None, unregistered: int = 0,
    meta: Optional[dict] = None,
) -> dict:
    return {
        "schema": SCHEMA,
        "label": label,
        "fingerprint": fingerprint,
        "run_id": run_id,
        "stale": bool(stale),
        "metrics": metrics,
        "unregistered_metrics": unregistered,
        "source": source,
        "source_path": source_path,
        "meta": meta or {},
    }


# -- per-source record builders ----------------------------------------------


def _capture_fp_str(rec: dict) -> Optional[str]:
    fp = compare_lib.capture_fingerprint(rec)
    if fp is None:
        return None
    return "capture:" + ":".join(str(x) for x in fp)


def record_from_bench(
    rec: dict, *, source_path: str, seen_captures: set,
) -> dict:
    """One bench record → one archive record. The fingerprint is the
    capture identity when stamped, a canonical content hash otherwise
    (pre-stamp legacy records like ``LAST_GOOD_BENCH.json``). A record
    that self-declares ``stale: true`` or re-emits a capture already in
    ``seen_captures`` is flagged STALE — and gets a content-suffixed
    fingerprint so the stale COPY archives as its own excluded record
    instead of dedup-colliding with the fresh original."""
    base = _capture_fp_str(rec) or ("content:" + _sha(
        json.dumps(rec, sort_keys=True))[:16])
    reemitted = base.startswith("capture:") and base in seen_captures
    stale = bool(rec.get("stale")) or reemitted
    if base.startswith("capture:") and not stale:
        seen_captures.add(base)
    fingerprint = base
    if stale:
        fingerprint = base + ":stale:" + _sha(
            json.dumps(rec, sort_keys=True))[:8]
    metrics, unregistered = _registered_scalars(rec)
    meta = {
        k: rec[k]
        for k in ("unit", "captured_date", "captured_round", "hardware",
                  "age_days", "note")
        if k in rec
    }
    if reemitted:
        meta["reemitted_capture"] = True
    return _record(
        str(rec.get("metric") or "bench"), metrics, fingerprint,
        source="bench", source_path=source_path, stale=stale,
        unregistered=unregistered, meta=meta,
    )


def _bench_lines_from_tail(tail: str) -> List[dict]:
    """The driver wrapper's captured stdout: any full line that parses
    as a JSON object with a ``metric`` key is a bench record (the
    ``bench: emitted stale...`` stderr echo does not start with ``{``,
    so the same record is not double-counted)."""
    out: List[dict] = []
    for line in (tail or "").splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and rec.get("metric"):
            out.append(rec)
    return out


def records_from_driver_bench(
    data: dict, *, source_path: str, seen_captures: set,
) -> List[dict]:
    """A ``BENCH_r0N.json`` driver wrapper (``{n, cmd, rc, tail,
    parsed}``). The embedded bench records (``parsed`` when the driver
    parsed one, otherwise JSON lines fished out of ``tail``) archive as
    bench records stamped with the round; a wrapper holding NO bench
    record archives as one empty STALE ``bench_probe`` record — the
    empty trajectory is committed evidence, not a silent gap."""
    parsed = data.get("parsed")
    if isinstance(parsed, dict) and parsed.get("metric"):
        bench_recs = [parsed]
    elif isinstance(parsed, list):
        bench_recs = [r for r in parsed
                      if isinstance(r, dict) and r.get("metric")]
    else:
        bench_recs = _bench_lines_from_tail(data.get("tail", ""))
    rnd = data.get("n")
    if not bench_recs:
        name = os.path.basename(source_path)
        fingerprint = (
            f"driver:{name}:n={rnd}:rc={data.get('rc')}:"
            + _sha(str(data.get("tail", "")))[:12]
        )
        return [_record(
            "bench_probe", {}, fingerprint,
            source="driver_bench", source_path=source_path, stale=True,
            meta={"round": rnd, "rc": data.get("rc"), "empty": True},
        )]
    out = []
    for rec in bench_recs:
        ar = record_from_bench(
            rec, source_path=source_path, seen_captures=seen_captures,
        )
        ar["source"] = "driver_bench"
        ar["meta"]["round"] = rnd
        ar["meta"]["rc"] = data.get("rc")
        out.append(ar)
    return out


def record_from_multichip(data: dict, *, source_path: str) -> dict:
    """A ``MULTICHIP_r0N.json`` driver wrapper (``{n_devices, rc, ok,
    skipped, tail}``) → one pass/fail point on the multichip axis."""
    name = os.path.basename(source_path)
    fingerprint = (
        f"multichip:{name}:" + _sha(json.dumps(data, sort_keys=True))[:12]
    )
    metrics = {"multichip_ok": 1.0 if data.get("ok") else 0.0}
    return _record(
        "multichip_dryrun", metrics, fingerprint,
        source="multichip", source_path=source_path,
        stale=bool(data.get("skipped")),
        meta={"n_devices": data.get("n_devices"), "rc": data.get("rc")},
    )


def record_from_history(path: str) -> dict:
    """A ``--log_file`` JSONL → one archive record over the summarize
    report's scalars. The fingerprint is the stamped capture identity
    (``summarize.capture_stamp`` — a content hash, so re-summarizing
    the same log dedupes)."""
    records, bad = summ.load_records(path)
    if not records:
        raise ValueError(f"no records in {path}")
    report = summ.summarize(records, bad)
    stamp = summ.capture_stamp(path)
    scalars = compare_lib.report_scalars(report)
    metrics = {
        k: v for k, v in scalars.items()
        if not k.startswith("_") and isinstance(v, (int, float))
        and not isinstance(v, bool)
    }
    return _record(
        "history", metrics, "history:" + stamp["fingerprint"],
        source="history", source_path=path, run_id=report.get("run_id"),
        meta={"n_records": len(records), "bad_lines": bad},
    )


def record_from_report(data: dict, *, source_path: str) -> dict:
    """A schema-pinned analysis report (``shard_report`` /
    ``plan_report`` / ``tune_report``): every registered scalar found
    anywhere in the tree archives under the report's schema tag."""
    tag = str(data.get("schema"))
    metrics: Dict[str, float] = {}
    unregistered = 0

    def walk(node):
        nonlocal unregistered
        if isinstance(node, dict):
            found, skipped = _registered_scalars(node)
            unregistered += skipped
            for k, v in found.items():
                metrics.setdefault(k, v)
            for v in node.values():
                walk(v)
        elif isinstance(node, list):
            for v in node:
                walk(v)

    walk(data)
    name = os.path.basename(source_path)
    fingerprint = (
        f"report:{name}:" + _sha(json.dumps(data, sort_keys=True))[:12]
    )
    return _record(
        tag.rsplit("_v", 1)[0], metrics, fingerprint,
        source="report", source_path=source_path,
        unregistered=unregistered, meta={"schema": tag},
    )


def hub_snapshot_record(
    snapshot: dict, *, fingerprint: str, source_path: str = "<hub>",
) -> dict:
    """One :class:`TelemetryHub` collect() snapshot → one archive record
    (``obs hub --archive``): the pod rollups become gateable series, so
    fleet goodput / breach count / chip capacity trend like any bench
    metric. The caller owns the fingerprint (one per scrape interval)."""
    roll = snapshot.get("rollup") or {}
    metrics: Dict[str, float] = {}
    for src, name in (
        ("runs_dead", "pod_runs_dead"),
        ("breach_count", "pod_breach_count"),
        ("total_chips", "pod_total_chips"),
        ("worst_stall_frac", "pod_worst_stall_frac"),
    ):
        v = roll.get(src)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            metrics[name] = v
    for kind, v in (roll.get("goodput_by_kind") or {}).items():
        if kind in ("train", "serve") and isinstance(v, (int, float)):
            metrics[f"pod_goodput_frac_{kind}"] = v
    return _record(
        "pod", metrics, fingerprint,
        source="hub", source_path=source_path,
        meta={
            "scrapes": snapshot.get("scrapes"),
            "runs_aggregated": roll.get("runs_aggregated"),
            "drops": snapshot.get("drops"),
        },
    )


# -- archive file I/O --------------------------------------------------------


def load_archive(path: str) -> Tuple[List[dict], dict]:
    """Torn-tail-tolerant, forward-compat archive loader: returns
    ``(records, counts)`` where counts reports ``bad_lines`` (torn /
    non-JSON), ``skipped_schema`` (lines that are not archive records at
    all), and ``newer_schema`` (``archive_record_v2+`` lines — read by
    their known fields, per the house additive-bump contract)."""
    counts = {"bad_lines": 0, "skipped_schema": 0, "newer_schema": 0}
    records: List[dict] = []
    if not os.path.exists(path):
        return records, counts
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                counts["bad_lines"] += 1
                continue
            if not isinstance(rec, dict):
                counts["bad_lines"] += 1
                continue
            tag = rec.get("schema")
            if not isinstance(tag, str) or \
                    not tag.startswith("archive_record_v"):
                counts["skipped_schema"] += 1
                continue
            try:
                ver = int(tag.rsplit("v", 1)[1])
            except ValueError:
                counts["skipped_schema"] += 1
                continue
            if ver > SCHEMA_VERSION:
                counts["newer_schema"] += 1
            records.append(rec)
    return records, counts


def append_records(path: str, records: List[dict]) -> None:
    """Append-only write, healing a torn tail first: if the file does
    not end in a newline (the previous writer died mid-line), a newline
    is inserted so the torn fragment stays isolated on its own line
    (counted by the loader) instead of corrupting the first new record."""
    if not records:
        return
    needs_nl = False
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            if f.tell():
                f.seek(-1, os.SEEK_END)
                needs_nl = f.read(1) != b"\n"
    except OSError:
        needs_nl = False
    payload = "".join(json.dumps(r, sort_keys=True) + "\n" for r in records)
    # tpu-dist: ignore[TD002] — the archive is appended by the single
    # ingest/CLI/hub process that owns the file, not by training ranks
    with open(path, "a") as f:
        if needs_nl:
            f.write("\n")
        f.write(payload)


# -- ingest ------------------------------------------------------------------


def _classify_json(data) -> str:
    if isinstance(data, dict):
        if data.get("metric"):
            return "bench"
        if "parsed" in data and "rc" in data and "cmd" in data:
            return "driver_bench"
        if "n_devices" in data and "rc" in data and "ok" in data:
            return "multichip"
        tag = data.get("schema")
        if isinstance(tag, str) and tag.startswith(
            ("shard_report", "plan_report", "tune_report")
        ):
            return "report"
    raise ValueError("unrecognized JSON artifact shape")


def records_from_path(path: str, *, seen_captures: set) -> List[dict]:
    """Classify one input artifact and build its archive record(s).
    Raises OSError on an unreadable file and ValueError on a shape no
    ingester recognizes — the CLI maps both to exit 2."""
    with open(path) as f:
        text = f.read()
    try:
        data = json.loads(text)
        whole = True
    except json.JSONDecodeError:
        whole = False
    if whole:
        kind = _classify_json(data)
        if kind == "bench":
            return [record_from_bench(
                data, source_path=path, seen_captures=seen_captures,
            )]
        if kind == "driver_bench":
            return records_from_driver_bench(
                data, source_path=path, seen_captures=seen_captures,
            )
        if kind == "multichip":
            return [record_from_multichip(data, source_path=path)]
        return [record_from_report(data, source_path=path)]
    # JSONL: a history (kind-keyed) or a bench stream (metric-keyed)
    kinds = 0
    metrics = 0
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if not isinstance(rec, dict):
            continue
        if rec.get("kind"):
            kinds += 1
        elif rec.get("metric"):
            metrics += 1
    if kinds:
        return [record_from_history(path)]
    if metrics:
        return [
            record_from_bench(
                rec, source_path=path, seen_captures=seen_captures,
            )
            for rec in compare_lib._load_bench_list(path)
        ]
    raise ValueError(f"{path}: neither a JSON artifact nor a JSONL "
                     "history/bench stream this ingester recognizes")


def ingest_records(
    records: List[dict], archive_path: str, *, source_path: str = "<api>",
) -> dict:
    """Ingest already-loaded bench records (the ``bench.py --archive``
    self-ingest path). Same idempotence as :func:`ingest_paths`."""
    existing, counts = load_archive(archive_path)
    known = {r.get("fingerprint") for r in existing}
    by_fp = {r.get("fingerprint"): r for r in existing}
    seen_captures = _archived_captures(existing)
    fresh: List[dict] = []
    deduped = 0
    for rec in records:
        ar = record_from_bench(
            rec, source_path=source_path, seen_captures=seen_captures,
        )
        if _dedupe_or_keep(ar, known, by_fp):
            deduped += 1
            continue
        fresh.append(ar)
    _assign_seq(existing, fresh)
    append_records(archive_path, fresh)
    return {
        "archive": archive_path, "appended": len(fresh),
        "deduped": deduped, "records_seen": len(records), **counts,
    }


def _is_rearchival(ar: dict, archived: Optional[dict]) -> bool:
    """A record flagged as a capture re-emission that is actually a
    byte-equivalent RE-INGEST of the archived fresh record (same label,
    metrics, provenance) is a dedupe, not a stale copy — otherwise
    ingest idempotence would mint a spurious STALE record per pass."""
    if archived is None or archived.get("stale"):
        return False
    meta = {k: v for k, v in (ar.get("meta") or {}).items()
            if k != "reemitted_capture"}
    return (
        ar.get("label") == archived.get("label")
        and ar.get("metrics") == archived.get("metrics")
        and ar.get("unregistered_metrics")
        == archived.get("unregistered_metrics")
        and meta == (archived.get("meta") or {})
    )


def _dedupe_or_keep(
    ar: dict, known: set, by_fp: Dict[str, dict],
) -> bool:
    """True when ``ar`` is already archived (by fingerprint, or as the
    fresh original a flagged re-emission byte-matches)."""
    fp = ar["fingerprint"]
    if fp in known:
        return True
    if ar.get("meta", {}).get("reemitted_capture") and _is_rearchival(
        ar, by_fp.get(fp.split(":stale:")[0])
    ):
        return True
    known.add(fp)
    by_fp[fp] = ar
    return False


def _archived_captures(existing: List[dict]) -> set:
    out = set()
    for r in existing:
        fp = r.get("fingerprint")
        if isinstance(fp, str) and fp.startswith("capture:"):
            # strip any :stale:<hash> suffix back to the capture identity
            out.add(fp.split(":stale:")[0])
    return out


def _assign_seq(existing: List[dict], fresh: List[dict]) -> None:
    nxt = 1 + max(
        [r.get("seq", 0) for r in existing
         if isinstance(r.get("seq"), int)] + [len(existing)],
    ) if existing else 1
    for i, r in enumerate(fresh):
        r["seq"] = nxt + i


def ingest_paths(
    paths: List[str], archive_path: str,
) -> dict:
    """The ``archive ingest`` engine: classify every input, build its
    records, drop the ones whose fingerprint is already archived
    (idempotence), append the rest. Per-input accounting in the report —
    an input that fails to read or classify raises (exit 2 at the CLI);
    nothing is half-appended before the error because the append is one
    batch at the end."""
    existing, counts = load_archive(archive_path)
    known = {r.get("fingerprint") for r in existing}
    by_fp = {r.get("fingerprint"): r for r in existing}
    seen_captures = _archived_captures(existing)
    fresh: List[dict] = []
    inputs = []
    deduped = 0
    seen_total = 0
    for path in paths:
        recs = records_from_path(path, seen_captures=seen_captures)
        added = 0
        for ar in recs:
            seen_total += 1
            if _dedupe_or_keep(ar, known, by_fp):
                deduped += 1
                continue
            fresh.append(ar)
            added += 1
        inputs.append({
            "path": path, "records": len(recs), "appended": added,
            "stale": sum(1 for r in recs if r.get("stale")),
        })
    _assign_seq(existing, fresh)
    append_records(archive_path, fresh)
    return {
        "archive": archive_path, "inputs": inputs,
        "records_seen": seen_total, "appended": len(fresh),
        "deduped": deduped,
        "stale_appended": sum(1 for r in fresh if r.get("stale")),
        **counts,
    }


def format_ingest_text(report: dict) -> str:
    lines = [
        f"archive {report['archive']}: {report['appended']} appended"
        + (f" ({report['stale_appended']} STALE)"
           if report.get("stale_appended") else "")
        + (f", {report['deduped']} already archived (deduped)"
           if report["deduped"] else "")
        + (f", {report['bad_lines']} torn line(s)"
           if report.get("bad_lines") else "")
        + (f", {report['newer_schema']} newer-schema record(s) read"
           if report.get("newer_schema") else "")
    ]
    for i in report.get("inputs", []):
        lines.append(
            f"  {i['path']}: {i['records']} record(s), "
            f"{i['appended']} appended"
            + (f", {i['stale']} STALE" if i["stale"] else "")
        )
    return "\n".join(lines)


# -- MAD-band gating ---------------------------------------------------------


def band_for(
    records: List[dict], label: str, metric: str, *,
    window: int = DEFAULT_WINDOW,
) -> Optional[dict]:
    """The rolling band: median and MAD over the last ``window``
    non-stale archived values of (label, metric). None when no fresh
    record carries it."""
    vals = [
        r["metrics"][metric]
        for r in records
        if not r.get("stale") and r.get("label") == label
        and isinstance(r.get("metrics"), dict)
        and isinstance(r["metrics"].get(metric), (int, float))
        and not isinstance(r["metrics"].get(metric), bool)
    ]
    vals = vals[-window:]
    if not vals:
        return None
    med = _median(vals)
    return {"n": len(vals), "median": med, "mad": _mad(vals, med)}


def _has_stale(records: List[dict], label: str, metric: str) -> bool:
    return any(
        r.get("stale") and r.get("label") == label
        and isinstance(r.get("metrics"), dict)
        and metric in r["metrics"]
        for r in records
    )


def _gate_row(
    name: str, label: str, metric: str, cand, records: List[dict], *,
    k: float, window: int, rel_floor: float, cand_stale: bool = False,
) -> dict:
    if cand_stale:
        return {"metric": name, "baseline": "band", "candidate":
                "stale capture", "verdict": "STALE"}
    if not isinstance(cand, (int, float)) or isinstance(cand, bool):
        return {"metric": name, "baseline": "band", "candidate": cand,
                "verdict": "skipped"}
    b = band_for(records, label, metric, window=window)
    if b is None:
        if _has_stale(records, label, metric):
            # every archived point for this metric is a stale
            # re-emission — there is no band, and pretending the stale
            # numbers are one would be exactly the wound this archive
            # exists to close
            return {"metric": name, "baseline": "all archived records "
                    "STALE", "candidate": cand, "verdict": "STALE"}
        return {"metric": name, "baseline": None, "candidate": cand,
                "verdict": "skipped"}
    direction, slack = compare_lib.direction_of(metric)
    med, mad = b["median"], b["mad"]
    allowed = max(k * mad, rel_floor * abs(med)) + slack
    worse_by = (med - cand) if direction == "higher" else (cand - med)
    row = {
        "metric": name,
        "baseline": med,
        "candidate": cand,
        "band_n": b["n"],
        "mad": round(mad, 6),
        "allowed": round(allowed, 6),
        "delta": round(cand - med, 6),
        "verdict": "REGRESSED" if worse_by > allowed else "ok",
    }
    if med:
        row["delta_frac"] = round((cand - med) / abs(med), 4)
    return row


def gate_candidate(
    records: List[dict], candidate: str, *, bench: bool = False,
    k: float = DEFAULT_K, window: int = DEFAULT_WINDOW,
    rel_floor: float = REL_FLOOR,
) -> dict:
    """Gate a candidate artifact against the archive's rolling bands.

    ``bench=True``: the candidate is a bench JSONL — each record's
    registered fields gate against the (metric-label, field) band; a
    candidate record that self-declares stale or re-emits an archived
    capture fingerprint is a STALE row, never compared. Otherwise the
    candidate is a ``--log_file`` history gating its summarize scalars
    against the ``history`` label's bands."""
    rows: List[dict] = []
    archived_caps = _archived_captures(records)
    if bench:
        cand_map = compare_lib.load_bench_records(candidate)
        for name in sorted(cand_map):
            rec = cand_map[name]
            cap = _capture_fp_str(rec)
            cand_stale = bool(rec.get("stale")) or (
                cap is not None and cap in archived_caps
            )
            fields, _skipped = _registered_scalars(rec)
            if cand_stale:
                rows.append(_gate_row(
                    name, name, "value", None, records,
                    k=k, window=window, rel_floor=rel_floor,
                    cand_stale=True,
                ))
                continue
            for field in sorted(fields):
                rows.append(_gate_row(
                    f"{name}.{field}", name, field, fields[field],
                    records, k=k, window=window, rel_floor=rel_floor,
                ))
    else:
        scalars = compare_lib.load_history_scalars(candidate)
        for key in sorted(scalars):
            if key.startswith("_"):
                continue
            rows.append(_gate_row(
                key, "history", key, scalars[key], records,
                k=k, window=window, rel_floor=rel_floor,
            ))
    result = compare_lib._result(rows, threshold=rel_floor)
    result.update(band_k=k, band_window=window, candidate=candidate)
    return result


def gate_files(
    archive_path: str, candidate: str, *, bench: bool = False,
    k: float = DEFAULT_K, window: int = DEFAULT_WINDOW,
    rel_floor: float = REL_FLOOR,
) -> dict:
    """CLI engine for ``obs compare --against-archive``. Raises OSError
    on an unreadable file, ValueError on an empty archive — both exit 2
    at the CLI (a gate with no archive is broken, not passing)."""
    records, counts = load_archive(archive_path)
    if not records:
        raise ValueError(f"no archive records in {archive_path}")
    result = gate_candidate(
        records, candidate, bench=bench, k=k, window=window,
        rel_floor=rel_floor,
    )
    result["archive"] = archive_path
    result["archive_records"] = len(records)
    result["archive_counts"] = counts
    return result


def format_gate_text(result: dict) -> str:
    lines = [
        f"archive gate: candidate {result['candidate']} vs "
        f"{result['archive']} ({result['archive_records']} record(s), "
        f"band median ± max({result['band_k']:g}·MAD, "
        f"{result['threshold'] * 100:g}%·|median|) + slack, "
        f"window {result['band_window']})"
    ]
    w = max([len(r["metric"]) for r in result["rows"]] + [6])

    def cell(v):
        if isinstance(v, float):
            return format(v, ".6g").rjust(12)
        return str(v if v is not None else "-").rjust(12)

    lines.append(
        f"  {'metric'.ljust(w)} {'band median':>12} {'candidate':>12} "
        f"{'allowed':>10} {'n':>3}  verdict"
    )
    for r in result["rows"]:
        lines.append(
            f"  {r['metric'].ljust(w)} {cell(r.get('baseline'))} "
            f"{cell(r.get('candidate'))} "
            f"{cell(r.get('allowed'))[-10:]:>10} "
            f"{str(r.get('band_n', '-')):>3}  {r['verdict']}"
        )
    lines.append(
        f"archive gate: {result['regressions']} regression(s) over "
        f"{result['compared']} compared metric(s)"
        + (f", {result['skipped']} skipped" if result["skipped"] else "")
        + (f", {result['stale']} STALE" if result.get("stale") else "")
    )
    return "\n".join(lines)


# -- trend + changepoint blame -----------------------------------------------


def detect_changepoint(
    values: List[float], *, min_seg: int = CUSUM_MIN_SEG,
    z: float = CUSUM_Z, rel_min: float = CUSUM_REL_MIN,
) -> Optional[dict]:
    """Offline CUSUM split: the candidate changepoint is the index
    maximizing |cumulative deviation from the global mean|; it is
    accepted when the segment-mean shift clears ``z`` MADs of the
    within-segment residual noise AND ``rel_min`` of |before-mean| (so
    float dust on a flat series never flags). Returns ``{"index": i,
    ...}`` where ``i`` is the FIRST index of the shifted segment."""
    m = len(values)
    if m < 2 * min_seg:
        return None
    mean_all = sum(values) / m
    s = 0.0
    best_t: Optional[int] = None
    best = 0.0
    for t in range(m - 1):
        s += values[t] - mean_all
        if min_seg - 1 <= t <= m - min_seg - 1 and abs(s) > best:
            best, best_t = abs(s), t
    if best_t is None:
        return None
    before, after = values[:best_t + 1], values[best_t + 1:]
    mb = sum(before) / len(before)
    ma = sum(after) / len(after)
    resid = [v - mb for v in before] + [v - ma for v in after]
    noise = _mad(resid)
    shift = abs(ma - mb)
    if shift <= z * noise or shift <= rel_min * abs(mb):
        return None
    return {
        "index": best_t + 1,
        "before_mean": round(mb, 6),
        "after_mean": round(ma, 6),
        "shift": round(ma - mb, 6),
        "n_before": len(before),
        "n_after": len(after),
    }


def trend_report(
    records: List[dict], *, metric: Optional[str] = None,
    window: Optional[int] = None,
) -> dict:
    """Per-(label, metric) series in archive order (non-stale points
    only — stale re-emissions are counted, never plotted as data), each
    with its changepoint verdict and, when one fired, the BLAME: the
    first archived record after the shift, by fingerprint + run_id +
    source path. ``metric`` filters by metric name; ``window`` keeps
    only the trailing points."""
    by_key: Dict[Tuple[str, str], List[dict]] = {}
    n_stale: Dict[Tuple[str, str], int] = {}
    for r in records:
        label = r.get("label")
        mets = r.get("metrics")
        if not isinstance(mets, dict):
            continue
        for name, val in mets.items():
            if metric is not None and name != metric:
                continue
            if isinstance(val, bool) or not isinstance(val, (int, float)):
                continue
            key = (str(label), name)
            if r.get("stale"):
                n_stale[key] = n_stale.get(key, 0) + 1
                continue
            by_key.setdefault(key, []).append({
                "seq": r.get("seq"),
                "value": val,
                "fingerprint": r.get("fingerprint"),
                "run_id": r.get("run_id"),
                "source_path": r.get("source_path"),
            })
    series = []
    for (label, name), points in sorted(by_key.items()):
        if window:
            points = points[-window:]
        values = [p["value"] for p in points]
        cp = detect_changepoint(values)
        entry = {
            "label": label,
            "metric": name,
            "n": len(points),
            "n_stale": n_stale.get((label, name), 0),
            "values": values,
            "points": points,
            "changepoint": cp,
        }
        if cp is not None:
            try:
                direction, _slack = compare_lib.direction_of(name)
                worse = (cp["shift"] < 0) if direction == "higher" \
                    else (cp["shift"] > 0)
                cp["kind"] = "regressed" if worse else "improved"
            except KeyError:
                cp["kind"] = "shifted"
            cp["blame"] = points[cp["index"]]
        series.append(entry)
    # stale-only metrics still show up (counted), so an archive of pure
    # re-emissions renders as "no fresh data", never as an empty page
    for key, count in sorted(n_stale.items()):
        if key not in by_key:
            series.append({
                "label": key[0], "metric": key[1], "n": 0,
                "n_stale": count, "values": [], "points": [],
                "changepoint": None,
            })
    return {"series": series, "n_records": len(records)}


def format_trend_text(report: dict, *, blame: bool = False) -> str:
    lines = [f"trend over {report['n_records']} archived record(s):"]
    for s in report["series"]:
        head = f"  {s['label']}.{s['metric']}: {s['n']} point(s)"
        if s["n_stale"]:
            head += f" (+{s['n_stale']} STALE excluded)"
        if s["values"]:
            vmin, vmax = min(s["values"]), max(s["values"])
            last = s["values"][-1]
            head += (f"  min {vmin:.6g}  max {vmax:.6g}  last {last:.6g}")
        lines.append(head)
        cp = s.get("changepoint")
        if cp is not None:
            lines.append(
                f"    changepoint [{cp.get('kind', 'shifted')}] at point "
                f"{cp['index']}: mean {cp['before_mean']:.6g} -> "
                f"{cp['after_mean']:.6g} (shift {cp['shift']:+.6g})"
            )
            if blame:
                b = cp["blame"]
                lines.append(
                    "    blame: first shifted record is "
                    f"fingerprint {b.get('fingerprint')} "
                    f"(run_id {b.get('run_id')}, seq {b.get('seq')}, "
                    f"source {b.get('source_path')})"
                )
    return "\n".join(lines)


# -- the TD124 injected-fault probe ------------------------------------------


def inject_probe(
    records: List[dict], *, k: float = DEFAULT_K,
    window: int = DEFAULT_WINDOW, rel_floor: float = REL_FLOOR,
    max_bands: int = 8,
) -> dict:
    """The ``--inject-regression`` probe (TD124): against the archive's
    own bands, a synthetic candidate pushed past the allowance in the
    WORSE direction must come back REGRESSED and one pushed the same
    distance in the BETTER direction must come back clean; against a
    synthetic flat series with one injected step, the changepoint
    detector must localize the exact record. A detector that misses any
    of the three is DEAD — the CLI maps that to exit 2."""
    bands: List[dict] = []
    seen_keys: set = set()
    for r in records:
        if r.get("stale") or not isinstance(r.get("metrics"), dict):
            continue
        for name in r["metrics"]:
            key = (r.get("label"), name)
            if key in seen_keys:
                continue
            seen_keys.add(key)
            b = band_for(records, key[0], name, window=window)
            if b is not None:
                bands.append({"label": key[0], "metric": name, **b})
    bands = bands[:max_bands]
    gate_results = []
    missed = flagged_improvement = 0
    for b in bands:
        direction, slack = compare_lib.direction_of(b["metric"])
        allowed = max(k * b["mad"], rel_floor * abs(b["median"])) + slack
        delta = allowed + max(0.05 * abs(b["median"]), 1e-6)
        sign = -1.0 if direction == "higher" else 1.0
        worse = b["median"] + sign * delta
        better = b["median"] - sign * delta
        row_worse = _gate_row(
            b["metric"], b["label"], b["metric"], worse, records,
            k=k, window=window, rel_floor=rel_floor,
        )
        row_better = _gate_row(
            b["metric"], b["label"], b["metric"], better, records,
            k=k, window=window, rel_floor=rel_floor,
        )
        caught = row_worse["verdict"] == "REGRESSED"
        clean = row_better["verdict"] == "ok"
        missed += not caught
        flagged_improvement += not clean
        gate_results.append({
            "label": b["label"], "metric": b["metric"],
            "injected_worse": worse, "injected_better": better,
            "caught": caught, "improvement_clean": clean,
        })
    # synthetic changepoint: 8 flat points, then a 10% step down —
    # the detector must name index 8's record, exactly
    step_at = 8
    synth_records = []
    for i in range(step_at + 6):
        v = 100.0 if i < step_at else 90.0
        synth_records.append(_record(
            "synthetic", {"value": v}, f"synthetic:{i}",
            source="probe", source_path="<inject-probe>",
        ))
        synth_records[-1]["seq"] = i
    synth_trend = trend_report(synth_records, metric="value")
    cp = synth_trend["series"][0]["changepoint"] if \
        synth_trend["series"] else None
    localized = (
        cp is not None and cp["index"] == step_at
        and cp.get("blame", {}).get("fingerprint") == f"synthetic:{step_at}"
        and cp.get("kind") == "regressed"
    )
    return {
        "bands_probed": len(bands),
        "gate_probe": (
            "caught" if bands and not missed else
            "dead" if bands else "no-bands"
        ),
        "improvements_clean": not flagged_improvement,
        "changepoint_probe": "localized" if localized else "dead",
        "changepoint": cp,
        "gate_results": gate_results,
    }


def format_probe_text(probe: dict) -> str:
    lines = [
        f"inject-regression probe: {probe['bands_probed']} band(s) — "
        f"gate {probe['gate_probe']}, improvements "
        f"{'clean' if probe['improvements_clean'] else 'WRONGLY FLAGGED'}"
        f", changepoint {probe['changepoint_probe']}"
    ]
    for g in probe["gate_results"]:
        lines.append(
            f"  {g['label']}.{g['metric']}: injected "
            f"{g['injected_worse']:.6g} -> "
            f"{'caught' if g['caught'] else 'MISSED'}; improvement "
            f"{g['injected_better']:.6g} -> "
            f"{'clean' if g['improvement_clean'] else 'FLAGGED'}"
        )
    return "\n".join(lines)


def probe_is_dead(probe: dict) -> bool:
    """True when any leg of the injected-fault probe failed — the
    archive gate or the changepoint detector would silently pass real
    regressions (exit 2 at the CLI; a TD124 violation in the audit)."""
    return (
        probe["gate_probe"] != "caught"
        or not probe["improvements_clean"]
        or probe["changepoint_probe"] != "localized"
    )


# -- hub integration ---------------------------------------------------------


def append_hub_snapshot(
    path: str, snapshot: dict, *, now: Optional[float] = None,
) -> dict:
    """Append one pod-rollup record per hub interval (``obs hub
    --archive``): the fingerprint is host+pid+scrape-count(+time), so a
    looped hub archives one record per pass and a restarted hub never
    collides with its predecessor's lines."""
    import socket
    import time as time_lib

    t = time_lib.time() if now is None else now
    fingerprint = (
        f"hub:{socket.gethostname()}:{os.getpid()}:"
        f"{snapshot.get('scrapes', 0)}:{t:.3f}"
    )
    rec = hub_snapshot_record(
        snapshot, fingerprint=fingerprint, source_path=path,
    )
    rec["meta"]["time"] = round(t, 3)
    existing, _counts = load_archive(path)
    _assign_seq(existing, [rec])
    append_records(path, [rec])
    return rec
