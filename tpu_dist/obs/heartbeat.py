"""Per-process heartbeat files — liveness signal for external watchdogs
(``docs/observability.md``).

A pod orchestrator watching a training job from outside cannot tell a HUNG
step (deadlocked collective, dead loader producer) from a SLOW one (big
compile, cold cache) by looking at the process table — both look like a
silent process. The heartbeat file answers it: every process rewrites its
own small JSON file at the step grain (rank 0 the bare ``--heartbeat_file``
path, rank k ``.h<k>`` — liveness is per-host) with a strictly monotonic
beat counter plus the (epoch, step) position; a watchdog that sees a
counter stop advancing for N× the recent step time knows that host is
wedged, not slow.

Discipline:

* **Atomic** — write-to-temp + ``os.replace``, so a reader never sees a
  torn file (same discipline as the checkpoint writers).
* **Throttled** — ``min_interval`` caps the write rate (default 1 s) so a
  fast step loop costs at most one small write per interval; position
  changes that MUST land (preemption observed, epoch boundaries, sweep)
  pass ``force=True``.
* **Swept on clean exit** — a leftover heartbeat means the process died;
  its absence after exit is itself the "ended cleanly" signal.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

from tpu_dist.obs import counters


class Heartbeat:
    """One writer per file (the trainer derives one path per process)."""

    def __init__(self, path: str, min_interval: float = 1.0):
        self.path = path
        self.min_interval = min_interval
        self.counter = 0
        self._last_write = float("-inf")  # last ATTEMPT (drives the throttle)
        self._last_ok = float("-inf")     # last write that LANDED (drives age)
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)

    def beat(
        self,
        *,
        epoch: Optional[int] = None,
        step: Optional[int] = None,
        phase: str = "train",
        force: bool = False,
    ) -> bool:
        """Advance the beat counter; write the file unless inside the
        throttle window (``force`` bypasses it). Returns True when the
        file was (re)written. Never raises on I/O: a full/absent disk must
        not kill the training step that beats."""
        self.counter += 1
        counters.inc("heartbeat.beats")
        now = time.monotonic()
        if not force and now - self._last_write < self.min_interval:
            return False
        self._last_write = now
        payload = {
            "counter": self.counter,
            "epoch": epoch,
            "step": step,
            "phase": phase,
            "ts": round(time.time(), 3),
            "mono_s": round(now, 3),
            "pid": os.getpid(),
        }
        tmp = self.path + ".tmp"
        try:
            # tpu-dist: ignore[TD002,TD007] — deliberately per-process
            # I/O: each rank owns its own derived heartbeat path, so this
            # never needs the rank-0 guard the lint looks for
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, self.path)
        except OSError:
            counters.inc("heartbeat.write_errors")
            return False
        self._last_ok = now
        return True

    def age(self) -> float:
        """Seconds since this writer's last beat LANDED on disk
        (monotonic) — the exporter publishes it as ``heartbeat.age_s``.
        Failed writes (full disk) do not reset it: the age must track the
        file an external watchdog reads, not our attempts. ``inf``
        before the first successful write."""
        last = self._last_ok
        return float("inf") if last == float("-inf") else time.monotonic() - last

    def sweep(self) -> None:
        """Remove the file — clean-exit signal. Best-effort by design."""
        for p in (self.path, self.path + ".tmp"):
            try:
                os.remove(p)
            except FileNotFoundError:
                pass
        _LAST_GOOD.pop(self.path, None)


def per_rank_path(base: str, rank: int) -> str:
    """The shared per-rank file naming (``--per_host_log`` and heartbeat
    alike): rank 0 keeps the bare path, rank k appends ``.h<k>``. ONE
    definition — the launcher's watchdog and ``obs pod`` read exactly the
    scheme the trainer writes, so the three sites can never drift."""
    return base if rank == 0 else f"{base}.h{rank}"


def sweep_stale_ranks(base: str, world: int) -> int:
    """Remove per-rank derived files (``base.h<k>``, plus their
    ``.tmp``) for ranks OUTSIDE the current world (``k >= world``).

    The elastic-resize hole this closes (docs/resilience.md "Elastic
    training"): after a shrink (8→4), ranks 4-7's heartbeat/metrics
    files from the departed world linger on disk — the watchdog would
    read their frozen counters and ``obs pod`` would row them as dead
    workers, when they are simply no longer part of the run. The
    launcher calls this for every injected base path before spawning a
    round. Returns the number of files removed; best-effort (a racing
    unlink is fine — the file being gone IS the goal)."""
    d = os.path.dirname(os.path.abspath(base))
    name = os.path.basename(base)
    prefix = name + ".h"
    removed = 0
    try:
        entries = os.listdir(d)
    except OSError:
        return 0
    for entry in entries:
        if not entry.startswith(prefix):
            continue
        suffix = entry[len(prefix):]
        core = suffix[:-4] if suffix.endswith(".tmp") else suffix
        if core.isdigit() and int(core) >= world:
            try:
                os.remove(os.path.join(d, entry))
                removed += 1
            except OSError:  # tpu-dist: ignore[TD006] — racing unlink:
                pass  # the file being gone is exactly the goal
    return removed


# last successfully parsed beat per path: the torn-read fallback below.
# Process-local by design — each watchdog process keeps its own view.
_LAST_GOOD: dict = {}


def read(path: str) -> Optional[dict]:
    """Watchdog-side read; None when absent (clean exit or not started).

    Torn-read hardening: ``os.replace`` is atomic on POSIX local
    filesystems, but on NFS (and some overlay mounts) a reader racing
    the replace can observe a truncated/partial file. A beat that fails
    to parse is NOT a dead worker — so instead of reporting None (which
    a watchdog reads as "exited"), return the PREVIOUS good parse for
    this path and count it (``heartbeat.torn_reads``). A genuinely
    absent file still returns None and forgets the cache: absence is the
    clean-exit signal and must not be masked by a stale beat."""
    try:
        with open(path) as f:
            rec = json.load(f)
    except FileNotFoundError:
        _LAST_GOOD.pop(path, None)
        return None
    except (json.JSONDecodeError, OSError):
        counters.inc("heartbeat.torn_reads")
        return _LAST_GOOD.get(path)
    if isinstance(rec, dict):
        _LAST_GOOD[path] = rec
        return rec
    counters.inc("heartbeat.torn_reads")
    return _LAST_GOOD.get(path)
