"""The postmortem drill — ``make postmortem-drill`` / ``python -m
tpu_dist.obs.drill``.

The end-to-end proof of the crash-forensics chain
(docs/observability.md "Crash forensics"), self-contained on
CPU-emulated devices:

1. **Wedge** — a REAL trainer (``vit_tiny``, synthetic data) runs under
   the REAL launcher with the full forensic kit injected
   (``--heartbeat_dir`` + ``--metrics_dir`` + ``--crash_dir`` +
   watchdog flags) and a deterministic ``hang@epoch=E:step=S`` fault:
   at that step the rank stops beating but stays alive — the failure
   mode no exit code ever reports.
2. **Detect + capture** — the launcher watchdog notices the frozen beat
   counter, sends ``SIGUSR1`` (the rank's registered faulthandler dump
   fires, naming the hang site), waits for the dump, THEN escalates
   SIGTERM→SIGKILL — and auto-invokes the postmortem assembler.
3. **Verify** — the launcher exited nonzero-and-not-75 (a wedge is a
   crash, never a requeue), its stderr names the wedged worker AND the
   stuck frame, the bundle's decoded flight ring ends exactly at the
   wedged step, the stack dump's current thread sits in the hang loop,
   and the ``postmortem`` record (history schema v9) landed in the
   run's JSONL where ``obs tail``/``summarize``/``pod`` render it.

One subprocess round, one wedged rank — the multi-rank wedge semantics
(healthy ranks torn down by the fail-fast SIGTERM) are covered by the
launcher watchdog tests; this drill proves the forensic CHAIN.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import List, Optional, Sequence

from tpu_dist.obs import flight as flight_lib
from tpu_dist.obs import postmortem as postmortem_lib


def _say(msg: str) -> None:
    # tpu-dist: ignore[TD002,TD007] — single-process CLI; stdout is the report
    print(f"postmortem-drill: {msg}", flush=True)


def _fail(msg: str) -> int:
    _say(f"FAIL: {msg}")
    return 1


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tpu_dist.obs.drill",
        description="hang -> watchdog -> SIGUSR1 dump -> postmortem drill "
                    "(CPU)",
    )
    p.add_argument("--workdir", required=True, help="scratch dir")
    p.add_argument("--devices", type=int, default=4)
    p.add_argument("--model", default="vit_tiny")
    p.add_argument("--steps_per_epoch", type=int, default=6)
    p.add_argument("--batch_size", type=int, default=64)
    p.add_argument("--hang_epoch", type=int, default=0)
    p.add_argument("--hang_step", type=int, default=3)
    p.add_argument(
        "--watchdog_timeout", type=float, default=10.0,
        help="must exceed the cold-compile stall of --model on this host "
             "(vit_tiny compiles in ~2s on CPU; raise for bigger models)",
    )
    p.add_argument("--watchdog_dump_grace", type=float, default=6.0)
    p.add_argument("--watchdog_grace", type=float, default=3.0)
    p.add_argument(
        "--round_timeout", type=float, default=600.0,
        help="hard cap on the whole launcher round — the drill must "
             "never itself wedge the CI job that runs it",
    )
    args = p.parse_args(argv)

    os.makedirs(args.workdir, exist_ok=True)
    log = os.path.join(args.workdir, "run.jsonl")
    fault = f"hang@epoch={args.hang_epoch}:step={args.hang_step}"
    launch_cmd = [
        sys.executable, "-m", "tpu_dist.cli.launch",
        "--nproc", "1", "--devices_per_proc", str(args.devices),
        "--heartbeat_dir", args.workdir,
        "--metrics_dir", args.workdir,
        "--crash_dir", args.workdir,
        "--watchdog_timeout", str(args.watchdog_timeout),
        "--watchdog_dump_grace", str(args.watchdog_dump_grace),
        "--watchdog_grace", str(args.watchdog_grace),
        "--",
        sys.executable, "-m", "tpu_dist.cli.train",
        "--dataset", "synthetic", "--model", args.model,
        "--num_classes", "10",
        "--batch_size", str(args.batch_size),
        "--epochs", "2", "--steps_per_epoch", str(args.steps_per_epoch),
        "--synthetic_n", str(4 * args.batch_size),
        "--seed", "0", "--eval_every", "0", "--log_every", "2",
        "--log_file", log,
        "--fault_plan", fault,
    ]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""  # skip TPU plugin registration
    _say(f"wedging a real {args.model} run with {fault!r} under the "
         f"watchdog (timeout {args.watchdog_timeout:.0f}s)")
    try:
        proc = subprocess.run(
            launch_cmd, env=env, timeout=args.round_timeout,
            capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        return _fail(
            f"launcher round exceeded {args.round_timeout:.0f}s — the "
            "watchdog never fired (is --watchdog_timeout sized right?)"
        )
    sys.stderr.write(proc.stderr)
    _say(f"launcher exit {proc.returncode}")

    failures: List[str] = []
    if proc.returncode in (0, 75):
        failures.append(
            f"launcher exited {proc.returncode} — a wedge must be a "
            "crash, never clean / requeue-75"
        )
    if "WATCHDOG: worker 0 wedged" not in proc.stderr:
        failures.append("watchdog never reported the wedged worker")
    if "stack dump: stuck in" not in proc.stderr:
        failures.append(
            "watchdog did not name the stuck frame from the SIGUSR1 dump"
        )
    if "postmortem bundle written" not in proc.stderr:
        failures.append("watchdog did not auto-invoke the postmortem")

    bundle_path = os.path.join(args.workdir, postmortem_lib.BUNDLE_NAME)
    if not os.path.exists(bundle_path):
        failures.append(f"no bundle at {bundle_path}")
    else:
        with open(bundle_path) as f:
            bundle = json.load(f)
        rank0 = next(
            (r for r in bundle.get("ranks", []) if r.get("rank") == 0), None
        )
        if rank0 is None:
            failures.append("bundle holds no rank-0 report")
        else:
            if rank0.get("verdict") != "no-clean-exit":
                failures.append(
                    f"rank-0 verdict {rank0.get('verdict')!r}, expected "
                    "'no-clean-exit' (the hard-kill signature)"
                )
            ls = (rank0.get("flight") or {}).get("last_step") or {}
            if (ls.get("epoch"), ls.get("step")) != (
                args.hang_epoch, args.hang_step
            ):
                failures.append(
                    f"flight ring ends at epoch {ls.get('epoch')} step "
                    f"{ls.get('step')}, expected the wedged step "
                    f"({args.hang_epoch}, {args.hang_step})"
                )
            else:
                _say(
                    f"flight ring ends at the wedged step (epoch "
                    f"{ls.get('epoch')}, step {ls.get('step')}) ✓"
                )
            stuck = (rank0.get("stack") or {}).get("stuck_frame") or ""
            if "_hang" not in stuck and "on_step" not in stuck:
                failures.append(
                    f"stack dump names {stuck!r}, expected the hang site "
                    "(faults._hang / faults.on_step)"
                )
            else:
                _say(f"stack dump names the hang site: {stuck} ✓")

    # the crash must be renderable from the run's own log (schema v9)
    pm_recs = []
    try:
        with open(log) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # the dead writer's torn tail — expected here
                if isinstance(rec, dict) and rec.get("kind") == "postmortem":
                    pm_recs.append(rec)
    except OSError:
        failures.append(f"run log {log} unreadable")
    if not pm_recs:
        failures.append(
            "no 'postmortem' record in the run's JSONL — the watchdog's "
            "annotate step did not land"
        )
    else:
        _say("postmortem record landed in the run's JSONL ✓")

    # and the ring must decode directly too (the CLI path)
    ring = os.path.join(args.workdir, flight_lib.RING_NAME)
    try:
        dec = flight_lib.decode(ring)
        _say(
            f"ring decodes: {len(dec['records'])} record(s), "
            f"{dec['torn_slots']} torn slot(s)"
        )
    except OSError as e:
        failures.append(f"flight ring unreadable: {e}")

    if failures:
        for msg in failures:
            _say(f"FAIL: {msg}")
        return 1
    _say("PASS: wedge detected, stack captured, bundle assembled — the "
         "whole forensic chain holds")
    return 0


if __name__ == "__main__":
    sys.exit(main())
