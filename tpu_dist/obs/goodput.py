"""Goodput ledger — where did this run's wall-clock actually go?
(``docs/observability.md``.)

Production TPU fleets budget in *goodput*: of every wall-clock second a
job consumed — compiles, checkpoint I/O, input stalls, evals, preemptions
and the restarts after them — what fraction was productive training?
PRs 4-5 instrumented each ingredient (spans, counters, step phases,
compile seconds) but never closed the books. This module does: it
partitions the run's wall-clock, **from Trainer construction through
exit, resumed segments of the same logical run included**, into named
buckets that sum to the elapsed time by construction.

Buckets (field ``<name>_s`` in every record):

* ``productive`` — steady-state step-loop time (dispatch + the in-loop
  host work that paces it); the goodput numerator,
* ``compile`` — XLA backend-compile wall time (the ``compile.seconds``
  counter fed by the ``jax.monitoring`` listener),
* ``ckpt`` — checkpoint save/restore, the restore ladder included,
* ``data_stall`` — blocking in the loader iterator (the step-phase
  ``data_wait`` the trainer already measures),
* ``eval`` — validation,
* ``preempt`` — preemption/restart loss: the SIGTERM-to-exit tail in the
  dying process plus (offline) the wall-clock gap between a segment's
  last record and the resumed segment's construction,
* ``preempt_for_serve`` — the fleet arbiter took this run's chips for a
  breached serving SLO: a world-change gap whose resume record carries
  a propagated ``decision_id`` with cause ``serve_breach`` (schema
  v15). Split out of ``recovery`` so "we chose to pay this for the
  SLO" and "elastic kept us alive" are budgeted separately,
* ``recovery`` — divergence auto-recovery (restore + LR backoff), plus
  (offline) the relaunch gap of any OTHER elastic resize,
* ``unattributed`` — whatever remains; never hidden, so a growing
  remainder is itself a finding.

Two halves share the bucket taxonomy:

* **Live** (:class:`GoodputLedger`) — the Trainer attributes seconds as
  they happen and emits one ``goodput`` history record per epoch window
  plus a run-end totals record (schema v4, additive) and a rank-0 ledger
  line. Windows chain: each record's ``window_s`` runs from the previous
  record to this one, so the records partition the run exactly.
* **Offline** (:func:`run_ledger`) — fold a ``--log_file`` JSONL
  (possibly holding several resumed segments) back into one run-level
  ledger; ``obs summarize`` prints it and ``obs compare --goodput``
  gates on its ``goodput_frac``.

Stdlib-only on purpose: the offline half must run anywhere the log can
be copied to, and the live half is pure host arithmetic (the TD106
telemetry contract covers it — nothing here touches the traced step).
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, List, Optional, Tuple

#: Attributable buckets, in report order. ``unattributed`` is derived
#: (window minus the rest), never written to directly.
BUCKETS: Tuple[str, ...] = (
    "productive", "compile", "ckpt", "data_stall", "eval",
    "preempt", "preempt_for_serve", "recovery",
)
ALL_BUCKETS: Tuple[str, ...] = BUCKETS + ("unattributed",)


class GoodputLedger:
    """Live wall-clock bookkeeping for one process's run.

    The clock origin is the Trainer's construction instant; every
    attribution is host arithmetic on ``time.monotonic`` readings.
    ``window_record()`` closes the current window (everything since the
    previous record), deriving ``unattributed`` as the unexplained
    remainder, and folds it into the run totals — so the per-window
    records partition ``[t0, now]`` exactly and the invariant *bucket
    sum equals elapsed wall-clock* holds by construction.
    """

    def __init__(self, t0: Optional[float] = None):
        self.t0 = t0 if t0 is not None else time.monotonic()
        self._mark = self.t0
        self._window: Dict[str, float] = {b: 0.0 for b in BUCKETS}
        self._totals: Dict[str, float] = {b: 0.0 for b in ALL_BUCKETS}

    def add(self, bucket: str, seconds: float) -> None:
        """Attribute ``seconds`` of the current window to ``bucket``.
        Negative inputs (clock weirdness) clamp to zero rather than
        corrupt the invariant."""
        if bucket not in self._window:
            raise ValueError(f"unknown goodput bucket {bucket!r}; have {BUCKETS}")
        if seconds > 0:
            self._window[bucket] += float(seconds)

    @contextlib.contextmanager
    def timed(self, bucket: str):
        """Attribute a region's wall time to ``bucket`` (exception-safe:
        a failing checkpoint write still spent the seconds)."""
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.add(bucket, time.monotonic() - t0)

    def window_value(self, bucket: str) -> float:
        """Seconds attributed to ``bucket`` in the OPEN window — lets the
        trainer subtract e.g. mid-epoch ckpt time out of an epoch's
        productive remainder."""
        return self._window[bucket]

    def window_record(self, now: Optional[float] = None) -> Dict[str, float]:
        """Close the current window: per-bucket seconds + ``window_s`` +
        the derived ``unattributed_s``; folds into the run totals and
        starts the next window at ``now``."""
        now = time.monotonic() if now is None else now
        window_s = max(now - self._mark, 0.0)
        attributed = sum(self._window.values())
        # over-attribution (overlapping regions double-counted) would push
        # the remainder negative; clamp and let the buckets overshoot the
        # window visibly rather than silently rescale them
        unattributed = max(window_s - attributed, 0.0)
        rec = {f"{b}_s": round(self._window[b], 4) for b in BUCKETS}
        rec["unattributed_s"] = round(unattributed, 4)
        rec["window_s"] = round(window_s, 4)
        for b in BUCKETS:
            self._totals[b] += self._window[b]
            self._window[b] = 0.0
        self._totals["unattributed"] += unattributed
        self._mark = now
        return rec

    def run_totals(self, now: Optional[float] = None) -> Dict[str, float]:
        """Whole-run ledger: per-bucket totals over every CLOSED window,
        total elapsed, and ``goodput_frac``. Call :meth:`window_record`
        first to fold the open tail in."""
        now = time.monotonic() if now is None else now
        elapsed = max(self._mark - self.t0, 0.0)
        out = {f"{b}_s": round(self._totals[b], 4) for b in ALL_BUCKETS}
        out["elapsed_s"] = round(elapsed, 4)
        out["goodput_frac"] = round(
            self._totals["productive"] / elapsed, 4
        ) if elapsed > 0 else 0.0
        return out


def resume_direction(rec: dict) -> Optional[str]:
    """Classify a ``resume`` record's elastic direction — ONE home for
    the ``prev_dp``/``dp`` comparison every consumer renders or charges
    by (this ledger, ``summarize``, ``tail``, ``pod``):

    * ``'grown'`` — the world got BIGGER (scale-up / fleet receipt),
    * ``'resharded'`` — any other elastic resize: a shrink, or a
      same-size restore whose dp-dependent leaves were re-laid,
    * ``None`` — a plain same-world resume (no elastic resize at all).
    """
    prev_dp, dp = rec.get("prev_dp"), rec.get("dp")
    ints = isinstance(prev_dp, int) and isinstance(dp, int)
    if ints and dp > prev_dp:
        return "grown"
    if rec.get("resharded") or (ints and dp != prev_dp):
        return "resharded"
    return None


def fleet_move_phrase(rec: dict) -> str:
    """The "who → whom" phrase of a ``fleet`` decision record — ONE home
    for the three renderers (``summarize``, ``tail``, ``pod``). Handles
    a grant (no donor: chips from the free pool), a donation (no
    recipient: chips bank as pending for ``for_run``), and the paired
    form foreign tooling may still write."""
    donor, recipient = rec.get("donor"), rec.get("recipient")
    if donor and recipient:
        phrase = f"{donor} -> {recipient}"
    elif recipient:
        phrase = f"free pool -> {recipient}"
    elif donor:
        phrase = f"{donor} -> pending pool"
        if rec.get("for_run"):
            phrase += f" (toward {rec['for_run']})"
    else:
        phrase = "?"
    phrase += f" ({rec.get('chips')} chip(s))"
    if rec.get("preempt"):
        # an SLO-breach preemption (multi-tenant pod): the move was
        # demanded by a serving breach, not offered by a stalled donor
        phrase += " [SLO preemption]"
    if rec.get("decision_id") is not None:
        # causal arbitration tracing (schema v15): every renderer names
        # the arbitration, so a donate and its completion grant read as
        # one chain at a glance
        phrase += f" [decision #{rec['decision_id']}]"
    return phrase


# -- offline: fold a run's JSONL records back into one ledger ---------------


def _zero_totals() -> Dict[str, float]:
    out = {f"{b}_s": 0.0 for b in ALL_BUCKETS}
    out["elapsed_s"] = 0.0
    return out


def run_ledger(records: List[dict]) -> Optional[dict]:
    """Fold a history's ``goodput`` records — across resumed segments —
    into one run-level ledger.

    Segments are delimited the way ``summarize`` delimits them: a
    ``run_id`` change mid-file is a restart (same logical run, fresh
    process). Within a segment the run-end totals record (``final: true``)
    is authoritative; a segment that died before writing one (preemption,
    crash) is reconstructed by summing its window records. The wall-clock
    gap between a segment's LAST record and the next segment's
    construction instant (its first record's ``ts - rel_s``) is the
    restart loss nobody inside either process could see — it lands in
    ``preempt_s``, except when the new segment opens with an ELASTIC
    ``resume`` record: one flagged resharded, or one whose world size
    changed (``prev_dp != dp`` — a probe-triggered grow or a
    scheduler-initiated donation can re-lay zero leaves when the padded
    lengths happen to agree, and a voluntary resize must never inflate
    ``preempt_s``). That gap is the reshard/resize+relaunch cost of
    keeping the run alive at a new world size and is charged to
    ``recovery_s`` — UNLESS the resume carries a propagated
    ``decision_id`` with ``decision_cause == "serve_breach"`` (schema
    v15: the fleet arbiter preempted this run for a breached serving
    SLO), in which case it is charged to ``preempt_for_serve_s``: the
    pod CHOSE to pay that gap for the SLO, and budgeting it as generic
    elastic recovery would hide the cost of the co-scheduling policy
    (docs/resilience.md "Elastic training" / "Scale-up & fleet
    scheduling"). The partition invariant is untouched: all three gap
    accumulators land in ``restart_gap_s`` and ``elapsed_s``, so the
    buckets still sum to wall-clock exactly. Returns None when the log
    holds no goodput records (an old-schema log)."""
    totals = _zero_totals()
    n_segments = 0
    saw_goodput = False
    cur_run = object()
    seg_final: Optional[dict] = None
    seg_windows = _zero_totals()
    seg_has_window = False
    last_ts: Optional[float] = None
    restart_s = 0.0
    reshard_gap_s = 0.0
    serve_gap_s = 0.0

    def fold_segment():
        nonlocal seg_final, seg_windows, seg_has_window
        src = None
        if seg_final is not None:
            src = seg_final
        elif seg_has_window:
            src = seg_windows
        if src is not None:
            for b in ALL_BUCKETS:
                totals[f"{b}_s"] += float(src.get(f"{b}_s", 0.0) or 0.0)
            totals["elapsed_s"] += float(src.get("elapsed_s", 0.0) or 0.0)
        seg_final, seg_windows, seg_has_window = None, _zero_totals(), False

    for rec in records:
        rid = rec.get("run_id")
        if n_segments == 0:
            cur_run = rid
            n_segments = 1
        elif rid is not None and rid != cur_run:
            # a NON-None run_id change is a restart (same rule summarize
            # uses for its counter-delta resets); id-less records — old
            # schemas, foreign lines — never split a segment
            fold_segment()
            # restart gap: previous segment's last visible instant to
            # this segment's construction (ts minus its rel_s offset).
            # A segment whose boundary record is a resharded 'resume'
            # came back at a NEW world size — its gap is elastic
            # recovery, not preemption loss
            ts, rel = rec.get("ts"), rec.get("rel_s")
            if (
                last_ts is not None
                and isinstance(ts, (int, float))
                and isinstance(rel, (int, float))
            ):
                gap = max(float(ts) - float(rel) - last_ts, 0.0)
                if (
                    rec.get("kind") == "resume"
                    and resume_direction(rec) is not None
                ):
                    if (
                        rec.get("decision_cause") == "serve_breach"
                        and rec.get("decision_id") is not None
                    ):
                        # the fleet arbiter took the chips for a
                        # breached serving SLO (the relaunch env
                        # propagated its decision_id here) — this gap
                        # is the chosen cost of the co-scheduling
                        # policy, not generic elastic recovery
                        serve_gap_s += gap
                    else:
                        reshard_gap_s += gap
                else:
                    restart_s += gap
            cur_run = rid
            n_segments += 1
        if isinstance(rec.get("ts"), (int, float)):
            last_ts = float(rec["ts"])
        if rec.get("kind") != "goodput":
            continue
        saw_goodput = True
        if rec.get("final"):
            seg_final = rec
        else:
            seg_has_window = True
            for b in ALL_BUCKETS:
                seg_windows[f"{b}_s"] += float(rec.get(f"{b}_s", 0.0) or 0.0)
            seg_windows["elapsed_s"] += float(rec.get("window_s", 0.0) or 0.0)
    fold_segment()
    if not saw_goodput:
        return None
    totals["preempt_s"] = round(totals["preempt_s"] + restart_s, 4)
    totals["preempt_for_serve_s"] = round(
        totals["preempt_for_serve_s"] + serve_gap_s, 4
    )
    totals["recovery_s"] = round(totals["recovery_s"] + reshard_gap_s, 4)
    totals["restart_gap_s"] = round(
        restart_s + reshard_gap_s + serve_gap_s, 4
    )
    totals["elapsed_s"] = round(
        totals["elapsed_s"] + restart_s + reshard_gap_s + serve_gap_s, 4
    )
    for b in ALL_BUCKETS:
        totals[f"{b}_s"] = round(totals[f"{b}_s"], 4)
    totals["n_segments"] = n_segments
    totals["goodput_frac"] = round(
        totals["productive_s"] / totals["elapsed_s"], 4
    ) if totals["elapsed_s"] > 0 else 0.0
    return totals


def ledger_line(totals: dict) -> str:
    """One-line rank-0 rendering of a run ledger (live or offline)."""
    parts = []
    for b in ALL_BUCKETS:
        v = totals.get(f"{b}_s", 0.0) or 0.0
        if v:
            parts.append(f"{b} {v:.1f}s")
    frac = totals.get("goodput_frac")
    return (
        f"goodput: {frac:.1%} of {totals.get('elapsed_s', 0.0):.1f}s "
        "wall-clock productive"
        + (f" ({', '.join(parts)})" if parts else "")
        + (
            f" across {totals['n_segments']} segment(s)"
            if totals.get("n_segments", 1) > 1 else ""
        )
    )
