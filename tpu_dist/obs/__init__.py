"""Run telemetry + device-side training health + the fleet layer + the
LIVE layer + the analytics layer + the FORENSICS layer: span tracing,
subsystem counters, heartbeat, straggler detection, in-step health
scalars (``device_stats``), cost/MFU accounting and capture calibration
(``costmodel``), anomaly detection, the goodput ledger (``goodput``),
triggered device profiling (``profile``), capture read-back analytics
(``xprof`` — device-time attribution, comm/compute overlap), pod
aggregation (``aggregate``), OpenMetrics/Prometheus export
(``export``), declarative threshold alerting (``alerts``), crash
forensics (``flight`` — the SIGKILL-surviving per-rank flight ring +
faulthandler stack capture; ``postmortem`` — the bundle assembler), and
the ``python -m tpu_dist.obs summarize`` / ``compare`` / ``pod`` /
``tail`` / ``xprof`` / ``postmortem`` CLI.

Contract (audited by TD106/TD107/TD108/TD109/TD110/TD113): the
host-telemetry half — goodput ledger, profiler trigger control, capture
auto-analysis, live exporter, alert engine, and the crash-forensics kit
included — is host-side only: arming it leaves the traced train step
byte-identical and adds no per-step device transfers. The one
deliberately device-side piece, ``device_stats`` (opt-in
``--device_metrics``), adds zero collectives and rides the existing
single per-step metrics fetch. See ``docs/observability.md``.
"""

from tpu_dist.obs import counters, goodput, spans  # noqa: F401


def __getattr__(name):
    # lazy: straggler/heartbeat/device_stats/costmodel pull in jax or the
    # (jax-importing) logging layer; the offline CLI and the loader
    # producer thread only need counters/spans/goodput (stdlib-only)
    if name == "Heartbeat":
        from tpu_dist.obs.heartbeat import Heartbeat

        return Heartbeat
    if name == "epoch_skew":
        from tpu_dist.obs.straggler import epoch_skew

        return epoch_skew
    if name == "AnomalyDetector":
        from tpu_dist.obs.anomaly import AnomalyDetector

        return AnomalyDetector
    if name == "TriggeredProfiler":
        from tpu_dist.obs.profile import TriggeredProfiler

        return TriggeredProfiler
    if name == "GoodputLedger":
        return goodput.GoodputLedger
    if name == "MetricsExporter":
        from tpu_dist.obs.export import MetricsExporter

        return MetricsExporter
    if name == "AlertEngine":
        from tpu_dist.obs.alerts import AlertEngine

        return AlertEngine
    if name == "FlightRecorder":
        from tpu_dist.obs.flight import FlightRecorder

        return FlightRecorder
    raise AttributeError(f"module 'tpu_dist.obs' has no attribute {name!r}")
