"""Run telemetry: span tracing, subsystem counters, heartbeat, straggler
detection, and the offline ``python -m tpu_dist.obs summarize`` CLI.

Contract (audited by TD106): everything in this package is host-side —
arming telemetry leaves the traced train step byte-identical and adds no
per-step device transfers. See ``docs/observability.md``.
"""

from tpu_dist.obs import counters, spans  # noqa: F401


def __getattr__(name):
    # lazy: straggler/heartbeat pull in the (jax-importing) logging layer;
    # the offline CLI and the loader producer thread only need counters/spans
    if name == "Heartbeat":
        from tpu_dist.obs.heartbeat import Heartbeat

        return Heartbeat
    if name == "epoch_skew":
        from tpu_dist.obs.straggler import epoch_skew

        return epoch_skew
    raise AttributeError(f"module 'tpu_dist.obs' has no attribute {name!r}")
