"""Profile analytics — read ``jax.profiler`` captures back
(``docs/observability.md`` "Trace analytics").

The triggered profiler (``obs/profile.py``) writes captures a human must
open in Perfetto to learn anything from; this module closes the loop by
parsing the Chrome-trace JSON JAX writes under every capture directory
(``plugins/profile/<run>/<host>.trace.json.gz`` — gzip + JSON, no proto
deps) into a structured attribution report:

* **Per-category device seconds** — every op event on the device track
  is classified (``matmul_conv`` / ``collective`` / ``infeed_outfeed`` /
  ``fusion_other`` / ``host`` runtime bookkeeping) and charged its SELF
  time (duration minus nested children), so the category seconds sum to
  total device busy time by construction — the invariant the tests pin.
* **Comm/compute overlap** — the fraction of collective wall time during
  which compute was also executing (interval-union intersection across
  the device's op threads). Low overlap on a big collective share means
  the schedule serializes communication the mesh layout promised to hide.
* **Collectives by kind**, **top-k ops by self time**, and
  **infeed-stall seconds** (the device idling on host input).

Device-track selection: real accelerator captures carry ``/device:*``
processes, and their ``XLA Ops`` thread is the op line (other device
threads are alternate views of the same time — never summed).
CPU-emulation captures (the test environment) have no device process;
there, XLA op executions are selected by CONTENT — events stamped with
``args.hlo_op``/``hlo_module``, which XLA:CPU scatters across the
``/host:*`` process's pools (Eigen, TFRT client dispatch, even the
inline ``python`` thread) — and runtime bookkeeping is excluded. A
capture with neither is a typed :class:`NoDeviceTrackError`.

Failure posture: this analyzer runs inside the training process (the
auto-analyze hook fires on every capture close), so malformed input must
NEVER crash it — a truncated gzip, a torn JSON tail, or a track-less
trace file becomes a counted drop in a partial report, and only a
capture with NOTHING analyzable raises (a :class:`CaptureError`
subclass the hook catches). Pure stdlib — no jax, no protobuf; the
report runs anywhere the capture directory can be copied to.
"""

from __future__ import annotations

import gzip
import json
import os
import re
from typing import Dict, List, Optional, Tuple

#: Attribution categories; their seconds sum to ``device_busy_s``.
CATEGORIES = (
    "matmul_conv", "collective", "infeed_outfeed", "fusion_other", "host",
)

#: HLO collective stems (async ``-start``/``-done`` halves fold into the
#: base kind). Order-independent: matching is exact-stem or stem + "-".
COLLECTIVE_KINDS = (
    "all-reduce",
    "all-gather",
    "all-to-all",
    "ragged-all-to-all",
    "reduce-scatter",
    "collective-permute",
    "collective-broadcast",
    "send",
    "recv",
)

#: HLO instruction names are lowercase ``[a-z0-9_.-]``; anything else on
#: an op thread (``ThreadpoolListener::Record``, ``D2D Dispatch``,
#: ``TfrtCpuExecutable::Execute``) is runtime bookkeeping → ``host``.
_HLO_NAME = re.compile(r"^[a-z0-9_.\-]+$")
#: Numeric / rewrite suffixes stripped to recover the op stem
#: (``tanh.11.clone`` → ``tanh``, ``all-reduce.12`` → ``all-reduce``).
_STEM_SUFFIX = re.compile(r"(\.(\d+|clone|remat\d*))+$")

#: Matmul/conv stems. Deliberately NOT a bare ``conv`` prefix — the
#: ubiquitous dtype-cast op ``convert`` must stay in ``fusion_other``.
_MATMUL_STEMS = ("dot", "convolution", "cudnn-conv", "conv-", "conv2d")


# --------------------------------------------------------------------------
# Typed errors — the auto-analyze hook's catch surface.
# --------------------------------------------------------------------------


class CaptureError(Exception):
    """Base: this capture yielded no analyzable device timeline."""

    kind = "capture_error"


class EmptyCaptureError(CaptureError):
    """No ``*.trace.json.gz`` under the capture directory at all."""

    kind = "empty_capture"


class MalformedTraceError(CaptureError):
    """Trace file unreadable: truncated gzip, torn/invalid JSON."""

    kind = "malformed_trace"


class NoDeviceTrackError(CaptureError):
    """The trace parsed but carries no device/XLA-op track to attribute."""

    kind = "no_device_track"


# --------------------------------------------------------------------------
# Classification
# --------------------------------------------------------------------------


def op_stem(name: str) -> str:
    """``all-reduce.12`` → ``all-reduce``; ``tanh.11.clone`` → ``tanh``."""
    return _STEM_SUFFIX.sub("", name)


def collective_kind(name: str) -> Optional[str]:
    """The collective family of an HLO op name, or None. Async halves
    (``all-gather-start.3``) report their base kind — the wire time is
    one transfer however many HLO ops XLA splits it into."""
    stem = op_stem(name)
    for kind in COLLECTIVE_KINDS:
        if stem == kind or stem.startswith(kind + "-"):
            return kind
    return None


def classify(name: str) -> str:
    """Category of one op-thread event name (see :data:`CATEGORIES`)."""
    if not _HLO_NAME.match(name):
        return "host"
    stem = op_stem(name)
    if collective_kind(name) is not None:
        return "collective"
    if stem.startswith("infeed") or stem.startswith("outfeed"):
        return "infeed_outfeed"
    if (
        any(stem.startswith(m) for m in _MATMUL_STEMS)
        or stem == "conv" or "gemm" in stem or "matmul" in stem
    ):
        return "matmul_conv"
    return "fusion_other"


# --------------------------------------------------------------------------
# Interval math
# --------------------------------------------------------------------------


def _self_times_us(events: List[Tuple[float, float, int]]) -> Dict[int, float]:
    """Self time (duration minus nested children, µs) per event index for
    ONE thread's complete events ``(ts, dur, idx)``. Children are clipped
    to their parent, so the per-thread self times sum to the union length
    of the thread's top-level intervals — the invariant that makes the
    category seconds sum to total busy time."""
    out: Dict[int, float] = {}
    stack: List[Tuple[float, int]] = []  # (end_us, idx) of open ancestors
    for ts, dur, idx in sorted(events, key=lambda e: (e[0], -e[1])):
        end = ts + dur
        while stack and stack[-1][0] <= ts:
            stack.pop()
        if stack:
            p_end, p_idx = stack[-1]
            end = min(end, p_end)  # clip clock-jitter overhang to parent
            covered = end - ts
            if covered > 0:
                out[p_idx] = out.get(p_idx, 0.0) - covered
        dur = max(end - ts, 0.0)
        out[idx] = out.get(idx, 0.0) + dur
        stack.append((end, idx))
    return out


def _merge_intervals(ivs: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
    if not ivs:
        return []
    ivs = sorted(ivs)
    out = [list(ivs[0])]
    for a, b in ivs[1:]:
        if a <= out[-1][1]:
            out[-1][1] = max(out[-1][1], b)
        else:
            out.append([a, b])
    return [(a, b) for a, b in out]


def _union_len(ivs: List[Tuple[float, float]]) -> float:
    return sum(b - a for a, b in _merge_intervals(ivs))


def _intersect_len(
    a: List[Tuple[float, float]], b: List[Tuple[float, float]]
) -> float:
    a, b = _merge_intervals(a), _merge_intervals(b)
    i = j = 0
    total = 0.0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            total += hi - lo
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return total


# --------------------------------------------------------------------------
# Trace loading
# --------------------------------------------------------------------------


def find_traces(capture_dir: str) -> List[str]:
    """Every ``*.trace.json.gz`` under ``capture_dir`` (JAX writes
    ``plugins/profile/<run>/<host>.trace.json.gz``; multi-host captures
    and ``obs pod``-collected trees nest one layout per host — the walk
    finds them all). Sorted for deterministic reports."""
    out: List[str] = []
    for root, _dirs, files in os.walk(capture_dir):
        for f in files:
            if f.endswith(".trace.json.gz"):
                out.append(os.path.join(root, f))
    return sorted(out)


def load_trace(path: str) -> List[dict]:
    """The ``traceEvents`` list of one trace file (``.json`` or
    ``.json.gz``). Raises :class:`MalformedTraceError` on a truncated
    gzip or torn/invalid JSON — typed, so the auto-analyze hook can count
    the drop instead of dying."""
    try:
        if path.endswith(".gz"):
            with gzip.open(path, "rt", encoding="utf-8", errors="replace") as f:
                data = json.load(f)
        else:
            with open(path, encoding="utf-8", errors="replace") as f:
                data = json.load(f)
    except (OSError, EOFError, gzip.BadGzipFile) as e:
        raise MalformedTraceError(
            f"{path}: unreadable trace (truncated gzip?): {e}"
        ) from e
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise MalformedTraceError(
            f"{path}: torn/invalid trace JSON: {e}"
        ) from e
    if isinstance(data, list):  # bare event-array form of the spec
        return [e for e in data if isinstance(e, dict)]
    if isinstance(data, dict) and isinstance(data.get("traceEvents"), list):
        return [e for e in data["traceEvents"] if isinstance(e, dict)]
    raise MalformedTraceError(f"{path}: no traceEvents array")


# --------------------------------------------------------------------------
# Per-trace analysis
# --------------------------------------------------------------------------


def _track_selector(
    events: List[dict],
) -> Tuple[set, set]:
    """``(device_op_tids, host_pids)`` — the attribution universe.

    Real accelerator captures carry ``/device:*`` processes; their
    ``XLA Ops`` line holds the op executions (other device threads are
    alternate VIEWS of the same time — summing them would double-count),
    so when any exists, those threads are the universe and every event
    on them counts. CPU-emulation captures have no device process; XLA
    op executions are scattered across the ``/host:*`` process's thread
    pools (``tf_XLAEigen``, the TFRT client dispatch threads, even the
    calling ``python`` thread for inlined ops), so selection there is by
    CONTENT instead: events stamped with ``args.hlo_op``/``hlo_module``
    count, runtime bookkeeping (``start_trace``, ``ExecuteHelper``,
    threadpool markers) does not."""
    pid_name: Dict[object, str] = {}
    tid_name: Dict[Tuple[object, object], str] = {}
    for e in events:
        if e.get("ph") != "M":
            continue
        args = e.get("args") or {}
        if e.get("name") == "process_name":
            pid_name[e.get("pid")] = str(args.get("name", ""))
        elif e.get("name") == "thread_name":
            tid_name[(e.get("pid"), e.get("tid"))] = str(args.get("name", ""))
    device_pids = {p for p, n in pid_name.items() if n.startswith("/device:")}
    if device_pids:
        ops = {
            k for k, n in tid_name.items()
            if k[0] in device_pids and n.startswith("XLA Ops")
        }
        if ops:
            return ops, set()
        # no "XLA Ops" line (GPU stream threads, older layouts): every
        # thread of the device processes
        return {k for k in tid_name if k[0] in device_pids}, set()
    return set(), {p for p, n in pid_name.items() if n.startswith("/host:")}


def _is_hlo_event(e: dict) -> bool:
    args = e.get("args")
    return isinstance(args, dict) and (
        "hlo_op" in args or "hlo_module" in args
    )


def analyze_events(events: List[dict]) -> dict:
    """Attribution over one trace's event list. Raises
    :class:`NoDeviceTrackError` when no device/XLA-op events exist."""
    device_tids, host_pids = _track_selector(events)
    # complete events per op thread: (ts, dur, index into flat lists)
    per_thread: Dict[Tuple[object, object], List[Tuple[float, float, int]]] = {}
    names: List[str] = []
    cats: List[str] = []
    for e in events:
        if e.get("ph") != "X":
            continue
        key = (e.get("pid"), e.get("tid"))
        if device_tids:
            if key not in device_tids:
                continue
        elif not (key[0] in host_pids and _is_hlo_event(e)):
            continue
        ts, dur = e.get("ts"), e.get("dur")
        if not isinstance(ts, (int, float)) or not isinstance(dur, (int, float)):
            continue
        name = str(e.get("name", ""))
        idx = len(names)
        names.append(name)
        cats.append(classify(name))
        per_thread.setdefault(key, []).append((float(ts), float(dur), idx))
    if not per_thread:
        raise NoDeviceTrackError(
            "no device track: the trace has no /device:* 'XLA Ops' thread "
            "and no /host:* XLA op events (args.hlo_op) to attribute"
        )
    cat_us = {c: 0.0 for c in CATEGORIES}
    coll_us: Dict[str, float] = {}
    infeed_us = 0.0
    op_self_us: Dict[str, float] = {}
    op_count: Dict[str, int] = {}
    comm_ivs: List[Tuple[float, float]] = []
    compute_ivs: List[Tuple[float, float]] = []
    busy_us = 0.0
    for evs in per_thread.values():
        selfs = _self_times_us(evs)
        for ts, dur, idx in evs:
            s = selfs.get(idx, 0.0)
            cat = cats[idx]
            cat_us[cat] += s
            busy_us += s
            if cat == "collective":
                kind = collective_kind(names[idx]) or "other"
                coll_us[kind] = coll_us.get(kind, 0.0) + s
                comm_ivs.append((ts, ts + dur))
            elif cat in ("matmul_conv", "fusion_other"):
                compute_ivs.append((ts, ts + dur))
            if cat == "infeed_outfeed" and op_stem(names[idx]).startswith("infeed"):
                infeed_us += s
            if cat != "host":
                op_self_us[names[idx]] = op_self_us.get(names[idx], 0.0) + s
                op_count[names[idx]] = op_count.get(names[idx], 0) + 1
    comm_us = _union_len(comm_ivs)
    overlapped_us = _intersect_len(comm_ivs, compute_ivs)
    sec = 1e-6
    return {
        "op_threads": len(per_thread),
        "n_op_events": len(names),
        "device_busy_s": busy_us * sec,
        "categories": {c: cat_us[c] * sec for c in CATEGORIES},
        "collectives": {
            k: v * sec for k, v in sorted(coll_us.items())
        },
        "infeed_stall_s": infeed_us * sec,
        "overlap": {
            "comm_s": comm_us * sec,
            "compute_s": _union_len(compute_ivs) * sec,
            "overlapped_s": overlapped_us * sec,
            "overlap_frac": (
                round(overlapped_us / comm_us, 4) if comm_us > 0 else None
            ),
        },
        "_op_self_s": {n: v * sec for n, v in op_self_us.items()},
        "_op_count": op_count,
    }


# --------------------------------------------------------------------------
# Capture-level analysis (the public entry points)
# --------------------------------------------------------------------------


def _top_ops(
    self_s: Dict[str, float], count: Dict[str, int], k: int
) -> List[dict]:
    return [
        {
            "name": n,
            "category": classify(n),
            "self_s": round(s, 6),
            "count": count.get(n, 0),
        }
        for n, s in sorted(self_s.items(), key=lambda kv: -kv[1])[:k]
    ]


def _merge_trace(total: dict, tr: dict) -> None:
    total["device_busy_s"] += tr["device_busy_s"]
    for c in CATEGORIES:
        total["categories"][c] += tr["categories"][c]
    for kind, s in tr["collectives"].items():
        total["collectives"][kind] = total["collectives"].get(kind, 0.0) + s
    total["infeed_stall_s"] += tr["infeed_stall_s"]
    for f in ("comm_s", "compute_s", "overlapped_s"):
        total["overlap"][f] += tr["overlap"][f]
    for n, s in tr["_op_self_s"].items():
        total["_op_self_s"][n] = total["_op_self_s"].get(n, 0.0) + s
    for n, c in tr["_op_count"].items():
        total["_op_count"][n] = total["_op_count"].get(n, 0) + c


def _finish(total: dict, top_k: int) -> dict:
    comm = total["overlap"]["comm_s"]
    total["overlap"]["overlap_frac"] = (
        round(total["overlap"]["overlapped_s"] / comm, 4) if comm > 0 else None
    )
    for f in ("comm_s", "compute_s", "overlapped_s"):
        total["overlap"][f] = round(total["overlap"][f], 6)
    busy = total["device_busy_s"]
    total["collective_frac"] = (
        round(total["categories"]["collective"] / busy, 4) if busy > 0 else None
    )
    total["top_ops"] = _top_ops(
        total.pop("_op_self_s"), total.pop("_op_count"), top_k
    )
    total["categories"] = {
        c: round(v, 6) for c, v in total["categories"].items()
    }
    # the reported busy is the sum of the ROUNDED categories, so the
    # sum-to-busy invariant survives the 6-decimal rounding exactly
    total["device_busy_s"] = round(sum(total["categories"].values()), 6)
    total["collectives"] = {
        k: round(v, 6) for k, v in sorted(total["collectives"].items())
    }
    total["infeed_stall_s"] = round(total["infeed_stall_s"], 6)
    return total


def _fresh_total() -> dict:
    return {
        "device_busy_s": 0.0,
        "categories": {c: 0.0 for c in CATEGORIES},
        "collectives": {},
        "infeed_stall_s": 0.0,
        "overlap": {"comm_s": 0.0, "compute_s": 0.0, "overlapped_s": 0.0},
        "_op_self_s": {},
        "_op_count": {},
    }


def analyze_capture(capture_dir: str, top_k: int = 10) -> dict:
    """The attribution report over every trace file under a capture
    directory (one per host in a multi-host capture — their device times
    sum; the overlap fraction is the ratio of summed overlapped to summed
    comm seconds).

    Per-file failures (truncated gzip, torn JSON, no device track) become
    counted entries in ``report["dropped"]`` + ``report["errors"]`` — a
    PARTIAL report, never an exception — as long as at least one trace
    analyzes. With nothing analyzable the capture is useless and a typed
    :class:`CaptureError` subclass says why (empty dir vs all-malformed
    vs no-device-track)."""
    if not os.path.isdir(capture_dir):
        raise EmptyCaptureError(f"{capture_dir}: not a directory")
    paths = find_traces(capture_dir)
    if not paths:
        raise EmptyCaptureError(
            f"{capture_dir}: no *.trace.json.gz under it — the capture "
            "wrote nothing (profiler backend unavailable, or the dir is "
            "not a jax.profiler output)"
        )
    total = _fresh_total()
    traces: List[dict] = []
    errors: List[dict] = []
    dropped = {"malformed_trace": 0, "no_device_track": 0}
    for path in paths:
        try:
            tr = analyze_events(load_trace(path))
        except CaptureError as e:
            dropped[e.kind] = dropped.get(e.kind, 0) + 1
            errors.append({"path": path, "kind": e.kind, "error": str(e)[:300]})
            continue
        _merge_trace(total, tr)
        traces.append({
            "path": path,
            "op_threads": tr["op_threads"],
            "n_op_events": tr["n_op_events"],
            "device_busy_s": round(tr["device_busy_s"], 6),
        })
    if not traces:
        kinds = {e["kind"] for e in errors}
        cls = (
            NoDeviceTrackError if kinds == {"no_device_track"}
            else MalformedTraceError
        )
        raise cls(
            f"{capture_dir}: none of {len(paths)} trace file(s) analyzable "
            f"({'; '.join(e['error'] for e in errors[:3])})"
        )
    report = _finish(total, top_k)
    report.update({
        "capture_dir": capture_dir,
        "n_traces": len(paths),
        "analyzed": len(traces),
        "traces": traces,
        "dropped": {k: v for k, v in dropped.items() if v},
        "errors": errors,
    })
    return report


def analyze_trace_file(path: str, top_k: int = 10) -> dict:
    """Analyze ONE Chrome trace file (``.json`` or ``.json.gz``) — the
    offline path for a trace pulled out of a capture by hand. (The
    merged timeline ``obs pod --trace-out`` writes holds HOST spans,
    not XLA op events — it has no device track to attribute, so it
    raises :class:`NoDeviceTrackError` by design; pod-collected CAPTURE
    trees — per-host ``plugins/profile`` layouts under one root — go
    through :func:`analyze_capture`, whose walk finds them all.)"""
    total = _fresh_total()
    _merge_trace(total, analyze_events(load_trace(path)))
    report = _finish(total, top_k)
    report.update({
        "capture_dir": path, "n_traces": 1, "analyzed": 1,
        "traces": [{"path": path}], "dropped": {}, "errors": [],
    })
    return report


# --------------------------------------------------------------------------
# Report shaping — the compact record + the rank-0 line
# --------------------------------------------------------------------------


def compact(report: dict, top_k: int = 3) -> dict:
    """The history-record payload (``profile_analysis``, schema v6): the
    category split, overlap, collective share, and the top few ops —
    small enough to stamp per capture without bloating the JSONL."""
    out = {
        "device_busy_s": report["device_busy_s"],
        "categories": dict(report["categories"]),
        "collectives": dict(report["collectives"]),
        "collective_frac": report.get("collective_frac"),
        "overlap_frac": report["overlap"]["overlap_frac"],
        "comm_s": report["overlap"]["comm_s"],
        "infeed_stall_s": report["infeed_stall_s"],
        "top_ops": [
            {"name": o["name"], "self_s": o["self_s"]}
            for o in report.get("top_ops", [])[:top_k]
        ],
        "analyzed_traces": report.get("analyzed", 1),
    }
    if report.get("dropped"):
        out["dropped"] = dict(report["dropped"])
    return out


def summary_line(report: dict) -> str:
    """One rank-0 line of attribution per capture — the answer a capture
    exists to give, without opening Perfetto. Accepts both the full
    report and the :func:`compact` record shape."""
    busy = report.get("device_busy_s") or 0.0
    cats = report.get("categories") or {}

    def pct(c):
        v = cats.get(c, 0.0)
        return f"{v / busy:.0%}" if busy > 0 else "-"

    colls = report.get("collectives") or {}
    coll_detail = (
        " (" + ", ".join(f"{k} {v:.3f}s" for k, v in colls.items()) + ")"
        if colls else ""
    )
    ov = (report.get("overlap") or {}).get(
        "overlap_frac", report.get("overlap_frac")
    )
    parts = [
        f"device busy {busy:.3f}s:",
        f"matmul/conv {pct('matmul_conv')},",
        f"collectives {pct('collective')}{coll_detail},",
        f"infeed/outfeed {pct('infeed_outfeed')},",
        f"fusion/other {pct('fusion_other')},",
        f"host {pct('host')};",
        f"comm/compute overlap {ov:.0%};" if isinstance(ov, (int, float))
        else "comm/compute overlap -;",
        f"infeed stall {report.get('infeed_stall_s', 0.0):.3f}s",
    ]
    if report.get("dropped"):
        n = sum(report["dropped"].values())
        parts.append(f"({n} trace file(s) dropped)")
    return " ".join(parts)


def format_text(report: dict) -> str:
    """Full human rendering for the ``obs xprof`` CLI."""
    lines = [
        f"capture {report.get('capture_dir')}: "
        f"{report.get('analyzed')}/{report.get('n_traces')} trace file(s) "
        f"analyzed"
    ]
    for e in report.get("errors", []):
        lines.append(f"  DROPPED [{e['kind']}] {e['error']}")
    busy = report["device_busy_s"]
    lines.append(f"device busy: {busy:.6f}s across "
                 f"{sum(t.get('op_threads', 0) for t in report.get('traces', []))} "
                 "op thread(s)")
    lines.append(f"{'category':>16} {'seconds':>12} {'share':>7}")
    for c in CATEGORIES:
        v = report["categories"][c]
        share = f"{v / busy:.1%}" if busy > 0 else "-"
        lines.append(f"{c:>16} {v:>12.6f} {share:>7}")
    if report.get("collectives"):
        lines.append("collectives by kind:")
        for k, v in report["collectives"].items():
            lines.append(f"{k:>16} {v:>12.6f}")
    ov = report["overlap"]
    frac = ov.get("overlap_frac")
    lines.append(
        f"comm/compute overlap: "
        + (f"{frac:.1%}" if isinstance(frac, (int, float)) else "-")
        + f" ({ov['overlapped_s']:.6f}s of {ov['comm_s']:.6f}s comm "
        f"overlapped with {ov['compute_s']:.6f}s compute)"
    )
    lines.append(f"infeed stall: {report['infeed_stall_s']:.6f}s")
    if report.get("top_ops"):
        lines.append("top ops by self time:")
        for o in report["top_ops"]:
            lines.append(
                f"  {o['self_s']:>10.6f}s  {o['name']}  "
                f"[{o['category']}] ×{o['count']}"
            )
    return "\n".join(lines)
