"""Triggered on-device profiler capture (``docs/observability.md``).

``--profile_dir`` alone captures epoch 0 and nothing else — but the step
you actually want on an XLA timeline is the one where something went
wrong: the loss spiked, a host straggled, the step silently recompiled.
By then a whole-run trace would be gigabytes deep. This module keeps the
profiler DISARMED until a health signal fires, then captures a bounded
window of steps:

* **Triggers** (``--profile_trigger``): anomaly findings, straggler
  flags, and mid-run retraces arm a capture; ``auto`` enables all three,
  a comma list (``anomaly,retrace``) selects. Anomaly/retrace captures
  run on rank 0; a straggler capture runs on the flagged host — the one
  whose timeline explains the skew.
* **Manual** (``--profile_steps a:b``): capture global steps ``[a, b)``
  unconditionally — the "I know which step is bad" path.
* **Bounds**: each triggered capture covers ``--profile_window`` steps
  (a manual capture owns its full ``[a, b)`` range), consecutive
  captures are separated by ``--profile_cooldown`` steps, and at most
  ``--profile_max_captures`` triggered captures run per process — an
  anomaly storm cannot turn the run into one endless trace.

This module is the ONE owner of the ``jax.profiler`` arming surface:
the triggered/manual capture machinery below, the :func:`trace`
blanket-capture context manager behind ``--profile_dir`` alone, the
:func:`annotate_step` marker, and the :class:`StepTimer` step clock
(all formerly ``tpu_dist/metrics/profiler.py`` — folded here so exactly
one module can hold the profiler lock).

Closing the loop: a capture answers nothing until something reads it
back, so every capture close runs the ``obs/xprof.py`` analyzer over
the freshly written directory (:func:`analyze_capture_quietly`) and
attaches the attribution to the stop event — the trainer turns that
into a ``profile_analysis`` history record (schema v6) and a rank-0
summary line. Analysis failures are counted (``xprof.analyze_errors``)
and reported in the event, never raised: forensics must not kill the
training process that captured them.

Cost contract: arming a trigger is host bookkeeping only, and even an
OPEN capture window only observes the program XLA already built — the
jaxpr-audit rule **TD108** proves the traced step is byte-identical with
a trigger armed and with a capture in flight (the TD105-TD107
discipline), and **TD110** extends the same proof across the armed
auto-analyze hook (a capture closed AND analyzed mid-run). Capture
failures (no profiler backend, a second trace already active) are
counted and disable further captures; they must never kill the training
step that tripped them.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Iterator, Optional, Tuple

from tpu_dist.obs import counters

#: Trigger kinds ``--profile_trigger`` may name (``auto`` = all three).
TRIGGER_KINDS = ("anomaly", "straggler", "retrace")


# --------------------------------------------------------------------------
# Blanket capture + step annotation (formerly tpu_dist/metrics/profiler.py)
# --------------------------------------------------------------------------


@contextlib.contextmanager
def trace(logdir: str, *, primary_only: bool = True) -> Iterator[None]:
    """Profile a whole region to ``logdir`` (the ``--profile_dir`` alone
    epoch-0 blanket capture; view in TensorBoard's profile tab or feed to
    ``obs xprof``). ``primary_only`` keeps the rank-0 discipline: other
    processes run the region untraced."""
    import jax  # noqa: PLC0415

    if primary_only and jax.process_index() != 0:
        yield
        return
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate_step(step: int):
    """Mark a training step in captures (shows as a named range)."""
    import jax  # noqa: PLC0415

    return jax.profiler.StepTraceAnnotation("train_step", step_num=step)


class StepTimer:
    """Steady-state throughput: skips warmup/compile steps, no per-step
    device sync (the device queue keeps the TPU busy; only ``finish``
    blocks).

    Beyond the mean, each post-warmup ``tick`` records a per-step lap on
    the monotonic clock, so the trainer's epoch summary can report tail
    latency (:meth:`percentiles`) — the p99 is where input stalls and
    stragglers live; a mean hides them completely."""

    def __init__(self, warmup_steps: int = 3):
        self.warmup_steps = warmup_steps
        self._seen = 0
        self._t0: Optional[float] = None
        self._last: Optional[float] = None
        self.steps = 0
        self.laps: list = []  # post-warmup per-step seconds, tick-to-tick

    def tick(self) -> None:
        now = time.perf_counter()
        self._seen += 1
        if self._seen == self.warmup_steps:
            self._t0 = now
            self._last = now
        elif self._seen > self.warmup_steps:
            self.steps += 1
            if self._last is not None:
                self.laps.append(now - self._last)
            self._last = now

    def finish(self, blocker=None) -> Optional[float]:
        """Seconds per steady-state step (None if too few steps).
        ``blocker``: array to ``block_until_ready`` before the clock."""
        if blocker is not None:
            import jax  # noqa: PLC0415

            jax.block_until_ready(blocker)
        if self._t0 is None or self.steps == 0:
            return None
        return (time.perf_counter() - self._t0) / self.steps

    def percentiles(self, qs=(50, 95, 99)) -> Optional[dict]:
        """``{"p50": s, "p95": s, "p99": s}`` over the recorded laps
        (nearest-rank; None with no laps — e.g. a 1-step epoch where
        every step was warmup)."""
        if not self.laps:
            return None
        laps = sorted(self.laps)
        n = len(laps)
        return {
            f"p{q}": laps[min(n - 1, max(0, int(round(q / 100.0 * n)) - 1))]
            for q in qs
        }


# --------------------------------------------------------------------------
# Auto-analysis of a closed capture (obs/xprof.py behind a never-raise wall)
# --------------------------------------------------------------------------


def analyze_capture_quietly(
    capture_dir: str, top_k: int = 10
) -> Tuple[Optional[dict], Optional[str]]:
    """Run the xprof analyzer over a freshly closed capture directory.
    Returns ``(compact_record, None)`` on success or ``(None, error)``
    on any failure — NEVER raises (the hook runs inside the training
    process; ``xprof.analyze_errors`` counts what went wrong, and
    per-trace drops inside a partial report count into
    ``xprof.dropped_traces``)."""
    try:
        from tpu_dist.obs import xprof  # noqa: PLC0415

        report = xprof.analyze_capture(capture_dir, top_k=top_k)
        rec = xprof.compact(report)
    except Exception as e:
        counters.inc("xprof.analyze_errors")
        return None, str(e)[:300]
    counters.inc("xprof.analyses")
    dropped = sum((report.get("dropped") or {}).values())
    if dropped:
        counters.inc("xprof.dropped_traces", dropped)
    return rec, None


def parse_trigger(spec: str) -> frozenset:
    """``off`` → empty set, ``auto`` → all kinds, else a comma list of
    :data:`TRIGGER_KINDS`. Raises ValueError on anything else."""
    spec = (spec or "off").strip().lower()
    if spec in ("off", ""):
        return frozenset()
    if spec == "auto":
        return frozenset(TRIGGER_KINDS)
    kinds = frozenset(p.strip() for p in spec.split(",") if p.strip())
    bad = kinds - frozenset(TRIGGER_KINDS)
    if bad:
        raise ValueError(
            f"unknown profile trigger(s) {sorted(bad)}; use 'off', 'auto', "
            f"or a comma list of {TRIGGER_KINDS}"
        )
    return kinds


def parse_steps(spec: Optional[str]) -> Optional[Tuple[int, int]]:
    """``--profile_steps a:b`` → ``(a, b)`` global-step window ``[a, b)``.
    Raises ValueError on a malformed or empty range."""
    if not spec:
        return None
    parts = spec.split(":")
    try:
        a, b = (int(p) for p in parts)
    except (TypeError, ValueError):
        raise ValueError(
            f"--profile_steps must be 'a:b' (global steps, capture [a, b)), "
            f"got {spec!r}"
        ) from None
    if a < 0 or b <= a:
        raise ValueError(
            f"--profile_steps needs 0 <= a < b, got {spec!r} (empty window)"
        )
    return a, b


class TriggeredProfiler:
    """Bounded ``jax.profiler`` windows armed by health signals.

    The trainer calls :meth:`on_step` once per step (host-side, before
    dispatch) with the run-global step index; :meth:`arm` is called from
    the anomaly/straggler/retrace sites. Each capture lands in its own
    subdirectory of ``out_dir`` (``capture_<n>_s<step>_<reason>``), so a
    TensorBoard pointed at ``out_dir`` lists every window.
    """

    def __init__(
        self,
        out_dir: str,
        *,
        window_steps: int = 8,
        cooldown_steps: int = 200,
        max_captures: int = 3,
        manual_range: Optional[Tuple[int, int]] = None,
        analyze: bool = True,
    ):
        if window_steps < 1:
            raise ValueError(f"window_steps must be >= 1, got {window_steps}")
        if cooldown_steps < 0 or max_captures < 0:
            raise ValueError("cooldown_steps/max_captures must be >= 0")
        self.out_dir = out_dir
        self.window_steps = window_steps
        self.cooldown_steps = cooldown_steps
        self.max_captures = max_captures
        self.manual_range = manual_range
        self.analyze = analyze  # run obs/xprof over every closed capture
        self.captures = 0            # triggered captures taken (cap applies)
        self._armed: Optional[str] = None
        self._active: Optional[dict] = None  # {"reason","start_step","dir"}
        self._last_stop_step: Optional[int] = None
        self._last_step: Optional[int] = None  # newest on_step() index seen
        self._manual_done = False
        self._broken = False         # a capture failed: no more attempts

    @property
    def armed(self) -> Optional[str]:
        return self._armed

    @property
    def active(self) -> bool:
        return self._active is not None

    def arm(self, reason: str) -> bool:
        """Request a capture starting at the next step. No-ops (False)
        while a capture is in flight, once the capture cap is spent, or
        after a backend failure."""
        if self._broken or self._active is not None:
            return False
        if self.captures >= self.max_captures:
            counters.inc("profile.skipped_capped")
            return False
        if self._armed is None:
            counters.inc("profile.armed")
        self._armed = reason
        return True

    def on_step(self, step: int) -> Optional[dict]:
        """Advance the capture state machine at global step ``step``.
        Returns a ``{"event": "start"|"stop", ...}`` dict when a window
        opened or closed on this call (the trainer logs it), else None."""
        self._last_step = step
        if self._active is not None:
            # a manual capture owns its FULL [a, b) range — window_steps
            # bounds triggered captures only
            if self._active["reason"] == "manual":
                if self.manual_range is not None and step >= self.manual_range[1]:
                    return self._stop(step)
            elif step - self._active["start_step"] >= self.window_steps:
                return self._stop(step)
            return None
        if (
            self.manual_range is not None
            and not self._manual_done
            and self.manual_range[0] <= step < self.manual_range[1]
        ):
            self._manual_done = True
            return self._start(step, "manual")
        if self._armed is not None:
            if (
                self._last_stop_step is not None
                and step - self._last_stop_step < self.cooldown_steps
            ):
                return None  # stays armed; fires when the cooldown expires
            reason, self._armed = self._armed, None
            self.captures += 1
            return self._start(step, reason)
        return None

    def _start(self, step: int, reason: str) -> Optional[dict]:
        tag = "".join(
            c if c.isalnum() or c in "-_" else "_" for c in reason
        )[:48]
        n = self.captures if reason != "manual" else "manual"
        d = os.path.join(self.out_dir, f"capture_{n}_s{step}_{tag}")
        try:
            import jax  # noqa: PLC0415

            os.makedirs(d, exist_ok=True)
            jax.profiler.start_trace(d)
        except Exception as e:
            # a second live trace, a missing profiler backend, a full disk:
            # training outranks forensics — record and stand down for good
            self._broken = True
            self._active = None
            counters.inc("profile.errors")
            return {"event": "error", "reason": reason, "error": str(e)[:200]}
        self._active = {"reason": reason, "start_step": step, "dir": d}
        counters.inc("profile.captures")
        return {
            "event": "start", "reason": reason, "step": step, "dir": d,
            "window_steps": (
                self.manual_range[1] - self.manual_range[0]
                if reason == "manual" and self.manual_range is not None
                else self.window_steps
            ),
        }

    def _stop(self, step: int) -> Optional[dict]:
        info, self._active = self._active, None
        self._last_stop_step = step
        try:
            import jax  # noqa: PLC0415

            jax.profiler.stop_trace()
        except Exception as e:
            self._broken = True
            counters.inc("profile.errors")
            return {"event": "error", "reason": info["reason"],
                    "error": str(e)[:200]}
        ev = {
            "event": "stop", "reason": info["reason"],
            "start_step": info["start_step"], "stop_step": step,
            "steps": step - info["start_step"], "dir": info["dir"],
        }
        if self.analyze:
            # the auto-analyze hook: read the capture back NOW, while the
            # trainer still knows which steps it covered. Host-side file
            # crunching on a closed capture — TD110 proves the traced step
            # is byte-identical across the whole arm→capture→analyze
            # cycle; failures are counted, reported, and never raised.
            analysis, err = analyze_capture_quietly(info["dir"])
            if analysis is not None:
                ev["analysis"] = analysis
            elif err is not None:
                ev["analysis_error"] = err
        return ev

    def close(self) -> Optional[dict]:
        """Stop any in-flight capture (fit exit, including error exits) —
        an unterminated trace would hold the profiler lock for the
        process's life. The stop event reports the steps that actually
        ran (the newest ``on_step`` index, not the planned window) and is
        flagged ``aborted`` so the record never overstates coverage."""
        if self._active is None:
            return None
        last = (
            self._last_step if self._last_step is not None
            else self._active["start_step"]
        )
        ev = self._stop(last + 1)
        if ev is not None and ev.get("event") == "stop":
            ev["aborted"] = True  # the run ended inside the window
        return ev
