"""Triggered on-device profiler capture (``docs/observability.md``).

``--profile_dir`` alone captures epoch 0 and nothing else — but the step
you actually want on an XLA timeline is the one where something went
wrong: the loss spiked, a host straggled, the step silently recompiled.
By then a whole-run trace would be gigabytes deep. This module keeps the
profiler DISARMED until a health signal fires, then captures a bounded
window of steps:

* **Triggers** (``--profile_trigger``): anomaly findings, straggler
  flags, and mid-run retraces arm a capture; ``auto`` enables all three,
  a comma list (``anomaly,retrace``) selects. Anomaly/retrace captures
  run on rank 0; a straggler capture runs on the flagged host — the one
  whose timeline explains the skew.
* **Manual** (``--profile_steps a:b``): capture global steps ``[a, b)``
  unconditionally — the "I know which step is bad" path.
* **Bounds**: each triggered capture covers ``--profile_window`` steps
  (a manual capture owns its full ``[a, b)`` range), consecutive
  captures are separated by ``--profile_cooldown`` steps, and at most
  ``--profile_max_captures`` triggered captures run per process — an
  anomaly storm cannot turn the run into one endless trace.

Cost contract: arming a trigger is host bookkeeping only, and even an
OPEN capture window only observes the program XLA already built — the
jaxpr-audit rule **TD108** proves the traced step is byte-identical with
a trigger armed and with a capture in flight (the TD105-TD107
discipline). Capture failures (no profiler backend, a second trace
already active) are counted and disable further captures; they must
never kill the training step that tripped them.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

from tpu_dist.obs import counters

#: Trigger kinds ``--profile_trigger`` may name (``auto`` = all three).
TRIGGER_KINDS = ("anomaly", "straggler", "retrace")


def parse_trigger(spec: str) -> frozenset:
    """``off`` → empty set, ``auto`` → all kinds, else a comma list of
    :data:`TRIGGER_KINDS`. Raises ValueError on anything else."""
    spec = (spec or "off").strip().lower()
    if spec in ("off", ""):
        return frozenset()
    if spec == "auto":
        return frozenset(TRIGGER_KINDS)
    kinds = frozenset(p.strip() for p in spec.split(",") if p.strip())
    bad = kinds - frozenset(TRIGGER_KINDS)
    if bad:
        raise ValueError(
            f"unknown profile trigger(s) {sorted(bad)}; use 'off', 'auto', "
            f"or a comma list of {TRIGGER_KINDS}"
        )
    return kinds


def parse_steps(spec: Optional[str]) -> Optional[Tuple[int, int]]:
    """``--profile_steps a:b`` → ``(a, b)`` global-step window ``[a, b)``.
    Raises ValueError on a malformed or empty range."""
    if not spec:
        return None
    parts = spec.split(":")
    try:
        a, b = (int(p) for p in parts)
    except (TypeError, ValueError):
        raise ValueError(
            f"--profile_steps must be 'a:b' (global steps, capture [a, b)), "
            f"got {spec!r}"
        ) from None
    if a < 0 or b <= a:
        raise ValueError(
            f"--profile_steps needs 0 <= a < b, got {spec!r} (empty window)"
        )
    return a, b


class TriggeredProfiler:
    """Bounded ``jax.profiler`` windows armed by health signals.

    The trainer calls :meth:`on_step` once per step (host-side, before
    dispatch) with the run-global step index; :meth:`arm` is called from
    the anomaly/straggler/retrace sites. Each capture lands in its own
    subdirectory of ``out_dir`` (``capture_<n>_s<step>_<reason>``), so a
    TensorBoard pointed at ``out_dir`` lists every window.
    """

    def __init__(
        self,
        out_dir: str,
        *,
        window_steps: int = 8,
        cooldown_steps: int = 200,
        max_captures: int = 3,
        manual_range: Optional[Tuple[int, int]] = None,
    ):
        if window_steps < 1:
            raise ValueError(f"window_steps must be >= 1, got {window_steps}")
        if cooldown_steps < 0 or max_captures < 0:
            raise ValueError("cooldown_steps/max_captures must be >= 0")
        self.out_dir = out_dir
        self.window_steps = window_steps
        self.cooldown_steps = cooldown_steps
        self.max_captures = max_captures
        self.manual_range = manual_range
        self.captures = 0            # triggered captures taken (cap applies)
        self._armed: Optional[str] = None
        self._active: Optional[dict] = None  # {"reason","start_step","dir"}
        self._last_stop_step: Optional[int] = None
        self._last_step: Optional[int] = None  # newest on_step() index seen
        self._manual_done = False
        self._broken = False         # a capture failed: no more attempts

    @property
    def armed(self) -> Optional[str]:
        return self._armed

    @property
    def active(self) -> bool:
        return self._active is not None

    def arm(self, reason: str) -> bool:
        """Request a capture starting at the next step. No-ops (False)
        while a capture is in flight, once the capture cap is spent, or
        after a backend failure."""
        if self._broken or self._active is not None:
            return False
        if self.captures >= self.max_captures:
            counters.inc("profile.skipped_capped")
            return False
        if self._armed is None:
            counters.inc("profile.armed")
        self._armed = reason
        return True

    def on_step(self, step: int) -> Optional[dict]:
        """Advance the capture state machine at global step ``step``.
        Returns a ``{"event": "start"|"stop", ...}`` dict when a window
        opened or closed on this call (the trainer logs it), else None."""
        self._last_step = step
        if self._active is not None:
            # a manual capture owns its FULL [a, b) range — window_steps
            # bounds triggered captures only
            if self._active["reason"] == "manual":
                if self.manual_range is not None and step >= self.manual_range[1]:
                    return self._stop(step)
            elif step - self._active["start_step"] >= self.window_steps:
                return self._stop(step)
            return None
        if (
            self.manual_range is not None
            and not self._manual_done
            and self.manual_range[0] <= step < self.manual_range[1]
        ):
            self._manual_done = True
            return self._start(step, "manual")
        if self._armed is not None:
            if (
                self._last_stop_step is not None
                and step - self._last_stop_step < self.cooldown_steps
            ):
                return None  # stays armed; fires when the cooldown expires
            reason, self._armed = self._armed, None
            self.captures += 1
            return self._start(step, reason)
        return None

    def _start(self, step: int, reason: str) -> Optional[dict]:
        tag = "".join(
            c if c.isalnum() or c in "-_" else "_" for c in reason
        )[:48]
        n = self.captures if reason != "manual" else "manual"
        d = os.path.join(self.out_dir, f"capture_{n}_s{step}_{tag}")
        try:
            import jax  # noqa: PLC0415

            os.makedirs(d, exist_ok=True)
            jax.profiler.start_trace(d)
        except Exception as e:
            # a second live trace, a missing profiler backend, a full disk:
            # training outranks forensics — record and stand down for good
            self._broken = True
            self._active = None
            counters.inc("profile.errors")
            return {"event": "error", "reason": reason, "error": str(e)[:200]}
        self._active = {"reason": reason, "start_step": step, "dir": d}
        counters.inc("profile.captures")
        return {
            "event": "start", "reason": reason, "step": step, "dir": d,
            "window_steps": (
                self.manual_range[1] - self.manual_range[0]
                if reason == "manual" and self.manual_range is not None
                else self.window_steps
            ),
        }

    def _stop(self, step: int) -> Optional[dict]:
        info, self._active = self._active, None
        self._last_stop_step = step
        try:
            import jax  # noqa: PLC0415

            jax.profiler.stop_trace()
        except Exception as e:
            self._broken = True
            counters.inc("profile.errors")
            return {"event": "error", "reason": info["reason"],
                    "error": str(e)[:200]}
        return {
            "event": "stop", "reason": info["reason"],
            "start_step": info["start_step"], "stop_step": step,
            "steps": step - info["start_step"], "dir": info["dir"],
        }

    def close(self) -> Optional[dict]:
        """Stop any in-flight capture (fit exit, including error exits) —
        an unterminated trace would hold the profiler lock for the
        process's life. The stop event reports the steps that actually
        ran (the newest ``on_step`` index, not the planned window) and is
        flagged ``aborted`` so the record never overstates coverage."""
        if self._active is None:
            return None
        last = (
            self._last_step if self._last_step is not None
            else self._active["start_step"]
        )
        ev = self._stop(last + 1)
        if ev is not None and ev.get("event") == "stop":
            ev["aborted"] = True  # the run ended inside the window
        return ev
