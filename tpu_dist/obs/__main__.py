"""CLI: ``python -m tpu_dist.obs`` — offline run-telemetry reports.

Subcommands::

    summarize <run.jsonl> [--format text|json]
        Per-epoch throughput, step-time p50/p95/p99, data-stall fraction,
        counter deltas, straggler/alert findings — from a ``--log_file``
        JSONL.  With ``--bench`` the input is a bench.py JSON instead:
        per-record table with capture fingerprints, flagging byte-
        identical re-emitted captures as STALE.

    tail <run.jsonl> [--heartbeat hb.json] [--interval S] [--once]
        Follow a LIVE run from another terminal: rolling per-epoch table
        (throughput / p50 / stall / MFU / goodput) plus live alert,
        anomaly, straggler, and heartbeat-liveness lines, torn-tail
        tolerant.  Exits when the run-end record lands; ``--once``
        renders the current state and returns.

    export-trace <run.jsonl> [-o trace.json]
        Chrome trace-event JSON (Perfetto / chrome://tracing loadable)
        from the run's drained spans + synthesized epoch/eval bars.

    compare <baseline.jsonl> <candidate.jsonl> [--threshold 0.05]
            [--bench] [--goodput] [--slo] [--format text|json]
    compare <candidate> --against-archive <archive.jsonl> [--bench]
            [--band-k 3.0] [--band-window 20]
        Regression gate: diff throughput, step-time percentiles, stall
        fraction, MFU, goodput fraction, and final metrics between two
        runs' logs (or, with --bench, two bench.py JSON outputs).
        --goodput restricts the gate to the time-to-useful-work metrics
        (run-level goodput_frac + stall fraction); --slo to the serving
        SLO metrics (requests/s, latency p50/p99, TTFB, availability —
        lower latency is never flagged). Exits 1 on any regression
        beyond the threshold — wire it into CI.  With
        --against-archive the single input gates against the rolling
        median ± k·MAD band of the archive's last N non-stale records
        per metric (obs/archive.py) instead of one baseline — a stale
        or all-stale band is exit 2, never a silent pass.

    archive ingest <artifact> [<artifact> ...] --archive <archive.jsonl>
        Longitudinal run archive (``obs/archive.py``): fold run
        artifacts — bench JSONLs / LAST_GOOD_BENCH.json, the driver's
        BENCH_*.json / MULTICHIP_*.json wrappers, --log_file histories,
        shard/plan/tune reports — into one append-only archive of
        schema-pinned archive_record_v1 lines. Idempotent by capture/
        content fingerprint; stale re-emissions archive FLAGGED (the
        PR 7 staleness discipline) and never join a band; torn tails
        and newer schemas are counted, never fatal.

    trend <archive.jsonl> [--metric NAME] [--window N] [--blame]
          [--inject-regression] [--format text|json]
        Per-metric series over the archive with an offline CUSUM
        changepoint detector; --blame names the first archived record
        where each shifted metric moved (fingerprint + run_id + source
        — i.e. which PR's artifact moved it). --inject-regression runs
        the TD124 probe: a synthetic past-band candidate must come
        back REGRESSED, an improvement clean, and an injected step
        localized to the exact record — a dead detector exits 2.

    hub --run name=metrics.prom[,hb=hb.json][,port=P][,kind=serve] ...
        [--fleet fleet.prom] [--out federated.prom] [--port P]
        [--interval S] [--once]
        Pod telemetry hub (``obs/hub.py``): pull-aggregate every run's
        OpenMetrics exposition into ONE federated exposition with
        per-run labels plus pod rollups (chips from the capacity
        ledger, per-class goodput, worst stall, breach count, last
        arbitration decision id), torn/stale/dead-run tolerant with
        counted drops.  ``--once`` scrapes once and prints (or writes
        ``--out``); otherwise loops at ``--interval``, publishing to
        the textfile and/or an HTTP ``/metrics`` on ``--port``.

    pod <host0.jsonl> <host1.jsonl> ... [--heartbeat hb.json ...]
        [--trace-out pod_trace.json] [--format text|json]
        Cross-host aggregation: per-host goodput ledgers side by side,
        per-epoch skew with phase attribution, heartbeat liveness,
        per-host profiler captures with their analysis rollups, and
        (with --trace-out) one merged Perfetto timeline with a track per
        host, aligned on the shared run clock.

    xprof <capture_dir | trace.json[.gz]> [--top K] [--format text|json]
        Offline device-time attribution of a ``jax.profiler`` capture
        (``obs/xprof.py``): per-category device seconds, collectives by
        kind, comm/compute overlap fraction, infeed stall, top ops.
        Accepts a capture directory (``plugins/profile/...`` inside —
        multi-host trees included) or one Chrome trace file.

    memory <run.jsonl> [--format text|json]
    memory --oom <traceback.txt> [--format text|json]
        HBM report (``obs/memory.py``): the run's ledger snapshots
        (static per-leaf accounting, XLA memory_analysis waterfall,
        census/allocator reconciliation), the per-epoch ``mem.*`` gauge
        series, OOM events, and the ``peak_hbm_bytes`` scalar the
        compare gate regresses on. With ``--oom`` the input is a raw
        XLA RESOURCE_EXHAUSTED traceback instead, parsed into the typed
        allocation report. Exit 1 when the history holds no memory
        telemetry (or the text parses as no OOM).

    postmortem <dir> [<dir> ...] [--out bundle.json] [--annotate]
        [--tail N] [--format text|json]
        Crash forensics (``obs/postmortem.py``): walk the given dirs for
        per-rank artifacts — SIGKILL-surviving flight rings + stack
        dumps (``--crash_dir``), left-behind heartbeats, last
        OpenMetrics expositions, history JSONLs — and fold them into
        one bundle: decoded ring tails (last step before death), parsed
        stack dumps (the stuck frame by name), per-rank verdicts
        (clean / preempted / fatal / no-clean-exit). ``--annotate``
        appends a ``postmortem`` record to the discovered history (the
        launcher watchdog's auto-invoke does this). Exit 1 when the
        dirs hold no forensic artifacts.

Exit codes: 0 ok, 1 empty/unusable input (or, for ``compare``, a
regression), 2 bad invocation or I/O error.
The analysis itself is pure file crunching — no device, no backend.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tpu_dist.obs import summarize as summ


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu_dist.obs",
        description="offline run-telemetry reports over a --log_file JSONL",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    s = sub.add_parser("summarize", help="per-epoch throughput/latency/counter report")
    s.add_argument("log", help="JSONL history written by --log_file")
    s.add_argument("--format", choices=("text", "json"), default="text")
    s.add_argument(
        "--bench", action="store_true",
        help="input is a bench.py JSON (one record per line): per-record "
             "report with capture fingerprints; byte-identical re-emitted "
             "captures are flagged STALE instead of read as fresh",
    )
    tl = sub.add_parser(
        "tail", help="follow a live run: rolling epoch table + alerts"
    )
    tl.add_argument("log", help="the run's --log_file JSONL (may still be growing)")
    tl.add_argument(
        "--heartbeat", default=None, metavar="FILE",
        help="the run's --heartbeat_file for a liveness/staleness row",
    )
    tl.add_argument("--interval", type=float, default=2.0, metavar="S",
                    help="poll/redraw interval (default 2s)")
    tl.add_argument("--once", action="store_true",
                    help="render the current state once and exit")
    tl.add_argument("--rows", type=int, default=None, metavar="N",
                    help="epochs kept in the rolling table")
    t = sub.add_parser("export-trace", help="write Chrome trace-event JSON")
    t.add_argument("log", help="JSONL history written by --log_file")
    t.add_argument("-o", "--out", default=None, help="output path (default: <log>.trace.json)")
    c = sub.add_parser(
        "compare",
        help="regression gate: diff two runs' telemetry, exit 1 on regression",
    )
    c.add_argument("baseline", help="baseline --log_file JSONL (or bench "
                                    "JSON with --bench); with "
                                    "--against-archive this is the ONE "
                                    "candidate input")
    c.add_argument("candidate", nargs="?", default=None,
                   help="candidate --log_file JSONL (or bench JSON with "
                        "--bench); omitted with --against-archive")
    c.add_argument(
        "--threshold", type=float, default=0.05, metavar="FRAC",
        help="relative regression tolerance (default 0.05 = 5%%); each "
             "metric adds its own absolute noise slack on top",
    )
    c.add_argument(
        "--bench", action="store_true",
        help="inputs are bench.py JSON outputs (one object per line), "
             "matched by their 'metric' name",
    )
    c.add_argument(
        "--goodput", action="store_true",
        help="gate on the time-to-useful-work metrics only (run-level "
             "goodput fraction + data-stall fraction); two goodput-less "
             "pre-v4 logs then compare nothing → exit 2, never a silent "
             "pass",
    )
    c.add_argument(
        "--slo", action="store_true",
        help="gate on the serving SLO metrics only (requests/s, latency "
             "p50/p99 bounds, TTFB p99, availability, batch occupancy — "
             "from serve records, schema v10); directions come from the "
             "metric registry, so a lower-latency candidate is never "
             "flagged; two serve-less logs compare nothing → exit 2",
    )
    c.add_argument(
        "--against-archive", default=None, metavar="ARCHIVE",
        dest="against_archive",
        help="gate the single candidate input against this longitudinal "
             "archive's rolling median ± k·MAD bands (last N non-stale "
             "records per metric, obs/archive.py) instead of one "
             "baseline; a candidate re-emitting an archived capture, or "
             "a band left with only STALE records, never passes "
             "silently (exit 2)",
    )
    c.add_argument("--band-k", type=float, default=None, metavar="K",
                   help="band half-width in MADs (--against-archive; "
                        "default 3.0)")
    c.add_argument("--band-window", type=int, default=None, metavar="N",
                   help="band over the last N non-stale records "
                        "(--against-archive; default 20)")
    c.add_argument("--format", choices=("text", "json"), default="text")
    ar = sub.add_parser(
        "archive",
        help="longitudinal run archive: fold bench/driver/history/report "
             "artifacts into one append-only fingerprinted archive.jsonl",
    )
    ar.add_argument("action", choices=("ingest",),
                    help="'ingest' folds the given artifacts in "
                         "(idempotent by fingerprint)")
    ar.add_argument("inputs", nargs="+",
                    help="artifacts: bench JSONL / LAST_GOOD_BENCH.json, "
                         "driver BENCH_*.json / MULTICHIP_*.json, "
                         "--log_file histories, shard/plan/tune reports")
    ar.add_argument("--archive", "-a", default="archive.jsonl",
                    metavar="PATH", help="the archive JSONL to append to "
                                         "(default archive.jsonl)")
    ar.add_argument("--format", choices=("text", "json"), default="text")
    tr = sub.add_parser(
        "trend",
        help="per-metric series over the archive + CUSUM changepoint "
             "blame (--blame) + the TD124 --inject-regression probe",
    )
    tr.add_argument("archive", help="the archive JSONL (archive ingest)")
    tr.add_argument("--metric", default=None,
                    help="render only this metric's series")
    tr.add_argument("--window", type=int, default=None, metavar="N",
                    help="keep only the trailing N points per series")
    tr.add_argument("--blame", action="store_true",
                    help="name the first archived record after each "
                         "detected shift (fingerprint + run_id + source)")
    tr.add_argument(
        "--inject-regression", action="store_true",
        dest="inject_regression",
        help="TD124 probe: injected past-band candidates must come back "
             "caught, improvements clean, and an injected changepoint "
             "localized to the exact record — a dead detector exits 2",
    )
    tr.add_argument("--format", choices=("text", "json"), default="text")
    hb = sub.add_parser(
        "hub",
        help="pod telemetry hub: federate every run's exposition into "
             "one /metrics with per-run labels + pod rollups",
    )
    hb.add_argument(
        "--run", action="append", default=[], metavar="SPEC", dest="runs",
        help="one run source: name=metrics_path[,hb=heartbeat][,port=P]"
             "[,kind=train|serve] (or name=port:P for HTTP-only); "
             "repeatable — the hub needs at least one",
    )
    hb.add_argument(
        "--fleet", default=None, metavar="FILE",
        help="the fleet scheduler's exposition (write_exposition) — the "
             "capacity ledger the chip/decision rollups come from",
    )
    hb.add_argument("--out", default=None, metavar="FILE",
                    help="publish the federated exposition to this "
                         "textfile (atomic tmp+replace)")
    hb.add_argument("--port", type=int, default=None, metavar="P",
                    help="also serve GET /metrics on this port")
    hb.add_argument("--interval", type=float, default=5.0, metavar="S",
                    help="scrape/publish interval (default 5s)")
    hb.add_argument("--once", action="store_true",
                    help="one aggregation pass, print (or --out), exit")
    hb.add_argument("--stale-after", type=float, default=None, metavar="S",
                    help="heartbeat age beyond which a run reads dead "
                         "(default: hub.STALE_AFTER_S)")
    hb.add_argument("--archive", default=None, metavar="PATH",
                    help="append one pod-rollup archive_record_v1 per "
                         "aggregation pass to this longitudinal archive "
                         "(obs/archive.py) — fleet goodput / breach "
                         "count / chip capacity trend like bench metrics")
    pd = sub.add_parser(
        "pod",
        help="merge per-host logs into one cross-host report / timeline",
    )
    pd.add_argument("logs", nargs="+", help="per-host JSONL histories")
    pd.add_argument(
        "--heartbeat", action="append", default=[], metavar="FILE",
        help="per-host heartbeat file(s) to include as liveness rows",
    )
    pd.add_argument(
        "--trace-out", default=None, metavar="PATH",
        help="also write one merged Perfetto trace (a track per host)",
    )
    pd.add_argument("--format", choices=("text", "json"), default="text")
    xp = sub.add_parser(
        "xprof",
        help="device-time attribution of a jax.profiler capture",
    )
    xp.add_argument(
        "capture",
        help="capture directory (plugins/profile/<run>/*.trace.json.gz "
             "inside, pod-collected per-host trees included) or a single "
             "Chrome trace .json/.json.gz file",
    )
    xp.add_argument("--top", type=int, default=10, metavar="K",
                    help="ops listed in the top-self-time table")
    xp.add_argument("--format", choices=("text", "json"), default="text")
    mm = sub.add_parser(
        "memory",
        help="HBM report: ledger snapshots, mem.* gauge series, OOM "
             "events, peak-HBM gate scalar (or --oom: parse a raw "
             "RESOURCE_EXHAUSTED traceback)",
    )
    mm.add_argument(
        "input",
        help="a --log_file JSONL history (default) or, with --oom, a "
             "text file holding an XLA RESOURCE_EXHAUSTED message",
    )
    mm.add_argument(
        "--oom", action="store_true",
        help="the input is a raw OOM traceback text, not a history — "
             "parse it into the typed allocation report",
    )
    mm.add_argument("--format", choices=("text", "json"), default="text")
    pm = sub.add_parser(
        "postmortem",
        help="assemble per-rank crash-forensics bundles from a run's "
             "leftover files (flight rings, stack dumps, heartbeats, "
             "expositions, history tails)",
    )
    pm.add_argument(
        "dirs", nargs="+",
        help="directories to scan (--crash_dir / --heartbeat_dir / "
             "--metrics_dir / wherever the run's files landed); first "
             "dir receives the bundle by default",
    )
    pm.add_argument("--out", default=None, metavar="PATH",
                    help="bundle output path (default <first dir>/"
                         "postmortem.json)")
    pm.add_argument(
        "--annotate", action="store_true",
        help="append a 'postmortem' record (history schema v9) to the "
             "discovered rank-0 history JSONL so summarize/tail/pod "
             "render the crash — the watchdog auto-invoke sets this",
    )
    pm.add_argument("--tail", type=int, default=40, metavar="N",
                    help="ring records kept per rank in the bundle")
    pm.add_argument("--format", choices=("text", "json"), default="text")
    args = ap.parse_args(argv)

    if args.cmd == "memory":
        from tpu_dist.obs import memory as memory_lib

        if args.oom:
            try:
                with open(args.input, errors="replace") as f:
                    text = f.read()
            except OSError as e:
                print(f"tpu_dist.obs: cannot read {args.input}: {e}",
                      file=sys.stderr)
                return 2
            report = memory_lib.parse_resource_exhausted(text)
            if report is None:
                print(
                    f"tpu_dist.obs: {args.input} carries no "
                    "RESOURCE_EXHAUSTED / out-of-memory signature",
                    file=sys.stderr,
                )
                return 1
            if args.format == "json":
                print(json.dumps(report, indent=2))
            else:
                print(memory_lib.format_oom_text(report))
            return 0
        try:
            records, _bad = summ.load_records(args.input)
        except OSError as e:
            print(f"tpu_dist.obs: cannot read {args.input}: {e}",
                  file=sys.stderr)
            return 2
        report = memory_lib.memory_report(records)
        if not (report["ledgers"] or report["epoch_series"]
                or report["ooms"]):
            print(
                f"tpu_dist.obs: no memory telemetry (memory records or "
                f"mem.* gauges) in {args.input}", file=sys.stderr,
            )
            return 1
        if args.format == "json":
            print(json.dumps(report, indent=2, default=str))
        else:
            print(memory_lib.format_report_text(report))
        return 0

    if args.cmd == "postmortem":
        from tpu_dist.obs import postmortem as postmortem_lib

        missing = [d for d in args.dirs if not os.path.isdir(d)]
        if missing:
            print(
                f"tpu_dist.obs: cannot read director"
                f"{'y' if len(missing) == 1 else 'ies'} "
                + ", ".join(missing), file=sys.stderr,
            )
            return 2
        report, bundle = postmortem_lib.run_postmortem(
            args.dirs, out=args.out, annotate=args.annotate, tail=args.tail,
        )
        if bundle is None:
            print(
                "tpu_dist.obs: no forensic artifacts (flight rings, "
                "stack dumps, heartbeats, expositions, histories) found "
                "in " + ", ".join(args.dirs), file=sys.stderr,
            )
            return 1
        if args.format == "json":
            print(json.dumps(report, indent=2, default=str))
        else:
            print(postmortem_lib.format_text(report))
        print(f"bundle written to {bundle}")
        return 0

    if args.cmd == "xprof":
        from tpu_dist.obs import xprof as xprof_lib

        if not os.path.exists(args.capture):
            print(f"tpu_dist.obs: cannot read {args.capture}: no such "
                  "file or directory", file=sys.stderr)
            return 2
        try:
            if os.path.isdir(args.capture):
                report = xprof_lib.analyze_capture(args.capture, top_k=args.top)
            else:
                report = xprof_lib.analyze_trace_file(
                    args.capture, top_k=args.top
                )
        except xprof_lib.CaptureError as e:
            # typed: empty capture / all traces malformed / no device track
            print(f"tpu_dist.obs: {e}", file=sys.stderr)
            return 1
        except OSError as e:
            print(f"tpu_dist.obs: cannot read {args.capture}: {e}",
                  file=sys.stderr)
            return 2
        if args.format == "json":
            print(json.dumps(report, indent=2))
        else:
            print(xprof_lib.format_text(report))
        return 0

    if args.cmd == "tail":
        from tpu_dist.obs import tail as tail_lib

        return tail_lib.run_tail(
            args.log,
            heartbeat=args.heartbeat,
            interval=args.interval,
            once=args.once,
            **({"rows": args.rows} if args.rows else {}),
        )

    if args.cmd == "summarize" and args.bench:
        from tpu_dist.obs import compare as compare_lib

        try:
            report = compare_lib.bench_report(args.log)
        except OSError as e:
            print(f"tpu_dist.obs: cannot read {args.log}: {e}", file=sys.stderr)
            return 2
        except ValueError as e:
            print(f"tpu_dist.obs: {e}", file=sys.stderr)
            return 1
        if args.format == "json":
            print(json.dumps(report, indent=2))
        else:
            print(compare_lib.format_bench_report(report))
        return 0

    if args.cmd == "hub":
        from tpu_dist.obs import hub as hub_lib

        if not args.runs:
            print("tpu_dist.obs: hub needs at least one --run "
                  "name=metrics_path[,hb=...,port=...,kind=...]",
                  file=sys.stderr)
            return 2
        try:
            sources = [hub_lib.parse_source(s) for s in args.runs]
            h = hub_lib.TelemetryHub(
                sources,
                fleet_exposition=args.fleet,
                **({"stale_after_s": args.stale_after}
                   if args.stale_after is not None else {}),
            )
        except ValueError as e:
            print(f"tpu_dist.obs: {e}", file=sys.stderr)
            return 2
        if args.once:
            snap = h.collect()
            text = h.federated(snap)
            if args.out:
                h.write(args.out, snap)
                print(f"federated {snap['rollup']['runs_aggregated']} "
                      f"run(s) to {args.out}")
            else:
                print(text, end="")
            if args.archive:
                from tpu_dist.obs import archive as archive_lib

                archive_lib.append_hub_snapshot(args.archive, snap)
            return 0 if snap["rollup"]["runs_aggregated"] else 1
        server = hub_lib.HubServer(args.port) if args.port else None
        if server is not None:
            print(f"hub serving /metrics on :{server.port}")
        try:
            import time as _time

            while True:
                snap = h.collect()
                text = h.federated(snap)
                if args.out:
                    h.write(args.out, snap)
                if server is not None:
                    server.publish(text)
                if args.archive:
                    from tpu_dist.obs import archive as archive_lib

                    # one pod-rollup record per interval — the fleet's
                    # goodput/breach/chip series grows while the hub runs
                    archive_lib.append_hub_snapshot(args.archive, snap)
                _time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0
        finally:
            if server is not None:
                server.close()

    if args.cmd == "pod":
        from tpu_dist.obs import aggregate as aggregate_lib

        hosts = []
        for path in args.logs:
            try:
                records, _bad = summ.load_records(path)
            except OSError as e:
                print(f"tpu_dist.obs: cannot read {path}: {e}", file=sys.stderr)
                return 2
            if not records:
                print(f"tpu_dist.obs: no records in {path}", file=sys.stderr)
                return 1
            hosts.append((path, records))
        report = aggregate_lib.pod_report(hosts, heartbeats=args.heartbeat)
        if args.format == "json":
            print(json.dumps(report, indent=2))
        else:
            print(aggregate_lib.format_text(report))
        if args.trace_out:
            trace = aggregate_lib.pod_trace(hosts)
            with open(args.trace_out, "w") as f:
                json.dump(trace, f)
            print(
                f"wrote {len(trace['traceEvents'])} event(s) across "
                f"{len(hosts)} host track(s) to {args.trace_out}"
            )
        return 0

    if args.cmd == "archive":
        from tpu_dist.obs import archive as archive_lib

        try:
            report = archive_lib.ingest_paths(args.inputs, args.archive)
        except (OSError, ValueError) as e:
            print(f"tpu_dist.obs: archive ingest failed: {e}",
                  file=sys.stderr)
            return 2
        if args.format == "json":
            print(json.dumps(report, indent=2))
        else:
            print(archive_lib.format_ingest_text(report))
        if report["records_seen"] == 0:
            print("tpu_dist.obs: the inputs held no archivable records",
                  file=sys.stderr)
            return 1
        return 0

    if args.cmd == "trend":
        from tpu_dist.obs import archive as archive_lib

        try:
            records, _counts = archive_lib.load_archive(args.archive)
        except OSError as e:
            print(f"tpu_dist.obs: cannot read {args.archive}: {e}",
                  file=sys.stderr)
            return 2
        if not records:
            print(f"tpu_dist.obs: no archive records in {args.archive}",
                  file=sys.stderr)
            return 1
        if args.inject_regression:
            probe = archive_lib.inject_probe(records)
            if args.format == "json":
                print(json.dumps(probe, indent=2))
            else:
                print(archive_lib.format_probe_text(probe))
            if archive_lib.probe_is_dead(probe):
                # an injected regression that came back unflagged, a
                # wrongly flagged improvement, or an injected
                # changepoint --blame cannot localize: the detector is
                # dead and every real pass through it is vacuous
                print(
                    "tpu_dist.obs: the injected-regression probe came "
                    "back CLEAN — the archive gate / changepoint "
                    "detector is dead (TD124)", file=sys.stderr,
                )
                return 2
            return 0
        report = archive_lib.trend_report(
            records, metric=args.metric, window=args.window,
        )
        if args.format == "json":
            print(json.dumps(report, indent=2))
        else:
            print(archive_lib.format_trend_text(report, blame=args.blame))
        return 0

    if args.cmd == "compare":
        from tpu_dist.obs import compare as compare_lib

        if args.against_archive:
            from tpu_dist.obs import archive as archive_lib

            if args.candidate is not None:
                print(
                    "tpu_dist.obs: --against-archive takes ONE candidate "
                    "positional (the archive IS the baseline)",
                    file=sys.stderr,
                )
                return 2
            if args.goodput or args.slo:
                print(
                    "tpu_dist.obs: --goodput/--slo gate two history "
                    "logs; the archive gate bands every registered "
                    "metric", file=sys.stderr,
                )
                return 2
            try:
                result = archive_lib.gate_files(
                    args.against_archive, args.baseline, bench=args.bench,
                    **({"k": args.band_k} if args.band_k is not None
                       else {}),
                    **({"window": args.band_window}
                       if args.band_window is not None else {}),
                )
            except (OSError, ValueError) as e:
                print(f"tpu_dist.obs: archive gate failed: {e}",
                      file=sys.stderr)
                return 2
            if args.format == "json":
                print(json.dumps(result, indent=2))
            else:
                print(archive_lib.format_gate_text(result))
            if result["compared"] == 0:
                # all-stale bands or no overlap: the gate compared
                # nothing and must not pass silently
                print(
                    "tpu_dist.obs: the archive band compared nothing"
                    + (
                        " — every relevant record is STALE"
                        if result.get("stale") else ""
                    ),
                    file=sys.stderr,
                )
                return 2
            return 1 if result["regressions"] else 0
        if args.candidate is None:
            print("tpu_dist.obs: compare needs a baseline and a "
                  "candidate (or --against-archive)", file=sys.stderr)
            return 2
        if args.band_k is not None or args.band_window is not None:
            print("tpu_dist.obs: --band-k/--band-window only apply with "
                  "--against-archive", file=sys.stderr)
            return 2
        try:
            result = compare_lib.compare_files(
                args.baseline, args.candidate,
                threshold=args.threshold, bench=args.bench,
                goodput_only=args.goodput, slo_only=args.slo,
            )
        except (OSError, ValueError) as e:
            print(f"tpu_dist.obs: compare failed: {e}", file=sys.stderr)
            return 2
        if args.format == "json":
            print(json.dumps(result, indent=2))
        else:
            print(compare_lib.format_text(result))
        if result["compared"] == 0:
            # a gate that compared nothing must not pass silently
            print(
                "tpu_dist.obs: no comparable metrics between the two "
                "inputs", file=sys.stderr,
            )
            return 2
        return 1 if result["regressions"] else 0

    try:
        records, bad = summ.load_records(args.log)
    except OSError as e:
        print(f"tpu_dist.obs: cannot read {args.log}: {e}", file=sys.stderr)
        return 2
    if not records:
        print(f"tpu_dist.obs: no records in {args.log}", file=sys.stderr)
        return 1

    if args.cmd == "summarize":
        report = summ.summarize(records, bad)
        # stamp the capture identity + source path into the report
        # header: archive ingest dedupes history reports by exactly this
        # fingerprint (bench records carry their own capture stamps;
        # histories get a content-hash identity here)
        summ.stamp_capture(report, args.log)
        if args.format == "json":
            print(json.dumps(report, indent=2))
        else:
            print(summ.format_text(report))
        return 0

    out_path = args.out or (args.log + ".trace.json")
    trace = summ.export_trace(records)
    with open(out_path, "w") as f:
        json.dump(trace, f)
    print(f"wrote {len(trace['traceEvents'])} event(s) to {out_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
