"""CLI: ``python -m tpu_dist.obs`` — offline run-telemetry reports.

Subcommands::

    summarize <run.jsonl> [--format text|json]
        Per-epoch throughput, step-time p50/p95/p99, data-stall fraction,
        counter deltas, straggler findings — from a ``--log_file`` JSONL.

    export-trace <run.jsonl> [-o trace.json]
        Chrome trace-event JSON (Perfetto / chrome://tracing loadable)
        from the run's drained spans + synthesized epoch/eval bars.

Exit codes: 0 ok, 1 empty/unusable input, 2 bad invocation or I/O error.
The analysis itself is pure file crunching — no device, no backend.
"""

from __future__ import annotations

import argparse
import json
import sys

from tpu_dist.obs import summarize as summ


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu_dist.obs",
        description="offline run-telemetry reports over a --log_file JSONL",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    s = sub.add_parser("summarize", help="per-epoch throughput/latency/counter report")
    s.add_argument("log", help="JSONL history written by --log_file")
    s.add_argument("--format", choices=("text", "json"), default="text")
    t = sub.add_parser("export-trace", help="write Chrome trace-event JSON")
    t.add_argument("log", help="JSONL history written by --log_file")
    t.add_argument("-o", "--out", default=None, help="output path (default: <log>.trace.json)")
    args = ap.parse_args(argv)

    try:
        records, bad = summ.load_records(args.log)
    except OSError as e:
        print(f"tpu_dist.obs: cannot read {args.log}: {e}", file=sys.stderr)
        return 2
    if not records:
        print(f"tpu_dist.obs: no records in {args.log}", file=sys.stderr)
        return 1

    if args.cmd == "summarize":
        report = summ.summarize(records, bad)
        if args.format == "json":
            print(json.dumps(report, indent=2))
        else:
            print(summ.format_text(report))
        return 0

    out_path = args.out or (args.log + ".trace.json")
    trace = summ.export_trace(records)
    with open(out_path, "w") as f:
        json.dump(trace, f)
    print(f"wrote {len(trace['traceEvents'])} event(s) to {out_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
