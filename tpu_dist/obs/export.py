"""Live OpenMetrics/Prometheus export of the run telemetry
(``docs/observability.md``).

Everything ``tpu_dist/obs`` measures lands in the JSONL history — which
is *post-hoc*: you learn a run is sick by reading the file after it
died.  This module is the live half: the same counter/gauge registry
plus the latest epoch rollup (throughput, step percentiles, stall
fraction, MFU, goodput fractions, norms, heartbeat age) rendered as
OpenMetrics text and published two ways:

* **Textfile** (``--metrics_file``) — node-exporter textfile-collector
  format, written atomically (tmp + ``os.replace``, the heartbeat
  discipline) at the same step-grain throttle as the heartbeat, so a
  scraper/``cat`` never sees a torn exposition and a fast step loop
  pays at most one small write per interval.
* **HTTP** (``--metrics_port``) — a rank-0-only background
  ``http.server`` thread serving ``GET /metrics``.  The handler serves
  the LAST RENDERED SNAPSHOT (bytes under a lock) — it never reads jax
  state, the counter registry, or the trainer from the serving thread,
  so a scrape can never race or stall a training step.  Binding is
  refused on rank ≥ 1: one pod-visible endpoint per run, the same
  posture as the rank-0 JSONL.

Cost contract: rendering/writing is host-side string work on values the
trainer already holds; the jaxpr-audit rule **TD109** proves the traced
train step is byte-identical with the exporter (and the alert engine)
armed vs off.

Metric naming: every name is prefixed ``tpu_dist_`` and sanitized to
the OpenMetrics grammar (dots → underscores), e.g. the
``loader.data_wait_s`` counter exports as ``tpu_dist_loader_data_wait_s``
and the capture-calibration gauges (``cost.calibration_*``, set by the
auto-analyze hook via ``obs/costmodel.py``) as
``tpu_dist_cost_calibration_*`` — the registry snapshot carries them
into every exposition with no per-metric plumbing.
Alert states export as ``tpu_dist_alert_active{rule="<name>"}`` 0/1
gauges (``obs/alerts.py``).  Stdlib-only on purpose — the HTTP thread
and the textfile writer must never import jax.
"""

from __future__ import annotations

import os
import re
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from tpu_dist.obs import counters

#: Exposition content type (Prometheus accepts both; OpenMetrics scrapers
#: negotiate this one).
CONTENT_TYPE = "application/openmetrics-text; version=1.0.0; charset=utf-8"

#: Every exported family is prefixed so a shared Prometheus never
#: collides with another job's namespace.
PREFIX = "tpu_dist_"

_NAME_OK = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")


def metric_name(raw: str) -> str:
    """Registry name → OpenMetrics family name (``ckpt.bytes_written`` →
    ``tpu_dist_ckpt_bytes_written``)."""
    name = PREFIX + _SANITIZE.sub("_", raw)
    if not _NAME_OK.match(name):  # leading digit after the prefix etc.
        name = PREFIX + "_" + _SANITIZE.sub("_", raw)
    return name


def _fmt_value(v: float) -> str:
    """OpenMetrics number rendering: integers without a trailing ``.0``
    (counter semantics read better), floats with repr precision."""
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int) or (isinstance(v, float) and v.is_integer()):
        return str(int(v))
    return repr(float(v))


def render(
    values: Dict[str, float],
    labeled: Optional[Dict[str, Dict[str, float]]] = None,
    label_keys: Optional[Dict[str, str]] = None,
    histograms: Optional[Dict[str, dict]] = None,
) -> str:
    """Render one exposition: ``values`` maps raw (dotted) metric names to
    numbers; ``labeled`` maps raw names to ``{label_value: number}``
    samples emitted as ``name{<key>="..."}`` — the label key per family
    comes from ``label_keys`` and defaults to ``rule`` (the alert gauges,
    the original labeled family; the fleet scheduler passes ``run``).
    ``histograms`` maps raw names to the OpenMetrics ``histogram`` shape
    (``{"buckets": [(le, cumulative_count), ...], "sum": s, "count": n}``
    — ``serve/slo.py::LatencyHistogram.to_openmetrics``), emitted as
    ``name_bucket{le="..."}`` / ``name_sum`` / ``name_count`` so a
    Prometheus computes real ``histogram_quantile()``s over the serving
    latencies; the bucket list must already be cumulative and end with
    ``+Inf`` (the producer's contract — this renderer is a formatter,
    not a validator). Non-numeric registry entries (info gauges — run
    id, mode strings) are skipped: OpenMetrics samples are numbers.
    Ends with the mandatory ``# EOF``."""
    lines = []
    for raw in sorted(values):
        v = values[raw]
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        name = metric_name(raw)
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {_fmt_value(v)}")
    for raw in sorted(labeled or {}):
        name = metric_name(raw)
        key = (label_keys or {}).get(raw, "rule")
        lines.append(f"# TYPE {name} gauge")
        for label, v in sorted((labeled or {})[raw].items()):
            safe = str(label).replace("\\", "\\\\").replace('"', '\\"')
            lines.append(f'{name}{{{key}="{safe}"}} {_fmt_value(v)}')
    for raw in sorted(histograms or {}):
        fam = (histograms or {})[raw]
        name = metric_name(raw)
        lines.append(f"# TYPE {name} histogram")
        for le, cum in fam.get("buckets") or []:
            lines.append(f'{name}_bucket{{le="{le}"}} {_fmt_value(cum)}')
        lines.append(f"{name}_sum {_fmt_value(float(fam.get('sum', 0.0)))}")
        lines.append(f"{name}_count {_fmt_value(int(fam.get('count', 0)))}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def parse(text: str) -> Dict[str, float]:
    """Minimal exposition parser — the launcher watchdog (and tests) read
    back what :func:`render` wrote to say WHY a worker is sick.  Returns
    ``{name_or_name{labels}: value}`` with the ``tpu_dist_`` prefix kept
    (names are compared against :func:`metric_name` output)."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.rsplit(" ", 1)
        if len(parts) != 2:
            continue
        try:
            out[parts[0]] = float(parts[1])
        except ValueError:
            continue
    return out


class _Handler(BaseHTTPRequestHandler):
    server_version = "tpu-dist-metrics/1"

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        if self.path.split("?", 1)[0] not in ("/metrics", "/"):
            self.send_error(404)
            return
        body = self.server.exporter_body()  # type: ignore[attr-defined]
        counters.inc("export.scrapes")
        self.send_response(200)
        self.send_header("Content-Type", CONTENT_TYPE)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # silence per-request stderr lines
        pass


class MetricsExporter:
    """One publisher per process (the trainer creates it on rank 0).

    ``update(values, labeled, force=...)`` renders the exposition and
    (a) rewrites the textfile atomically unless inside the throttle
    window, (b) swaps the snapshot the HTTP thread serves.  ``rank``
    guards the endpoint: a non-zero rank asking for a port is refused at
    construction (one pod-visible endpoint per run), while the textfile
    works on any rank — its path is the caller's to derive."""

    def __init__(
        self,
        *,
        textfile: Optional[str] = None,
        port: Optional[int] = None,
        rank: int = 0,
        min_interval: float = 1.0,
    ):
        if port is not None and rank != 0:
            raise ValueError(
                f"--metrics_port is rank-0-only (one /metrics endpoint per "
                f"run); refusing to bind on rank {rank} — rank {rank} still "
                "exports via its own --metrics_file when asked"
            )
        self.textfile = textfile
        self.min_interval = min_interval
        self._last_write = float("-inf")
        self._lock = threading.Lock()
        self._body = b"# EOF\n"
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None
        if textfile:
            d = os.path.dirname(os.path.abspath(textfile))
            os.makedirs(d, exist_ok=True)
        if port is not None:
            srv = ThreadingHTTPServer(("", port), _Handler)
            srv.daemon_threads = True
            # the handler reads ONLY this closure — last rendered bytes
            # under the lock; never the live registry or jax state
            srv.exporter_body = self._snapshot  # type: ignore[attr-defined]
            self._server = srv
            self.port = srv.server_address[1]  # resolves port=0 requests
            self._thread = threading.Thread(
                target=srv.serve_forever, name="metrics-exporter", daemon=True
            )
            self._thread.start()

    def _snapshot(self) -> bytes:
        with self._lock:
            return self._body

    def update(
        self,
        values: Dict[str, float],
        labeled: Optional[Dict[str, Dict[str, float]]] = None,
        *,
        histograms: Optional[Dict[str, dict]] = None,
        force: bool = False,
    ) -> bool:
        """Publish a new exposition.  Returns True when the textfile was
        (re)written — inside the throttle window only the in-memory HTTP
        snapshot moves (it is free), matching the heartbeat's step-grain
        discipline.  ``histograms`` adds OpenMetrics histogram families
        (the serving latency distributions).  Never raises on I/O: a
        full disk must not kill the training step that exported."""
        text = render(values, labeled, histograms=histograms)
        with self._lock:
            self._body = text.encode()
        if not self.textfile:
            return False
        now = time.monotonic()
        if not force and now - self._last_write < self.min_interval:
            return False
        self._last_write = now
        tmp = f"{self.textfile}.tmp.{os.getpid()}"
        try:
            # tpu-dist: ignore[TD002,TD007] — per-process by construction:
            # the caller derives one textfile path per rank (the heartbeat
            # per_rank_path discipline), so this write never contends
            with open(tmp, "w") as f:
                f.write(text)
            os.replace(tmp, self.textfile)
        except OSError:
            counters.inc("export.write_errors")
            return False
        counters.inc("export.writes")
        return True

    def close(self) -> None:
        """Stop the HTTP thread; the textfile is left behind deliberately
        (the last exposition documents how the run ended — a scraper sees
        final totals, not a 404)."""
        if self._server is not None:
            srv, self._server = self._server, None
            srv.shutdown()
            srv.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "MetricsExporter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def active_labels(
    vals: Dict[str, float], family: str = "alert_active"
) -> list:
    """Label values of ``family``'s nonzero samples in a scraped
    exposition (``{name{key="label"}: value}`` as :func:`parse` returns
    them), sorted — e.g. the firing alert-rule names. ONE home for the
    label-grammar parsing the launcher watchdog and the fleet scheduler
    both read back."""
    prefix = metric_name(family) + "{"
    out = []
    for name, v in vals.items():
        if not name.startswith(prefix) or not v:
            continue
        parts = name[len(prefix):].split('"')
        # parse() admits any `name{...} value` line, quoted or not — a
        # foreign/hand-written sample without a quoted label must be
        # skipped, not crash the scraper (read_signals' never-raises
        # contract, and the watchdog's sick-report shares this helper)
        if len(parts) >= 2:
            out.append(parts[1])
    return sorted(out)


#: The "why was this worker sick" gauge set — ONE list shared by the
#: launcher watchdog's wedge report and the postmortem assembler, so the
#: two reads can never drift: (raw registry name, short label, format).
KEY_GAUGES = (
    ("train.epoch", "epoch", "g"),
    ("train.data_stall_frac", "stall", ".1%"),
    ("train.mfu", "mfu", ".3f"),
    ("goodput.goodput_frac", "goodput", ".1%"),
    ("compile.retraces", "retraces", "g"),
    # the memory layer (obs/memory.py): worst-chip peak HBM and the free
    # headroom fraction — a sick worker that was about to OOM says so
    ("mem.peak_bytes_in_use", "peak_hbm_B", "g"),
    ("mem.headroom_frac", "hbm_free", ".1%"),
    # the serving layer (serve/slo.py): a sick SERVING replica's report
    # must say WHY — was the queue exploding, was availability gone, was
    # the p99 bound blown — not just that the process wedged
    ("serve.queue_depth", "queue", "g"),
    ("serve.availability", "avail", ".1%"),
    ("serve.latency_p99_ms", "p99_ms", ".1f"),
)


def key_gauges(vals: Dict[str, float]) -> Dict[str, str]:
    """The :data:`KEY_GAUGES` subset of a scraped exposition, formatted:
    ``{"epoch": "2", "stall": "41.0%", ...}`` — absent gauges omitted."""
    out: Dict[str, str] = {}
    for raw, label, spec in KEY_GAUGES:
        v = vals.get(metric_name(raw))
        if v is not None:
            out[label] = format(v, spec)
    return out


def scrape(
    *, textfile: Optional[str] = None, port: Optional[int] = None,
    host: str = "127.0.0.1", timeout: float = 2.0,
) -> Optional[Dict[str, float]]:
    """Watchdog-side read of a live exposition: the textfile when given
    (preferred — works across mounts, no socket), else one HTTP GET.
    None when nothing is readable — the caller degrades to its
    heartbeat-only report, never raises."""
    if textfile:
        try:
            with open(textfile) as f:
                return parse(f.read())
        except OSError:
            return None
    if port:
        try:
            with socket.create_connection((host, port), timeout=timeout) as s:
                s.sendall(
                    f"GET /metrics HTTP/1.0\r\nHost: {host}\r\n\r\n".encode()
                )
                chunks = []
                while True:
                    b = s.recv(65536)
                    if not b:
                        break
                    chunks.append(b)
            raw = b"".join(chunks).decode("utf-8", "replace")
            body = raw.split("\r\n\r\n", 1)
            return parse(body[1]) if len(body) == 2 else None
        except OSError:
            return None
    return None
