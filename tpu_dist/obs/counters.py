"""Process-global counter/gauge registry — the numeric half of the run
telemetry (``docs/observability.md``).

Every subsystem that does host-side work increments named counters here:
the checkpoint layer (writes / retried writes / quarantines / bytes), the
data loader (batches produced/consumed, producer/consumer wait seconds —
the producer THREAD writes too, hence the lock), the resilience layer
(faults fired, preemptions observed), and the trainer (steps, epochs).
:class:`~tpu_dist.metrics.history.MetricsHistory` snapshots the registry
into every JSONL record, so ``python -m tpu_dist.obs summarize`` can report
per-epoch counter deltas offline.

Design constraints:

* **No jax import** — the loader producer thread and the fault-injection
  hooks run before/without a backend; this module is plain stdlib.
* **Thread-safe** — one ``RLock`` around every mutation; values are
  ints/floats (counters, monotonically increasing) or arbitrary
  JSON-serializable scalars (gauges/info, last-write-wins).
* **Zero hot-path device cost** — everything here is host arithmetic; the
  TD106 audit proves the traced train step is byte-identical whether or
  not telemetry is armed.

Counters and gauges share one flat namespace (dotted names,
``subsystem.metric``); :func:`snapshot` returns them merged. Counter names
in use are catalogued in ``docs/observability.md``.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

# RLock, not Lock: a Python-level signal handler or a re-entrant caller on
# the same thread must never deadlock against its own snapshot in flight.
_LOCK = threading.RLock()
_COUNTERS: Dict[str, float] = {}
_GAUGES: Dict[str, object] = {}


def inc(name: str, n: float = 1) -> float:
    """Add ``n`` to counter ``name`` (created at 0); returns the new value.
    Counters are monotonic by convention — use :func:`set_gauge` for values
    that move both ways."""
    with _LOCK:
        v = _COUNTERS.get(name, 0) + n
        _COUNTERS[name] = v
        return v


def add_seconds(name: str, seconds: float) -> float:
    """Accumulate a duration counter (float seconds). Same as :func:`inc`;
    named separately so call sites read as what they measure."""
    return inc(name, float(seconds))


def set_gauge(name: str, value: object) -> None:
    """Last-write-wins gauge/info value (number or short string — must be
    JSON-serializable; history records embed it verbatim)."""
    with _LOCK:
        _GAUGES[name] = value


def get(name: str, default: float = 0) -> float:
    with _LOCK:
        return _COUNTERS.get(name, default)


def snapshot() -> Dict[str, object]:
    """One consistent flat copy of counters + gauges (counters win a name
    collision — they are the monotonic, delta-able series)."""
    with _LOCK:
        out: Dict[str, object] = dict(_GAUGES)
        out.update(_COUNTERS)
        return out


def reset() -> None:
    """Clear everything — test isolation and the start of a fresh run."""
    with _LOCK:
        _COUNTERS.clear()
        _GAUGES.clear()


def delta(prev: Optional[Dict[str, object]], cur: Dict[str, object]) -> Dict[str, float]:
    """Numeric difference ``cur - prev`` per key (offline analysis of two
    history snapshots). Keys that are non-numeric in either snapshot
    (gauges/info strings) and zero deltas are omitted; a key absent from
    ``prev`` counts from 0."""
    prev = prev or {}
    out: Dict[str, float] = {}
    for k, v in cur.items():
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        p = prev.get(k, 0)
        if isinstance(p, bool) or not isinstance(p, (int, float)):
            continue
        d = v - p
        if d:
            out[k] = round(d, 6) if isinstance(d, float) else d
    return out
