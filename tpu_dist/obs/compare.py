"""Run-compare regression gate — ``python -m tpu_dist.obs compare``.

Diffs two runs' telemetry and exits nonzero on a regression, so CI can
gate a change on measured training health instead of an eyeballed JSON
diff. Two input modes:

* **history mode** (default): both inputs are ``--log_file`` JSONLs; each
  is folded through :func:`tpu_dist.obs.summarize.summarize` and the
  comparison runs over the derived scalars — mean throughput, step-time
  p50/p95/p99, data-stall fraction, mean MFU, final train loss, final
  val top-1.
* **bench mode** (``--bench``): both inputs are ``bench.py`` output files
  (one JSON object per line, ``BENCH_*.json``); records are matched by
  their ``metric`` name and compared on throughput / step-time /
  sec-per-epoch / MFU.

A metric regresses when the candidate is worse than the baseline by more
than ``threshold`` (relative, default 5%) plus the metric's absolute
slack (noise floor — stall fraction and MFU move in absolute points on
quiet runs, a pure ratio would flag 0.1% vs 0.2% stall as a 2× blowup).
Better-than-baseline is never flagged, metrics missing from either side
are reported as skipped (never silently dropped), and a self-compare is
zero regressions by construction.

Pure host-side file crunching: no jax, runs anywhere the package imports.
All output formatting returns strings — printing (and the exit code)
belongs to ``obs/__main__.py``.
"""

from __future__ import annotations

import json
from typing import List, Optional, Tuple

from tpu_dist.obs import summarize as summ

#: ONE metric-direction registry: ``name -> (direction, absolute slack)``
#: for every scalar any compare mode gates on. Direction is which way is
#: BETTER (``lower`` = latency-style, ``higher`` = throughput-style);
#: slack is added to the relative allowance (noise floor — fractions
#: move in absolute points on quiet runs). The metric tables below
#: (history / bench / ``--slo``) all derive from this registry via
#: :func:`direction_of`, so a new latency or queue metric declares its
#: direction ONCE instead of hand-rolling it per comparison (the
#: overlap/collective special-casing of PR 8, generalized).
METRIC_DIRECTIONS: dict = {
    "images_per_sec_mean": ("higher", 0.0),
    "step_time_p50_s": ("lower", 0.0),
    "step_time_p95_s": ("lower", 0.0),
    "step_time_p99_s": ("lower", 0.0),
    "data_stall_frac": ("lower", 0.02),
    "mfu_mean": ("higher", 0.005),
    "final_loss": ("lower", 0.02),
    "final_val_top1": ("higher", 0.5),
    "goodput_frac": ("higher", 0.01),
    # capture-derived schedule health (obs/xprof.py, profile_analysis
    # records): mean comm/compute overlap — LOWER overlap means newly
    # serialized collectives — and the collectives' share of device busy
    # time, which growing means the step got more communication-bound.
    # Absolute slacks because both are fractions that wobble a few
    # points run to run on quiet captures.
    "overlap_frac": ("higher", 0.05),
    "collective_frac": ("lower", 0.03),
    # the memory layer's gating scalar (schema v11 'memory' records +
    # mem.* gauges, obs/memory.py): the run's worst observed per-chip
    # peak HBM — HIGHER is a regression (a config that crept toward the
    # chip ceiling fails CI before it OOMs a pod). Absolute slack of
    # 1 MiB: allocator peaks wobble by small workspace allocations on
    # otherwise identical runs, and a pure ratio would flag them.
    "peak_hbm_bytes": ("lower", 1024 * 1024),
    # the planner's gating scalar (TD119, schema v12 'plan' records +
    # bench records; analysis/planner.py): |predicted - achieved| /
    # achieved step time. HIGHER is a regression — the cost model the
    # --auto_shard ranking rests on drifted from the hardware. Absolute
    # slack of 0.02: achieved step time wobbles a couple of points on
    # quiet reruns, and a pure ratio of a small fraction would flag them.
    "planner_error_frac": ("lower", 0.02),
    # the async-checkpoint layer's gating scalar (goodput ledger bucket,
    # obs/goodput.py; ckpt/checkpoint.py two-phase sharded saves): total
    # wall-clock seconds the step loop spent blocked on checkpoint
    # save/restore. HIGHER is a regression — a save that used to hide
    # behind compute (snapshot-then-write, --async_ckpt) has started
    # blocking again. Absolute slack of 0.25 s: restore ladders and
    # first-save directory creation wobble tenths of a second run to run.
    "ckpt_s": ("lower", 0.25),
    # the co-scheduling layer's gating scalar (goodput ledger bucket,
    # schema v15; obs/goodput.py): total wall-clock seconds this run
    # spent relaunching because the fleet arbiter preempted it for a
    # breached serving SLO (world-change gaps whose resume carried a
    # propagated decision_id with cause serve_breach). HIGHER is a
    # regression — the policy started paying more training time for the
    # same SLO. Absolute slack of 0.25 s, the relaunch-wobble floor.
    "preempt_for_serve_s": ("lower", 0.25),
    # bench-mode per-record fields
    "value": ("higher", 0.0),          # images/sec (or tokens/sec)
    "sec_per_epoch": ("lower", 0.0),
    "step_ms": ("lower", 0.0),
    "step_ms_p50": ("lower", 0.0),
    "step_ms_p95": ("lower", 0.0),
    "step_ms_p99": ("lower", 0.0),
    "mfu": ("higher", 0.005),
    # bench --ckpt records (bench.py checkpoint drill): milliseconds the
    # step loop was blocked per save — the snapshot window for async
    # saves, the whole serialize+CRC+write for sync ones. LOWER is
    # better; absolute slack of 5 ms because host-side device_get of a
    # small model wobbles a few ms on shared CI machines.
    "ckpt_blocked_ms": ("lower", 5.0),
    # serving (``--slo`` gate + bench --serve records, serve/slo.py):
    # latency/queue metrics are lower-is-better; a LOWER-latency
    # candidate is an improvement and must never be flagged.
    # compiled-communication accounting (bench records, shardlint —
    # tpu_dist/analysis/shardlint.py): wire bytes of ONE step derived from
    # the optimized HLO the compiler actually emitted. HIGHER is a
    # regression — GSPMD grew an implicit reshard or a wire leg widened —
    # and the number is static+deterministic (zero slack), so a compiled-
    # comm regression gates in CI even while the TPU tunnel is down.
    "hlo_wire_bytes_per_step": ("lower", 0.0),
    "requests_per_s": ("higher", 0.0),
    "serve_requests_per_s": ("higher", 0.0),
    "latency_p50_ms": ("lower", 0.0),
    "latency_p99_ms": ("lower", 0.0),
    "serve_latency_p50_ms": ("lower", 0.0),
    "serve_latency_p99_ms": ("lower", 0.0),
    "serve_ttfb_p99_ms": ("lower", 0.0),
    "serve_availability": ("higher", 0.001),
    "batch_occupancy": ("higher", 0.02),
    "serve_batch_occupancy": ("higher", 0.02),
    "serve_queue_depth_max": ("lower", 1.0),
    # longitudinal-archive series (obs/archive.py). multichip_ok is the
    # driver's MULTICHIP_* pass/fail as a 0/1 point — a dry run that
    # stopped passing is a regression. The pod_* gauges are the hub
    # rollups `obs hub --archive` snapshots per interval: dead runs /
    # SLO breaches growing or chips shrinking regress; goodput means
    # carry the history gate's absolute point slack; the stall slack
    # matches data_stall_frac. Integer counters get a 0.5 slack so an
    # exactly-equal count never flags on the band's relative floor.
    "multichip_ok": ("higher", 0.0),
    "pod_runs_dead": ("lower", 0.5),
    "pod_breach_count": ("lower", 0.5),
    "pod_total_chips": ("higher", 0.0),
    "pod_worst_stall_frac": ("lower", 0.02),
    "pod_goodput_frac_train": ("higher", 0.01),
    "pod_goodput_frac_serve": ("higher", 0.01),
}


def direction_of(metric: str) -> Tuple[str, float]:
    """Registry lookup with two documented suffix defaults: ``*_ms`` /
    ``*_s`` / ``*_seconds`` metrics are latencies (lower is better,
    zero slack), ``*_per_s`` are rates (higher). Anything else must be
    registered explicitly — an unknown direction silently guessed wrong
    would invert a gate, so this raises instead."""
    hit = METRIC_DIRECTIONS.get(metric)
    if hit is not None:
        return hit
    if metric.endswith("_per_s"):
        return ("higher", 0.0)
    if metric.endswith(("_ms", "_s", "_seconds")):
        return ("lower", 0.0)
    raise KeyError(
        f"metric {metric!r} has no registered direction "
        "(obs/compare.py METRIC_DIRECTIONS) and no suffix default"
    )


def _table(names: Tuple[str, ...]) -> Tuple[Tuple[str, str, float], ...]:
    return tuple((n, *direction_of(n)) for n in names)


#: history-mode metrics: (key, direction, absolute slack), derived from
#: the registry.
REPORT_METRICS: Tuple[Tuple[str, str, float], ...] = _table((
    "images_per_sec_mean", "step_time_p50_s", "step_time_p95_s",
    "step_time_p99_s", "data_stall_frac", "mfu_mean", "final_loss",
    "final_val_top1", "goodput_frac", "overlap_frac", "collective_frac",
    "peak_hbm_bytes", "planner_error_frac", "ckpt_s",
    "preempt_for_serve_s",
))

#: the ``--goodput`` gate's metric set: time-to-useful-work only. The
#: fraction is the headline; the stall fraction rides along because a
#: goodput regression's most common cause is an input-pipeline change,
#: and the serve-preemption seconds because a co-scheduling policy that
#: started charging training more for the same SLO is a goodput story
#: even when the fraction hides it in a long run.
GOODPUT_METRICS: Tuple[str, ...] = (
    "goodput_frac", "data_stall_frac", "preempt_for_serve_s",
)

#: the ``--slo`` gate's metric set (serving runs, ``serve`` records):
#: request rate, latency ceilings (upper-bound quantiles in ms),
#: availability, and batching efficiency — directions from the registry,
#: so lower latency is NEVER flagged.
SLO_METRICS: Tuple[Tuple[str, str, float], ...] = _table((
    "serve_requests_per_s", "serve_latency_p50_ms",
    "serve_latency_p99_ms", "serve_ttfb_p99_ms", "serve_availability",
    "serve_batch_occupancy",
))

#: bench-mode per-record fields: (field, direction, absolute slack).
#: ``goodput_frac`` keeps bench's historical wider slack (bench windows
#: are short, the fraction noisier than a whole run's ledger).
BENCH_FIELDS: Tuple[Tuple[str, str, float], ...] = _table((
    "value", "sec_per_epoch", "step_ms", "step_ms_p50", "step_ms_p95",
    "step_ms_p99", "mfu",
    # bench records carry XLA's static per-step memory accounting
    # (``peak_hbm_bytes`` from ``memory_analysis()``) — CPU-valid, so
    # memory regressions gate even while the TPU tunnel is down
    "peak_hbm_bytes",
    # ...and the compiled-collective wire bytes (shardlint over the
    # optimized HLO), the communication twin of that memory gate
    "hlo_wire_bytes_per_step",
    # ...and the planner's predicted-vs-achieved drift (TD119,
    # analysis/planner.py) — bench measures real step time next to the
    # plan's prediction, so cost-model drift gates per bench record too
    "planner_error_frac",
    # ...and the checkpoint drill's blocking window (bench.py --ckpt) —
    # a save that stopped hiding behind the step loop gates here
    "ckpt_blocked_ms",
    # serving bench records (bench.py --serve)
    "requests_per_s", "latency_p50_ms", "latency_p99_ms",
    "batch_occupancy",
)) + (("goodput_frac", "higher", 0.02),)


def _mean(vals: List) -> Optional[float]:
    nums = [v for v in vals if isinstance(v, (int, float))]
    return sum(nums) / len(nums) if nums else None


def report_scalars(report: dict) -> dict:
    """Flatten a :func:`summarize` report into the comparable scalars."""
    epochs = report.get("epochs", [])
    losses = [r.get("loss") for r in epochs if isinstance(r.get("loss"), (int, float))]
    top1s = [
        r.get("val_top1") for r in epochs
        if isinstance(r.get("val_top1"), (int, float))
    ]
    gp = report.get("goodput") or {}
    pas = [
        p for p in (report.get("profile_analyses") or [])
        if not p.get("error")
    ]
    sw = report.get("serve_windows") or []
    return {
        "images_per_sec_mean": report["totals"].get("images_per_sec_mean"),
        "step_time_p50_s": _mean([r.get("step_time_p50_s") for r in epochs]),
        "step_time_p95_s": _mean([r.get("step_time_p95_s") for r in epochs]),
        "step_time_p99_s": _mean([r.get("step_time_p99_s") for r in epochs]),
        "data_stall_frac": _mean([r.get("data_stall_frac") for r in epochs]),
        "mfu_mean": report["totals"].get("mfu_mean"),
        "final_loss": losses[-1] if losses else None,
        "final_val_top1": top1s[-1] if top1s else None,
        # the run-level ledger's fraction (obs/goodput.py): resumed
        # segments folded, restart gaps counted against it
        "goodput_frac": gp.get("goodput_frac"),
        # capture-derived means (profile_analysis records); None — and
        # therefore a skipped row, never a fake pass — on capture-less runs
        "overlap_frac": _mean([p.get("overlap_frac") for p in pas]),
        "collective_frac": _mean([p.get("collective_frac") for p in pas]),
        # serving SLO means over the run's serve windows (schema v10);
        # None — skipped, never faked — on a training-only log. The
        # ``--slo`` gate compares exactly these (SLO_METRICS).
        "serve_requests_per_s": _mean([w.get("requests_per_s") for w in sw]),
        "serve_latency_p50_ms": _mean([w.get("latency_p50_ms") for w in sw]),
        "serve_latency_p99_ms": _mean([w.get("latency_p99_ms") for w in sw]),
        "serve_ttfb_p99_ms": _mean([w.get("ttfb_p99_ms") for w in sw]),
        "serve_availability": _mean([w.get("availability") for w in sw]),
        "serve_batch_occupancy": _mean([w.get("batch_occupancy") for w in sw]),
        # the memory layer's worst observed per-chip peak (schema v11);
        # None — skipped, never faked — on a memory-less / pre-v11 log
        "peak_hbm_bytes": (report.get("memory") or {}).get("peak_hbm_bytes"),
        # the planner layer's drift scalar (TD119, schema v12 'plan'
        # records); None — skipped, never faked — on an unprofiled or
        # plan-less run
        "planner_error_frac": (report.get("plan") or {}).get(
            "planner_error_frac"
        ),
        # the async-checkpoint layer's blocking total (goodput ledger
        # 'ckpt' bucket); None — skipped, never faked — on a ledger-less
        # log. Gates the two-phase save's whole point: hiding the write.
        "ckpt_s": gp.get("ckpt_s"),
        # the co-scheduling layer's chosen cost (goodput ledger
        # 'preempt_for_serve' bucket, schema v15); None — skipped,
        # never faked — on a ledger-less log
        "preempt_for_serve_s": gp.get("preempt_for_serve_s"),
    }


def _row(
    metric: str, direction: str, slack: float,
    base, cand, threshold: float,
) -> dict:
    if not isinstance(base, (int, float)) or not isinstance(cand, (int, float)):
        return {"metric": metric, "baseline": base, "candidate": cand,
                "verdict": "skipped"}
    worse_by = (base - cand) if direction == "higher" else (cand - base)
    allowed = abs(base) * threshold + slack
    regressed = worse_by > allowed
    out = {
        "metric": metric,
        "baseline": base,
        "candidate": cand,
        "delta": round(cand - base, 6),
        "verdict": "REGRESSED" if regressed else "ok",
    }
    if base:
        out["delta_frac"] = round((cand - base) / abs(base), 4)
    return out


def compare_scalars(
    base: dict, cand: dict, threshold: float = 0.05,
    goodput_only: bool = False, slo_only: bool = False,
) -> dict:
    if slo_only:
        metrics = list(SLO_METRICS)
    else:
        metrics = [
            m for m in REPORT_METRICS
            if not goodput_only or m[0] in GOODPUT_METRICS
        ]
    rows = [
        _row(key, direction, slack, base.get(key), cand.get(key), threshold)
        for key, direction, slack in metrics
    ]
    return _result(rows, threshold)


def _result(rows: List[dict], threshold: float) -> dict:
    return {
        "threshold": threshold,
        "rows": rows,
        "regressions": sum(r["verdict"] == "REGRESSED" for r in rows),
        "compared": sum(r["verdict"] not in ("skipped", "STALE") for r in rows),
        "skipped": sum(r["verdict"] == "skipped" for r in rows),
        "stale": sum(r["verdict"] == "STALE" for r in rows),
    }


def capture_fingerprint(rec: dict) -> Optional[tuple]:
    """The bench record's capture identity (``bench.py`` stamps hostname,
    a per-invocation id, and a monotonic capture time into every record).
    Two records with the SAME fingerprint are one physical capture — a
    candidate re-emitting the baseline's fingerprint is a stale copy,
    not a fresh measurement. None on pre-stamp (legacy) records."""
    cap = rec.get("capture")
    if isinstance(cap, dict) and cap.get("bench_run_id"):
        return (cap.get("host"), cap.get("bench_run_id"), cap.get("mono_s"))
    return None


# -- input loading -----------------------------------------------------------


def load_history_scalars(path: str) -> dict:
    """``--log_file`` JSONL → comparable scalars; raises ValueError on an
    empty/unusable file (a gate comparing nothing must fail loudly). A
    serving-only log (``serve`` windows, no ``train_epoch`` records) is
    usable — the ``--slo`` gate compares exactly those."""
    records, _bad = summ.load_records(path)
    if not records:
        raise ValueError(f"no records in {path}")
    report = summ.summarize(records)
    if not report["epochs"] and not report.get("serve_windows"):
        raise ValueError(f"no train_epoch or serve records in {path}")
    scalars = report_scalars(report)
    scalars["_run_id"] = report.get("run_id")
    return scalars


def load_bench_records(path: str) -> dict:
    """bench.py output (JSON object per line) → ``{metric_name: record}``.
    Tolerates a torn tail like the history loader; raises ValueError when
    nothing parses."""
    return {rec["metric"]: rec for rec in _load_bench_list(path)}


def compare_bench(base: dict, cand: dict, threshold: float = 0.05) -> dict:
    """Compare two ``{metric: record}`` bench maps field-by-field; metrics
    present on only one side are reported as skipped rows."""
    rows: List[dict] = []
    for name in sorted(set(base) | set(cand)):
        b, c = base.get(name), cand.get(name)
        if b is None or c is None:
            rows.append({
                "metric": name,
                "baseline": None if b is None else "present",
                "candidate": None if c is None else "present",
                "verdict": "skipped",
            })
            continue
        fp_b, fp_c = capture_fingerprint(b), capture_fingerprint(c)
        if (fp_b is not None and fp_b == fp_c) or b.get("stale") or c.get("stale"):
            # the candidate is a byte-identical re-emission of the
            # baseline's capture (the r03–r05 staleness failure mode), or
            # either side carries bench's own stale:true last-good-
            # fallback stamp: comparing those numbers would read as "no
            # regression" when nothing was measured — flag, don't compare
            rows.append({
                "metric": name,
                "baseline": (
                    "stale capture" if b.get("stale")
                    else "capture " + str((fp_b or ("?",) * 2)[1])
                ),
                "candidate": (
                    "stale capture" if c.get("stale") else "same capture"
                ),
                "verdict": "STALE",
            })
            continue
        for field, direction, slack in BENCH_FIELDS:
            if field not in b and field not in c:
                continue
            rows.append(_row(
                f"{name}.{field}", direction, slack,
                b.get(field), c.get(field), threshold,
            ))
    return _result(rows, threshold)


def compare_files(
    baseline: str, candidate: str, *,
    threshold: float = 0.05, bench: bool = False,
    goodput_only: bool = False, slo_only: bool = False,
) -> dict:
    """The CLI engine: load both inputs and diff. Raises OSError on an
    unreadable file and ValueError on an unusable one — the caller maps
    both to exit 2 (a broken gate, distinct from exit 1's regression).
    ``goodput_only`` (the ``--goodput`` flag) restricts the gate to the
    time-to-useful-work metrics; ``slo_only`` (``--slo``) to the serving
    SLO metrics (``serve`` records, directions from the registry — a
    lower-latency candidate is never flagged). Inputs without the
    gated records then compare nothing, which the CLI surfaces as a
    broken gate (exit 2) rather than a silent pass."""
    if bench and (goodput_only or slo_only):
        raise ValueError(
            "--goodput/--slo gate history-mode logs; bench records carry "
            "their serving/goodput fields as ordinary compared fields"
        )
    if goodput_only and slo_only:
        raise ValueError("--goodput and --slo are separate gates; pick one")
    if bench:
        result = compare_bench(
            load_bench_records(baseline), load_bench_records(candidate),
            threshold,
        )
    else:
        b = load_history_scalars(baseline)
        c = load_history_scalars(candidate)
        result = compare_scalars(
            b, c, threshold, goodput_only=goodput_only, slo_only=slo_only,
        )
        result["baseline_run_id"] = b.get("_run_id")
        result["candidate_run_id"] = c.get("_run_id")
    result["baseline"] = baseline
    result["candidate"] = candidate
    return result


def format_text(result: dict) -> str:
    lines = [
        f"compare: baseline {result['baseline']} vs candidate "
        f"{result['candidate']} (threshold {result['threshold'] * 100:g}%)"
    ]
    w = max([len(r["metric"]) for r in result["rows"]] + [6])

    def cell(v):
        if isinstance(v, float):
            return format(v, ".6g").rjust(12)
        return str(v if v is not None else "-").rjust(12)

    lines.append(f"  {'metric'.ljust(w)} {'baseline':>12} {'candidate':>12} "
                 f"{'delta%':>8}  verdict")
    for r in result["rows"]:
        frac = r.get("delta_frac")
        lines.append(
            f"  {r['metric'].ljust(w)} {cell(r.get('baseline'))} "
            f"{cell(r.get('candidate'))} "
            f"{(format(frac * 100, '+.1f') if frac is not None else '-'):>8}"
            f"  {r['verdict']}"
        )
    lines.append(
        f"compare: {result['regressions']} regression(s) over "
        f"{result['compared']} compared metric(s)"
        + (f", {result['skipped']} skipped" if result["skipped"] else "")
        + (
            f", {result['stale']} STALE (candidate re-emits the "
            "baseline's capture — not a fresh measurement)"
            if result.get("stale") else ""
        )
    )
    return "\n".join(lines)


# -- bench staleness report (`obs summarize --bench`) ------------------------


def _load_bench_list(path: str) -> List[dict]:
    """Order-preserving bench loader that keeps duplicates — the
    staleness report must SEE re-emitted records, which the by-metric
    dict of :func:`load_bench_records` (built on this) collapses."""
    out: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict) and rec.get("metric"):
                out.append(rec)
    if not out:
        raise ValueError(f"no bench records in {path}")
    return out


def bench_report(path: str) -> dict:
    """Per-record bench summary with capture-staleness flags: a record is
    ``stale`` when it carries the self-declared ``stale: true`` stamp
    (bench's last-good fallback) or repeats an earlier record's capture
    fingerprint byte-for-byte (a re-emission inside one artifact)."""
    seen: dict = {}
    rows: List[dict] = []
    for rec in _load_bench_list(path):
        fp = capture_fingerprint(rec)
        reemitted = fp is not None and fp in seen
        row = {
            "metric": rec.get("metric"),
            "value": rec.get("value"),
            "unit": rec.get("unit"),
            "mfu": rec.get("mfu"),
            "stale": bool(rec.get("stale")) or reemitted,
        }
        if fp is not None:
            row["capture"] = {
                "host": fp[0], "bench_run_id": fp[1], "mono_s": fp[2],
            }
            if reemitted:
                row["stale_of"] = seen[fp]
            else:
                seen[fp] = rec.get("metric")
        if rec.get("age_days") is not None:
            row["age_days"] = rec["age_days"]
        rows.append(row)
    return {
        "path": path,
        "records": rows,
        "n_stale": sum(r["stale"] for r in rows),
        "n_unfingerprinted": sum("capture" not in r for r in rows),
    }


def format_bench_report(report: dict) -> str:
    lines = [
        f"bench {report['path']}: {len(report['records'])} record(s)"
        + (f", {report['n_stale']} STALE" if report["n_stale"] else "")
        + (
            f", {report['n_unfingerprinted']} without capture fingerprint "
            "(pre-stamp)"
            if report["n_unfingerprinted"] else ""
        )
    ]
    w = max([len(str(r["metric"])) for r in report["records"]] + [6])
    for r in report["records"]:
        cap = r.get("capture") or {}
        lines.append(
            f"  {str(r['metric']).ljust(w)} "
            f"{str(r.get('value')).rjust(10)} {str(r.get('unit') or ''):<11}"
            + (
                f" capture {cap.get('bench_run_id')}@{cap.get('host')}"
                if cap else " (no fingerprint)"
            )
            + (
                "  STALE"
                + (f" (re-emits {r['stale_of']})" if r.get("stale_of") else "")
                + (
                    f" ({r['age_days']}d old)"
                    if r.get("age_days") is not None else ""
                )
                if r["stale"] else ""
            )
        )
    return "\n".join(lines)
