"""Declarative threshold alerting over the live telemetry
(``docs/observability.md``).

The JSONL history answers "what happened"; this module answers "page me
when it happens".  Rules are data, not code: a TOML/JSON spec
(``--alert_rules``) names a metric path, a comparator, a threshold, a
sustain count, and a cooldown — the engine keeps the per-rule streak
state and fires when a breach SUSTAINS for N consecutive observation
windows, then stands down for the cooldown.  A fired rule surfaces four
ways (trainer wiring): an ``alert`` history record (schema v5,
additive), a rank-0 warning line, an exporter gauge flip
(``tpu_dist_alert_active{rule="..."}`` — ``obs/export.py``), and —
when the rule says ``profile = true`` — an armed triggered-profiler
capture (``obs/profile.py``), so the steps that explain the breach land
on an XLA timeline.

Observation windows: the engine is fed at two cadences and a rule
participates wherever its metric appears — epoch metrics
(``data_stall_frac``, ``mfu``, ``goodput_frac``, counter deltas) at the
epoch grain, step metrics (``grad_norm``, ``loss``) at the
``--log_every`` fetch cadence.  An observation without the rule's
metric neither advances nor resets its streak (the metric simply was
not measured), so mixed-cadence feeding is safe by construction.

Spec grammar (TOML shown; JSON is the same shape as a list under
``rule``)::

    [[rule]]
    name = "stall_high"            # unique; the alert_active label
    metric = "data_stall_frac"     # flat metric path (counter names too)
    op = ">"                       # > < >= <=
    threshold = 0.3
    sustain = 2                    # consecutive breaching windows (>= 1)
    cooldown = 5                   # rate limit: no re-fire for the
                                   # next 5 observations (>= 0)
    # delta = true                 # rule on the per-window CHANGE
    # profile = true               # arm the triggered profiler on fire

    [[rule]]
    builtin = "mfu_low"            # start from the library...
    threshold = 0.4                # ...and override fields

``--alert_rules default`` loads the whole built-in library unmodified.
Stdlib-only: Python 3.11+ parses TOML with ``tomllib``; older
interpreters fall back to a built-in parser for exactly the flat
``[[rule]]`` grammar above (the spec's own subset — anything fancier
says "use JSON" rather than half-parsing).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional

_OPS = {
    ">": lambda v, t: v > t,
    "<": lambda v, t: v < t,
    ">=": lambda v, t: v >= t,
    "<=": lambda v, t: v <= t,
}


@dataclasses.dataclass(frozen=True)
class AlertRule:
    """One declarative threshold rule (see the module grammar)."""

    name: str
    metric: str
    op: str
    threshold: float
    sustain: int = 1
    cooldown: int = 0
    delta: bool = False
    profile: bool = False

    def __post_init__(self):
        if self.op not in _OPS:
            raise ValueError(
                f"rule {self.name!r}: op must be one of {sorted(_OPS)}, "
                f"got {self.op!r}"
            )
        # type-check every numeric field at LOAD time: a quoted threshold
        # in a JSON spec must fail at Trainer construction, not as a
        # TypeError inside the fit loop hours later
        if isinstance(self.threshold, bool) or not isinstance(
            self.threshold, (int, float)
        ):
            raise ValueError(
                f"rule {self.name!r}: threshold must be a number, got "
                f"{self.threshold!r}"
            )
        if isinstance(self.sustain, bool) or not isinstance(self.sustain, int):
            raise ValueError(
                f"rule {self.name!r}: sustain must be an integer, got "
                f"{self.sustain!r}"
            )
        if isinstance(self.cooldown, bool) or not isinstance(self.cooldown, int):
            raise ValueError(
                f"rule {self.name!r}: cooldown must be an integer, got "
                f"{self.cooldown!r}"
            )
        if self.sustain < 1:
            raise ValueError(
                f"rule {self.name!r}: sustain must be >= 1, got {self.sustain}"
            )
        if self.cooldown < 0:
            raise ValueError(
                f"rule {self.name!r}: cooldown must be >= 0, got {self.cooldown}"
            )
        if not self.name or not self.metric:
            raise ValueError("rule needs a non-empty name and metric")


#: The built-in library — the alert set a production run wants armed by
#: default (``--alert_rules default``), each override-able from a spec
#: via ``builtin = "<name>"``.  Thresholds are deliberately conservative:
#: an alert that cries wolf gets disarmed.
BUILTIN_RULES: Dict[str, AlertRule] = {
    r.name: r
    for r in (
        # input pipeline starving the step loop for 2 epochs straight
        AlertRule("stall_high", "data_stall_frac", ">", 0.30,
                  sustain=2, cooldown=3),
        # hardware paid for, math not happening
        AlertRule("mfu_low", "mfu", "<", 0.20, sustain=2, cooldown=3),
        # run-level time-to-useful-work floor (goodput ledger fraction)
        AlertRule("goodput_low", "goodput_frac", "<", 0.50,
                  sustain=2, cooldown=3),
        # numeric blow-up in flight: fire fast, capture the step timeline
        AlertRule("grad_norm_high", "grad_norm", ">", 1e3,
                  sustain=1, cooldown=50, profile=True),
        # a watchdog/tail-side rule: feed heartbeat_age_s from the file's
        # mtime clock; the trainer itself never observes this metric
        AlertRule("heartbeat_stale", "heartbeat_age_s", ">", 60.0,
                  sustain=1, cooldown=10),
        # ANY mid-run retrace is a full compile stall (delta of the
        # monotonic compile.retraces counter per window)
        AlertRule("retrace", "compile.retraces", ">", 0.0,
                  sustain=1, cooldown=1, delta=True, profile=True),
        # the worst chip is within 10% of its HBM ceiling for 2 windows
        # straight: the next shape change / fragmentation creep OOMs the
        # pod. Fed by the mem.headroom_frac gauge (free fraction of the
        # allocator's bytes_limit — obs/memory.py, trainer epoch gauges);
        # backends without allocator limits (CPU) never observe the
        # metric, so the rule stays silently unarmed there.
        AlertRule("memory_headroom_low", "mem.headroom_frac", "<", 0.10,
                  sustain=2, cooldown=3),
    )
}

_RULE_FIELDS = {f.name for f in dataclasses.fields(AlertRule)}


def _rule_from_dict(
    d: dict, idx: int, builtins: Optional[Dict[str, AlertRule]] = None
) -> AlertRule:
    d = dict(d)
    base: Optional[AlertRule] = None
    library = builtins if builtins is not None else BUILTIN_RULES
    builtin = d.pop("builtin", None)
    if builtin is not None:
        if builtin not in library:
            raise ValueError(
                f"rule #{idx}: unknown builtin {builtin!r}; have "
                f"{sorted(library)}"
            )
        base = library[builtin]
    unknown = set(d) - _RULE_FIELDS
    if unknown:
        raise ValueError(
            f"rule #{idx}: unknown field(s) {sorted(unknown)}; valid: "
            f"{sorted(_RULE_FIELDS)} (+ builtin)"
        )
    if base is not None:
        return dataclasses.replace(base, **d)
    missing = {"name", "metric", "op", "threshold"} - set(d)
    if missing:
        raise ValueError(
            f"rule #{idx}: missing required field(s) {sorted(missing)} "
            "(or name a builtin)"
        )
    return AlertRule(**d)


def _parse_toml_minimal(text: str, path: str) -> List[dict]:
    """The fallback TOML reader for interpreters without ``tomllib``
    (< 3.11): exactly the flat ``[[rule]]`` grammar the spec documents —
    comments, bare ``key = value`` scalars (quoted string / number /
    bool).  Anything else raises with a pointer to the JSON spec form
    rather than half-parsing."""
    rules: List[dict] = []
    cur: Optional[dict] = None
    for ln, raw in enumerate(text.splitlines(), start=1):
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        if line == "[[rule]]":
            cur = {}
            rules.append(cur)
            continue
        if "=" in line and cur is not None:
            key, _, val = line.partition("=")
            key, val = key.strip(), val.strip()
            if val.startswith('"') and val.endswith('"') and len(val) >= 2:
                cur[key] = val[1:-1]
            elif val in ("true", "false"):
                cur[key] = val == "true"
            else:
                try:
                    cur[key] = int(val)
                except ValueError:
                    try:
                        cur[key] = float(val)
                    except ValueError:
                        raise ValueError(
                            f"{path}:{ln}: unsupported TOML value {val!r} "
                            "(this interpreter has no tomllib; the built-in "
                            "reader takes strings/numbers/bools only — or "
                            "use the JSON spec form)"
                        ) from None
            continue
        raise ValueError(
            f"{path}:{ln}: unsupported TOML construct {line!r} (the spec "
            "grammar is [[rule]] tables of scalar key = value lines; use "
            "the JSON form for anything else)"
        )
    return rules


def load_rules(
    spec: str, builtins: Optional[Dict[str, AlertRule]] = None
) -> List[AlertRule]:
    """``--alert_rules`` → validated rule list.  ``default``/``builtin``
    loads the library; otherwise the value is a ``.toml``/``.json`` path.
    Raises ValueError on a malformed spec (the trainer calls this at
    construction so a typo fails before any model/data work).
    ``builtins`` overrides the library ``builtin =`` references resolve
    against (and what ``default`` returns) — the serving SLO loader
    passes the merged training+serving set (``serve/slo.py``)."""
    if spec in ("default", "builtin"):
        return list((builtins if builtins is not None else BUILTIN_RULES).values())
    if spec.endswith(".json"):
        with open(spec) as f:
            data = json.load(f)
        raw = data.get("rule") if isinstance(data, dict) else data
    elif spec.endswith(".toml"):
        with open(spec) as f:
            text = f.read()
        try:
            import tomllib  # noqa: PLC0415 — 3.11+

            raw = tomllib.loads(text).get("rule")
        except ImportError:
            raw = _parse_toml_minimal(text, spec)
    else:
        raise ValueError(
            f"--alert_rules must be 'default' or a .toml/.json spec path, "
            f"got {spec!r}"
        )
    if not isinstance(raw, list) or not raw:
        raise ValueError(f"{spec}: expected a non-empty list of [[rule]] tables")
    rules = [
        _rule_from_dict(d, i, builtins) for i, d in enumerate(raw)
        if isinstance(d, dict) or _bad_entry(spec, i, d)
    ]
    names = [r.name for r in rules]
    dupes = sorted({n for n in names if names.count(n) > 1})
    if dupes:
        raise ValueError(f"{spec}: duplicate rule name(s) {dupes}")
    return rules


def _bad_entry(spec: str, idx: int, d) -> bool:
    raise ValueError(f"{spec}: rule #{idx} is not a table/object: {d!r}")


class AlertEngine:
    """Streak/cooldown state machine over a rule list.

    :meth:`observe` takes one flat metrics window (epoch rollup, counter
    snapshot, step fetch — whatever the caller has) and returns the
    rules that FIRED on it.  Per rule: a breaching observation of its
    metric advances the streak, a clean one resets it; the rule fires
    when the streak reaches ``sustain`` with no cooldown pending, then
    cannot re-fire for the next ``cooldown`` observations of that metric
    (a rate limit — breaching observations drain it too).
    ``delta`` rules breach on the change since the metric's previous
    observation (monotonic counters — mid-run retraces).  Pure host
    arithmetic, no jax — TD109 proves arming it leaves the traced step
    byte-identical."""

    def __init__(self, rules: List[AlertRule]):
        names = [r.name for r in rules]
        if len(names) != len(set(names)):
            raise ValueError(f"duplicate rule names in {names}")
        self.rules = list(rules)
        self._streak: Dict[str, int] = {r.name: 0 for r in rules}
        self._cooldown: Dict[str, int] = {r.name: 0 for r in rules}
        self._prev: Dict[str, float] = {}
        self._active: Dict[str, float] = {r.name: 0.0 for r in rules}
        self.fired_total = 0

    def seed_deltas(self, window: Dict[str, object]) -> None:
        """Baseline the delta rules at run start: later observations fire
        on the change relative to NOW. Without this, a counter born
        mid-run (``compile.retraces`` first exists at the first retrace)
        would spend its first sighting establishing a baseline and the
        retrace that created it would never alert. Metrics absent from
        ``window`` baseline at 0 — the registry convention for counters
        that have not fired yet."""
        for rule in self.rules:
            if not rule.delta or rule.name in self._prev:
                continue
            v = window.get(rule.metric, 0)
            self._prev[rule.name] = (
                float(v)
                if isinstance(v, (int, float)) and not isinstance(v, bool)
                else 0.0
            )

    def observe(self, window: Dict[str, object]) -> List[dict]:
        """Evaluate every rule whose metric appears in ``window``;
        returns the fired alerts as history-ready dicts."""
        fired: List[dict] = []
        for rule in self.rules:
            raw = window.get(rule.metric)
            if isinstance(raw, bool) or not isinstance(raw, (int, float)):
                continue  # not measured this window: state untouched
            value = float(raw)
            if rule.delta:
                prev = self._prev.get(rule.name)
                self._prev[rule.name] = value
                if prev is None:
                    continue  # first sighting: no delta yet
                value = value - prev
            breach = _OPS[rule.op](value, rule.threshold)
            # cooldown = a rate limit: after a fire, the NEXT N
            # observations of this metric (breaching or not — they drain
            # it either way) can never re-fire, however sustained
            cooling = self._cooldown[rule.name] > 0
            if cooling:
                self._cooldown[rule.name] -= 1
            self._streak[rule.name] = (
                self._streak[rule.name] + 1 if breach else 0
            )
            sustained = breach and self._streak[rule.name] >= rule.sustain
            self._active[rule.name] = 1.0 if sustained else 0.0
            if sustained and not cooling:
                self._cooldown[rule.name] = rule.cooldown
                self.fired_total += 1
                fired.append({
                    "rule": rule.name,
                    "metric": rule.metric,
                    "value": round(value, 6),
                    "threshold": rule.threshold,
                    "op": rule.op,
                    "sustained": self._streak[rule.name],
                    **({"delta": True} if rule.delta else {}),
                    **({"profile": True} if rule.profile else {}),
                })
        return fired

    def active(self) -> Dict[str, float]:
        """Rule → 0/1 view for the exporter's ``alert_active`` gauges: 1
        while the rule's condition is currently sustained (fired or
        holding through its cooldown), 0 once a clean window lands."""
        return dict(self._active)
