"""Per-host straggler detection (``docs/observability.md``).

Pod-scale studies attribute most TPU scaling loss to per-host skew and
input stalls (MLPerf-0.6 on TPU-v3 pods, arXiv:1909.09756; Kumar et al.,
arXiv:2011.03641): one host with a slow disk or a hot neighbor drags every
step, because the collectives make the pod march at the slowest host's
pace. The signal is cheap to compute and this repo simply never looked: at
each epoch end, allgather every process's ``(epoch_time, data_stall_frac)``
and compare max against median.

This is a HOST-grain check (one value per process, a few floats over DCN,
once per epoch) — not a per-step device profiler. The allgather is a
collective: every process must call :func:`epoch_skew` at the same point
(the trainer does, right after each epoch), which is also why the check
lives outside the traced step and costs TD106 nothing.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from tpu_dist.metrics.logging import rank0_print
from tpu_dist.obs import counters


def _default_allgather(row: np.ndarray) -> np.ndarray:
    import jax  # noqa: PLC0415

    if jax.process_count() <= 1:
        return row[None, :]
    from jax.experimental import multihost_utils  # noqa: PLC0415

    return np.asarray(multihost_utils.process_allgather(row))


def epoch_skew(
    epoch_time: float,
    stall_frac: float = 0.0,
    *,
    epoch: Optional[int] = None,
    threshold: float = 1.5,
    allgather: Optional[Callable[[np.ndarray], np.ndarray]] = None,
) -> dict:
    """Allgather this process's epoch walltime + stall fraction, compute
    the max/median skew, and rank-0-warn when it exceeds ``threshold``.

    COLLECTIVE: every process must reach this call once per epoch.
    ``allgather`` is injectable for tests (rows of ``[time, stall]``).
    Returns the skew record (also what the trainer logs to history)::

        {"skew": 1.8, "straggler": True, "worst_rank": 3,
         "median_s": 10.2, "max_s": 18.4,
         "epoch_times": [...], "stall_fracs": [...]}
    """
    gather = allgather or _default_allgather
    rows = np.asarray(
        gather(np.asarray([epoch_time, stall_frac], np.float64)), np.float64
    ).reshape(-1, 2)
    times, stalls = rows[:, 0], rows[:, 1]
    median = float(np.median(times))
    worst = int(np.argmax(times))
    skew = float(times[worst] / median) if median > 0 else 1.0
    rec = {
        "skew": round(skew, 4),
        "straggler": bool(threshold > 0 and skew > threshold),
        "worst_rank": worst,
        "median_s": round(median, 4),
        "max_s": round(float(times[worst]), 4),
        "epoch_times": [round(float(t), 4) for t in times],
        "stall_fracs": [round(float(s), 4) for s in stalls],
    }
    if rec["straggler"]:
        counters.inc("straggler.epochs_flagged")
        rank0_print(
            f"WARNING: straggler detected{f' (epoch {epoch})' if epoch is not None else ''}: "
            f"process {worst} took {rec['max_s']:.2f}s vs median "
            f"{rec['median_s']:.2f}s ({skew:.2f}x > threshold {threshold}x); "
            f"its data-stall fraction is {float(stalls[worst]):.2%} — "
            "check that host's input pipeline/disk before blaming the model"
        )
    return rec
