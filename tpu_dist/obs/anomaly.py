"""Rolling-window anomaly detection over the per-step health scalars.

Host-side and stdlib-only (the loader/obs design constraint: no jax, no
numpy — the detector consumes ALREADY-FETCHED floats, so it adds zero
device work; TD106/TD107 stay intact). The trainer feeds it the metrics
it fetches anyway at the log cadence — loss always, ``grad_norm`` /
``nonfinite_grads`` when ``--device_metrics`` is on — and acts on the
findings per ``--anomaly_action``:

* ``warn`` (default) — rank-0 warning + an ``anomaly`` history record.
* ``snapshot`` — additionally write an exact mid-epoch checkpoint via the
  emergency-snapshot discipline (stamped ``mid_epoch_step`` like the
  periodic/interrupt saves), so the pre-divergence state is on disk for
  forensics/rollback BEFORE the NaN guard would fire.
* ``off`` — detector not constructed.

Detection is deliberately simple and robust (medians, not means — one
spike must not drag its own threshold up):

* **loss spike** — loss > ``loss_spike`` × rolling median of the last
  ``window`` observations (median > 0 and the window warm).
* **grad-norm explosion** — grad_norm > ``grad_spike`` × rolling median
  of recent grad norms.
* **nonfinite** — a non-finite loss or a positive ``nonfinite_grads``
  count, reported here for the record; the trainer's NaN-guard /
  auto-recover path still owns the raise (composition, not replacement).

After a finding the detector holds a per-kind cooldown (``min_points``
observations) so a plateau of bad steps yields one actionable record, not
a record per step. Observed values ALWAYS enter the window — a genuine
regime change stops firing once the median catches up.
"""

from __future__ import annotations

import math
from collections import deque
from statistics import median
from typing import List, Optional


class AnomalyDetector:
    def __init__(
        self,
        window: int = 50,
        loss_spike: float = 3.0,
        grad_spike: float = 10.0,
        min_points: Optional[int] = None,
    ):
        if window < 2:
            raise ValueError(f"anomaly window must be >= 2, got {window}")
        self.window = int(window)
        self.loss_spike = float(loss_spike)
        self.grad_spike = float(grad_spike)
        # warm-up/cooldown grain: enough points for a meaningful median,
        # never more than half the window
        self.min_points = (
            int(min_points) if min_points is not None
            else max(2, min(8, self.window // 2))
        )
        self._losses: deque = deque(maxlen=self.window)
        self._gnorms: deque = deque(maxlen=self.window)
        self._cooldown: dict = {}  # kind -> observations left to skip

    def _cooling(self, kind: str) -> bool:
        """Tick ``kind``'s cooldown on EVERY observation of its stream (not
        only on would-fire ones — a kind must come off cooldown after
        ``min_points`` observations regardless of what they looked like,
        or isolated later anomalies get silently swallowed)."""
        left = self._cooldown.get(kind, 0)
        if left > 0:
            self._cooldown[kind] = left - 1
            return True
        return False

    def _fire(self, kind: str, finding: dict) -> dict:
        self._cooldown[kind] = self.min_points
        return finding

    def _check_spike(
        self, kind: str, value: float, series: deque, factor: float,
        epoch, step,
    ) -> Optional[dict]:
        cooling = self._cooling(kind)
        out = None
        if not cooling and len(series) >= self.min_points:
            med = float(median(series))
            if med > 0.0 and value > factor * med:
                out = self._fire(kind, {
                    "anomaly": kind,
                    "epoch": epoch,
                    "step": step,
                    "value": round(value, 6),
                    "median": round(med, 6),
                    "ratio": round(value / med, 3),
                    "threshold": factor,
                })
        series.append(value)  # spikes enter the window too (self-limiting)
        return out

    def observe(
        self,
        *,
        epoch: Optional[int] = None,
        step: Optional[int] = None,
        loss: Optional[float] = None,
        grad_norm: Optional[float] = None,
        nonfinite: Optional[float] = None,
    ) -> List[dict]:
        """Feed one fetched-metrics observation; returns the (possibly
        empty) list of finding dicts — each self-describing enough to be a
        history record verbatim."""
        findings: List[dict] = []
        if loss is not None:
            loss = float(loss)
            if not math.isfinite(loss):
                if not self._cooling("nonfinite_loss"):
                    findings.append(self._fire("nonfinite_loss", {
                        "anomaly": "nonfinite_loss", "epoch": epoch,
                        "step": step, "value": str(loss),
                    }))
            else:
                self._cooling("nonfinite_loss")  # finite loss ticks it too
                f = self._check_spike(
                    "loss_spike", loss, self._losses, self.loss_spike,
                    epoch, step,
                )
                if f:
                    findings.append(f)
        if grad_norm is not None:
            grad_norm = float(grad_norm)
            if math.isfinite(grad_norm):
                f = self._check_spike(
                    "grad_norm_explosion", grad_norm, self._gnorms,
                    self.grad_spike, epoch, step,
                )
                if f:
                    findings.append(f)
        if nonfinite is not None:
            cooling = self._cooling("nonfinite_grads")
            if float(nonfinite) > 0 and not cooling:
                findings.append(self._fire("nonfinite_grads", {
                    "anomaly": "nonfinite_grads", "epoch": epoch,
                    "step": step, "value": float(nonfinite),
                }))
        return findings
