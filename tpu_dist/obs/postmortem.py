"""``obs postmortem`` — assemble per-rank crash-forensics bundles
(``docs/observability.md`` "Crash forensics").

After a wedge/kill, the evidence is scattered across per-rank files that
different subsystems left behind: the SIGKILL-surviving flight ring and
the faulthandler stack dumps (``--crash_dir``, ``obs/flight.py``), the
heartbeat left un-swept (``--heartbeat_file``), the last OpenMetrics
exposition (``--metrics_file``), and the history JSONL's tail
(``--log_file``). This module walks a set of directories, groups the
artifacts by rank (the shared ``.h<k>`` naming — ``heartbeat.
per_rank_path``), and folds them into ONE report per rank:

* the decoded ring tail (ordered records, torn-slot count, the last
  ``step`` slot — where the rank was when it stopped writing),
* the parsed stack dump (all threads, the stuck frame by name),
* the last heartbeat (position + phase),
* the last exposition's key gauges + active alerts,
* a verdict: ``clean`` / ``preempted`` / ``interrupted`` / ``fatal`` /
  ``no-clean-exit`` (the hard-kill/wedge signature: a ring that simply
  stops).

The launcher watchdog auto-invokes this after killing a wedged worker
(``cli/launch.py``), appending one ``postmortem`` record (history schema
v9) to the run's JSONL so ``obs summarize`` / ``tail`` / ``pod`` render
the crash next to the telemetry that led up to it.

Pure host-side file crunching — no jax, runs anywhere the files can be
copied to. CLI in ``obs/__main__.py``::

    python -m tpu_dist.obs postmortem <dir> [<dir> ...] [--out bundle.json]
        [--annotate] [--tail N] [--format text|json]

Exit codes: 0 bundle assembled, 1 no forensic artifacts found in the
given dirs, 2 unreadable input.
"""

from __future__ import annotations

import json
import os
import re
import time
from typing import Dict, List, Optional, Tuple

from tpu_dist.obs import export as export_lib
from tpu_dist.obs import flight as flight_lib
from tpu_dist.obs import heartbeat as heartbeat_lib
from tpu_dist.obs import memory as memory_lib
from tpu_dist.obs import summarize as summ

#: Default bundle file name (written into the first scanned dir).
BUNDLE_NAME = "postmortem.json"

#: ``postmortem`` records stamp the CURRENT history schema (metrics/
#: history.py — v9 introduced this kind; v15 is current after the
#: additive causal-tracing fields). Kept as a literal so this module
#: stays jax-free (the watchdog's auto-invoke and any laptop holding
#: the copied files must not need a backend); pinned to the real
#: SCHEMA_VERSION by ``tests/test_flight.py`` — the fleet-module
#: discipline (``FLEET_SCHEMA_VERSION``).
POSTMORTEM_SCHEMA_VERSION = 15

#: Artifact stems recognized during discovery; each may carry the
#: ``.h<k>`` per-rank suffix. History files are any ``*.jsonl``.
_HB_STEM = "hb.json"
_METRICS_STEM = "metrics.prom"

_RANK_SUFFIX_RE = re.compile(r"^(?P<stem>.+?)\.h(?P<rank>\d+)$")


def _split_rank(name: str) -> Tuple[str, int]:
    m = _RANK_SUFFIX_RE.match(name)
    if m:
        return m.group("stem"), int(m.group("rank"))
    return name, 0


def discover(dirs: List[str]) -> dict:
    """Walk the given dirs (non-recursive) and group forensic artifacts
    by rank: ``{"rings": {rank: path}, "stacks": {...}, "heartbeats":
    {...}, "expositions": {...}, "histories": {rank: path}, "ooms":
    {rank: path}, "scanned": [dirs that existed]}``. First occurrence of
    a (kind, rank) wins — pass the most authoritative dir first."""
    rings: Dict[int, str] = {}
    stacks: Dict[int, str] = {}
    hbs: Dict[int, str] = {}
    expos: Dict[int, str] = {}
    hists: Dict[int, str] = {}
    ooms: Dict[int, str] = {}
    scanned: List[str] = []
    for d in dirs:
        try:
            entries = sorted(os.listdir(d))
        except OSError:
            continue
        scanned.append(d)
        for entry in entries:
            stem, rank = _split_rank(entry)
            path = os.path.join(d, entry)
            if stem == flight_lib.RING_NAME:
                rings.setdefault(rank, path)
            elif stem == flight_lib.STACKS_NAME:
                stacks.setdefault(rank, path)
            elif stem == memory_lib.OOM_NAME:
                ooms.setdefault(rank, path)
            elif stem == _HB_STEM or (
                stem.endswith(".json") and "hb" in stem.split(".")[0]
            ):
                hbs.setdefault(rank, path)
            elif stem == _METRICS_STEM or stem.endswith(".prom"):
                expos.setdefault(rank, path)
            elif stem.endswith(".jsonl"):
                hists.setdefault(rank, path)
    return {
        "rings": rings, "stacks": stacks, "heartbeats": hbs,
        "expositions": expos, "histories": hists, "ooms": ooms,
        "scanned": scanned,
    }


def _ring_section(path: str, tail: int) -> Optional[dict]:
    try:
        dec = flight_lib.decode(path)
    except OSError:
        return {"file": path, "error": "unreadable"}
    last = flight_lib.last_step(dec)
    fatals = flight_lib.fatal_records(dec)
    recs = dec["records"]
    return {
        "file": path,
        "header": dec.get("header"),
        "n_records": len(recs),
        "torn_slots": dec["torn_slots"],
        "records": recs[-tail:],
        "last": dec.get("last"),
        "last_step": last,
        "fatal": fatals[-1] if fatals else None,
    }


def _stack_section(path: str) -> Optional[dict]:
    parsed = flight_lib.read_stack_dump(path)
    if parsed is None:
        return None
    return {
        "file": path,
        "n_dumps": parsed["n_dumps"],
        "n_threads": len(parsed["threads"]),
        "threads": [
            {
                "name": t.get("name"),
                "current": t["current"],
                "top": (
                    f"{t['frames'][0][2]} "
                    f"({t['frames'][0][0]}:{t['frames'][0][1]})"
                    if t["frames"] else None
                ),
            }
            for t in parsed["threads"]
        ],
        "stuck_frame": flight_lib.stuck_frame(parsed),
    }


def _exposition_section(path: str) -> Optional[dict]:
    vals = export_lib.scrape(textfile=path)
    if not vals:
        return None
    out = {"file": path, "gauges": export_lib.key_gauges(vals)}
    active = export_lib.active_labels(vals)
    if active:
        out["active_alerts"] = active
    return out


def _fatal_oom(ring: Optional[dict]) -> Optional[dict]:
    """The parsed OOM report hiding in a ring's fatal slot, when the
    fatal message (truncated to the slot budget) still carries the
    RESOURCE_EXHAUSTED signature — the fallback when the full
    ``oom.json`` artifact was lost with the filesystem."""
    fatal = (ring or {}).get("fatal")
    if not fatal:
        return None
    text = f"{fatal.get('error')}: {fatal.get('message')}"
    return memory_lib.parse_resource_exhausted(text)


def _verdict(ring: Optional[dict], stack: Optional[dict],
             heartbeat: Optional[dict], oom: Optional[dict] = None) -> str:
    """Classify how the rank ended. A ring whose terminal record is
    ``exit``/``preempt``/``interrupt`` ended on its own terms; one that
    just stops (plus a left-behind heartbeat) is the wedge/hard-kill
    signature ``obs postmortem`` exists for. A rank whose ``oom``
    section was resolved (a left-behind ``oom.json``, or the fatal slot
    re-parsed by the caller via :func:`_fatal_oom`) gets the distinct
    ``oom`` verdict (obs/memory.py): the fix is sharding/batch math,
    not a stack trace."""
    if oom is not None:
        return "oom"
    if ring and ring.get("fatal"):
        return "fatal"
    last = (ring or {}).get("last") or {}
    kind = last.get("kind")
    if kind == "exit":
        return "clean" if last.get("clean") else "failed"
    if kind == "preempt":
        return "preempted"
    if kind == "interrupt":
        return "interrupted"
    if ring and ring.get("n_records"):
        return "no-clean-exit"
    if heartbeat is not None:
        return "no-clean-exit"
    return "unknown"


def assemble(
    dirs: List[str], *, tail: int = 40, history_tail: int = 20,
) -> dict:
    """The bundle: one per-rank report over everything :func:`discover`
    found, plus the shared history tail. Tolerates every per-artifact
    failure (a half-written file is the expected input here)."""
    found = discover(dirs)
    ranks = sorted(
        set(found["rings"]) | set(found["stacks"]) | set(found["heartbeats"])
        | set(found["expositions"]) | set(found["ooms"])
    )
    rank_reports: List[dict] = []
    for rank in ranks:
        ring = (
            _ring_section(found["rings"][rank], tail)
            if rank in found["rings"] else None
        )
        stack = (
            _stack_section(found["stacks"][rank])
            if rank in found["stacks"] else None
        )
        hb = (
            heartbeat_lib.read(found["heartbeats"][rank])
            if rank in found["heartbeats"] else None
        )
        expo = (
            _exposition_section(found["expositions"][rank])
            if rank in found["expositions"] else None
        )
        # the full OOM artifact (parsed allocation report + the ledger
        # snapshot live at the crash) when the rank wrote one; else the
        # report re-parsed out of the ring's truncated fatal slot
        oom = (
            memory_lib.read_oom_report(found["ooms"][rank])
            if rank in found["ooms"] else None
        )
        if oom is None:
            parsed = _fatal_oom(ring)
            if parsed is not None:
                oom = {"oom": parsed, "source": "flight_ring"}
        rank_reports.append({
            "rank": rank,
            "verdict": _verdict(ring, stack, hb, oom),
            "flight": ring,
            "stack": stack,
            "heartbeat": hb,
            "exposition": expo,
            **({"oom": oom} if oom is not None else {}),
        })
    histories = []
    for rank in sorted(found["histories"]):
        path = found["histories"][rank]
        try:
            records, bad = summ.load_records(path)
        except OSError:
            histories.append({"rank": rank, "file": path,
                              "error": "unreadable"})
            continue
        histories.append({
            "rank": rank,
            "file": path,
            "n_records": len(records),
            "bad_lines": bad,
            "run_id": next(
                (r["run_id"] for r in reversed(records) if r.get("run_id")),
                None,
            ),
            "tail": records[-history_tail:],
        })
    return {
        "generated_ts": round(time.time(), 3),
        "scanned_dirs": found["scanned"],
        "n_ranks": len(rank_reports),
        "ranks": rank_reports,
        "histories": histories,
    }


def write_bundle(report: dict, out_path: str) -> str:
    # tpu-dist: ignore[TD002] — postmortem tooling runs in the single
    # watchdog/CLI process, never inside a multi-process training job
    with open(out_path, "w") as f:
        json.dump(report, f, indent=2, default=str)
    return out_path


def history_record(report: dict, bundle_path: Optional[str]) -> dict:
    """The compact ``postmortem`` history record (schema v9): enough for
    ``obs summarize``/``tail``/``pod`` to render the crash without
    re-reading the bundle."""
    verdicts = {str(r["rank"]): r["verdict"] for r in report["ranks"]}
    stuck = {
        str(r["rank"]): r["stack"]["stuck_frame"]
        for r in report["ranks"]
        if r.get("stack") and r["stack"].get("stuck_frame")
    }
    fatal = {
        str(r["rank"]): (
            f"{r['flight']['fatal'].get('error')}: "
            f"{r['flight']['fatal'].get('message')}"
        )
        for r in report["ranks"]
        if r.get("flight") and r["flight"].get("fatal")
    }
    last_steps = {
        str(r["rank"]): {
            k: r["flight"]["last_step"].get(k) for k in ("epoch", "step")
        }
        for r in report["ranks"]
        if r.get("flight") and r["flight"].get("last_step")
    }
    ooms = {
        str(r["rank"]): memory_lib.oom_summary_line(r["oom"]["oom"])
        for r in report["ranks"]
        if isinstance(r.get("oom"), dict)
        and isinstance(r["oom"].get("oom"), dict)
    }
    rec = {
        "n_ranks": report["n_ranks"],
        "verdicts": verdicts,
    }
    if bundle_path:
        rec["bundle"] = bundle_path
    if stuck:
        rec["stuck_frames"] = stuck
    if fatal:
        rec["fatal"] = fatal
    if ooms:
        rec["oom"] = ooms
    if last_steps:
        rec["last_steps"] = last_steps
    return rec


def sorted_ranks(mapping: dict) -> List[str]:
    """Rank keys of a ``postmortem`` record's per-rank dicts, NUMERICALLY
    ordered (they are JSON string keys — a lexicographic sort would print
    0,1,10,11,...,2 on a 16-rank pod). ONE home for the ordering every
    renderer (summarize/tail/pod) shares."""
    return sorted(
        mapping,
        key=lambda r: (
            not str(r).isdigit(),
            int(r) if str(r).isdigit() else 0,
            str(r),
        ),
    )


def rank_summary(rec: dict, rank: str) -> str:
    """One line for one rank of a ``postmortem`` history record —
    ``'fatal, stuck in get (loader.py:118), flight ring ends at epoch 2
    step 3'``. ONE formatter shared by ``obs summarize``/``tail``/``pod``
    so the three renderings can never drift."""
    verdict = (rec.get("verdicts") or {}).get(rank, "unknown")
    stuck = (rec.get("stuck_frames") or {}).get(rank)
    fatal = (rec.get("fatal") or {}).get(rank)
    oom = (rec.get("oom") or {}).get(rank)
    ls = (rec.get("last_steps") or {}).get(rank) or {}
    return (
        str(verdict)
        + (f", stuck in {stuck}" if stuck else "")
        + (f", {oom}" if oom else (f", fatal {fatal}" if fatal else ""))
        + (
            f", flight ring ends at epoch {ls.get('epoch')} step "
            f"{ls.get('step')}" if ls else ""
        )
    )


def append_history_record(report: dict, bundle_path: Optional[str],
                          history_path: str) -> dict:
    """Append the ``postmortem`` record to the run's JSONL in the
    MetricsHistory line format (the watchdog's auto-invoke path — the
    crash lands in the same log the run was writing, where ``obs tail``
    picks it up as it lands)."""
    rec = {
        "ts": round(time.time(), 3),
        "schema_version": POSTMORTEM_SCHEMA_VERSION,
        "kind": "postmortem",
        **history_record(report, bundle_path),
    }
    # tpu-dist: ignore[TD002] — single watchdog/CLI process (see above)
    with open(history_path, "a") as f:
        f.write(json.dumps(rec, default=str) + "\n")
    return rec


def run_postmortem(
    dirs: List[str], *, out: Optional[str] = None, annotate: bool = False,
    tail: int = 40,
) -> Tuple[dict, Optional[str]]:
    """The whole auto-invoke path (watchdog + CLI): assemble, write the
    bundle next to the evidence, optionally annotate the discovered
    primary history. Returns ``(report, bundle_path)``; ``bundle_path``
    is None when nothing at all was found (no bundle worth writing)."""
    report = assemble(dirs, tail=tail)
    if not report["ranks"] and not report["histories"]:
        return report, None
    bundle = out or os.path.join(
        (report["scanned_dirs"] or dirs)[0], BUNDLE_NAME
    )
    write_bundle(report, bundle)
    if annotate:
        primary = next(
            (h["file"] for h in report["histories"]
             if h.get("rank") == 0 and not h.get("error")),
            None,
        )
        if primary:
            append_history_record(report, bundle, primary)
    return report, bundle


def format_text(report: dict) -> str:
    lines = [
        f"postmortem — {report['n_ranks']} rank(s) across "
        f"{len(report.get('scanned_dirs') or [])} dir(s)"
    ]
    for r in report["ranks"]:
        lines.append(f"rank {r['rank']}: {r['verdict'].upper()}")
        ring = r.get("flight")
        if ring:
            if ring.get("error"):
                lines.append(f"  flight ring: {ring['error']} ({ring['file']})")
            else:
                ls = ring.get("last_step")
                lines.append(
                    f"  flight ring: {ring['n_records']} record(s)"
                    + (
                        f", {ring['torn_slots']} torn slot(s)"
                        if ring.get("torn_slots") else ""
                    )
                    + (
                        f" — last step epoch {ls.get('epoch')} step "
                        f"{ls.get('step')}" if ls else " — no step record"
                    )
                )
                fatal = ring.get("fatal")
                if fatal:
                    lines.append(
                        f"  fatal: {fatal.get('error')}: "
                        f"{fatal.get('message')}"
                    )
                    for fr in fatal.get("frames") or []:
                        lines.append(f"    {fr}")
                last = ring.get("last") or {}
                if last.get("kind") in ("exit", "preempt", "interrupt"):
                    lines.append(f"  terminal record: {last['kind']}")
        stack = r.get("stack")
        if stack:
            lines.append(
                f"  stack dump: {stack['n_threads']} thread(s), "
                f"{stack['n_dumps']} dump(s)"
                + (
                    f" — stuck in {stack['stuck_frame']}"
                    if stack.get("stuck_frame") else ""
                )
            )
            for t in stack["threads"]:
                if not t["current"] and t.get("top"):
                    lines.append(
                        f"    thread {t.get('name') or '?'}: {t['top']}"
                    )
        oom = r.get("oom")
        if isinstance(oom, dict) and isinstance(oom.get("oom"), dict):
            for ln in memory_lib.format_oom_text(oom["oom"]).splitlines():
                lines.append(f"  {ln}")
            led = oom.get("ledger")
            if isinstance(led, dict):
                lines.append("  " + memory_lib.summary_line(led))
        hb = r.get("heartbeat")
        if hb:
            lines.append(
                f"  heartbeat left behind: beat {hb.get('counter')} at "
                f"epoch {hb.get('epoch')} step {hb.get('step')} phase "
                f"{hb.get('phase')!r}"
            )
        expo = r.get("exposition")
        if expo:
            gauges = ", ".join(
                f"{k} {v}" for k, v in (expo.get("gauges") or {}).items()
            )
            lines.append(f"  last exposition: {gauges or '(empty)'}")
            if expo.get("active_alerts"):
                lines.append(
                    "  active alerts: " + ", ".join(expo["active_alerts"])
                )
    for h in report.get("histories", []):
        if h.get("error"):
            lines.append(f"history {h['file']}: {h['error']}")
            continue
        lines.append(
            f"history {h['file']}: {h['n_records']} record(s)"
            + (f", {h['bad_lines']} torn line(s)" if h.get("bad_lines") else "")
            + (f", run {h['run_id']}" if h.get("run_id") else "")
        )
        for rec in (h.get("tail") or [])[-5:]:
            lines.append(
                f"  [{rec.get('rel_s')}] {rec.get('kind')}"
                + (
                    f" epoch {rec.get('epoch')}"
                    if rec.get("epoch") is not None else ""
                )
            )
    return "\n".join(lines)
