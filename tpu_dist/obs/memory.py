"""HBM observability — the structured memory ledger, pre-flight
feasibility lint, and OOM forensics (``docs/observability.md`` "HBM
ledger & OOM forensics").

Time is fully instrumented (goodput ledger, xprof attribution, serving
histograms); this module instruments the OTHER binding constraint. Pod-
scale runs die on memory long before they die on FLOPs (PAPERS.md
"Exploring the limits of Concurrency in ML Training on Google TPUs"),
and ZeRO-1 exists entirely because of per-chip memory (arXiv:2004.13336)
— yet before this module an OOM here was an unparsed
``RESOURCE_EXHAUSTED`` traceback and the only memory telemetry was two
epoch-end allocator gauges. Four layers, all host-side metadata work
(rule TD115 pins that arming every one of them leaves the traced train
step byte-identical):

* **Static per-leaf ledger** — :func:`static_ledger` walks pytrees
  (params / opt state / error-feedback residuals / BN state / a batch)
  and accounts bytes from avals + shardings alone: shape x itemsize per
  leaf, at the leaf's SHARDED extent per device (a ZeRO-1 flat momentum
  vector laid ``P('data')`` over 8 devices counts ceil(L/8) elements per
  chip, not L). CPU-valid: no device transfer, no compile — the exact
  input the ``--auto_shard`` planner's HBM budget needs (ROADMAP item 3).
* **Live census + reconciliation** — :func:`live_census` sums
  ``jax.live_arrays()`` per device (again from sharding metadata);
  :func:`reconcile` sets it against the allocator's own
  ``memory_stats()`` counters so that ``attributed + unattributed ==
  bytes_in_use`` holds EXACTLY, by construction: unattributed is
  *defined* as the difference — XLA workspace, fragmentation, and
  donated-but-alive handles get their own tracked gauge instead of
  silently inflating "model memory". Where the backend keeps no
  allocator stats (CPU), the census itself is the authority
  (``source: "census"``) and the invariant still holds exactly.
* **Pre-flight feasibility** — :func:`feasibility` /
  :func:`preflight_check` compare the static estimate against the
  per-chip HBM budget (``costmodel.CHIP_HBM_BYTES``) scaled by a
  headroom fraction, BEFORE the first compile can OOM; the trainer wires
  it as ``--memory_check warn|refuse`` with ``--memory_headroom`` — the
  lint-style HBM-infeasibility rule ROADMAP item 3 names.
* **OOM forensics** — :func:`parse_resource_exhausted` turns XLA's
  ``RESOURCE_EXHAUSTED`` text (both the GPU/BFC "while trying to
  allocate N bytes" shape with its "Largest program allocations" buffer
  table and the TPU "Used X of Y hbm / Exceeded hbm capacity by Z"
  shape) into a typed report; the trainer stamps it into the flight
  ring, writes the full report + the ledger snapshot that was live as
  ``oom.json`` in ``--crash_dir``, and ``obs postmortem`` classifies the
  rank's verdict as ``oom``.

Everything lands in the ordinary telemetry plumbing: ``mem.*`` gauges in
the counter registry (-> every history record and OpenMetrics
exposition), ONE ``memory`` history record per run (schema v11,
additive) at first dispatch, a ``memory_headroom_low`` built-in alert
rule, summarize/tail/pod rendering, and a ``peak_hbm_bytes`` scalar the
``obs compare`` gate regresses on (higher = worse, the direction
registry's first bytes metric).

This module imports jax ONLY inside the functions that need a backend
(the ledger/census); the parser, reconciliation, feasibility math, and
every formatter are plain stdlib — they run in the postmortem CLI on any
laptop the crash files were copied to.

CLI: ``python -m tpu_dist.obs memory <run.jsonl>`` (ledger report over a
history) and ``python -m tpu_dist.obs memory --oom <traceback.txt>``
(parse a raw RESOURCE_EXHAUSTED text). Exit codes: 0 report, 1 no
memory telemetry / unparseable, 2 unreadable input.
"""

from __future__ import annotations

import json
import math
import re
import time
from typing import Dict, List, Optional

from tpu_dist.obs import counters as counters_lib

#: Per-section leaves listed by size in the ledger (the rest are summed).
TOP_LEAVES = 5

#: Canonical per-rank OOM-report artifact name inside a ``--crash_dir``
#: (rank 0 bare, rank k ``.h<k>`` — the flight-ring naming scheme).
OOM_NAME = "oom.json"


class InfeasibleMemoryError(ValueError):
    """The static ledger does not fit the per-chip HBM budget and
    ``--memory_check refuse`` asked for a hard stop before compiling."""


# --------------------------------------------------------------------------
# Static per-leaf ledger — avals + shardings, no device work.
# --------------------------------------------------------------------------


def _leaf_entry(path: str, leaf) -> Optional[dict]:
    """One leaf's byte accounting from metadata alone: ``bytes_total`` =
    shape x itemsize; ``bytes_per_device`` = the SHARDED extent (what one
    chip actually holds — ``sharding.shard_shape``), equal to the total
    on replicated/host leaves. None for non-array leaves."""
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        return None
    try:
        import numpy as np  # noqa: PLC0415

        itemsize = np.dtype(dtype).itemsize
    except TypeError:
        return None
    total = int(math.prod(shape)) * itemsize if shape else itemsize
    per_device = total
    sharded = False
    sharding = getattr(leaf, "sharding", None)
    if sharding is not None:
        try:
            shard_shape = sharding.shard_shape(tuple(shape))
            per_device = int(math.prod(shard_shape)) * itemsize if shard_shape else itemsize
            sharded = per_device < total
        except Exception:  # tpu-dist: ignore[TD006] — an exotic sharding
            pass  # degrades to the replicated (total) count, never raises
    return {
        "path": path,
        "bytes_per_device": per_device,
        "bytes_total": total,
        "shape": [int(s) for s in shape],
        "dtype": str(dtype),
        "sharded": sharded,
    }


def static_ledger(**sections) -> dict:
    """Per-leaf static accounting of named pytrees (``params=...,
    opt_state=..., ef=..., bn_state=..., batch=...``): per section the
    per-device and total bytes, leaf count, sharded-leaf count, and the
    :data:`TOP_LEAVES` largest leaves by per-device bytes. Sections that
    are None/empty are recorded with zero bytes (the report says "no EF
    state" instead of omitting the row)."""
    import jax  # noqa: PLC0415

    out_sections: Dict[str, dict] = {}
    per_device = total = leaves = 0
    for name, tree in sections.items():
        entries: List[dict] = []
        if tree is not None:
            for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
                e = _leaf_entry(jax.tree_util.keystr(path), leaf)
                if e is not None:
                    entries.append(e)
        sec_dev = sum(e["bytes_per_device"] for e in entries)
        sec_tot = sum(e["bytes_total"] for e in entries)
        entries.sort(key=lambda e: -e["bytes_per_device"])
        out_sections[name] = {
            "bytes_per_device": sec_dev,
            "bytes_total": sec_tot,
            "n_leaves": len(entries),
            "sharded_leaves": sum(e["sharded"] for e in entries),
            "top": entries[:TOP_LEAVES],
        }
        per_device += sec_dev
        total += sec_tot
        leaves += len(entries)
    return {
        "sections": out_sections,
        "bytes_per_device": per_device,
        "bytes_total": total,
        "n_leaves": leaves,
    }


# --------------------------------------------------------------------------
# Live census + allocator reconciliation.
# --------------------------------------------------------------------------


def live_census() -> dict:
    """Sum ``jax.live_arrays()`` per device from sharding metadata (no
    transfer, no sync): ``{"n_arrays", "bytes_total", "bytes_by_device":
    {device_id: bytes}, "bytes_device0"}``. ``bytes_device0`` is the
    first local device's attribution — what :func:`reconcile` sets
    against that device's allocator counters."""
    import jax  # noqa: PLC0415

    by_device: Dict[int, int] = {}
    n = 0
    total = 0
    for arr in jax.live_arrays():
        e = _leaf_entry("", arr)
        if e is None:
            continue
        n += 1
        total += e["bytes_total"]
        sharding = getattr(arr, "sharding", None)
        devices = sorted(
            getattr(sharding, "device_set", None) or [],
            key=lambda d: d.id,
        )
        if not devices:
            devices = [jax.local_devices()[0]]
        for d in devices:
            by_device[d.id] = by_device.get(d.id, 0) + e["bytes_per_device"]
    dev0 = jax.local_devices()[0].id
    return {
        "n_arrays": n,
        "bytes_total": total,
        "bytes_by_device": {str(k): v for k, v in sorted(by_device.items())},
        "bytes_device0": by_device.get(dev0, 0),
    }


def reconcile(census: dict, allocator: Optional[dict]) -> dict:
    """The ledger's closing identity: ``attributed + unattributed ==
    bytes_in_use``, EXACT by construction.

    ``attributed`` is the census's first-device bytes (every live array
    the process can name); ``allocator`` must therefore be the SAME
    device's counters (:func:`ledger` passes device 0's raw
    ``memory_stats()`` — NOT :func:`costmodel.device_memory_stats`,
    whose scalars report the worst chip: pairing device 0's census with
    another chip's allocator would book cross-device sharding skew as
    workspace). ``unattributed`` is *defined* as that device's
    ``bytes_in_use`` minus the attribution — XLA workspace, allocator
    fragmentation, and (negative) donated buffers whose Python handles
    outlive their device memory. Where the backend keeps no allocator
    stats (``allocator`` None/empty — CPU), the census itself is the
    authority: ``bytes_in_use := attributed``, ``unattributed := 0``,
    ``source: "census"`` — the invariant holds exactly either way, so a
    consumer never needs a backend-conditional code path."""
    attributed = int(census.get("bytes_device0", 0))
    in_use = (allocator or {}).get("bytes_in_use")
    if isinstance(in_use, (int, float)):
        in_use = int(in_use)
        return {
            "attributed_bytes": attributed,
            "unattributed_bytes": in_use - attributed,
            "bytes_in_use": in_use,
            "source": "allocator",
        }
    return {
        "attributed_bytes": attributed,
        "unattributed_bytes": 0,
        "bytes_in_use": attributed,
        "source": "census",
    }


def ledger(static: Optional[dict] = None, xla: Optional[dict] = None) -> dict:
    """One full ledger snapshot: the construction-time static accounting
    (``static``), the compile-time ``memory_analysis()`` waterfall
    (``xla`` — ``costmodel.memory_analysis_bytes``), the live census,
    the allocator counters (per-device max/min/skew —
    ``costmodel.device_memory_stats``), and the reconciliation. This is
    the ``memory`` history record (schema v11) and the crash snapshot
    ``oom.json`` embeds."""
    import jax  # noqa: PLC0415

    from tpu_dist.obs import costmodel  # noqa: PLC0415

    census = live_census()
    allocator = costmodel.device_memory_stats()
    # reconcile against DEVICE 0's raw counters — the same device the
    # census's bytes_device0 attributes. The worst-chip scalars in
    # `allocator` belong to the skew report, not the identity: pairing
    # device 0's census with another chip's allocator would book
    # cross-device sharding skew as workspace (see reconcile()).
    try:
        dev0_stats = jax.local_devices()[0].memory_stats()
    except Exception:  # tpu-dist: ignore[TD006] — stat-less backend:
        dev0_stats = None  # reconcile degrades to census authority
    rec: dict = {
        "census": census,
        "reconciliation": reconcile(census, dev0_stats),
    }
    if static is not None:
        rec["static"] = static
    if xla is not None:
        rec["xla"] = xla
    if allocator is not None:
        rec["allocator"] = allocator
    return rec


def publish_ledger(rec: dict) -> None:
    """Stamp a ledger snapshot into the ``mem.*`` gauges — every later
    history record and OpenMetrics exposition carries the numbers
    (``counters.snapshot`` feeds both)."""
    static = rec.get("static") or {}
    if static.get("bytes_per_device"):
        counters_lib.set_gauge(
            "mem.static_bytes_per_device", static["bytes_per_device"]
        )
    xla = rec.get("xla") or {}
    for key, gauge in (
        ("argument_bytes", "mem.xla_argument_bytes"),
        ("output_bytes", "mem.xla_output_bytes"),
        ("temp_bytes", "mem.xla_temp_bytes"),
        ("generated_code_bytes", "mem.xla_code_bytes"),
        ("peak_bytes", "mem.xla_peak_bytes"),
    ):
        v = xla.get(key)
        if isinstance(v, (int, float)):
            counters_lib.set_gauge(gauge, int(v))
    rc = rec.get("reconciliation") or {}
    for key, gauge in (
        ("attributed_bytes", "mem.attributed_bytes"),
        ("unattributed_bytes", "mem.unattributed_bytes"),
    ):
        v = rc.get(key)
        if isinstance(v, (int, float)):
            counters_lib.set_gauge(gauge, int(v))


def record_peak_hbm(rec: dict) -> Optional[int]:
    """The snapshot's single gating scalar: the worst chip's allocator
    peak when the backend reports one (the TRUE number), else XLA's
    static ``peak_bytes`` estimate, else the reconciled ``bytes_in_use``
    (census authority on CPU). None on an empty record."""
    alloc = rec.get("allocator") or {}
    v = alloc.get("peak_bytes_in_use")
    if isinstance(v, (int, float)) and v > 0:
        return int(v)
    xla = rec.get("xla") or {}
    v = xla.get("peak_bytes")
    if isinstance(v, (int, float)) and v > 0:
        return int(v)
    v = (rec.get("reconciliation") or {}).get("bytes_in_use")
    return int(v) if isinstance(v, (int, float)) and v > 0 else None


# --------------------------------------------------------------------------
# Pre-flight feasibility — the HBM lint (ROADMAP item 3).
# --------------------------------------------------------------------------


def feasibility(
    required_bytes: int, budget_bytes: int, headroom: float = 0.9,
) -> dict:
    """Does a per-device static requirement fit a per-chip HBM budget?
    ``headroom`` is the fraction of the budget the STATIC estimate may
    claim — the rest is reserved for XLA temps/workspace/fragmentation,
    which the static ledger cannot see (the ``unattributed`` gauge
    measures them after the fact). ``utilization`` is required/budget
    (headroom-independent, the number humans compare across chips)."""
    if budget_bytes <= 0:
        raise ValueError(f"budget_bytes must be positive, got {budget_bytes}")
    if not 0.0 < headroom <= 1.0:
        raise ValueError(f"headroom must be in (0, 1], got {headroom}")
    allowed = int(budget_bytes * headroom)
    return {
        "required_bytes": int(required_bytes),
        "budget_bytes": int(budget_bytes),
        "headroom": headroom,
        "allowed_bytes": allowed,
        "utilization": round(required_bytes / budget_bytes, 4),
        "fits": required_bytes <= allowed,
    }


def preflight_check(
    required_bytes: int,
    *,
    budget_bytes: Optional[int] = None,
    headroom: float = 0.9,
    action: str = "warn",
    chip_kind: Optional[str] = None,
) -> Optional[dict]:
    """The trainer's pre-compile HBM lint. ``budget_bytes`` overrides the
    chip-table lookup (``costmodel.chip_hbm_bytes`` — tests, exotic
    parts); an unknown chip with no override (CPU emulation) returns
    None: no budget, no lint, never a guess. ``action``: ``"off"`` skips
    entirely, ``"warn"`` returns the report (the caller prints),
    ``"refuse"`` raises :class:`InfeasibleMemoryError` on a miss — the
    run stops BEFORE the first compile can OOM."""
    if action not in ("off", "warn", "refuse"):
        raise ValueError(
            f"memory_check must be off|warn|refuse, got {action!r}"
        )
    if action == "off":
        return None
    if budget_bytes is None:
        from tpu_dist.obs import costmodel  # noqa: PLC0415

        budget_bytes = costmodel.chip_hbm_bytes(chip_kind)
    if budget_bytes is None:
        return None
    report = feasibility(required_bytes, budget_bytes, headroom)
    if not report["fits"] and action == "refuse":
        raise InfeasibleMemoryError(
            f"static HBM requirement {fmt_bytes(report['required_bytes'])} "
            f"per device exceeds {headroom:.0%} of the "
            f"{fmt_bytes(report['budget_bytes'])} per-chip budget "
            f"(allowed {fmt_bytes(report['allowed_bytes'])}) — the config "
            "cannot fit before XLA temps are even counted; shard more "
            "(--shard_weight_update/--fsdp), shrink the batch, or raise "
            "--memory_headroom / pass --memory_check warn to proceed anyway"
        )
    return report


# --------------------------------------------------------------------------
# OOM forensics — RESOURCE_EXHAUSTED text -> typed report.
# --------------------------------------------------------------------------

#: "2.50G" / "750.6M" / "1.1KiB" / "123B" — XLA's human-size rendering.
_SIZE_RE = r"(\d+(?:\.\d+)?)\s*([KMGTP]i?B?|B|bytes?)"
_OOM_MARKERS = (
    "RESOURCE_EXHAUSTED", "Out of memory", "Ran out of memory",
    "OOM when allocating",
)
#: Multiplier per size-prefix letter; the ``iB``/``B`` tail and letter
#: case are normalized away in :func:`_to_bytes` (the size regexes run
#: IGNORECASE, so a lowercase ``2.5g`` must not silently parse as 2 B).
_UNIT_PREFIX = {
    "K": 1024, "M": 1024 ** 2, "G": 1024 ** 3,
    "T": 1024 ** 4, "P": 1024 ** 5,
}

_ALLOCATE_RE = re.compile(
    r"allocat\w+\s+(?:of\s+)?" + _SIZE_RE, re.IGNORECASE
)
_USED_OF_RE = re.compile(
    r"Used\s+" + _SIZE_RE + r"\s+of\s+" + _SIZE_RE, re.IGNORECASE
)
_EXCEEDED_RE = re.compile(
    r"Exceeded\s+\w+\s+capacity\s+by\s+" + _SIZE_RE, re.IGNORECASE
)
_BUFFER_RE = re.compile(r"^\s*(\d+)\.\s+Size:\s*" + _SIZE_RE)
_SHAPE_RE = re.compile(r"^\s*Shape:\s*(\S.*)$")
_OP_RE = re.compile(r'^\s*Operator:\s*op_name="([^"]*)"')
_XLA_LABEL_RE = re.compile(r"^\s*XLA Label:\s*(\S.*)$")


def _to_bytes(num: str, unit: str) -> int:
    u = unit.strip()
    if u.lower() in ("b", "byte", "bytes"):
        return int(float(num))
    return int(float(num) * _UNIT_PREFIX.get(u[0].upper(), 1))


def parse_resource_exhausted(text: str) -> Optional[dict]:
    """Structure an XLA ``RESOURCE_EXHAUSTED`` message. Returns None when
    the text carries no OOM marker at all (garbage / a different error);
    otherwise a typed report with whatever the (possibly TRUNCATED —
    flight-ring slots cap messages at 200 chars) text still holds:

    * ``headline`` — the first marker line, trimmed,
    * ``requested_bytes`` — the failed allocation ("while trying to
      allocate 2.50G"),
    * ``used_bytes`` / ``limit_bytes`` / ``excess_bytes`` — the TPU
      "Used X of Y hbm … Exceeded hbm capacity by Z" accounting,
    * ``buffers`` — the "Largest program allocations" table, each entry
      ``{rank, size_bytes, shape?, op?}`` (up to 16),
    * ``buffers_bytes`` — their sum.

    Absent fields were simply not in the text; a report with only a
    headline is still a report (the truncated-ring case)."""
    if not text or not any(m in text for m in _OOM_MARKERS):
        return None
    report: dict = {"kind": "oom"}
    for line in text.splitlines():
        if any(m in line for m in _OOM_MARKERS):
            report["headline"] = line.strip()[:240]
            break
    m = _ALLOCATE_RE.search(text)
    if m:
        report["requested_bytes"] = _to_bytes(m.group(1), m.group(2))
    m = _USED_OF_RE.search(text)
    if m:
        report["used_bytes"] = _to_bytes(m.group(1), m.group(2))
        report["limit_bytes"] = _to_bytes(m.group(3), m.group(4))
    m = _EXCEEDED_RE.search(text)
    if m:
        report["excess_bytes"] = _to_bytes(m.group(1), m.group(2))
    buffers: List[dict] = []
    cur: Optional[dict] = None
    for line in text.splitlines():
        bm = _BUFFER_RE.match(line)
        if bm:
            if len(buffers) >= 16:
                break
            cur = {
                "rank": int(bm.group(1)),
                "size_bytes": _to_bytes(bm.group(2), bm.group(3)),
            }
            buffers.append(cur)
            continue
        if cur is None:
            continue
        sm = _SHAPE_RE.match(line)
        if sm:
            cur["shape"] = sm.group(1).strip()[:120]
            continue
        om = _OP_RE.match(line) or _XLA_LABEL_RE.match(line)
        if om and "op" not in cur:
            cur["op"] = om.group(1).strip()[:160]
    if buffers:
        report["buffers"] = buffers
        report["buffers_bytes"] = sum(b["size_bytes"] for b in buffers)
    return report


def oom_summary_line(report: dict) -> str:
    """One human line for the rank-0 warning / tail event / postmortem:
    ``'OOM: requested 2.5GiB, used 15.9GiB of 16.0GiB (3 largest buffers
    account for 12.1GiB)'``."""
    parts = []
    if report.get("requested_bytes"):
        parts.append(f"requested {fmt_bytes(report['requested_bytes'])}")
    if report.get("used_bytes") and report.get("limit_bytes"):
        parts.append(
            f"used {fmt_bytes(report['used_bytes'])} of "
            f"{fmt_bytes(report['limit_bytes'])}"
        )
    elif report.get("excess_bytes"):
        parts.append(f"over capacity by {fmt_bytes(report['excess_bytes'])}")
    if report.get("buffers"):
        parts.append(
            f"{len(report['buffers'])} largest buffers account for "
            f"{fmt_bytes(report.get('buffers_bytes', 0))}"
        )
    return "OOM: " + (", ".join(parts) if parts else
                      report.get("headline", "RESOURCE_EXHAUSTED"))


def write_oom_report(
    path: str, report: dict, snapshot: Optional[dict] = None,
) -> Optional[str]:
    """The crash artifact: the parsed allocation report plus the ledger
    snapshot that was live at the time, as one JSON next to the flight
    ring. Never raises — a full disk must not mask the OOM that is
    already propagating."""
    rec = {"ts": round(time.time(), 3), "oom": report}
    if snapshot:
        rec["ledger"] = snapshot
    try:
        # tpu-dist: ignore[TD002] — per-rank artifact by construction:
        # the caller derives one oom.json path per rank (per_rank_path),
        # exactly the flight-ring discipline
        with open(path, "w") as f:
            json.dump(rec, f, indent=2, default=str)
    except OSError:
        counters_lib.inc("mem.oom_report_errors")
        return None
    return path


def read_oom_report(path: str) -> Optional[dict]:
    """Postmortem-side read of :func:`write_oom_report`'s artifact; None
    on a missing/torn file (the expected input after a crash)."""
    try:
        with open(path) as f:
            rec = json.load(f)
    except (OSError, json.JSONDecodeError, UnicodeDecodeError):
        return None
    return rec if isinstance(rec, dict) else None


# --------------------------------------------------------------------------
# Formatting — shared by the CLI, summarize, tail, and the trainer line.
# --------------------------------------------------------------------------


def fmt_bytes(n) -> str:
    """Human bytes: ``'1.5GiB'`` / ``'320.0MiB'`` / ``'512B'`` / ``'-'``."""
    if not isinstance(n, (int, float)):
        return "-"
    neg = n < 0
    v = float(abs(n))
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if v < 1024 or unit == "TiB":
            body = f"{v:.0f}B" if unit == "B" else f"{v:.1f}{unit}"
            return ("-" if neg else "") + body
        v /= 1024
    return str(n)


def summary_line(rec: dict) -> str:
    """One line per ledger snapshot — trainer rank-0 print, ``obs tail``
    event, and the pod report share it so the renderings cannot drift."""
    static = rec.get("static") or {}
    xla = rec.get("xla") or {}
    rc = rec.get("reconciliation") or {}
    parts = []
    if static.get("bytes_per_device"):
        parts.append(f"static {fmt_bytes(static['bytes_per_device'])}/device")
    if isinstance(xla.get("peak_bytes"), (int, float)):
        parts.append(f"xla peak {fmt_bytes(xla['peak_bytes'])}")
    if rc:
        parts.append(
            f"in use {fmt_bytes(rc.get('bytes_in_use'))} "
            f"(attributed {fmt_bytes(rc.get('attributed_bytes'))} + "
            f"unattributed {fmt_bytes(rc.get('unattributed_bytes'))}, "
            f"{rc.get('source')})"
        )
    return "memory ledger: " + (", ".join(parts) or "(empty)")


def format_ledger_text(rec: dict) -> str:
    """The full ledger rendering (``obs memory``): per-section table,
    the XLA waterfall, the reconciliation identity, allocator skew."""
    lines = [summary_line(rec)]
    static = rec.get("static") or {}
    sections = static.get("sections") or {}
    if sections:
        lines.append(
            f"  {'section':>10} {'per-device':>12} {'total':>12} "
            f"{'leaves':>7} {'sharded':>8}"
        )
        for name in sorted(
            sections, key=lambda n: -sections[n]["bytes_per_device"]
        ):
            s = sections[name]
            lines.append(
                f"  {name:>10} {fmt_bytes(s['bytes_per_device']):>12} "
                f"{fmt_bytes(s['bytes_total']):>12} {s['n_leaves']:>7} "
                f"{s['sharded_leaves']:>8}"
            )
            for e in s.get("top") or []:
                lines.append(
                    f"      {fmt_bytes(e['bytes_per_device']):>10}  "
                    f"{e['path']} {e['dtype']}{e['shape']}"
                    + (" [sharded]" if e.get("sharded") else "")
                )
    xla = rec.get("xla") or {}
    if xla:
        lines.append(
            "  xla waterfall: args "
            f"{fmt_bytes(xla.get('argument_bytes'))}, outputs "
            f"{fmt_bytes(xla.get('output_bytes'))}, temps "
            f"{fmt_bytes(xla.get('temp_bytes'))}, codegen "
            f"{fmt_bytes(xla.get('generated_code_bytes'))} -> peak "
            f"{fmt_bytes(xla.get('peak_bytes'))}"
        )
    alloc = rec.get("allocator") or {}
    if alloc:
        skew = alloc.get("bytes_in_use_skew")
        lines.append(
            "  allocator: in use "
            f"{fmt_bytes(alloc.get('bytes_in_use'))} (worst chip)"
            + (
                f", min {fmt_bytes(alloc.get('bytes_in_use_min'))}, "
                f"skew {fmt_bytes(skew)}"
                if skew is not None else ""
            )
            + (
                f", peak {fmt_bytes(alloc.get('peak_bytes_in_use'))}"
                if alloc.get("peak_bytes_in_use") is not None else ""
            )
            + (
                f", limit {fmt_bytes(alloc.get('bytes_limit'))}"
                if alloc.get("bytes_limit") is not None else ""
            )
        )
    return "\n".join(lines)


def format_oom_text(report: dict) -> str:
    lines = [oom_summary_line(report)]
    if report.get("headline"):
        lines.append(f"  {report['headline']}")
    for b in report.get("buffers") or []:
        lines.append(
            f"  {b['rank']:>3}. {fmt_bytes(b['size_bytes']):>10}"
            + (f"  {b['shape']}" if b.get("shape") else "")
            + (f"  {b['op']}" if b.get("op") else "")
        )
    return "\n".join(lines)


# --------------------------------------------------------------------------
# History-report engine (`obs memory <run.jsonl>`).
# --------------------------------------------------------------------------


def memory_report(records: List[dict]) -> dict:
    """Fold a run's history into the memory view: the ``memory`` ledger
    records (schema v11), the per-epoch ``mem.*`` gauge series out of
    the counter snapshots, any OOM events, and the single
    ``peak_hbm_bytes`` scalar ``obs compare`` gates on."""
    ledgers: List[dict] = []
    ooms: List[dict] = []
    series: List[dict] = []
    peak: Optional[int] = None
    for rec in records:
        kind = rec.get("kind")
        if kind == "memory":
            if rec.get("event") == "oom":
                ooms.append({
                    k: rec.get(k) for k in ("epoch", "oom", "ledger")
                    if rec.get(k) is not None
                })
            else:
                ledgers.append(rec)
                p = record_peak_hbm(rec)
                if p is not None:
                    peak = max(peak or 0, p)
        cnt = rec.get("counters")
        if kind == "train_epoch" and isinstance(cnt, dict):
            row = {
                k.split("mem.", 1)[1]: v for k, v in cnt.items()
                if k.startswith("mem.") and isinstance(v, (int, float))
            }
            if row:
                row["epoch"] = rec.get("epoch")
                series.append(row)
        if isinstance(cnt, dict):
            v = cnt.get("mem.peak_bytes_in_use")
            if isinstance(v, (int, float)) and v > 0:
                peak = max(peak or 0, int(v))
    return {
        "ledgers": ledgers,
        "ooms": ooms,
        "epoch_series": series,
        "peak_hbm_bytes": peak,
    }


def format_report_text(report: dict) -> str:
    lines: List[str] = []
    for led in report["ledgers"]:
        lines.append(format_ledger_text(led))
    if report["epoch_series"]:
        lines.append("per-epoch mem.* gauges (worst chip):")
        lines.append(
            f"  {'epoch':>5} {'in_use':>10} {'peak':>10} {'headroom':>9} "
            f"{'skew':>10}"
        )
        for row in report["epoch_series"]:
            hr = row.get("headroom_frac")
            ep = row.get("epoch")
            lines.append(
                f"  {(ep if ep is not None else '-'):>5} "
                f"{fmt_bytes(row.get('bytes_in_use')):>10} "
                f"{fmt_bytes(row.get('peak_bytes_in_use')):>10} "
                f"{(format(hr, '.1%') if isinstance(hr, (int, float)) else '-'):>9} "
                f"{fmt_bytes(row.get('bytes_in_use_skew')):>10}"
            )
    for o in report["ooms"]:
        lines.append("OOM event" + (
            f" at epoch {o['epoch']}" if o.get("epoch") is not None else ""
        ) + ":")
        if isinstance(o.get("oom"), dict):
            lines.append("  " + oom_summary_line(o["oom"]))
    if report["peak_hbm_bytes"] is not None:
        lines.append(
            f"peak HBM (compare gate scalar): "
            f"{fmt_bytes(report['peak_hbm_bytes'])} "
            f"({report['peak_hbm_bytes']} B)"
        )
    if not lines:
        lines.append("no memory telemetry in this history")
    return "\n".join(lines)
