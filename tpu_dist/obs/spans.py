"""Host-side span tracing — Chrome-trace-event output with an XLA bridge
(``docs/observability.md``).

The reference repo's timing story is two ``time.time()`` reads around the
epoch loop printed on rank 0; ``jax.profiler`` captures the DEVICE side but
says nothing about the host work that starves it (checkpoint serialization,
loader waits, eval loops). This module records **host spans** on a
monotonic clock (``time.perf_counter``) and emits them in the Chrome
trace-event format, so one file loads in Perfetto / ``chrome://tracing``
and shows the host timeline; each span additionally enters a
``jax.profiler.TraceAnnotation`` while open, so when an XLA profile is
being captured (``--profile_dir``), the SAME spans appear as named ranges
on the XLA timeline — host and device views line up by construction.

Contract (audited by TD106): arming the recorder changes NOTHING inside
the traced train step — spans wrap host code only, and a disabled
recorder's :func:`span` returns a shared no-op context (one global read,
no allocation). Nesting needs no explicit stack: complete (``"ph": "X"``)
events on the same thread nest by interval containment, which is exactly
how the viewers render them.

Usage::

    spans.enable()
    with spans.span("ckpt/save", epoch=3):
        ...
    spans.export_chrome_trace("trace.json")   # or drain() into history
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional

#: Cap on buffered events: a week-long run must not grow host memory
#: without bound. Overflow drops new events and counts them (the count is
#: surfaced in the exported trace metadata, never silently).
MAX_EVENTS = 200_000

_LOCK = threading.Lock()
_ENABLED = False
_EVENTS: List[dict] = []
_DROPPED = 0
_PID = 0
# One clock zero for every event in the process, set at import and reset by
# enable(): perf_counter is monotonic and sub-microsecond, and a common
# origin keeps cross-thread spans comparable in the viewer.
_T0 = time.perf_counter()
_ANNOTATION = None  # cached jax.profiler.TraceAnnotation (resolved lazily)
# Span-OPEN listener (the flight recorder's tap, docs/observability.md
# "Crash forensics"): called with (name, args) the moment a span opens,
# INDEPENDENT of the recorder being enabled — crash forensics runs on
# every rank, while span buffering stays rank-0-only. The listener must
# never raise (FlightRecorder.record is never-raise by contract).
_OPEN_LISTENER = None


class _NullSpan:
    """Shared do-nothing context for the disabled recorder."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class _Span:
    __slots__ = ("name", "args", "_t0", "_ann")

    def __init__(self, name: str, args: Dict[str, object]):
        self.name = name
        self.args = args
        self._ann = None

    def __enter__(self):
        lis = _OPEN_LISTENER
        if lis is not None:
            lis(self.name, self.args)
        ann = _ANNOTATION
        if ann is not None and _ENABLED:
            # bridge: while this host span is open, the XLA profiler (when
            # capturing) tags device activity with the same name
            self._ann = ann(self.name)
            self._ann.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        end = time.perf_counter()
        if self._ann is not None:
            self._ann.__exit__(*exc)
        add_event(self.name, self._t0, end - self._t0, **self.args)
        return False


def span(name: str, **args):
    """Context manager timing a host region. Free when disabled (a real
    span is still constructed — without buffering — when only the crash-
    forensics open listener is set, so span opens reach the flight ring
    on every rank)."""
    if not _ENABLED and _OPEN_LISTENER is None:
        return _NULL
    return _Span(name, args)


def set_open_listener(fn) -> None:
    """Arm the span-open tap (one per process; the trainer points it at
    its :class:`~tpu_dist.obs.flight.FlightRecorder`). ``fn(name, args)``
    is called at every span open, enabled or not."""
    global _OPEN_LISTENER
    _OPEN_LISTENER = fn


def clear_open_listener() -> None:
    global _OPEN_LISTENER
    _OPEN_LISTENER = None


def add_event(name: str, t_start: float, duration: float, **args) -> None:
    """Record an already-timed region (``t_start`` from
    ``time.perf_counter()``). Lets call sites that measure phases anyway
    (the trainer's step-phase split) emit spans without double-timing."""
    global _DROPPED
    if not _ENABLED:
        return
    evt = {
        "name": name,
        "ph": "X",
        "ts": round((t_start - _T0) * 1e6, 1),  # Chrome traces are in us
        "dur": round(duration * 1e6, 1),
        "pid": _PID,
        "tid": threading.get_ident() & 0x7FFFFFFF,
    }
    if args:
        evt["args"] = args
    with _LOCK:
        if len(_EVENTS) >= MAX_EVENTS:
            _DROPPED += 1
            return
        _EVENTS.append(evt)


def enable(fresh: bool = True) -> None:
    """Arm the recorder (fresh buffer, clock re-zeroed). Rank-agnostic:
    every process MAY record; the trainer only enables (and exports) on
    rank 0, keeping the rank-0 output discipline.

    ``fresh=False`` re-arms WITHOUT clearing the buffer or moving the
    clock origin — for tooling (the TD106 audit) that must not destroy a
    live recorder's undrained events or shift later timestamps."""
    global _ENABLED, _DROPPED, _T0, _PID, _ANNOTATION
    if fresh:
        with _LOCK:
            _EVENTS.clear()
            _DROPPED = 0
        _T0 = time.perf_counter()
    try:  # resolve the bridge + process id once, not per span
        import jax  # noqa: PLC0415

        _ANNOTATION = jax.profiler.TraceAnnotation
        _PID = jax.process_index()
    except Exception:  # jax absent/uninitialized: host-only tracing still works
        _ANNOTATION = None
        _PID = 0
    _ENABLED = True


def disable() -> None:
    global _ENABLED
    _ENABLED = False


def enabled() -> bool:
    return _ENABLED


def events() -> List[dict]:
    """Copy of the buffered events (oldest first)."""
    with _LOCK:
        return list(_EVENTS)


def dropped() -> int:
    with _LOCK:
        return _DROPPED


def drain() -> List[dict]:
    """Return AND clear the buffer — the trainer calls this at epoch ends
    to move spans into the JSONL history incrementally (bounded memory)."""
    with _LOCK:
        out = list(_EVENTS)
        _EVENTS.clear()
        return out


def to_chrome_trace(extra_events: Optional[List[dict]] = None) -> dict:
    """The Perfetto/chrome://tracing JSON object for the buffered (plus any
    caller-supplied) events."""
    evts = events()
    if extra_events:
        evts = extra_events + evts
    out = {"traceEvents": evts, "displayTimeUnit": "ms"}
    d = dropped()
    if d:
        out["metadata"] = {"tpu_dist_dropped_events": d}
    return out


def export_chrome_trace(path: str, extra_events: Optional[List[dict]] = None) -> str:
    """Write the Chrome trace JSON to ``path``; returns the path. Caller
    owns the rank-0 guard (the trainer exports on the primary only)."""
    # tpu-dist: ignore[TD002] — the trainer calls this under its rank-0
    # telemetry guard; standalone users own their own process discipline
    with open(path, "w") as f:
        json.dump(to_chrome_trace(extra_events), f)
    return path
