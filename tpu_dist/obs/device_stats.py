"""In-step training-health scalars — the device half of ``--device_metrics``.

:func:`compute_device_stats` runs INSIDE the traced train step
(``train/step.py``), on the POST-reduce gradients: after the data-parallel
``pmean`` (or the quantized two-stage reduce) the gradient tree is
replica-identical, the params are replicated, and every statistic below is
plain local arithmetic — **zero extra collectives** on the pure-DP path,
and the resulting scalars ride the metrics dict the trainer already
fetches with its single per-step ``jax.device_get``. The jaxpr-audit rule
**TD107** pins both halves of that contract: flag off ⇒ byte-identical
jaxpr, flag on ⇒ collective/transfer counts unchanged.

The four scalars answer the "is this run healthy?" questions the loss
curve alone cannot (MLPerf-style pod-scaling practice):

* ``grad_norm`` — global L2 norm of the reduced (post-clip) gradient: the
  divergence leading indicator; feeds the rolling-window explosion
  detector (``obs/anomaly.py``).
* ``param_norm`` — global L2 norm of the parameters: slow drift context
  for the two ratios.
* ``update_ratio`` — ‖Δparams‖/‖params‖ for this step (the applied
  update, so LR schedule, clipping, and weight decay are all reflected):
  healthy training sits around 1e-3; ~1 means the step is rewriting the
  network, ~1e-7 means nothing is learning.
* ``nonfinite_grads`` — number of gradient LEAVES containing any
  non-finite element: localizes a NaN to a parameter group one step
  before the loss itself goes NaN (composes with the trainer's NaN
  guard, which still owns the raise).

Scoped to the replicated-param paths (plain DP/SP, any
``grad_compression``): under ZeRO-1/FSDP/TP/EP/PP the reduced gradient
exists only as shards and the global norms would need the extra
collectives TD107 forbids — ``make_train_step`` refuses the combination.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _sq_sum(tree) -> jnp.ndarray:
    """f32 sum of squares over every leaf of ``tree`` (0.0 for an empty
    tree, so degenerate param trees stay well-defined)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    return sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)


def compute_device_stats(grads, params, new_params, *, eps: float = 1e-12) -> dict:
    """The ``--device_metrics`` scalar dict (see module docstring).

    ``grads`` must be the POST-reduce (and post-clip — the stats describe
    what was applied) gradient tree; ``params``/``new_params`` the
    parameter tree before/after the optimizer update. Every output is an
    f32 scalar, replica-identical by construction on the replicated-param
    paths."""
    param_norm = jnp.sqrt(_sq_sum(params))
    update_sq = _sq_sum(
        jax.tree_util.tree_map(
            lambda n, p: n.astype(jnp.float32) - p.astype(jnp.float32),
            new_params,
            params,
        )
    )
    nonfinite = sum(
        jnp.any(~jnp.isfinite(g)).astype(jnp.float32)
        for g in jax.tree_util.tree_leaves(grads)
    )
    return {
        "grad_norm": jnp.sqrt(_sq_sum(grads)),
        "param_norm": param_norm,
        "update_ratio": jnp.sqrt(update_sq) / jnp.maximum(param_norm, eps),
        "nonfinite_grads": nonfinite,
    }
