// Native input pipeline: fused gather + pad + random-crop + normalize.
//
// TPU-native replacement for the role torchvision's C extensions play in the
// reference input path (utils/dataset.py:5-9 — RandomCrop(32, padding=4) +
// ToTensor + Normalize, applied per-sample in DataLoader worker processes).
// Here the whole batch transform is one fused, multi-threaded pass over
// uint8 NHWC source images producing the normalized f32 batch the device
// consumes: one read of the source bytes, one write of the output, no
// intermediate arrays, no worker processes.
//
// Determinism: crop offsets come from a per-(seed, batch_index) splitmix64,
// so a given (seed, epoch) reproduces exactly — the per-rank seeding
// semantics of the reference's init_seeds (distributed_mp.py:29-39).
//
// Build: `make -C tpu_dist/csrc` (g++ -O3 -shared -fPIC). Loaded via ctypes
// by tpu_dist/data/native.py; absent .so falls back to the numpy path.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// splitmix64: tiny, high-quality, stateless — one value per (seed, idx).
inline uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ull;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ull;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBull;
  return x ^ (x >> 31);
}

struct CropJob {
  const uint8_t* images;  // [N_src, H, W, C] uint8
  const int64_t* indices; // [n] gather indices into images
  float* out;             // [n, H, W, C] f32
  int64_t h, w, c;
  int64_t pad;
  uint64_t seed;
  const float* mean;      // [C] in 0..1 scale
  const float* stddev;    // [C]
  bool train;             // train: random crop; eval: identity window
};

void process_range(const CropJob& job, int64_t begin, int64_t end) {
  const int64_t h = job.h, w = job.w, c = job.c, pad = job.pad;
  const int64_t img_sz = h * w * c;
  // Precompute 1/255/std and -mean/std so the inner loop is one fma.
  std::vector<float> scale(c), shift(c);
  for (int64_t ch = 0; ch < c; ++ch) {
    scale[ch] = 1.0f / (255.0f * job.stddev[ch]);
    shift[ch] = -job.mean[ch] / job.stddev[ch];
  }
  for (int64_t i = begin; i < end; ++i) {
    const uint8_t* src = job.images + job.indices[i] * img_sz;
    float* dst = job.out + i * img_sz;
    int64_t dy = 0, dx = 0;
    if (job.train && pad > 0) {
      uint64_t r = splitmix64(job.seed * 0x100000001B3ull + (uint64_t)i);
      dy = (int64_t)(r % (uint64_t)(2 * pad + 1)) - pad;   // offset in [-pad, pad]
      dx = (int64_t)((r >> 32) % (uint64_t)(2 * pad + 1)) - pad;
    }
    for (int64_t y = 0; y < h; ++y) {
      const int64_t sy = y + dy;
      if (sy < 0 || sy >= h) {  // zero padding rows: out = (0 - mean)/std
        for (int64_t x = 0; x < w; ++x)
          for (int64_t ch = 0; ch < c; ++ch)
            dst[(y * w + x) * c + ch] = shift[ch];
        continue;
      }
      for (int64_t x = 0; x < w; ++x) {
        const int64_t sx = x + dx;
        if (sx < 0 || sx >= w) {
          for (int64_t ch = 0; ch < c; ++ch)
            dst[(y * w + x) * c + ch] = shift[ch];
        } else {
          const uint8_t* px = src + (sy * w + sx) * c;
          for (int64_t ch = 0; ch < c; ++ch)
            dst[(y * w + x) * c + ch] = (float)px[ch] * scale[ch] + shift[ch];
        }
      }
    }
  }
}

}  // namespace

extern "C" {

// Returns 0 on success. `train` != 0 applies the random crop.
int tpu_dist_augment_batch(
    const uint8_t* images, const int64_t* indices, float* out,
    int64_t n, int64_t h, int64_t w, int64_t c,
    int64_t pad, uint64_t seed, const float* mean, const float* stddev,
    int train, int n_threads) {
  if (!images || !indices || !out || n < 0) return 1;
  CropJob job{images, indices, out, h, w, c, pad, seed, mean, stddev, train != 0};
  int hw = (int)std::thread::hardware_concurrency();
  int nt = n_threads > 0 ? n_threads : (hw > 0 ? hw : 4);
  if (nt > n) nt = (int)(n > 0 ? n : 1);
  if (nt <= 1) {
    process_range(job, 0, n);
    return 0;
  }
  std::vector<std::thread> threads;
  threads.reserve(nt);
  const int64_t chunk = (n + nt - 1) / nt;
  for (int t = 0; t < nt; ++t) {
    const int64_t b = t * chunk;
    const int64_t e = b + chunk < n ? b + chunk : n;
    if (b >= e) break;
    threads.emplace_back([&, b, e] { process_range(job, b, e); });
  }
  for (auto& th : threads) th.join();
  return 0;
}

int tpu_dist_pipeline_abi_version() { return 1; }

}  // extern "C"
