"""Collective primitives over the mesh — the NCCL-replacement layer (N1).

The reference reaches native collectives at four call sites (SURVEY §2.2):
``all_reduce(SUM)`` inside ``reduce_mean`` (``utils/util.py:5-9``),
``barrier()`` (``distributed.py:95``, ``utils/validation.py:30``), DDP's
bucketed gradient allreduce (``distributed.py:60``) and SyncBN's statistics
allreduce (``distributed.py:59``). On TPU all four become XLA collectives
(``lax.pmean``/``lax.psum``/``lax.all_gather``) that lower onto ICI within a
slice and DCN across slices; inside one compiled step they are ordered by
XLA's dataflow, so the reference's defensive per-step ``barrier()`` has no
equivalent cost here.

Functions named ``*_mean``/``*_sum``/``all_gather`` are *traced* collectives:
call them inside a ``shard_map``-ed function with the mesh axis in scope.
``host_*`` helpers are eager, for host-side coordination between compiled
steps (multi-host bootstrap checks, checkpoint gating).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.comm.compat import shard_map


def reduce_mean(x, axis_name: str = mesh_lib.DATA_AXIS):
    """Cross-replica mean — drop-in for the reference's ``reduce_mean``
    (``utils/util.py:5-9``: clone → all_reduce(SUM) → /nprocs), fused into
    the surrounding computation by XLA instead of a separate NCCL launch."""
    return lax.pmean(x, axis_name)


def reduce_sum(x, axis_name: str = mesh_lib.DATA_AXIS):
    """Cross-replica sum (``dist.all_reduce(op=SUM)``)."""
    return lax.psum(x, axis_name)


def all_gather(x, axis_name: str = mesh_lib.DATA_AXIS, axis: int = 0, tiled: bool = True):
    """Gather shards from every replica (``dist.all_gather``)."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def broadcast_from(x, axis_name: str = mesh_lib.DATA_AXIS, src: int = 0):
    """Broadcast ``src``'s value to every replica — the DDP init-time
    parameter broadcast (``distributed.py:60`` wrap semantics)."""
    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == src, x, jnp.zeros_like(x))
    return lax.psum(masked, axis_name)


def barrier(mesh: Optional[Mesh] = None) -> None:
    """Host-level fence across the whole mesh.

    The reference calls ``dist.barrier()`` before every metric reduction
    (``distributed.py:95``, ``utils/validation.py:30``); under XLA that
    ordering is implied by dataflow, so this exists only for host-side
    coordination (e.g. "everyone finished the epoch before rank 0 writes a
    checkpoint"). Implemented as a tiny device psum that every process must
    join, then a blocking readback.
    """
    m = mesh if mesh is not None else mesh_lib.data_parallel_mesh()
    jax.block_until_ready(_fence_for(m)(jnp.zeros((), jnp.int32)))


@functools.lru_cache(maxsize=None)
def _fence_for(m: Mesh):
    return jax.jit(
        shard_map(
            lambda x: lax.psum(x + 1, mesh_lib.DATA_AXIS),
            mesh=m,
            in_specs=P(),
            out_specs=P(),
        )
    )


@functools.lru_cache(maxsize=None)
def _pmean_for(m: Mesh):
    return jax.jit(
        shard_map(
            lambda v: lax.pmean(v, mesh_lib.DATA_AXIS),
            mesh=m,
            in_specs=P(),
            out_specs=P(),
        )
    )


def host_allreduce_mean(x, mesh: Optional[Mesh] = None):
    """Eager cross-replica mean of a host value (returns numpy scalar/array).

    For occasional host-side aggregation outside the compiled step — e.g.
    averaging epoch wall-times. Not for the hot loop.
    """
    m = mesh if mesh is not None else mesh_lib.data_parallel_mesh()
    return jax.device_get(_pmean_for(m)(jnp.asarray(x)))
