"""Bounded device discovery — the anti-hang guard every TPU entry point
shares.

A wedged axon tunnel makes ``jax.devices()`` (PJRT client construction)
hang forever; a broken plugin registration makes it raise within seconds.
The two need different messages and different handling, and a plain
``thread.join(timeout)`` conflates them (an empty result list looks like a
timeout either way, with the real traceback lost to the daemon thread's
excepthook). This helper distinguishes the cases once, for ``bench.py``
and ``__graft_entry__`` both.
"""

from __future__ import annotations

import threading


def bounded_device_discovery(timeout_s: float):
    """``jax.devices()`` with a hang bound.

    Returns the device list on success. Re-raises the probe's OWN
    exception when discovery failed fast (plugin/registration errors keep
    their traceback). Raises ``TimeoutError`` when discovery is still
    blocked after ``timeout_s`` (the wedged-tunnel signature) — the probe
    thread is a daemon and dies with the process; callers that keep the
    process alive afterwards must release any machine-wide TPU lock they
    hold, since the hung probe could still complete the tunnel claim
    later.
    """
    result: list = []
    error: list = []

    def probe():
        try:
            import jax  # noqa: PLC0415

            result.append(jax.devices())
        except BaseException as e:  # surfaced on the caller's thread
            error.append(e)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if result:
        return result[0]
    if error:
        raise error[0]
    raise TimeoutError(
        f"device backend failed to initialize within {timeout_s:.0f}s "
        "(TPU tunnel unreachable?)"
    )
