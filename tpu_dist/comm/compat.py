"""Version adapters for JAX APIs that moved between releases.

This module is the ONE place allowed to touch version-fragile JAX import
paths (analysis rule TD004 enforces it): everything else in ``tpu_dist``
imports ``shard_map`` from here. The API has lived in three homes —
``jax.experimental.shard_map`` (0.4.x), ``jax.shard_map`` (0.5+), with the
replication-check kwarg renamed ``check_rep`` → ``check_vma`` along the way.
Call sites use the NEWEST spelling (``check_vma=``); the wrapper translates
down for older installs, so upgrading JAX never requires touching callers.
"""

from __future__ import annotations

import functools
import inspect

try:  # JAX >= 0.5: promoted to the top-level namespace
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]
except ImportError:  # JAX 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = inspect.signature(_shard_map).parameters
_HAS_CHECK_VMA = "check_vma" in _PARAMS
_HAS_CHECK_REP = "check_rep" in _PARAMS


@functools.wraps(_shard_map)
def shard_map(*args, **kwargs):
    """``jax.shard_map`` with the modern keyword surface on any JAX.

    Accepts ``check_vma=`` (and legacy ``check_rep=``) and forwards
    whichever spelling the installed JAX understands; drops the kwarg
    entirely if some future release removes both.
    """
    if "check_vma" in kwargs and "check_rep" in kwargs:
        raise TypeError("pass check_vma or check_rep, not both")
    check = kwargs.pop("check_vma", kwargs.pop("check_rep", None))
    if check is not None:
        if _HAS_CHECK_VMA:
            kwargs["check_vma"] = check
        elif _HAS_CHECK_REP:
            kwargs["check_rep"] = check
    return _shard_map(*args, **kwargs)


def axis_size(axis_name):
    """``lax.axis_size`` on any JAX.

    The named-axis size query only gained a public spelling in newer JAX;
    on older installs ``psum(1, axis)`` computes the same value (folded to
    a trace-time constant, no collective in the jaxpr)."""
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


__all__ = ["shard_map", "axis_size"]
