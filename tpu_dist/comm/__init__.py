from tpu_dist.comm.mesh import (  # noqa: F401
    data_parallel_mesh,
    device_mesh,
    initialize_distributed,
    local_device_count,
    process_count,
    process_index,
)
from tpu_dist.comm.collectives import (  # noqa: F401
    all_gather,
    barrier,
    broadcast_from,
    host_allreduce_mean,
    reduce_mean,
    reduce_sum,
)
