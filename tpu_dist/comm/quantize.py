"""Int8 wire-format quantization for gradient collectives.

The math layer under the ``grad_compression='int8'/'int8_ef'`` contract in
``tpu_dist.train.step``: per-chunk scaled symmetric int8 quantization with
optional stochastic rounding (EQuARX, arXiv:2506.17615 — quantized
allreduce inside XLA recovers most of the gradient bandwidth at negligible
quality cost; torch's ``PowerSGD``/``quantization_hooks`` family fills the
same role as DDP communication hooks).

Layout convention: the collective choreography in ``train/step.py`` works
on FLAT row-major vectors (``ravel_pytree`` of the grad tree, padded to a
multiple of the axis size), reshaped to ``(n, m)`` rows — one row per
destination shard. Quantization here is per-*chunk*: each row is cut into
``chunk``-element blocks, every block gets its own f32 scale
(``max|x| / 127``), so one outlier poisons at most ``chunk`` neighbours
instead of the whole tensor. The scale sideband is one f32 per ``chunk``
int8 elements — a factor ``chunk`` fewer elements, ``chunk/4`` fewer
BYTES (~1.6%% overhead at the default 256) — and travels as its own
(tiny) collective next to the payload.

Stochastic rounding (``key is not None``): ``q = floor(x/s + u)``,
``u ~ U[0,1)`` — unbiased per element (``E[q·s] = x``), which is what lets
plain ``int8`` train without error feedback at all: quantization noise
averages out across replicas and steps instead of accumulating as a bias.
``int8_ef`` additionally carries the *realized* per-replica error forward
(see ``train/step.py``), compensating even the variance.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Elements per quantization scale. 256 keeps the f32-scale sideband at
# 4 B per 256 B of int8 payload (≈1.6%) while still isolating outliers.
DEFAULT_CHUNK = 256

_QMAX = 127.0  # symmetric int8: [-127, 127] (-128 unused, keeps |q| ≤ 127)


def padded_len(length: int, n: int) -> int:
    """Smallest multiple of ``n`` that is >= ``length`` (flat-vector pad so
    every replica owns an equal shard). Matches the ZeRO-1 flat layout
    (``step.py::_sharded_update``: ``chunk * n``)."""
    return -(-int(length) // int(n)) * int(n)


def _chunked(x: jnp.ndarray, chunk: int):
    """Reshape ``(..., m)`` to ``(..., k, chunk)`` with zero tail-padding;
    returns ``(blocks, k, m)``. The padding is local arithmetic only — the
    wire carries the unpadded ``m`` elements (callers slice back)."""
    m = x.shape[-1]
    k = -(-m // chunk)
    pad = k * chunk - m
    if pad:
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
    return x.reshape(x.shape[:-1] + (k, chunk)), k, m


def quantize_int8(x: jnp.ndarray, chunk: int = DEFAULT_CHUNK, key=None):
    """Quantize ``(..., m)`` f32 to ``(int8 (..., m), f32 scales (..., k))``.

    ``key=None``: round-to-nearest (deterministic). With a key: stochastic
    rounding, unbiased per element. All-zero chunks quantize to zeros with
    scale 0 (dequantize maps them back to exact zeros).
    """
    blocks, k, m = _chunked(x.astype(jnp.float32), chunk)
    scales = jnp.max(jnp.abs(blocks), axis=-1) / _QMAX  # (..., k)
    inv = jnp.where(scales > 0.0, 1.0 / jnp.where(scales > 0.0, scales, 1.0), 0.0)
    v = blocks * inv[..., None]  # in [-127, 127]
    if key is None:
        q = jnp.round(v)
    else:
        # floor(v + u) with u ~ U[0,1): E[q] = v exactly
        u = jax.random.uniform(key, v.shape, jnp.float32)
        q = jnp.floor(v + u)
    q = jnp.clip(q, -_QMAX, _QMAX).astype(jnp.int8)
    return q.reshape(q.shape[:-2] + (k * chunk,))[..., :m], scales


def dequantize_int8(q: jnp.ndarray, scales: jnp.ndarray, chunk: int = DEFAULT_CHUNK):
    """Inverse of :func:`quantize_int8`: ``(..., m) int8 + (..., k) f32 →
    (..., m) f32``. Tolerates a ragged tail (``m`` need not divide by
    ``chunk``)."""
    m = q.shape[-1]
    k = scales.shape[-1]
    per_elem = jnp.repeat(scales, chunk, axis=-1)[..., : k * chunk][..., :m]
    return q.astype(jnp.float32) * per_elem


__all__ = [
    "DEFAULT_CHUNK",
    "padded_len",
    "quantize_int8",
    "dequantize_int8",
]
