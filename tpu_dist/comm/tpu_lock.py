"""One-TPU-process discipline, enforced in code.

The single tunneled TPU chip in this environment wedges irrecoverably when
two PJRT clients touch it concurrently (BENCH_NOTES.md rounds 1 and 2: both
driver bench artifacts were lost this way). The rule used to live in prose;
this module puts it where it can't be forgotten: a machine-wide lockfile
acquired by every TPU-touching entrypoint (``bench.py``, a TPU-backend
:class:`~tpu_dist.train.trainer.Trainer`, ``__graft_entry__`` probes)
*before* the JAX backend initializes — it is the PJRT client construction
itself that claims the tunnel, so checking after ``jax.devices()`` would be
too late.

Mechanism: ``fcntl.flock(LOCK_EX | LOCK_NB)`` on a well-known path. The
kernel releases the lock when the holder exits *for any reason* (including
SIGKILL), so a crashed run never blocks the next one — no stale-PID
heuristics, and none of the check-then-unlink races a PID-file scheme has.
The file's pid/owner content exists only to produce a helpful refusal
message; mutual exclusion is the kernel lock, not the file contents. The
lock rides the open file description, which ``fork()`` shares — a forked
child exiting does not drop the parent's claim.

A second TPU-touching process refuses to start with a clear message naming
the live holder instead of silently wedging the tunnel for the rest of the
round.

No reference counterpart: the reference's GPUs are process-exclusive by
CUDA context anyway; the closest analogue is ``torch.distributed``'s
rendezvous refusing a second world on one port.
"""

from __future__ import annotations

import atexit
import fcntl
import os
import sys
from typing import Optional

DEFAULT_LOCK_PATH = "/tmp/tpu_dist.tpu.lock"

# Process-local state, keyed by lock path: acquiring a path this process
# already holds is a no-op (Trainer inside bench.py, probes inside a
# Trainer script, ...). flock would otherwise deny our own second open
# file description on the same file.
_held: dict = {}


class TPULockError(RuntimeError):
    """Another live process holds the TPU. Message names its PID/owner."""


def tpu_possible() -> bool:
    """Could initializing JAX in this process touch the TPU tunnel?

    Reads the platform selection WITHOUT initializing any backend: the env
    var and ``jax.config.jax_platforms`` (which the test conftest sets to
    ``cpu`` — importing jax does not construct PJRT clients). Only an
    unambiguous CPU-only selection returns False; unset/ambiguous selections
    are treated as TPU-possible, which errs on the safe side.
    """
    selections = []
    env = os.environ.get("JAX_PLATFORMS", "").strip().lower()
    if env:
        selections.append(env)
    try:
        import jax

        cfg = jax.config.jax_platforms  # type: ignore[attr-defined]
        if cfg:
            selections.append(str(cfg).strip().lower())
    # tpu-dist: ignore[TD006] — platform probe: an unreadable jax config
    # falls through to the conservative "assume TPU" default
    except Exception:  # pragma: no cover - jax always importable here
        pass
    if not selections:
        return True
    # the *effective* selection is the config value when set, else env;
    # jax.config reflects the env var at import, so the last entry wins
    effective = selections[-1]
    plats = [p.strip() for p in effective.split(",") if p.strip()]
    return not plats or any(p != "cpu" for p in plats)


class TPULock:
    """Handle for a held lock; release via :meth:`release` or process exit
    (the kernel drops a dead holder's flock automatically)."""

    def __init__(self, path: str, owner: str, fd: int):
        self.path = path
        self.owner = owner
        self.pid = os.getpid()
        self._fd = fd
        self._released = False
        # Reentrancy refcount (ADVICE r3, medium): acquire() hands the SAME
        # handle to nested claimants (bench.py -> Trainer -> probe). Each
        # balanced release() only decrements; the flock drops at zero. A
        # Trainer whose construction fails therefore gives back only ITS
        # claim — the outer holder keeps the machine-wide lock.
        self._refs = 1

    def release(self, force: bool = False) -> None:
        # fork guard: a child inheriting this handle via atexit must not
        # act on the parent's lock (closing the child's fd copy would not
        # drop the flock anyway — it rides the shared open file
        # description — but keep the state bookkeeping parent-only)
        if self._released or os.getpid() != self.pid:
            return
        if not force:
            self._refs -= 1
            if self._refs > 0:
                return
        self._released = True
        if _held.get(self.path) is self:
            del _held[self.path]
        try:
            fcntl.flock(self._fd, fcntl.LOCK_UN)
        except OSError:  # tpu-dist: ignore[TD006] — release is best-effort:
            pass  # a dead fd means the kernel already dropped the flock
        try:
            os.close(self._fd)
        except OSError:  # tpu-dist: ignore[TD006] — double-close tolerated
            pass  # on teardown paths (atexit + explicit release)
        # The file deliberately stays on disk: unlinking a flock'd path
        # races with a contender that already opened the old inode.
        # "File exists" does not mean "held" — the flock does.

    def __enter__(self) -> "TPULock":
        return self

    def __exit__(self, *exc) -> None:
        self.release()


def _read_holder(fd: int) -> tuple:
    """Best-effort pid/owner of the current flock holder, for messages.
    Content is advisory, the flock is the truth; a fresh winner may not
    have written its pid yet, so re-read once after a beat to avoid naming
    the PREVIOUS (dead) holder."""
    import time as _time

    try:
        data = os.pread(fd, 256, 0).decode(errors="replace").splitlines()
        _time.sleep(0.05)
        data2 = os.pread(fd, 256, 0).decode(errors="replace").splitlines()
        if data2:
            data = data2
    except OSError:
        data = []
    pid = data[0] if data else "?"
    owner = data[1] if len(data) > 1 else "?"
    return pid, owner


def acquire(
    owner: str = "tpu_dist",
    path: Optional[str] = None,
    force_cpu_ok: bool = True,
    wait_s: float = 0.0,
) -> Optional[TPULock]:
    """Acquire the machine-wide TPU lock, or raise :class:`TPULockError`.

    Returns ``None`` (no-op) when this process is unambiguously CPU-only
    and ``force_cpu_ok`` — CPU test runs must not contend. Re-acquiring the
    same path in a process that already holds it returns the existing
    handle with its refcount bumped — each claimant must :meth:`release
    <TPULock.release>` exactly once; the flock drops when the last one does.

    ``wait_s > 0``: on contention, keep retrying (2 s poll) until the
    holder exits or the deadline passes, instead of refusing immediately.
    This is how the driver's end-of-round ``bench.py`` survives landing in
    the middle of a bounded probe (round 3: rc=4 because a watcher probe
    held the lock at that instant) — the probe exits within its own
    timeout, the waiter then wins the flock.
    """
    if path is None:
        path = DEFAULT_LOCK_PATH  # resolved at call time (testable)
    # normalize: two spellings/symlinks of one file must hit the same
    # reentrancy slot, or flock would refuse our own second descriptor
    path = os.path.realpath(path)
    if force_cpu_ok and not tpu_possible():
        return None
    existing = _held.get(path)
    if existing is not None and not existing._released:
        existing._refs += 1
        return existing

    try:
        fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o644)
    except OSError as e:
        # e.g. EACCES: lockfile owned by another user — still a clean
        # refusal, not a traceback
        raise TPULockError(
            f"cannot open TPU lock {path}: {e}. If another user's run "
            "created it, coordinate or choose a different lock path."
        )
    import errno as _errno
    import time as _time

    deadline = _time.monotonic() + max(0.0, wait_s)
    announced = False
    while True:
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            break
        except OSError as e:
            contention = e.errno in (_errno.EWOULDBLOCK, _errno.EAGAIN)
            # EACCES from flock is ambiguous (ADVICE r3): some kernels/
            # filesystems use it for contention, others for a locking-
            # infrastructure or permissions problem. Treat it as possibly
            # held, but say both in the message.
            maybe_held = contention or e.errno == _errno.EACCES
            if not maybe_held:  # ENOLCK etc.: infrastructure, not a holder
                os.close(fd)
                raise TPULockError(f"flock on TPU lock {path} failed: {e}")
            remaining = deadline - _time.monotonic()
            if remaining > 0:
                if not announced:
                    pid, own = _read_holder(fd)
                    # tpu-dist: ignore[TD002,TD007] — lock-contention
                    # diagnostic: each contending process must report its
                    # own wait state (deliberately NOT the rank-0 layer)
                    print(
                        f"{owner}: TPU lock {path} held by pid {pid} "
                        f"(owner: {own}); waiting up to {wait_s:.0f}s for "
                        "it to finish...",
                        file=sys.stderr,
                        flush=True,
                    )
                    announced = True
                _time.sleep(min(2.0, remaining))
                continue
            pid, own = _read_holder(fd)
            os.close(fd)
            waited = f" (waited {wait_s:.0f}s)" if wait_s > 0 else ""
            if contention:
                raise TPULockError(
                    f"TPU is held by live process {pid} "
                    f"(owner: {own}, lock: {path}){waited}. Refusing to "
                    "start a second TPU client — concurrent clients wedge "
                    "the tunnel for the rest of the session. Wait for it "
                    "to finish, or kill it and retry."
                )
            raise TPULockError(
                f"flock on TPU lock {path} failed with EACCES{waited}. "
                f"Either a live process holds it (last recorded holder: "
                f"pid {pid}, owner {own}) or this filesystem/permission "
                "setup cannot take the lock — check for a holder first; "
                "if none exists, check lockfile ownership/permissions or "
                "choose a different lock path."
            )
    # we hold it: record pid/owner for contenders' error messages
    os.ftruncate(fd, 0)
    os.pwrite(fd, f"{os.getpid()}\n{owner}\n".encode(), 0)
    lock = TPULock(path, owner, fd)
    _held[path] = lock
    # exit safety net, not a balanced release: drop the flock no matter
    # how many claimants never released (the kernel would anyway)
    atexit.register(lock.release, force=True)
    return lock


def guard_or_exit(
    owner: str, exit_code: int = 4, wait_s: float = 0.0
) -> Optional[TPULock]:
    """CLI-entrypoint wrapper: acquire or print the holder message to stderr
    and exit with ``exit_code`` (distinct from bench's 3 = tunnel timeout)."""
    try:
        return acquire(owner, wait_s=wait_s)
    except TPULockError as e:
        # tpu-dist: ignore[TD002,TD007] — CLI-entrypoint failure path: the
        # holder message must reach the operator from whichever process hit
        # it (deliberately NOT the rank-0 layer)
        print(f"{owner}: {e}", file=sys.stderr, flush=True)
        raise SystemExit(exit_code)
