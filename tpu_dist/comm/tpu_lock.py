"""One-TPU-process discipline, enforced in code.

The single tunneled TPU chip in this environment wedges irrecoverably when
two PJRT clients touch it concurrently (BENCH_NOTES.md rounds 1 and 2: both
driver bench artifacts were lost this way). The rule used to live in prose;
this module puts it where it can't be forgotten: a machine-wide lockfile
acquired by every TPU-touching entrypoint (``bench.py``, a TPU-backend
:class:`~tpu_dist.train.trainer.Trainer`, ``__graft_entry__`` probes)
*before* the JAX backend initializes — it is the PJRT client construction
itself that claims the tunnel, so checking after ``jax.devices()`` would be
too late.

Mechanism: ``fcntl.flock(LOCK_EX | LOCK_NB)`` on a well-known path. The
kernel releases the lock when the holder exits *for any reason* (including
SIGKILL), so a crashed run never blocks the next one — no stale-PID
heuristics, and none of the check-then-unlink races a PID-file scheme has.
The file's pid/owner content exists only to produce a helpful refusal
message; mutual exclusion is the kernel lock, not the file contents. The
lock rides the open file description, which ``fork()`` shares — a forked
child exiting does not drop the parent's claim.

A second TPU-touching process refuses to start with a clear message naming
the live holder instead of silently wedging the tunnel for the rest of the
round.

No reference counterpart: the reference's GPUs are process-exclusive by
CUDA context anyway; the closest analogue is ``torch.distributed``'s
rendezvous refusing a second world on one port.
"""

from __future__ import annotations

import atexit
import fcntl
import os
import sys
from typing import Optional

DEFAULT_LOCK_PATH = "/tmp/tpu_dist.tpu.lock"

# Process-local state, keyed by lock path: acquiring a path this process
# already holds is a no-op (Trainer inside bench.py, probes inside a
# Trainer script, ...). flock would otherwise deny our own second open
# file description on the same file.
_held: dict = {}


class TPULockError(RuntimeError):
    """Another live process holds the TPU. Message names its PID/owner."""


def tpu_possible() -> bool:
    """Could initializing JAX in this process touch the TPU tunnel?

    Reads the platform selection WITHOUT initializing any backend: the env
    var and ``jax.config.jax_platforms`` (which the test conftest sets to
    ``cpu`` — importing jax does not construct PJRT clients). Only an
    unambiguous CPU-only selection returns False; unset/ambiguous selections
    are treated as TPU-possible, which errs on the safe side.
    """
    selections = []
    env = os.environ.get("JAX_PLATFORMS", "").strip().lower()
    if env:
        selections.append(env)
    try:
        import jax

        cfg = jax.config.jax_platforms  # type: ignore[attr-defined]
        if cfg:
            selections.append(str(cfg).strip().lower())
    except Exception:  # pragma: no cover - jax always importable here
        pass
    if not selections:
        return True
    # the *effective* selection is the config value when set, else env;
    # jax.config reflects the env var at import, so the last entry wins
    effective = selections[-1]
    plats = [p.strip() for p in effective.split(",") if p.strip()]
    return not plats or any(p != "cpu" for p in plats)


class TPULock:
    """Handle for a held lock; release via :meth:`release` or process exit
    (the kernel drops a dead holder's flock automatically)."""

    def __init__(self, path: str, owner: str, fd: int):
        self.path = path
        self.owner = owner
        self.pid = os.getpid()
        self._fd = fd
        self._released = False

    def release(self) -> None:
        # fork guard: a child inheriting this handle via atexit must not
        # act on the parent's lock (closing the child's fd copy would not
        # drop the flock anyway — it rides the shared open file
        # description — but keep the state bookkeeping parent-only)
        if self._released or os.getpid() != self.pid:
            return
        self._released = True
        if _held.get(self.path) is self:
            del _held[self.path]
        try:
            fcntl.flock(self._fd, fcntl.LOCK_UN)
        except OSError:
            pass
        try:
            os.close(self._fd)
        except OSError:
            pass
        # The file deliberately stays on disk: unlinking a flock'd path
        # races with a contender that already opened the old inode.
        # "File exists" does not mean "held" — the flock does.

    def __enter__(self) -> "TPULock":
        return self

    def __exit__(self, *exc) -> None:
        self.release()


def acquire(
    owner: str = "tpu_dist",
    path: Optional[str] = None,
    force_cpu_ok: bool = True,
) -> Optional[TPULock]:
    """Acquire the machine-wide TPU lock, or raise :class:`TPULockError`.

    Returns ``None`` (no-op) when this process is unambiguously CPU-only
    and ``force_cpu_ok`` — CPU test runs must not contend. Re-acquiring the
    same path in a process that already holds it returns the existing
    handle.
    """
    if path is None:
        path = DEFAULT_LOCK_PATH  # resolved at call time (testable)
    # normalize: two spellings/symlinks of one file must hit the same
    # reentrancy slot, or flock would refuse our own second descriptor
    path = os.path.realpath(path)
    if force_cpu_ok and not tpu_possible():
        return None
    existing = _held.get(path)
    if existing is not None and not existing._released:
        return existing

    try:
        fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o644)
    except OSError as e:
        # e.g. EACCES: lockfile owned by another user — still a clean
        # refusal, not a traceback
        raise TPULockError(
            f"cannot open TPU lock {path}: {e}. If another user's run "
            "created it, coordinate or choose a different lock path."
        )
    try:
        fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
    except OSError as e:
        import errno as _errno

        held_by_other = e.errno in (_errno.EWOULDBLOCK, _errno.EAGAIN, _errno.EACCES)
        # locked by a live process: read its pid/owner for the message.
        # Best-effort — content is advisory, the flock is the truth; a
        # fresh winner may not have written its pid yet, so re-read once
        # after a beat to avoid naming the PREVIOUS (dead) holder.
        import time as _time

        try:
            data = os.pread(fd, 256, 0).decode(errors="replace").splitlines()
            _time.sleep(0.05)
            data2 = os.pread(fd, 256, 0).decode(errors="replace").splitlines()
            if data2:
                data = data2
        except OSError:
            data = []
        finally:
            os.close(fd)
        if not held_by_other:  # ENOLCK etc.: infrastructure, not a holder
            raise TPULockError(f"flock on TPU lock {path} failed: {e}")
        holder_pid = data[0] if data else "?"
        holder_owner = data[1] if len(data) > 1 else "?"
        raise TPULockError(
            f"TPU is held by live process {holder_pid} "
            f"(owner: {holder_owner}, lock: {path}). Refusing to "
            "start a second TPU client — concurrent clients wedge "
            "the tunnel for the rest of the session. Wait for it "
            "to finish, or kill it and retry."
        )
    # we hold it: record pid/owner for contenders' error messages
    os.ftruncate(fd, 0)
    os.pwrite(fd, f"{os.getpid()}\n{owner}\n".encode(), 0)
    lock = TPULock(path, owner, fd)
    _held[path] = lock
    atexit.register(lock.release)
    return lock


def guard_or_exit(owner: str, exit_code: int = 4) -> Optional[TPULock]:
    """CLI-entrypoint wrapper: acquire or print the holder message to stderr
    and exit with ``exit_code`` (distinct from bench's 3 = tunnel timeout)."""
    try:
        return acquire(owner)
    except TPULockError as e:
        print(f"{owner}: {e}", file=sys.stderr, flush=True)
        raise SystemExit(exit_code)
