"""Mesh construction, slice discovery and rank↔device mapping.

TPU-native replacement for the reference's NCCL bootstrap
(``dist.init_process_group(backend='nccl', init_method='tcp://ip:port', ...)``,
reference ``distributed.py:45-50`` and ``tutorials/0:34-54``):

* **Rendezvous** — ``jax.distributed.initialize(coordinator_address, ...)``
  replaces the TCP store: all processes block until the full slice joins,
  exactly the ``world_size`` barrier the reference documents
  (``README.md:84``).
* **Collectives fabric** — instead of NCCL rings over PCIe/NVLink, a
  :class:`jax.sharding.Mesh` lays the ``data`` axis over the slice so XLA
  lowers ``psum``/``pmean`` onto ICI (intra-slice) and DCN (across slices).
* **rank / local_rank** — ``process_index()`` is the host rank;
  device coordinates come from ``jax.devices()[i].coords`` on real TPU.

Everything here also runs on the CPU-emulated multi-device backend
(``XLA_FLAGS=--xla_force_host_platform_device_count=N``) used by the tests.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Canonical axis names. "data" is the batch axis (the only axis the reference
# exercises); the remaining names are reserved so model/sequence/expert
# parallelism can be layered on the same mesh without API changes.
DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
EXPERT_AXIS = "expert"
PIPE_AXIS = "pipe"


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host rendezvous (replaces ``dist.init_process_group``).

    On Cloud TPU the arguments are discovered from the runtime environment
    and may be omitted; off-TPU (or in heterogeneous setups) they mirror the
    reference's ``--ip/--port``/``world_size``/``rank`` flags
    (``distributed.py:45-50``). No-op when running single-process.
    """
    if num_processes is not None and num_processes <= 1 and coordinator_address is None:
        return
    if coordinator_address is None and num_processes is None:
        # Single-controller / single-host runs need no rendezvous.
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def process_index() -> int:
    """Host rank (the reference's ``rank``/``local_rank`` for logging guards)."""
    return jax.process_index()


def process_count() -> int:
    """Number of host processes (the reference's ``world_size`` / ``nprocs``)."""
    return jax.process_count()


def local_device_count() -> int:
    return jax.local_device_count()


def is_primary() -> bool:
    """True on the process allowed to print/checkpoint (rank-0 discipline,
    reference ``tutorials/2:§3`` and ``distributed.py:103``)."""
    return jax.process_index() == 0


def host_major_devices(
    devices: Optional[Sequence[jax.Device]] = None,
) -> list:
    """Global device list ordered host-major: all of process 0's devices,
    then process 1's, ... ``jax.devices()`` is sorted by device id, which on
    real multi-host TPU does NOT guarantee host grouping — this does. With
    host-major order, a row-major mesh reshape whose model axes are the
    TRAILING (fastest-varying) axes keeps each model group on one host
    whenever the group size divides the local device count, i.e. model
    collectives ride ICI, never DCN."""
    devs = list(devices) if devices is not None else jax.devices()
    return sorted(devs, key=lambda d: (d.process_index, d.id))


def device_mesh(
    axis_shapes: Sequence[int],
    axis_names: Sequence[str],
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a named mesh over the slice.

    ``axis_shapes`` multiplied together must equal the number of devices.
    Devices are laid out HOST-MAJOR (see :func:`host_major_devices`) and the
    reshape is row-major, so put model-ish axes (tp/ep/pp/sp) LAST: a model
    group of size ``w`` then spans ``w`` consecutive same-host devices
    whenever ``w`` divides the local device count — the ICI-vs-DCN split the
    multi-host design needs. Verify with :func:`model_axes_intra_host`; the
    Trainer does so and refuses layouts whose model axes would cross hosts.
    """
    devices = host_major_devices(devices)
    n = int(np.prod(axis_shapes))
    if n != len(devices):
        raise ValueError(
            f"mesh {tuple(axis_shapes)} needs {n} devices, have {len(devices)}"
        )
    dev_array = np.array(devices).reshape(tuple(axis_shapes))
    return Mesh(dev_array, tuple(axis_names))


def model_axes_intra_host(mesh: Mesh, axes: Sequence[str]) -> bool:
    """True iff every shard group along ``axes`` lives on a single host —
    i.e. the collectives over those axes never touch DCN."""
    names = list(mesh.axis_names)
    arr = mesh.devices
    model_idx = [names.index(a) for a in axes]
    other_idx = [i for i in range(arr.ndim) if i not in model_idx]
    for pos in np.ndindex(*(arr.shape[i] for i in other_idx)):
        slicer: list = [slice(None)] * arr.ndim
        for i, p in zip(other_idx, pos):
            slicer[i] = p
        group = arr[tuple(slicer)].ravel()
        if len({d.process_index for d in group}) > 1:
            return False
    return True


def data_parallel_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D mesh with every device on the ``data`` axis.

    This is the TPU equivalent of both reference engines at once:
    ``nn.DataParallel`` (``dataparallel.py:47``) because one process drives
    all local devices, and DDP (``distributed.py:60``) because gradients are
    averaged over the axis inside the compiled step.
    """
    devices = list(devices) if devices is not None else jax.devices()
    return device_mesh([len(devices)], [DATA_AXIS], devices)


def replicated(mesh: Mesh) -> NamedSharding:
    """Sharding for parameters/optimizer state: replicated on every device."""
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Sharding for a batch: leading dim split over the data axis."""
    return NamedSharding(mesh, P(axis))


def shard_batch(mesh: Mesh, batch, axis=DATA_AXIS):
    """Place a process-local numpy batch onto the mesh, sharded on ``axis``
    (a mesh axis name, or a tuple of names to split dim 0 over several axes).

    Replaces the reference's per-rank ``.cuda(local_rank, non_blocking=True)``
    H2D copies (``distributed.py:88-89``): here ONE process feeds all its
    local devices and, multi-host, the per-process shards assemble into one
    global ``jax.Array``.
    """
    sharding = NamedSharding(mesh, P(axis))
    return jax.tree_util.tree_map(
        functools.partial(_make_global, sharding), batch
    )


def _make_global(sharding: NamedSharding, x):
    x = np.asarray(x)
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    return jax.make_array_from_process_local_data(sharding, x)


def place_host_tree(mesh: Mesh, tree, specs=None):
    """Place a host (numpy) pytree onto the mesh with per-leaf partition
    specs (replicated when ``specs`` is None).

    Works single- AND multi-process: single-controller it is a plain
    ``device_put``; across processes each leaf is assembled with
    ``make_array_from_callback`` from the FULL host value (every process
    holds the whole leaf — true for params/opt state initialized from the
    same seed or restored from the same checkpoint — and materializes only
    its addressable shards). This is how TP/EP/PP-sharded state gets placed
    on a multi-host mesh, where ``device_put`` to non-addressable devices
    is not available.
    """
    if specs is None:
        specs = jax.tree_util.tree_map(lambda _: P(), tree)

    def put(x, spec):
        sharding = NamedSharding(mesh, spec)
        if jax.process_count() == 1:
            # device_put reshards device-resident leaves directly (no
            # host roundtrip)
            return jax.device_put(x, sharding)
        arr = np.asarray(x)
        return jax.make_array_from_callback(arr.shape, sharding, lambda idx: arr[idx])

    return jax.tree_util.tree_map(put, tree, specs)
