"""The trainer — TPU-native ``main_worker`` (SURVEY §1 L3).

One trainer replaces all six reference scripts: on TPU, DP and DDP collapse
into "one process per host drives all local chips, params replicated, grads
pmean-ed" (SURVEY §7 design stance), so the reference's script matrix
becomes config flags:

==============================================  =============================
reference script                                 config
==============================================  =============================
``dataparallel.py`` / ``distributed{_mp}.py``    defaults
``dataparallel_apex.py`` / ``distributed_apex``  ``bf16=True``
``distributed_gradient_accumulation.py``         ``grad_accu_steps=K``
SyncBN on/off (``distributed.py:59``)            ``sync_bn``
==============================================  =============================

Preserved reference behaviors (SURVEY §7 fidelity list): per-replica batch =
global/ N (``distributed.py:67``), epoch-seeded shuffle via ``set_epoch``
(``:81``), per-rank+epoch augmentation seeding (``distributed_mp.py:29-39``),
rank-0-only output, MultiStepLR/SGD hyperparameters, per-step metric
reduction and log line (``:104-111``), epoch wall-time print (``:113-115``),
per-epoch distributed validation. Deliberately dropped: the per-step
``dist.barrier()`` (ordering is XLA dataflow now, SURVEY §5).
"""

from __future__ import annotations

import functools
import time
from typing import Optional

import jax
import numpy as np
import jax.numpy as jnp
from tpu_dist import ckpt as ckpt_lib
from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.config import TrainConfig
from tpu_dist.data import (
    DataLoader,
    DistributedSampler,
    load_cifar10,
    load_cifar100,
    synthetic_cifar,
)
from tpu_dist.evaluation import validate
from tpu_dist.metrics import AverageMeter, rank0_print
from tpu_dist.obs.profile import StepTimer
from tpu_dist.nn import resnet18, resnet34, resnet50
from tpu_dist.obs import costmodel as costmodel_lib
from tpu_dist.obs import counters as counters_lib
from tpu_dist.obs import goodput as goodput_lib
from tpu_dist.obs import spans as spans_lib
from tpu_dist.resilience import faults, preemption
from tpu_dist.resilience.preemption import PreemptedError
from tpu_dist.train.optim import SGD, cosine_lr, multistep_lr
from tpu_dist.train.state import TrainState
from tpu_dist.train.step import make_eval_step, make_train_step

_MODELS = {"resnet18": resnet18, "resnet34": resnet34, "resnet50": resnet50}


class TrainingDivergedError(RuntimeError):
    """Raised by the NaN guard — the failure-detection subsystem the
    reference lacks entirely (SURVEY §5: no failure detection/recovery).
    Catch it and restore from ``ckpt_dir`` to implement auto-recovery."""


def _fetch_metrics(metrics) -> dict:
    """ONE device→host transfer for the whole metrics dict. A per-key
    ``float(v)`` comprehension issues one blocking D2H round-trip per
    scalar; ``jax.device_get`` fetches the tree in a single call, and the
    NaN guard / log line / history all reuse the same host copy."""
    return {k: float(v) for k, v in jax.device_get(metrics).items()}


def register_model(name: str, factory) -> None:
    """Extend the model zoo (``factory(num_classes=...) -> model`` with
    ``init``/``apply``). Lets users swap models the way the reference
    suggests swapping ``utils/model.py`` (BASELINE north star's ViT config).
    """
    _MODELS[name] = factory


def build_model(cfg: TrainConfig):
    try:
        from tpu_dist.nn.vit import vit_b16, vit_s16, vit_tiny  # noqa: PLC0415

        _MODELS.setdefault("vit_b16", vit_b16)
        _MODELS.setdefault("vit_s16", vit_s16)
        _MODELS.setdefault("vit_tiny", vit_tiny)

        from tpu_dist.nn.vit_moe import vit_moe_tiny  # noqa: PLC0415

        _MODELS.setdefault("vit_moe_tiny", vit_moe_tiny)

        from tpu_dist.nn.vit_pp import vit_pp_tiny  # noqa: PLC0415

        _MODELS.setdefault("vit_pp_tiny", vit_pp_tiny)

        from tpu_dist.nn.resnet import resnet50_imagenet  # noqa: PLC0415

        _MODELS.setdefault("resnet50_imagenet", resnet50_imagenet)
    except ImportError:
        pass
    if cfg.model not in _MODELS:
        raise ValueError(f"unknown model {cfg.model!r}; have {sorted(_MODELS)}")
    return _MODELS[cfg.model](num_classes=cfg.num_classes)


class Trainer:
    def __init__(self, cfg: TrainConfig, mesh=None):
        self.cfg = cfg
        # One-TPU-process rule (BENCH_NOTES rounds 1-2): claim the machine
        # lock BEFORE the first backend touch below; no-op on CPU configs.
        # Released on a failed construction (e.g. a config-validation raise)
        # so a caught ValueError doesn't hold the TPU for the process life.
        # acquire() refcounts reentrant claims (ADVICE r3), so this release
        # gives back only the Trainer's claim — an outer holder (bench.py,
        # __graft_entry__) keeps the machine-wide lock.
        from tpu_dist.comm import tpu_lock  # noqa: PLC0415

        self._tpu_lock = tpu_lock.acquire(owner="trainer")
        try:
            self._init_impl(cfg, mesh)
        except BaseException:
            if self._tpu_lock is not None:
                self._tpu_lock.release()
                self._tpu_lock = None
            raise

    def _init_impl(self, cfg: TrainConfig, mesh):
        # the telemetry counter registry is process-global and a "run" is
        # one Trainer's lifetime (run_id is stamped per construction, so
        # repeated fit() calls on one instance share it): start the
        # registry fresh here so a second Trainer in the same process
        # (tests, sweep drivers) doesn't report the previous run's totals
        # under its fresh run_id — and so the restore ladder's counters
        # (which run during THIS construction, below) attribute to this run
        counters_lib.reset()
        # the goodput ledger's wall-clock book opens NOW: construction —
        # the resume restore ladder included — is part of the run it
        # accounts, and every second from here to fit()'s exit lands in
        # exactly one bucket (obs/goodput.py)
        self._goodput = goodput_lib.GoodputLedger()
        # process-lifetime XLA compile-time accounting (compile.seconds):
        # idempotent, host-side, feeds the registry just reset above AND
        # the ledger's compile bucket (per-epoch counter deltas)
        costmodel_lib.install_compile_listener()
        if cfg.compile_cache_dir:
            # persistent XLA compile cache (VERDICT r1 #8): a rerun of the
            # same config loads compiled programs instead of recompiling
            jax.config.update("jax_compilation_cache_dir", cfg.compile_cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        mesh_lib.initialize_distributed(
            coordinator_address=cfg.coordinator_address if cfg.num_processes else None,
            num_processes=cfg.num_processes,
            process_id=cfg.process_id,
        )
        # --auto_shard: run the static sharding planner BEFORE this config
        # is consumed — 'apply' rewrites cfg to the chosen plan's family,
        # and the rewritten config then flows through every validation and
        # the real-model HBM preflight below like a hand-written one
        self._plan = None
        if cfg.auto_shard != "off":
            cfg = self._run_auto_shard(cfg, mesh)
            self.cfg = cfg
        # --tune_report: apply the overlap autotuner's chosen schedule
        # knobs for this config's family (AFTER --auto_shard apply, so the
        # knobs land on the family actually being trained)
        self._tune = None
        if cfg.tune_report:
            cfg = self._apply_tune_report(cfg)
            self.cfg = cfg
        if cfg.ckpt_io_retries < 0:
            raise ValueError(
                f"ckpt_io_retries must be >= 0, got {cfg.ckpt_io_retries}"
            )
        # transient-write retry ladder for every checkpoint file write
        # (process-global module state, same posture as compile_cache_dir)
        ckpt_lib.set_io_retries(cfg.ckpt_io_retries)
        # chaos harness: install the config/env fault plan (clears any plan
        # a previous Trainer in this process installed — a resumed run
        # without --fault_plan must not replay the crashed run's faults);
        # raises FaultPlanError on a malformed spec before training starts
        plan = faults.configure(cfg.fault_plan)
        if plan is not None and cfg.fused_epoch:
            stepwise = sorted(
                {c.site for c in plan.clauses} & faults.STEPWISE_SITES
            )
            if stepwise:
                raise ValueError(
                    f"--fault_plan sites {stepwise} act at the step/batch "
                    "grain, which --fused_epoch compiles away (the whole "
                    "epoch is one jit call and the streaming loader is "
                    "bypassed) — they would silently never fire. Use "
                    "ckpt_write/ckpt_corrupt clauses, or drop --fused_epoch "
                    "for chaos runs (refusing to silently ignore the plan)"
                )
        # --sharded_ckpt + --async_ckpt compose (snapshot-then-write): the
        # step loop blocks only for the device→host snapshot; serialization,
        # CRC, and the manifest commit run on the background writer, whose
        # commit barrier is filesystem-based — a jax collective never runs
        # off the main thread (ckpt/checkpoint.py::AsyncShardedCheckpointer,
        # docs/checkpointing.md "Two-phase sharded saves")
        # triggered on-device profiling (obs/profile.py): both specs are
        # validated HERE, before any model/data work, so a typo fails in
        # milliseconds rather than after the loaders built
        from tpu_dist.obs import profile as profile_lib  # noqa: PLC0415

        self._profile_triggers = profile_lib.parse_trigger(cfg.profile_trigger)
        manual_profile = profile_lib.parse_steps(cfg.profile_steps)
        self._profiler = None
        self._global_step = 0  # run-global step index (--profile_steps grid)
        if self._profile_triggers or manual_profile:
            if not cfg.profile_dir:
                raise ValueError(
                    "--profile_trigger/--profile_steps capture on-device "
                    "traces and need --profile_dir for the output "
                    "(refusing to silently ignore the flags)"
                )
            if cfg.fused_epoch:
                raise ValueError(
                    "--profile_trigger/--profile_steps need the per-step "
                    "grain; --fused_epoch compiles the epoch into one "
                    "call with no step boundary to open/close a capture "
                    "window at (use --profile_dir alone for the epoch-0 "
                    "blanket trace)"
                )
            import os as _os  # noqa: PLC0415

            out = (
                _os.path.join(cfg.profile_dir, f"host{mesh_lib.process_index()}")
                if mesh_lib.process_count() > 1 else cfg.profile_dir
            )
            # ctor validates window/cooldown/cap before training starts.
            # Created on EVERY process: anomaly/retrace triggers arm it on
            # rank 0 only, a straggler flag arms it on the flagged host —
            # the one whose timeline explains the skew.
            self._profiler = profile_lib.TriggeredProfiler(
                out,
                window_steps=cfg.profile_window,
                cooldown_steps=cfg.profile_cooldown,
                max_captures=cfg.profile_max_captures,
                manual_range=manual_profile,
            )
        # live telemetry (obs/export.py, obs/alerts.py): both specs are
        # validated HERE too — a bad rule file or port fails before any
        # model/data work, same posture as the profiler specs above
        if cfg.metrics_port < 0 or cfg.metrics_port > 65535:
            raise ValueError(
                f"metrics_port must be 0 (off) or a valid TCP port, got "
                f"{cfg.metrics_port}"
            )
        self._alert_rule_list = None
        if cfg.alert_rules:
            from tpu_dist.obs import alerts as alerts_lib  # noqa: PLC0415

            # raises on a malformed spec / unknown builtin / dup names
            self._alert_rule_list = alerts_lib.load_rules(cfg.alert_rules)
        if cfg.pp_interleave < 1:
            raise ValueError(f"pp_interleave must be >= 1, got {cfg.pp_interleave}")
        if cfg.pp_interleave > 1 and cfg.pp <= 1:
            raise ValueError(
                "pp_interleave > 1 has no effect without pp > 1 — set --pp "
                "to the stage count (refusing to silently ignore the flag)"
            )
        combined = sum(w > 1 for w in (cfg.sp, cfg.tp, cfg.ep, cfg.pp))
        if combined > 1 and not (
            combined == 2 and cfg.tp > 1 and (cfg.sp > 1 or cfg.pp > 1)
        ):
            raise ValueError(
                "only sp+tp (3-D DPxTPxSP) and pp+tp (Megatron DPxPPxTP) "
                "may be combined; other sp/tp/ep/pp combinations are not "
                "supported yet"
            )
        if mesh is not None:
            self.mesh = mesh
        elif cfg.sp > 1 and cfg.tp > 1:
            n = len(jax.devices())
            ways = cfg.sp * cfg.tp
            if n % ways:
                raise ValueError(f"{n} devices not divisible by tp*sp={ways}")
            self.mesh = mesh_lib.device_mesh(
                [n // ways, cfg.tp, cfg.sp],
                [mesh_lib.DATA_AXIS, mesh_lib.MODEL_AXIS, mesh_lib.SEQ_AXIS],
            )
        elif cfg.pp > 1 and cfg.tp > 1:
            # Megatron layout: tp innermost (adjacent devices — ICI-local
            # psums every block), pipe next (nearest-neighbor ppermute ring),
            # data outermost
            n = len(jax.devices())
            ways = cfg.pp * cfg.tp
            if n % ways:
                raise ValueError(f"{n} devices not divisible by pp*tp={ways}")
            self.mesh = mesh_lib.device_mesh(
                [n // ways, cfg.pp, cfg.tp],
                [mesh_lib.DATA_AXIS, mesh_lib.PIPE_AXIS, mesh_lib.MODEL_AXIS],
            )
        elif cfg.sp > 1 or cfg.tp > 1 or cfg.ep > 1 or cfg.pp > 1:
            ways = max(cfg.sp, cfg.tp, cfg.ep, cfg.pp)
            second = (
                mesh_lib.SEQ_AXIS if cfg.sp > 1
                else mesh_lib.MODEL_AXIS if cfg.tp > 1
                else mesh_lib.EXPERT_AXIS if cfg.ep > 1
                else mesh_lib.PIPE_AXIS
            )
            n = len(jax.devices())
            if n % ways:
                raise ValueError(f"{n} devices not divisible by sp/tp/ep/pp={ways}")
            self.mesh = mesh_lib.device_mesh(
                [n // ways, ways], [mesh_lib.DATA_AXIS, second]
            )
        else:
            self.mesh = mesh_lib.data_parallel_mesh()
        self._check_mesh_host_layout()
        # data-parallel width (batch divides over this, not over SP ways)
        self.n_data = int(self.mesh.shape[mesh_lib.DATA_AXIS])
        self.n_devices = int(self.mesh.devices.size)
        from tpu_dist.nn.attention import (  # noqa: PLC0415
            set_default_attention_impl,
        )

        # set BOTH directions: the default is process-global, and a later
        # Trainer in the same process must not inherit a stale 'flash'
        set_default_attention_impl("flash" if cfg.flash_attention else "xla")
        self.model = build_model(cfg)
        if cfg.sp_mode not in ("ring", "ulysses"):
            raise ValueError(
                f"sp_mode must be 'ring' or 'ulysses', got {cfg.sp_mode!r}"
            )
        if cfg.sp > 1:
            import inspect  # noqa: PLC0415

            if "seq_axis" not in inspect.signature(self.model.apply).parameters:
                raise ValueError(
                    f"model {cfg.model!r} does not support sequence parallelism "
                    f"(no seq_axis in apply); use a ViT model or sp=1"
                )
            if cfg.sp_mode == "ulysses":
                heads = getattr(self.model, "heads", None)
                # under sp x tp the attention sees heads/tp LOCAL heads
                # (column-sharded qkv) — validate the count it will see
                local_heads = (
                    heads // cfg.tp if heads is not None and cfg.tp > 1 else heads
                )
                if local_heads is not None and local_heads % cfg.sp:
                    raise ValueError(
                        f"sp_mode='ulysses' needs per-shard heads "
                        f"({local_heads}{f' = {heads}/tp' if cfg.tp > 1 else ''}) "
                        f"divisible by sp ({cfg.sp}); use sp_mode='ring'"
                    )
                if "sp_mode" not in inspect.signature(self.model.apply).parameters:
                    raise ValueError(
                        f"model {cfg.model!r} does not support sp_mode "
                        f"(ulysses); use a ViT model or sp_mode='ring'"
                    )
            if cfg.fused_epoch:
                raise ValueError("sp > 1 is not supported with fused_epoch")
            n_tokens = getattr(self.model, "n_patches", None)
            if n_tokens is not None and n_tokens % cfg.sp:
                raise ValueError(
                    f"model has {n_tokens} patch tokens, not divisible by "
                    f"sp={cfg.sp} — tokens would be dropped"
                )
            if cfg.batch_size % (self.n_data * cfg.sp):
                raise ValueError(
                    f"with sp>1, batch_size {cfg.batch_size} must also divide "
                    f"over the {self.n_data * cfg.sp} data x seq devices for "
                    f"evaluation sharding"
                )
        self._param_specs = None
        self._fsdp_specs = None
        if cfg.fsdp:
            if cfg.sp > 1 or cfg.ep > 1 or cfg.pp > 1:
                raise ValueError(
                    "fsdp composes with --tp (GSPMD spec overlay) but not "
                    "with sp/ep/pp: the ring/all_to_all/pipeline engines "
                    "are shard_map programs, and a leaf cannot be owned by "
                    "both a hand-written collective schedule and the "
                    "GSPMD partitioner"
                )
            if cfg.fused_epoch or cfg.shard_weight_update:
                raise ValueError(
                    "fsdp is incompatible with fused_epoch / zero1 (fsdp "
                    "supersedes ZeRO-1: momentum AND params are sharded)"
                )
            if cfg.fused_optimizer:
                raise ValueError(
                    "fsdp uses the plain SGD update (XLA fuses it into the "
                    "sharded program); fused_optimizer is shard_map-path only"
                )
            if not cfg.sync_bn:
                # not an error: BN-free models (ViT) legitimately pass
                # sync_bn=False; for BN models the flag simply cannot take
                # effect under GSPMD's global-batch semantics
                rank0_print(
                    "WARNING: --no_sync_bn has no effect under --fsdp — "
                    "BatchNorm statistics are global-batch (SyncBN) by "
                    "construction in the GSPMD engine"
                )
            if cfg.debug_replica_check:
                raise ValueError(
                    "debug_replica_check asserts replicated params; under "
                    "fsdp params are sharded by design"
                )
            if cfg.grad_compression != "none":
                rank0_print(
                    "WARNING: --grad_compression has no effect under --fsdp "
                    "— the engine's collectives (including the gradient "
                    "reduce-scatters the bf16/int8 wire formats would "
                    "compress) are GSPMD-inserted from sharding specs, not "
                    "hookable per-tensor (docs/compression.md)"
                )
            if cfg.flash_attention:
                raise ValueError(
                    "--fsdp with --flash_attention is not supported: the "
                    "Pallas kernel runs inside the GSPMD-partitioned jit "
                    "(no shard_map), where it has no SPMD partitioning "
                    "rule — XLA would replicate or fail to compile. Use "
                    "the default XLA attention under fsdp"
                )
        if cfg.tp > 1:
            import inspect  # noqa: PLC0415

            if "tp_axis" not in inspect.signature(self.model.apply).parameters:
                raise ValueError(
                    f"model {cfg.model!r} does not support tensor parallelism "
                    f"(no tp_axis in apply); use a ViT model or tp=1"
                )
            heads = getattr(self.model, "heads", None)
            if heads is not None and heads % cfg.tp:
                raise ValueError(f"{heads} heads not divisible by tp={cfg.tp}")
            if cfg.fused_epoch or cfg.shard_weight_update:
                raise ValueError(
                    "tp > 1 is incompatible with fused_epoch / zero1 "
                    "(grad_clip_norm composes — shard-aware norm in step.py)"
                )
            if cfg.pp <= 1:  # under PP×TP the pp branch sets combined specs
                self._param_specs = self.model.tp_param_specs(mesh_lib.MODEL_AXIS)
        from tpu_dist.train.step import QUANTIZED_MODES  # noqa: PLC0415

        if (
            cfg.grad_compression in QUANTIZED_MODES
            and not cfg.fsdp
            and (cfg.sp > 1 or cfg.tp > 1 or cfg.ep > 1 or cfg.pp > 1)
        ):
            # same wall as make_train_step, caught at the config layer: the
            # quantized two-stage reduce assumes one data axis over a
            # replicated param tree (docs/compression.md)
            raise ValueError(
                f"grad_compression={cfg.grad_compression!r} is scoped to "
                "the plain data-parallel, fused-epoch, and ZeRO-1 paths — "
                "it cannot combine with sp/tp/ep/pp (use "
                "--grad_compression bf16 there)"
            )
        if cfg.moe_top_k < 1:
            raise ValueError(f"moe_top_k must be >= 1, got {cfg.moe_top_k}")
        if cfg.moe_top_k > 1:
            import dataclasses as _dc  # noqa: PLC0415

            if not (_dc.is_dataclass(self.model) and hasattr(self.model, "top_k")):
                raise ValueError(
                    f"model {cfg.model!r} has no MoE router (no top_k field) "
                    f"— --moe_top_k applies to vit_moe_* models"
                )
            if cfg.moe_top_k > self.model.n_experts:
                raise ValueError(
                    f"moe_top_k={cfg.moe_top_k} exceeds the model's "
                    f"{self.model.n_experts} experts"
                )
            self.model = _dc.replace(self.model, top_k=cfg.moe_top_k)
        if cfg.ep > 1:
            import inspect  # noqa: PLC0415

            if "ep_axis" not in inspect.signature(self.model.apply).parameters:
                raise ValueError(
                    f"model {cfg.model!r} does not support expert parallelism "
                    f"(no ep_axis in apply); use a MoE model or ep=1"
                )
            n_exp = getattr(self.model, "n_experts", None)
            if n_exp is not None and n_exp % cfg.ep:
                raise ValueError(f"{n_exp} experts not divisible by ep={cfg.ep}")
            if cfg.fused_epoch or cfg.shard_weight_update:
                raise ValueError(
                    "ep > 1 is incompatible with fused_epoch / zero1 "
                    "(grad_clip_norm composes — shard-aware norm in step.py)"
                )
            if cfg.batch_size % self.n_devices:
                raise ValueError(
                    f"with ep>1, batch_size {cfg.batch_size} must divide over "
                    f"all {self.n_devices} devices (the expert axis carries data)"
                )
            self._param_specs = self.model.ep_param_specs(mesh_lib.EXPERT_AXIS)
        if cfg.pp > 1:
            import inspect  # noqa: PLC0415

            if "pp_axis" not in inspect.signature(self.model.apply).parameters:
                raise ValueError(
                    f"model {cfg.model!r} does not support pipeline parallelism "
                    f"(no pp_axis in apply); use vit_pp_* or pp=1"
                )
            if cfg.pp_interleave > 1:
                import dataclasses as _dc  # noqa: PLC0415

                m_check = cfg.pp_microbatches or cfg.pp
                if m_check < cfg.pp:
                    raise ValueError(
                        "pp_interleave > 1 requires pp_microbatches >= pp "
                        "(fewer microbatches than stages starves the "
                        "interleaved schedule's warmup ramp)"
                    )
                if not (
                    _dc.is_dataclass(self.model)
                    and hasattr(self.model, "interleave")
                    and hasattr(self.model, "pp_stages")
                ):
                    raise ValueError(
                        f"model {cfg.model!r} does not support the interleaved "
                        f"schedule (no interleave/pp_stages fields); use "
                        f"vit_pp_* or pp_interleave=1"
                    )
                # relay the virtual-stage layout into the model definition
                self.model = _dc.replace(
                    self.model, interleave=cfg.pp_interleave, pp_stages=cfg.pp
                )
            depth = getattr(self.model, "depth", None)
            chunks = cfg.pp * cfg.pp_interleave
            if depth is not None and depth % chunks:
                raise ValueError(
                    f"depth {depth} not divisible by pp*interleave={chunks} chunks"
                )
            if cfg.fused_epoch or cfg.shard_weight_update:
                raise ValueError(
                    "pp > 1 is incompatible with fused_epoch / zero1 "
                    "(grad_clip_norm composes — shard-aware norm in step.py)"
                )
            m = cfg.pp_microbatches or cfg.pp
            per_dev_batch = cfg.batch_size // max(1, self.n_data)
            if per_dev_batch % m:
                raise ValueError(
                    f"per-data-shard batch {per_dev_batch} must divide into "
                    f"{m} microbatches"
                )
            from tpu_dist.parallel.pipeline import bubble_fraction  # noqa: PLC0415

            rank0_print(
                f"pipeline: {cfg.pp} stages x {cfg.pp_interleave} virtual, "
                f"{m} microbatches, bubble fraction "
                f"{bubble_fraction(cfg.pp, m, cfg.pp_interleave):.3f}"
            )
            if cfg.tp > 1:
                if not hasattr(self.model, "pp_tp_param_specs"):
                    raise ValueError(
                        f"model {cfg.model!r} does not support the PP×TP "
                        f"layout (no pp_tp_param_specs); use vit_pp_* or tp=1"
                    )
                self._param_specs = self.model.pp_tp_param_specs(
                    mesh_lib.PIPE_AXIS, mesh_lib.MODEL_AXIS
                )
            else:
                self._param_specs = self.model.pp_param_specs(mesh_lib.PIPE_AXIS)

        # -- data ------------------------------------------------------------
        if cfg.dataset == "synthetic":
            self.train_data = synthetic_cifar(cfg.synthetic_n, cfg.num_classes, seed=1)
            self.test_data = synthetic_cifar(
                max(cfg.synthetic_n // 5, self.n_devices), cfg.num_classes, seed=2
            )
        elif cfg.dataset == "synthetic_learnable":
            from tpu_dist.data.synthetic import synthetic_quadrant  # noqa: PLC0415

            self.train_data = synthetic_quadrant(cfg.synthetic_n, seed=1)
            self.test_data = synthetic_quadrant(
                max(cfg.synthetic_n // 5, self.n_devices), seed=2
            )
        elif cfg.dataset == "synthetic_multifactor":
            from tpu_dist.data.synthetic import synthetic_multifactor  # noqa: PLC0415

            # train labels carry the task's noise; eval labels are clean so
            # val accuracy measures the true function (data/synthetic.py)
            self.train_data = synthetic_multifactor(cfg.synthetic_n, seed=1)
            self.test_data = synthetic_multifactor(
                max(cfg.synthetic_n // 5, self.n_devices), seed=2, label_noise=0.0
            )
        elif cfg.dataset == "cifar100":
            self.train_data = load_cifar100(cfg.data_dir, train=True)
            self.test_data = load_cifar100(cfg.data_dir, train=False)
        elif cfg.dataset == "cifar10":
            self.train_data = load_cifar10(cfg.data_dir, train=True)
            self.test_data = load_cifar10(cfg.data_dir, train=False)
        else:
            raise ValueError(f"unknown dataset {cfg.dataset!r}")
        _DATASET_CLASSES = {
            "cifar100": 100, "cifar10": 10,
            "synthetic_learnable": 4, "synthetic_multifactor": 16,
        }
        expected = _DATASET_CLASSES.get(cfg.dataset)
        if expected is not None and cfg.num_classes != expected:
            raise ValueError(
                f"dataset {cfg.dataset!r} has {expected} classes but "
                f"num_classes={cfg.num_classes}; pass --num_classes {expected}"
            )

        nproc, pid = mesh_lib.process_count(), mesh_lib.process_index()
        # reference: per-worker batch = global / nprocs (distributed.py:67);
        # here the per-process slice is further split over local chips by
        # the batch sharding, and grad accumulation slices it once more.
        if cfg.batch_size % self.n_data:
            raise ValueError(
                f"batch_size {cfg.batch_size} must divide over {self.n_data} "
                f"data-parallel devices"
            )
        # under ep>1 the batch shards over ALL devices (expert axis carries data)
        per_device = cfg.batch_size // (self.n_devices if cfg.ep > 1 else self.n_data)
        if per_device == 0 or per_device % cfg.grad_accu_steps:
            raise ValueError(
                f"per-device batch {per_device} must divide by grad_accu_steps="
                f"{cfg.grad_accu_steps}"
            )
        self.local_batch = cfg.batch_size // nproc
        seed = cfg.seed if cfg.seed is not None else 0

        self.train_sampler = DistributedSampler(
            len(self.train_data[0]), nproc, pid, shuffle=True, seed=seed,
            drop_last=cfg.drop_last or cfg.grad_accu_steps > 1,
        )
        self.test_sampler = DistributedSampler(
            len(self.test_data[0]), nproc, pid, shuffle=False, seed=seed
        )
        # fused C++ gather+crop+normalize when built; numpy otherwise.
        # Normalization statistics follow the dataset (CIFAR-100 stats are
        # the reference's utils/dataset.py:8,20).
        from tpu_dist.data import native, transforms  # noqa: PLC0415

        if cfg.dataset == "cifar10":
            stats = dict(mean=transforms.CIFAR10_MEAN, std=transforms.CIFAR10_STD)
        else:
            stats = dict(mean=transforms.CIFAR100_MEAN, std=transforms.CIFAR100_STD)

        # EP: the expert axis carries data everywhere outside the MoE, so the
        # TRAIN batch also shards over every device
        train_axes = (
            (mesh_lib.DATA_AXIS, mesh_lib.EXPERT_AXIS) if cfg.ep > 1 else mesh_lib.DATA_AXIS
        )
        divisor = max(1, (self.n_devices if cfg.ep > 1 else self.n_data) // nproc)
        # eval shards over every non-model axis (seq/expert ways hold
        # different examples — no SP/EP structure needed at eval time)
        if cfg.sp > 1:
            eval_axes = (mesh_lib.DATA_AXIS, mesh_lib.SEQ_AXIS)
        elif cfg.ep > 1:
            eval_axes = (mesh_lib.DATA_AXIS, mesh_lib.EXPERT_AXIS)
        else:
            eval_axes = mesh_lib.DATA_AXIS
        eval_ways = self.n_data * (cfg.sp if cfg.sp > 1 else cfg.ep if cfg.ep > 1 else 1)
        eval_divisor = max(1, eval_ways // nproc)
        self.train_loader = DataLoader(
            *self.train_data, self.local_batch, self.train_sampler, self.mesh,
            gather_transform=functools.partial(native.gather_augment, train=True, **stats),
            seed=seed, prefetch=cfg.num_workers, batch_divisor=divisor,
            shard_axes=train_axes,
        )
        self.test_loader = DataLoader(
            *self.test_data, self.local_batch, self.test_sampler, self.mesh,
            gather_transform=functools.partial(native.gather_augment, train=False, **stats),
            seed=seed, with_mask=True, prefetch=cfg.num_workers,
            batch_divisor=eval_divisor, shard_axes=eval_axes,
        )

        # -- model / optimizer state ----------------------------------------
        if cfg.optimizer == "adamw":
            if cfg.fused_optimizer:
                raise ValueError(
                    "fused_optimizer is the Pallas fused-SGD kernel; adamw "
                    "uses the plain (XLA-fused) update"
                )
            from tpu_dist.train.optim import AdamW  # noqa: PLC0415

            self.optimizer = AdamW(
                weight_decay=cfg.weight_decay,
                decay_mask=cfg.adamw_decay_mask,
            )
            rank0_print(
                f"=> adamw decay_mask={cfg.adamw_decay_mask} "
                "(auto: rank<=1 leaves excluded from weight decay; "
                "--adamw_decay_mask all restores decay-everything)"
            )
        elif cfg.optimizer == "sgd":
            self.optimizer = SGD(
                momentum=cfg.momentum,
                weight_decay=cfg.weight_decay,
                fused=cfg.fused_optimizer,
            )
        elif cfg.optimizer in ("lars", "lamb"):
            if cfg.fused_optimizer:
                raise ValueError(
                    "fused_optimizer is the Pallas fused-SGD kernel; "
                    f"{cfg.optimizer} uses the plain (XLA-fused) update"
                )
            if cfg.shard_weight_update:
                raise ValueError(
                    f"{cfg.optimizer} needs per-layer norms, which the "
                    "ZeRO-1 flat layout destroys — use --fsdp (leaf-"
                    "grained sharding) for a sharded large-batch run"
                )
            from tpu_dist.train.optim import LAMB, LARS  # noqa: PLC0415

            if cfg.optimizer == "lars":
                self.optimizer = LARS(
                    momentum=cfg.momentum, weight_decay=cfg.weight_decay
                )
            else:
                self.optimizer = LAMB(weight_decay=cfg.weight_decay)
            if cfg.lr_base_batch <= 0 or cfg.warmup_epochs <= 0:
                rank0_print(
                    f"=> WARNING: {cfg.optimizer} without the full "
                    "large-batch recipe (--lr_base_batch for linear LR "
                    "scaling + --warmup_epochs) — trust ratios alone "
                    "rarely save an unscaled schedule"
                )
        else:
            raise ValueError(
                f"unknown optimizer {cfg.optimizer!r} (sgd | adamw | lars | lamb)"
            )
        params, bn_state = self.model.init(jax.random.PRNGKey(seed))
        state = TrainState.create(params, bn_state, self.optimizer)
        if cfg.grad_compression == "int8_ef" and not cfg.fsdp:
            # error-feedback residuals are TrainState: zero-initialized
            # here, quantization error flows into them each step, and they
            # ride every checkpoint save/restore like the momentum buffers
            from tpu_dist.train.step import ef_state_host_zeros  # noqa: PLC0415

            state = state._replace(ef=ef_state_host_zeros(
                params, self.n_data, zero1=cfg.shard_weight_update
            ))
        self._fsdp_opt_specs = None
        if cfg.fsdp:
            from tpu_dist.parallel.fsdp import (  # noqa: PLC0415
                compose_fsdp_specs,
                fsdp_specs,
            )

            if cfg.tp > 1:
                # FSDP × TP: overlay data-axis sharding on the model's
                # Megatron specs; the GSPMD engine runs the PLAIN apply
                # (no tp_axis/psum — the partitioner inserts collectives
                # for both axes from the specs alone)
                self._fsdp_specs = compose_fsdp_specs(
                    params, self.mesh,
                    self.model.tp_param_specs(mesh_lib.MODEL_AXIS),
                )
            else:
                self._fsdp_specs = fsdp_specs(params, self.mesh)
            self._fsdp_opt_specs = self.optimizer.state_specs(self._fsdp_specs)
        if cfg.shard_weight_update and cfg.fused_epoch:
            raise ValueError(
                "shard_weight_update (ZeRO-1) is scoped to the plain DP "
                "step by design — the fused-epoch scan keeps params "
                "replicated; use --fsdp for sharded state"
            )
        if cfg.mid_epoch_save_every and cfg.fused_epoch:
            raise ValueError(
                "mid_epoch_save_every needs per-step granularity; "
                "--fused_epoch compiles the whole epoch into one call "
                "(no step boundary to snapshot at)"
            )
        if cfg.device_metrics:
            # same wall as make_train_step, caught at the config layer,
            # plus the two engine exclusions only the trainer knows about
            if (
                cfg.fsdp or cfg.shard_weight_update
                or cfg.tp > 1 or cfg.ep > 1 or cfg.pp > 1
            ):
                raise ValueError(
                    "--device_metrics is scoped to the replicated-param "
                    "paths (plain DP/SP, any --grad_compression): under "
                    "ZeRO-1/FSDP/TP/EP/PP the reduced gradient exists "
                    "only as shards, and the global norms would need the "
                    "extra collectives the TD107 zero-cost contract "
                    "forbids (docs/observability.md)"
                )
            if cfg.fused_epoch:
                raise ValueError(
                    "--device_metrics needs the per-step metrics fetch; "
                    "--fused_epoch compiles the epoch into one call with "
                    "epoch-mean metrics, so the per-step norms would be "
                    "averaged away (refusing to silently ignore the flag)"
                )
        if cfg.anomaly_action not in ("off", "warn", "snapshot"):
            raise ValueError(
                f"anomaly_action must be off|warn|snapshot, got "
                f"{cfg.anomaly_action!r}"
            )
        if cfg.anomaly_action == "snapshot" and not cfg.ckpt_dir:
            raise ValueError(
                "--anomaly_action snapshot writes an emergency mid-epoch "
                "checkpoint and needs --ckpt_dir (refusing to silently "
                "degrade to 'warn')"
            )
        self._anomaly = None
        if cfg.anomaly_action != "off":
            from tpu_dist.obs.anomaly import AnomalyDetector  # noqa: PLC0415

            # raises on a degenerate window before training starts
            self._anomaly = AnomalyDetector(
                window=cfg.anomaly_window,
                loss_spike=cfg.anomaly_loss_spike,
                grad_spike=cfg.anomaly_grad_spike,
            )
        # place on the mesh (DDP's init-time param broadcast; sharded
        # placements for TP params / ZeRO-1 optimizer state)
        self.state = self._place_state(state)
        # auto-recovery LR backoff: deterministic data order means a bare
        # retry of a diverged epoch would diverge identically — each
        # recovery scales the schedule down (cfg.recover_lr_factor)
        self._lr_scale = 1.0
        self._state_poisoned = False
        self._best_top1 = -1.0
        base_lr = cfg.lr
        if cfg.lr_base_batch > 0:
            # Goyal linear-scaling rule — the large-batch recipe's first
            # half; the second half is the warmup ramp below
            from tpu_dist.train.optim import linear_scaled_lr  # noqa: PLC0415

            base_lr = linear_scaled_lr(cfg.lr, cfg.lr_base_batch, cfg.batch_size)
            rank0_print(
                f"=> linear LR scaling: {cfg.lr} x {cfg.batch_size}/"
                f"{cfg.lr_base_batch} = {base_lr:g}"
            )
        if cfg.lr_schedule == "cosine":
            self.lr_schedule = cosine_lr(base_lr, cfg.epochs, cfg.warmup_epochs)
        else:
            self.lr_schedule = multistep_lr(
                base_lr, cfg.lr_milestones, cfg.lr_gamma,
                warmup_epochs=cfg.warmup_epochs,
            )

        compute_dtype = jnp.bfloat16 if cfg.bf16 else jnp.float32
        if cfg.fsdp:
            from tpu_dist.parallel.fsdp import (  # noqa: PLC0415
                make_fsdp_eval_step,
                make_fsdp_train_step,
            )

            self.train_step = make_fsdp_train_step(
                self.model.apply, self.optimizer, self.mesh, self._fsdp_specs,
                opt_specs=self._fsdp_opt_specs,
                grad_accum_steps=cfg.grad_accu_steps,
                compute_dtype=compute_dtype,
                label_smoothing=cfg.label_smoothing,
                grad_clip_norm=cfg.grad_clip_norm,
                moe_aux_coef=cfg.moe_aux_coef,
                remat=cfg.remat,
                model_kwargs=self._attn_model_kwargs() or None,
            )
            self.eval_step = make_fsdp_eval_step(
                self.model.apply, self.mesh, self._fsdp_specs,
                opt_specs=self._fsdp_opt_specs,
                compute_dtype=compute_dtype,
                model_kwargs=self._attn_model_kwargs() or None,
            )
        else:
            from tpu_dist.train.step import ef_state_spec  # noqa: PLC0415

            self.train_step = self._build_train_step(cfg, compute_dtype)
            self.eval_step = make_eval_step(
                self.model.apply, self.mesh, compute_dtype=compute_dtype,
                model_kwargs=self._attn_model_kwargs() or None,
                axis=eval_axes,
                tp_axis=mesh_lib.MODEL_AXIS if cfg.tp > 1 else None,
                ep_axis=mesh_lib.EXPERT_AXIS if cfg.ep > 1 else None,
                pp_axis=mesh_lib.PIPE_AXIS if cfg.pp > 1 else None,
                param_specs=self._param_specs,
                opt_specs=(
                    self.optimizer.state_specs(self._param_specs)
                    if self._param_specs is not None
                    else None
                ),
                ef_specs=ef_state_spec(
                    cfg.grad_compression, zero1=cfg.shard_weight_update
                ),
            )

        self._fused_runner = None
        if cfg.fused_epoch:
            from tpu_dist.train.epoch import (  # noqa: PLC0415
                make_fused_epoch,
                make_fused_eval,
                put_dataset_on_device,
            )

            self._fused_data = put_dataset_on_device(self.mesh, *self.train_data)
            self._fused_runner = make_fused_epoch(
                self.model.apply, self.optimizer, self.mesh,
                batch_per_device=cfg.batch_size // self.n_devices,
                sync_bn=cfg.sync_bn, compute_dtype=compute_dtype,
                moe_aux_coef=cfg.moe_aux_coef,
                grad_compression=cfg.grad_compression,
                model_kwargs=self._attn_model_kwargs() or None, **stats,
            )
            # round the test set UP to a device multiple with label=-1
            # padding so fused eval counts every real example exactly once
            ti, tl = self.test_data
            pad = (-len(tl)) % self.n_devices
            if pad:
                ti = np.concatenate([ti, np.zeros((pad,) + ti.shape[1:], ti.dtype)])
                tl = np.concatenate([tl, np.full(pad, -1, tl.dtype)])
            self._fused_test_data = put_dataset_on_device(self.mesh, ti, tl)
            from tpu_dist.train.step import ef_state_spec  # noqa: PLC0415

            self._fused_eval = make_fused_eval(
                self.model.apply, self.mesh,
                batch_per_device=cfg.batch_size // self.n_devices,
                compute_dtype=compute_dtype,
                ef_specs=ef_state_spec(cfg.grad_compression),
                model_kwargs=self._attn_model_kwargs() or None, **stats,
            )

        self._async_ckpt = None  # created lazily by _ckpt_io()
        self._heartbeat = None  # created by fit() (rank 0, --heartbeat_file)
        self._flight = None  # per-rank flight recorder, armed by fit()
        #                      (--crash_dir; obs/flight.py)
        self._fault_handle = None  # armed faulthandler (stack capture)
        self._exporter = None  # live OpenMetrics publisher, created by fit()
        self._alerts = None  # AlertEngine, created by fit() per run
        self._export_rollup = {}  # latest epoch/health scalars for export
        self._export_t = float("-inf")  # exposition throttle mark
        self._trace_events = []  # drained spans held for --trace_file export
        self._step_traced = False  # first dispatch of THIS Trainer compiles
        self._history = None  # live MetricsHistory while fit() runs — the
        #                       step loop's device_stats/anomaly records
        self._tb = None  # SummaryWriter while fit() runs (--tensorboard_dir)
        # XLA cost/memory accounting of the train step, captured ONCE at
        # first dispatch (obs/costmodel.py): {} = capture failed, don't retry
        self._step_cost = None
        # executable-cache watcher: counts compiles, flags mid-run retraces
        self._compile_watch = costmodel_lib.CompileWatcher(self.train_step)
        # -- HBM pre-flight (obs/memory.py, docs/observability.md "HBM
        # ledger & OOM forensics"): static per-leaf accounting of the
        # state (params/opt-state/EF/BN at their SHARDED extents — a
        # ZeRO-1 flat momentum counts ceil(L/n) per chip) plus one
        # per-device input shard, priced against the per-chip HBM budget
        # BEFORE the first compile can OOM — the lint ROADMAP item 3
        # names. Pure shape/sharding metadata arithmetic; TD115 pins
        # that arming it leaves the traced step byte-identical.
        from tpu_dist.obs import memory as memory_lib  # noqa: PLC0415

        batch_sds = None
        try:
            img, lbl = self.train_data
            per_dev = max(cfg.batch_size // self.n_devices, 1)
            batch_sds = {
                "images": jax.ShapeDtypeStruct(
                    (per_dev,) + tuple(img.shape[1:]), img.dtype
                ),
                "labels": jax.ShapeDtypeStruct((per_dev,), lbl.dtype),
            }
        except Exception:  # tpu-dist: ignore[TD006] — an exotic dataset
            pass  # shape only costs the batch row, never the pre-flight
        self._mem_static = memory_lib.static_ledger(
            params=self.state.params, opt_state=self.state.opt_state,
            ef=self.state.ef, bn_state=self.state.bn_state,
            batch=batch_sds,
        )
        counters_lib.set_gauge(
            "mem.static_bytes_per_device",
            self._mem_static["bytes_per_device"],
        )
        self._mem_record = None  # the first-dispatch ledger snapshot
        self._mem_feasibility = memory_lib.preflight_check(
            self._mem_static["bytes_per_device"],
            budget_bytes=cfg.hbm_budget_bytes,
            headroom=cfg.memory_headroom,
            action=cfg.memory_check,
        )  # InfeasibleMemoryError under --memory_check refuse
        if self._mem_feasibility and not self._mem_feasibility["fits"]:
            rank0_print(
                "WARNING: static HBM requirement "
                f"{memory_lib.fmt_bytes(self._mem_feasibility['required_bytes'])}"
                "/device exceeds "
                f"{cfg.memory_headroom:.0%} of the "
                f"{memory_lib.fmt_bytes(self._mem_feasibility['budget_bytes'])}"
                " per-chip budget — expect RESOURCE_EXHAUSTED; shard more "
                "or shrink the batch (--memory_check refuse stops here)"
            )
        # run identity: config hash + construction second, stamped ONCE per
        # Trainer (docs/observability.md) — every history record of this
        # run carries the same id, repeated fit() calls included, and a
        # resume (new process, same config) gets a fresh one
        import dataclasses as _dc  # noqa: PLC0415
        import hashlib  # noqa: PLC0415
        import json as _json  # noqa: PLC0415

        cfg_hash = hashlib.sha1(
            _json.dumps(_dc.asdict(cfg), sort_keys=True, default=str).encode()
        ).hexdigest()[:8]
        self._run_id = f"{cfg_hash}-{int(time.time())}"
        # arm host-span tracing on the primary BEFORE the resume-path
        # restore below, so the restore ladder's ckpt/restore spans land in
        # the trace (fit() re-arms with fresh=False, keeping them). The
        # monotonic stamp here is the run's single clock origin: the span
        # recorder zeroes on it now, and fit() hands it to MetricsHistory
        # as the rel_s origin — exported epoch bars and spans line up, and
        # a second fit() on this instance continues the same timeline.
        self._telemetry = bool(
            mesh_lib.is_primary() and (cfg.log_file or cfg.trace_file)
        )
        self._telemetry_t0 = time.monotonic()
        if self._telemetry:
            spans_lib.enable()
        self.start_epoch = 0
        self._resume_step = 0  # >0 only after restoring a mid-epoch snapshot
        self._resume_examples = 0  # >0 only on an ELASTIC mid-epoch resume
        #                            (consumed-prefix offset; sampler.set_offset)
        # the snapshot's final-step metrics: replayed when a resumed epoch
        # has zero steps left (the interrupt landed after the epoch's last
        # step), so the epoch record still matches the uninterrupted run
        self._resume_metrics = None
        self._step_metrics = None  # (epoch, steps_done, device metrics)
        self._epoch_start_examples = 0  # the running epoch's entry offset
        # logical param length L — the world-size-independent coordinate
        # every elastic flat layout (ZeRO-1 opt vectors, EF residuals) is
        # padded from; stamped into every checkpoint's elastic meta
        from tpu_dist.elastic.remap import params_len  # noqa: PLC0415

        self._params_len = params_len(self.state.params)
        self._last_reshard_s = 0.0  # wall time of the last elastic remap
        self._elastic_resume = None  # 'resume' history record, logged by fit
        # atomic training position for _emergency_save: (state, epoch,
        # steps_done, epoch_complete). Fresh start = complete through
        # epoch -1 (nothing to snapshot); _restore_latest re-publishes.
        self._progress = (self.state, -1, 0, True)
        if cfg.resume and cfg.ckpt_dir:
            # template = current state (matches sharded layouts too);
            # raises on a format-mismatched ckpt_dir (_restore_latest).
            # Goodput: the plain restore is ckpt time, but an ELASTIC
            # reshard (restore onto a new dp extent) is recovery time —
            # the ledger's recovery_s bucket carries reshard+relaunch cost
            t_res = time.monotonic()
            epoch = self._restore_latest()
            restore_s = time.monotonic() - t_res
            self._goodput.add(
                "ckpt", max(restore_s - self._last_reshard_s, 0.0)
            )
            self._goodput.add("recovery", self._last_reshard_s)
            if epoch is not None:
                # a mid-epoch snapshot re-enters its own epoch at the saved
                # step (or, elastically, at the consumed-example offset); a
                # clean end-of-epoch ckpt starts the next epoch
                self.start_epoch = (
                    epoch if (self._resume_step or self._resume_examples)
                    else epoch + 1
                )
                self._seed_global_step()

    def _seed_global_step(self) -> None:
        """Re-anchor the ``--profile_steps`` grid after a restore. The
        grid is RUN-global (the flag's contract: 'global steps'), so a
        resumed process must not restart it at 0 — a manual window that
        already ran before the preemption would re-fire aimed at the
        wrong steps. Per-epoch step count is the loader length capped by
        ``--steps_per_epoch``, the same bound ``train_epoch`` honors; a
        window cut short by the preemption resumes mid-range (the
        profiler captures the remaining overlap)."""
        n = len(self.train_loader)
        if self.cfg.steps_per_epoch is not None:
            n = min(n, self.cfg.steps_per_epoch)
        self._global_step = self.start_epoch * n + self._resume_step

    def _run_auto_shard(self, cfg: TrainConfig, mesh) -> TrainConfig:
        """``--auto_shard``: enumerate/price/filter the shardlint family
        matrix (analysis/planner.py) and print the ranked plan. ``apply``
        rewrites the returned config to the chosen family's flags — the
        rewritten config then passes through every downstream validation
        and the real-model HBM preflight exactly like a hand-written one.

        The chosen plan is TD118-verified here (fresh compile of the
        chosen family, inventory must match the priced one) — an
        unverifiable plan is refused in ``apply`` mode, warned in ``plan``
        mode. The plan lands in the history as a ``plan`` record (schema
        v12) at fit() start, and TD119 closes the loop after a profiled
        run (``_note_capture_analysis``)."""
        import dataclasses  # noqa: PLC0415

        from tpu_dist.analysis import planner  # noqa: PLC0415
        from tpu_dist.obs import memory as memory_lib  # noqa: PLC0415

        apply = cfg.auto_shard == "apply"
        if apply and (cfg.sp > 1 or cfg.tp > 1 or cfg.ep > 1 or cfg.pp > 1):
            raise ValueError(
                "--auto_shard apply plans over the flat data-parallel "
                "family matrix and would clobber an explicit sp/tp/ep/pp "
                "layout — use --auto_shard plan for an advisory table"
            )
        plan = planner.build_plan(
            mesh=mesh,
            hbm_budget_bytes=cfg.hbm_budget_bytes,
            memory_headroom=cfg.memory_headroom,
            applyable_only=apply,
        )
        chosen = plan.get("chosen")
        if chosen is None:
            rank0_print(planner.format_text(plan))
            if plan["counts"]["refused"]:
                raise memory_lib.InfeasibleMemoryError(
                    f"--auto_shard: all {plan['counts']['refused']} "
                    "candidate(s) exceed the per-chip HBM budget — shrink "
                    "the batch, raise --memory_headroom, or widen the mesh"
                )
            raise ValueError(
                "--auto_shard: no candidate could be planned "
                f"(skipped: {plan.get('skips')})"
            )
        probe, violations = planner.verify_plan(plan, mesh=mesh)
        plan["verification"] = probe
        rank0_print(planner.format_text(plan))
        if violations:
            for v in violations:
                rank0_print(f"=> {v}")
            if apply:
                raise ValueError(
                    "--auto_shard apply: the chosen plan failed TD118 "
                    "plan-must-verify (compiled collective inventory != "
                    "priced inventory) — refusing to train on a mispriced "
                    "ranking"
                )
        self._plan = {
            "family": chosen["family"],
            "mode": cfg.auto_shard,
            "applied": apply,
            "predicted_step_s": chosen.get("predicted_step_s"),
            "gauge_source": plan.get("gauge_source"),
            "n_candidates": plan["counts"]["candidates"],
            "n_refused": plan["counts"]["refused"],
        }
        if not apply:
            return cfg
        overrides = planner.family_train_overrides(chosen["family"])
        rank0_print(
            f"=> auto_shard apply: {chosen['family']} -> "
            + (", ".join(f"{k}={v}" for k, v in sorted(overrides.items()))
               or "reference flags")
        )
        return dataclasses.replace(cfg, **overrides)

    def _apply_tune_report(self, cfg: TrainConfig) -> TrainConfig:
        """``--tune_report``: load the overlap autotuner's report
        (analysis/overlap.py) and apply its chosen schedule knobs for this
        config's planner family. A knob flag the user set explicitly
        (non-default) wins over the report; every applied/overridden knob
        is printed and exported as a ``tune.*`` gauge at fit() start.
        A malformed report raises (typed ``TuneReportError``) — silently
        training untuned against an explicit --tune_report would be a
        lying flag."""
        import dataclasses  # noqa: PLC0415

        from tpu_dist.analysis import overlap as overlap_lib  # noqa: PLC0415
        from tpu_dist.analysis import planner  # noqa: PLC0415

        report = overlap_lib.load_tune_report(cfg.tune_report)
        family = planner.family_of(
            grad_compression=cfg.grad_compression,
            bf16=cfg.bf16,
            grad_accu_steps=cfg.grad_accu_steps,
            shard_weight_update=cfg.shard_weight_update,
            fsdp=cfg.fsdp,
        )
        self._tune = {
            "report": cfg.tune_report,
            "objective": report.get("objective"),
            "family": family,
            "applied": {},
            "user_overrides": {},
        }
        if family is None:
            rank0_print(
                "=> tune_report: this flag combination maps to no planner "
                "family — no tuned knobs to apply"
            )
            return cfg
        knobs = overlap_lib.chosen_knobs(report, family)
        if not knobs:
            rank0_print(
                f"=> tune_report: family {family} — baseline wins, "
                "no knob overrides"
            )
            return cfg
        defaults = TrainConfig()
        applied: dict = {}
        for knob, value in sorted(knobs.items()):
            if getattr(cfg, knob) != getattr(defaults, knob):
                # the user set this knob explicitly; the report yields
                self._tune["user_overrides"][knob] = getattr(cfg, knob)
                continue
            applied[knob] = value
        self._tune["applied"] = applied
        msg = ", ".join(f"{k}={v}" for k, v in sorted(applied.items()))
        skipped = ", ".join(
            f"{k}={v} (user)" for k, v in
            sorted(self._tune["user_overrides"].items())
        )
        rank0_print(
            f"=> tune_report apply [{family}]: {msg or 'nothing'}"
            + (f"; kept {skipped}" if skipped else "")
        )
        return dataclasses.replace(cfg, **applied) if applied else cfg

    def _ckpt_io(self):
        """Sync module functions, the sharded writer (``--sharded_ckpt``),
        or an async writer (``--async_ckpt``: plain, or snapshot-then-write
        sharded when combined with ``--sharded_ckpt``); the async writers
        are created lazily so each ``fit()`` gets a fresh pool after
        ``_ckpt_close()`` released the previous worker thread."""
        if not self.cfg.async_ckpt:
            if self.cfg.sharded_ckpt:
                # stateless (staticmethods) — hand back the class, same as
                # the emergency-save path uses it
                return ckpt_lib.ShardedCheckpointer
            return ckpt_lib
        if self._async_ckpt is None:
            self._async_ckpt = (
                ckpt_lib.AsyncShardedCheckpointer()
                if self.cfg.sharded_ckpt
                else ckpt_lib.AsyncCheckpointer()
            )
        return self._async_ckpt

    def _ckpt_close(self, suppress: bool = False) -> None:
        """Bounded drain + release of the async writer
        (``--ckpt_drain_timeout_s``; ≤0 waits forever). ``suppress=True``
        logs a writer error instead of raising — for paths where an
        exception is already propagating (interrupt/divergence) and must
        not be masked. A drain that times out with writes still in flight
        is a COUNTED, loud data loss (``ckpt.drain_abandoned``) — never a
        silent one: the newest data on disk is then the last published
        (plain) / committed (sharded) checkpoint."""
        if self._async_ckpt is None:
            return
        writer, self._async_ckpt = self._async_ckpt, None
        timeout = self.cfg.ckpt_drain_timeout_s
        timeout = timeout if timeout and timeout > 0 else None
        try:
            drained = writer.close(timeout=timeout)
        except Exception as e:
            if not suppress:
                raise
            rank0_print(f"WARNING: background checkpoint write failed: {e}")
            return
        if not drained:
            n = writer.in_flight
            counters_lib.inc("ckpt.drain_abandoned", n)
            rank0_print(
                f"WARNING: abandoned {n} in-flight background checkpoint "
                f"write(s) after the {timeout:.0f}s drain timeout "
                "(--ckpt_drain_timeout_s) — their snapshots are LOST; the "
                "newest checkpoint on disk is the last one committed"
            )
            if not suppress:
                raise RuntimeError(
                    f"background checkpoint drain timed out with {n} "
                    "write(s) in flight (see the warning above)"
                )

    def _build_train_step(self, cfg: TrainConfig, compute_dtype):
        mk = {}
        if cfg.pp > 1 and cfg.pp_microbatches:
            mk["n_microbatches"] = cfg.pp_microbatches
        if cfg.sp > 1 and cfg.sp_mode != "ring":
            mk["sp_mode"] = cfg.sp_mode
        mk.update(self._attn_model_kwargs())
        return make_train_step(
            self.model.apply, self.optimizer, self.mesh,
            grad_accum_steps=cfg.grad_accu_steps,
            sync_bn=cfg.sync_bn,
            compute_dtype=compute_dtype,
            shard_weight_update=cfg.shard_weight_update,
            label_smoothing=cfg.label_smoothing,
            grad_clip_norm=cfg.grad_clip_norm,
            moe_aux_coef=cfg.moe_aux_coef,
            seq_axis=mesh_lib.SEQ_AXIS if cfg.sp > 1 else None,
            tp_axis=mesh_lib.MODEL_AXIS if cfg.tp > 1 else None,
            ep_axis=mesh_lib.EXPERT_AXIS if cfg.ep > 1 else None,
            pp_axis=mesh_lib.PIPE_AXIS if cfg.pp > 1 else None,
            param_specs=self._param_specs,
            remat=cfg.remat,
            grad_compression=cfg.grad_compression,
            quant_chunk=cfg.quant_chunk or None,
            pmean_fusion=cfg.pmean_fusion,
            rs_ag_chunks=cfg.rs_ag_chunks,
            device_metrics=cfg.device_metrics,
            model_kwargs=mk or None,
        )

    def _attn_model_kwargs(self) -> dict:
        """Snapshot the attention implementation into the step closure at
        BUILD time. The process-global default (``set_default_attention_impl``)
        is only a fallback read at trace time — a second Trainer constructed
        before this one's step traces must not flip this one's attention
        (ADVICE r2)."""
        import inspect  # noqa: PLC0415

        if "attn_impl" in inspect.signature(self.model.apply).parameters:
            return {"attn_impl": "flash" if self.cfg.flash_attention else "xla"}
        return {}

    def _ckpt_meta(self) -> dict:
        """Layout tag written with every checkpoint. Interleaved pipeline
        storage permutes block order on disk (vit_pp device-major layout), so
        a ckpt is only loadable under the SAME pp/pp_interleave — the tag
        lets resume refuse a mismatch instead of silently training with
        permuted blocks. AdamW additionally stamps its decay mask (ADVICE
        r3): the opt-state SHAPES are mask-independent, so a resume under a
        different mask would succeed and silently change the update math."""
        cfg = self.cfg
        meta = {"pp": cfg.pp, "pp_interleave": cfg.pp_interleave}
        if cfg.optimizer == "adamw":
            meta["adamw_decay_mask"] = cfg.adamw_decay_mask
        if self._lr_scale != 1.0:
            # auto-recovery backoff survives preemption: a --resume that
            # replayed the UNSCALED schedule would re-diverge identically
            meta["lr_scale"] = self._lr_scale
        # mesh-shape portability stamp (docs/resilience.md "Elastic
        # training"): the dp extent the state is laid out for, the process
        # count (the sampler's shard count), and the logical param length
        # — what a restore onto a DIFFERENT world size needs to remap the
        # ZeRO-1/EF flat layouts deterministically
        from tpu_dist.elastic.remap import elastic_stamp  # noqa: PLC0415

        meta["elastic"] = elastic_stamp(
            self.n_data, mesh_lib.process_count(), self._params_len
        )
        return meta

    def _mid_epoch_position(self, steps_done: int) -> dict:
        """The data-position stamps of a mid-epoch snapshot. The legacy
        triple (step, GLOBAL batch size, seed) pins the position exactly
        for a same-world resume; ``mid_epoch_examples`` (the consumed
        prefix of the epoch permutation — entry offset plus steps since)
        and ``mid_epoch_procs`` make it world-portable: a resume at a
        different process count re-partitions ``order[examples:]`` over
        the new shards so nothing is dropped or double-seen."""
        cfg = self.cfg
        # clamp to the dataset size: the final batch of a drop_last=False
        # epoch is wrap-around padded, so step*batch can overshoot N — an
        # unclamped stamp would make the elastic resume's set_offset raise
        # at exactly the moment the feature exists for (offset == N means
        # "nothing left of this epoch", which is the truth)
        consumed = min(
            self._epoch_start_examples + steps_done * cfg.batch_size,
            len(self.train_data[0]),
        )
        out = {
            "mid_epoch_step": int(steps_done),
            "mid_epoch_batch_size": cfg.batch_size,
            "mid_epoch_seed": cfg.seed or 0,
            "mid_epoch_procs": mesh_lib.process_count(),
            "mid_epoch_examples": int(consumed),
        }
        # carry the final dispatched step's metrics when they describe
        # exactly this position: an interrupt that lands after an epoch's
        # LAST step resumes with nothing left to run, and without this
        # stamp the epoch record (loss above all) would silently vanish
        stamped = self._step_metrics
        prog = self._progress
        if (
            stamped is not None
            and stamped[0] == prog[1]
            and stamped[1] == int(steps_done)
        ):
            try:
                out["mid_epoch_metrics"] = _fetch_metrics(stamped[2])
            except RuntimeError:  # tpu-dist: ignore[TD006] — best-effort
                # garnish on the emergency snapshot: a donated/deleted
                # device buffer must never block the save itself (the
                # record then degrades to the pre-fix lossless-but-
                # lossy-logging behavior instead of dying mid-SIGTERM)
                pass
        return out

    def _check_ckpt_layout(self, path: str) -> None:
        self._check_ckpt_meta(ckpt_lib.read_meta(path), path)

    def _check_ckpt_meta(self, meta: dict, path: str) -> None:
        """Config-mismatch stamp checks. Everything here raises the typed
        :class:`ConfigMismatchError` — OPERATOR errors a restore must not
        fall past. A world-size change deliberately does NOT land here: it
        surfaces as :class:`ElasticShapeMismatch` from the checkpoint
        layer and is handled by the elastic remapper (docs/resilience.md
        "Elastic training"), so shrinking the pod no longer pattern-
        matches to config drift."""
        from tpu_dist.elastic.errors import ConfigMismatchError  # noqa: PLC0415

        cfg = self.cfg
        ck_v = meta.get("pp_interleave")
        ck_pp = meta.get("pp")
        if ck_v is None:
            # pre-layout-tag checkpoint: blocks are in logical depth order —
            # loadable only by non-interleaved configs
            if cfg.pp_interleave > 1:
                raise ConfigMismatchError(
                    f"checkpoint {path} has no pipeline-layout tag (written "
                    f"before interleaving existed, logical block order) — it "
                    f"cannot be resumed with pp_interleave={cfg.pp_interleave}"
                )
            return
        if ck_v != cfg.pp_interleave or (
            (ck_v > 1 or cfg.pp_interleave > 1) and ck_pp != cfg.pp
        ):
            raise ConfigMismatchError(
                f"checkpoint {path} was written with pp={ck_pp}, "
                f"pp_interleave={ck_v} — its block storage order is "
                f"layout-specific; resume with the same flags (got "
                f"pp={cfg.pp}, pp_interleave={cfg.pp_interleave})"
            )
        if cfg.optimizer == "adamw":
            ck_mask = meta.get("adamw_decay_mask")
            if ck_mask is None:
                # pre-stamp AdamW checkpoint: can't know which mask trained
                # it — warn rather than block (resume stays possible, but
                # the operator is told the math may shift)
                rank0_print(
                    f"WARNING: checkpoint {path} predates the "
                    "adamw_decay_mask stamp; resuming with "
                    f"--adamw_decay_mask {cfg.adamw_decay_mask} — if the "
                    "run was trained with a different mask, weight decay "
                    "on bias/norm leaves silently changes from here on"
                )
            elif ck_mask != cfg.adamw_decay_mask:
                raise ConfigMismatchError(
                    f"checkpoint {path} was trained with adamw_decay_mask="
                    f"{ck_mask!r} but this run uses "
                    f"{cfg.adamw_decay_mask!r} — the opt-state shapes are "
                    "identical, so resuming would silently change which "
                    "leaves get weight decay mid-training; pass "
                    f"--adamw_decay_mask {ck_mask} to resume faithfully"
                )

    def _check_mesh_host_layout(self) -> None:
        """Refuse multi-host meshes whose model axes cross hosts: TP/EP/PP
        collectives must ride ICI, not DCN (SURVEY §2.2 N1; device_mesh
        builds host-major so any group dividing the local device count is
        intra-host — this catches the layouts where it can't be)."""
        if jax.process_count() <= 1:
            return
        cfg = self.cfg
        hard = [
            a for a, w in (
                (mesh_lib.MODEL_AXIS, cfg.tp),
                (mesh_lib.EXPERT_AXIS, cfg.ep),
                (mesh_lib.PIPE_AXIS, cfg.pp),
            )
            if w > 1 and a in self.mesh.axis_names
        ]
        if hard and not mesh_lib.model_axes_intra_host(self.mesh, hard):
            raise ValueError(
                f"mesh lays model axes {hard} across hosts (DCN): with "
                f"{jax.local_device_count()} devices/host, keep "
                f"tp*ep*pp ways a divisor of the local device count"
            )
        if (
            cfg.sp > 1
            and mesh_lib.SEQ_AXIS in self.mesh.axis_names
            and not mesh_lib.model_axes_intra_host(self.mesh, [mesh_lib.SEQ_AXIS])
        ):
            # ring attention still works over DCN, just slower — warn only
            rank0_print(
                "WARNING: sequence-parallel axis spans hosts; ring attention "
                "will run over DCN instead of ICI"
            )

    def _place_state(self, state: TrainState) -> TrainState:
        """Mesh placement for every supported layout: replicated (default),
        per-leaf TP shardings, ZeRO-1 flat-sharded optimizer state, and the
        data-axis-sharded int8_ef residuals (placed apart from the
        replicated bulk — they are per-replica by construction)."""
        cfg = self.cfg
        ef = state.ef
        if ef:
            from tpu_dist.train.step import ef_state_spec  # noqa: PLC0415

            ef = mesh_lib.place_host_tree(
                self.mesh, ef,
                ef_state_spec(
                    cfg.grad_compression, zero1=cfg.shard_weight_update
                ),
            )
            state = state._replace(ef=())
            return self._place_state_bulk(state)._replace(ef=ef)
        return self._place_state_bulk(state)

    def _place_state_bulk(self, state: TrainState) -> TrainState:
        cfg = self.cfg
        rep = mesh_lib.replicated(self.mesh)
        if self._fsdp_specs is not None:  # FSDP: params+momentum data-sharded
            return TrainState(
                params=mesh_lib.place_host_tree(
                    self.mesh, state.params, self._fsdp_specs
                ),
                bn_state=mesh_lib.place_host_tree(self.mesh, state.bn_state),
                opt_state=mesh_lib.place_host_tree(
                    self.mesh, state.opt_state, self._fsdp_opt_specs
                ),
                step=mesh_lib.place_host_tree(self.mesh, state.step),
            )
        if self._param_specs is not None:  # TP/EP/PP per-leaf shardings
            # place_host_tree also covers the multi-host case, where
            # device_put cannot target non-addressable model shards.
            # Optimizer state may not mirror the param tree (AdamW) —
            # its layout comes from the optimizer.
            return TrainState(
                params=mesh_lib.place_host_tree(
                    self.mesh, state.params, self._param_specs
                ),
                bn_state=mesh_lib.place_host_tree(self.mesh, state.bn_state),
                opt_state=mesh_lib.place_host_tree(
                    self.mesh, state.opt_state,
                    self.optimizer.state_specs(self._param_specs),
                ),
                step=mesh_lib.place_host_tree(self.mesh, state.step),
            )
        if cfg.shard_weight_update:
            # replace the per-leaf init tree BEFORE replication — device_put
            # of the full mu/nu (2× params in f32) to every chip just to
            # discard it for the flat template would spike init HBM on
            # exactly the models ZeRO-1 exists for
            opt_np = state.opt_state
            placed = jax.device_put(state._replace(opt_state=()), rep)
            from tpu_dist.train.step import init_sharded_opt_state  # noqa: PLC0415

            tmpl = init_sharded_opt_state(
                state.params, self.mesh, optimizer=self.optimizer
            )
            # fresh init (per-leaf tree layout) vs a restored flat state:
            # restored matches the template's structure AND leaf shapes
            # (SGD: one 1-D vector; AdamW: {mu, nu} vectors + count scalar)
            t_leaves, t_def = jax.tree_util.tree_flatten(tmpl)
            o_leaves, o_def = jax.tree_util.tree_flatten(opt_np)
            if t_def == o_def and all(
                getattr(o, "shape", None) == t.shape
                for o, t in zip(o_leaves, t_leaves)
            ):
                # restored flat state: re-place each buffer with the
                # template's shard layout (a wrong-width checkpoint never
                # reaches here — the ckpt layer's shape validation raises
                # first)
                opt = jax.tree_util.tree_map(
                    lambda o, t: jax.device_put(np.asarray(o), t.sharding),
                    opt_np, tmpl,
                )
            else:
                opt = tmpl  # fresh init (per-leaf tree layout) → flat zeros
            return placed._replace(opt_state=opt)
        return jax.device_put(state, rep)

    # -- loops ---------------------------------------------------------------

    def train_epoch(
        self, epoch: int, start_step: int = 0, start_examples: int = 0
    ) -> dict:
        if self._fused_runner is not None:
            if start_step or start_examples:
                raise ValueError(
                    "mid-epoch resume (checkpoint carries mid_epoch_step="
                    f"{start_step or start_examples}) is not possible with "
                    "--fused_epoch: the whole epoch is one compiled call; "
                    "resume without --fused_epoch to continue from the "
                    "exact batch"
                )
            return self._train_epoch_fused(epoch)
        cfg = self.cfg
        self.train_sampler.set_epoch(epoch)  # shuffle correctness (tutorials/2:§2)
        if start_examples:
            # elastic mid-epoch re-entry: skip the old world's consumed
            # prefix of the epoch permutation and re-partition the
            # remainder over THIS world's shards (exactness argument in
            # sampler.set_offset; set_epoch above cleared any prior offset
            # so only the resumed epoch is shortened)
            self.train_sampler.set_offset(start_examples)
        self._epoch_start_examples = start_examples
        lr = self._lr(epoch)
        losses = AverageMeter("Loss", ":.4e")  # epoch-avg of the logged steps
        images_seen = 0
        t0 = time.time()
        nb = len(self.train_loader)
        metrics = {}
        # Step-phase split on EXISTING sync points only (docs/observability
        # .md): data-wait = blocking in the loader iterator, dispatch = the
        # train_step call (async enqueue; step 0 also holds the compile),
        # host-fetch = the metric device_get the loop already does. No new
        # device_get/block_until_ready enters the hot loop — TD106 and the
        # fetch-count test pin that.
        timer = StepTimer(warmup_steps=1)  # lap 0 would be the compile step
        phase = {"data": 0.0, "dispatch": 0.0, "fetch": 0.0}
        hb = self._heartbeat
        steps_run = 0
        # goodput baselines: compile seconds and ckpt time spent DURING
        # this epoch are attributed to their own buckets and subtracted
        # out of the epoch's productive remainder (obs/goodput.py)
        compile_s0 = counters_lib.get("compile.seconds")
        ckpt_s0 = self._goodput.window_value("ckpt")

        def timed_batches(src):
            it = iter(src)
            while True:
                t_w = time.perf_counter()
                try:
                    item = next(it)
                except StopIteration:
                    return
                d = time.perf_counter() - t_w
                phase["data"] += d
                spans_lib.add_event("train/data_wait", t_w, d, epoch=epoch)
                yield item

        # (state, epoch, completed steps, epoch_complete) published as ONE
        # attribute so an interrupt can never observe a half-updated pair —
        # _emergency_save reads ONLY this to decide what to snapshot
        self._progress = (self.state, epoch, start_step, False)
        for step, (images, labels) in enumerate(
            timed_batches(self.train_loader.iter_from(start_step)),
            start=start_step,
        ):
            if cfg.steps_per_epoch is not None and step >= cfg.steps_per_epoch:
                break
            if self._profiler is not None:
                # capture state machine BEFORE dispatch, so a window
                # opened here covers whole steps; host-side bookkeeping
                # only (TD108 pins that the traced step is unchanged)
                ev = self._profiler.on_step(self._global_step)
                if ev is not None:
                    self._note_profile_event(ev, epoch, step)
            self._global_step += 1
            t_d = time.perf_counter()
            new_state, metrics = self.train_step(self.state, images, labels, lr)
            d_d = time.perf_counter() - t_d
            phase["dispatch"] += d_d
            spans_lib.add_event(
                # only THIS Trainer's very first dispatch holds the trace/
                # compile — epoch 2's step 0 is a plain dispatch and must
                # not read as a retrace in the exported timeline
                "train/dispatch" if self._step_traced else "train/compile+dispatch",
                t_d, d_d, step=step,
            )
            if not self._step_traced:
                # first dispatch: the executable exists now — capture XLA's
                # cost accounting once (host-side abstract re-trace, no
                # device work) into the flops/bytes gauges + the per-epoch
                # MFU below. new_state, not state: state's buffers were
                # just donated to the step.
                self._capture_step_cost(new_state, images, labels, lr)
            self._step_traced = True
            if self._compile_watch.observe(context=f"epoch {epoch} step {step}"):
                # the executable cache grew after the first trace: a mid-run
                # retrace (shape/dtype drift) — a full XLA compile stall on
                # every host; counter + rank-0 warning live in the watcher
                # itself (the serving engine shares them), surfaced
                # per-epoch by `obs summarize`
                if (
                    self._profiler is not None
                    and "retrace" in self._profile_triggers
                    and mesh_lib.is_primary()
                ):
                    # catch the post-retrace steps on the device timeline
                    self._profiler.arm("retrace")
            self._step_metrics = (epoch, step + 1, metrics)
            self._progress = (new_state, epoch, step + 1, False)
            self.state = new_state
            images_seen += cfg.batch_size
            steps_run += 1
            timer.tick()
            if hb is not None:
                hb.beat(epoch=epoch, step=step)
            if self._flight is not None:
                # step-boundary slot (one atomic pwrite + counter delta):
                # the ring of a SIGKILLed rank ends exactly at the last
                # completed step — readable after the hardest of kills
                self._flight.step(epoch, step)
            if self._exporter is not None:
                # live exposition at the SAME step-grain throttle as the
                # heartbeat: inside the window only the in-memory HTTP
                # snapshot is (not even) refreshed — the throttle check is
                # the whole per-step cost
                self._export_live()
            if faults.active() is not None:  # zero-cost when no --fault_plan
                self._apply_step_faults(epoch, step, lr)
            want_save = (
                cfg.mid_epoch_save_every
                and cfg.ckpt_dir
                and (step + 1) % cfg.mid_epoch_save_every == 0
            )
            want_log = step % cfg.log_every == 0
            # ONE device fetch serves the snapshot's NaN guard AND the log
            # line — neither issues its own per-key sync
            t_f = time.perf_counter()
            m = _fetch_metrics(metrics) if (want_save or want_log) else None
            if m is not None:
                phase["fetch"] += time.perf_counter() - t_f
                # health layer rides the SAME host copy: device_stats
                # record, anomaly detection (incl. the nonfinite finding,
                # logged BEFORE the NaN guard below raises), per-step
                # TensorBoard scalars — no additional device traffic
                self._observe_health(epoch, step, nb, m)
            if want_save:
                # periodic EXACT snapshot (kill-9 safety for long epochs):
                # same stamp as the interrupt path — ckpt_{epoch} carries
                # the step offset until the clean end-of-epoch save
                # overwrites it. Rides the async writer when configured.
                # NaN guard FIRST: every other save path refuses to publish
                # a poisoned state, and this one must too (the log_every
                # guard below may not have run since divergence).
                if cfg.nan_guard and not np.isfinite(m["loss"]):
                    raise TrainingDivergedError(
                        f"non-finite loss {m['loss']} at epoch "
                        f"{epoch} step {step} (lr={lr}) — caught at the "
                        f"mid-epoch snapshot boundary before writing it; "
                        f"restore from ckpt_dir to recover"
                    )
                with self._goodput.timed("ckpt"):
                    self._ckpt_io().save(
                        cfg.ckpt_dir, new_state, epoch, cfg.keep_last_ckpts,
                        extra_meta={**self._ckpt_meta(),
                                    **self._mid_epoch_position(step + 1)},
                    )
            if want_log:
                if cfg.nan_guard and not np.isfinite(m["loss"]):
                    raise TrainingDivergedError(
                        f"non-finite loss {m['loss']} at epoch {epoch} step {step} "
                        f"(lr={lr}); restore from ckpt_dir to recover"
                    )
                losses.update(m["loss"], cfg.batch_size)
                # reference per-step line (distributed.py:104-111), plus
                # the health norms when --device_metrics computed them
                rank0_print(
                    f"Epoch:[{epoch}/{cfg.epochs}] step:[{step}/{nb}] "
                    f"lr={lr:.5f} loss={m['loss']:.4f} "
                    f"acc1={m['acc1']:.2f} acc5={m['acc5']:.2f}"
                    + (
                        f" gnorm={m['grad_norm']:.3e} "
                        f"upd={m['update_ratio']:.2e}"
                        if "grad_norm" in m else ""
                    )
                )
            if preemption.requested():
                # cooperative SIGTERM: the in-flight step is finished and
                # published in _progress — fit() runs the emergency-save
                # discipline on the way out (docs/resilience.md)
                raise PreemptedError(
                    f"SIGTERM observed at epoch {epoch} after step {step} "
                    f"— shutting down at the step boundary"
                )
        jax.block_until_ready(self.state.params)
        # end-of-epoch guard: catches divergence between logged steps BEFORE
        # fit() writes a checkpoint of the poisoned state. One fetch, reused
        # for the returned epoch metrics below.
        if metrics:
            out = _fetch_metrics(metrics)
        elif steps_run == 0 and (start_step or start_examples):
            # the interrupt landed after this epoch's LAST step: nothing
            # was left to run, so replay the snapshot's stamped final-step
            # metrics — the epoch record (loss above all) must match the
            # uninterrupted run, not vanish
            out = dict(self._resume_metrics or {})
        else:
            out = {}
        if cfg.nan_guard and out and not np.isfinite(out["loss"]):
            raise TrainingDivergedError(
                f"non-finite loss {out['loss']} at end of epoch {epoch} "
                f"(lr={lr}); restore from ckpt_dir to recover"
            )
        if cfg.debug_replica_check:
            from tpu_dist.metrics.consistency import check_replicated  # noqa: PLC0415

            check_replicated(self.state.params, "params")
            check_replicated(self.state.bn_state, "bn_state")
        dt = time.time() - t0
        ips = images_seen / dt if dt > 0 else 0.0
        # reference epoch wall-time print (distributed.py:113-115)
        rank0_print(
            f"Epoch {epoch} done in {dt:.2f}s ({ips:.0f} img/s, avg loss {losses.avg:.4f})"
        )
        out.update(epoch_time=dt, images_per_sec=ips)
        # step-phase summary: tail latency + where the wall time went
        # (host clocks only — no device sync was added to produce these)
        stall = phase["data"] / dt if dt > 0 else 0.0
        out.update(
            steps=steps_run,
            data_wait_s=round(phase["data"], 4),
            dispatch_s=round(phase["dispatch"], 4),
            host_fetch_s=round(phase["fetch"], 4),
            data_stall_frac=round(stall, 4),
        )
        pct = timer.percentiles()
        if pct:
            out.update(
                step_time_p50=round(pct["p50"], 6),
                step_time_p95=round(pct["p95"], 6),
                step_time_p99=round(pct["p99"], 6),
            )
            rank0_print(
                f"  step p50/p95/p99 {pct['p50'] * 1e3:.1f}/"
                f"{pct['p95'] * 1e3:.1f}/{pct['p99'] * 1e3:.1f} ms, "
                f"data stall {stall:.1%}"
            )
        # MFU from the captured XLA flop count over the steady-state step
        # time (p50 excludes the compile step; fallback: epoch mean). None
        # on unknown chips (CPU emulation) — never a made-up figure.
        if self._step_cost and steps_run:
            mfu = costmodel_lib.mfu(
                self._step_cost.get("flops_per_step"),
                pct["p50"] if pct else dt / steps_run,
                self.n_devices,
            )
            if mfu is not None:
                out["mfu"] = mfu
                rank0_print(f"  MFU {mfu:.1%}")
        self._publish_memory_gauges()
        # goodput attribution for this epoch's wall time: the measured
        # stall + the compile/ckpt seconds that landed inside it, with the
        # remainder — the step loop actually stepping — as productive.
        # The in-epoch remainder definition keeps the ledger's sum-equals-
        # wall-clock invariant exact instead of approximately true.
        compile_d = max(counters_lib.get("compile.seconds") - compile_s0, 0.0)
        ckpt_d = max(self._goodput.window_value("ckpt") - ckpt_s0, 0.0)
        self._goodput.add("data_stall", phase["data"])
        self._goodput.add("compile", compile_d)
        self._goodput.add(
            "productive", dt - phase["data"] - compile_d - ckpt_d
        )
        counters_lib.inc("train.epochs")
        counters_lib.inc("train.steps", steps_run)
        return out

    def _train_epoch_fused(self, epoch: int) -> dict:
        """One jit call for the whole epoch (tpu_dist/train/epoch.py)."""
        cfg = self.cfg
        # no per-step grain inside the jit: an interrupt mid-epoch falls
        # back to the previous clean boundary
        self._progress = (self.state, epoch, 0, False)
        lr = self._lr(epoch)
        compile_s0 = counters_lib.get("compile.seconds")
        t0 = time.time()
        t_pc = time.perf_counter()
        self.state, metrics = self._fused_runner(
            self.state, *self._fused_data, lr, epoch
        )
        m = _fetch_metrics(metrics)  # one transfer; blocks on completion
        # the fused epoch has no step grain: one span covers the whole
        # compiled call (compile included on its first trip)
        spans_lib.add_event(
            "train/fused_epoch", t_pc, time.perf_counter() - t_pc, epoch=epoch
        )
        counters_lib.inc("train.epochs")
        if self._heartbeat is not None:
            self._heartbeat.beat(epoch=epoch, phase="fused_epoch", force=True)
        if self._flight is not None:
            # the fused path's only grain: one step slot per epoch call
            self._flight.step(epoch, None)
        if cfg.nan_guard and not np.isfinite(m["loss"]):
            raise TrainingDivergedError(
                f"non-finite loss {m['loss']} in fused epoch {epoch} (lr={lr}); "
                f"restore from ckpt_dir to recover"
            )
        dt = time.time() - t0
        n_images = int(self._fused_data[0].shape[0])
        ips = n_images / dt if dt > 0 else 0.0
        rank0_print(
            f"Epoch:[{epoch}/{cfg.epochs}] (fused) lr={lr:.5f} "
            f"loss={m['loss']:.4f} acc1={m['acc1']:.2f} acc5={m['acc5']:.2f}"
        )
        rank0_print(f"Epoch {epoch} done in {dt:.2f}s ({ips:.0f} img/s)")
        # device-resident data: there IS no input pipeline to stall on
        m.update(epoch_time=dt, images_per_sec=ips, data_stall_frac=0.0)
        # cost/MFU: XLA counts the epoch program's step-scan body ONCE, so
        # the raw count already IS per-step flops (loop_trips=1 — the
        # epoch-level shuffle/pad epilogue is the only omission); the wall
        # side normalizes to one step by the trip count
        from tpu_dist.train.epoch import fused_steps_per_epoch  # noqa: PLC0415

        trips = fused_steps_per_epoch(n_images, cfg.batch_size)
        self._capture_step_cost(
            self.state, *self._fused_data, lr, epoch,
            runner=self._fused_runner, loop_trips=1,
        )
        if self._step_cost and self._step_traced:
            # MFU only from compile-free epochs: the first fused call's dt
            # includes the whole-epoch XLA compile (often several epochs'
            # worth of wall time), and a 5-10x-understated epoch-0 MFU
            # would pollute mfu_mean and the compare gate — the same
            # discipline as the per-step path's warmup-excluded p50
            mfu = costmodel_lib.mfu(
                self._step_cost.get("flops_per_step"), dt / trips,
                self.n_devices,
            )
            if mfu is not None:
                m["mfu"] = mfu
                rank0_print(f"  MFU {mfu:.1%}")
        self._step_traced = True
        self._publish_memory_gauges()
        # goodput: device-resident data means no stall bucket; the whole
        # call minus its compile time is productive step time
        compile_d = max(counters_lib.get("compile.seconds") - compile_s0, 0.0)
        self._goodput.add("compile", compile_d)
        self._goodput.add("productive", dt - compile_d)
        # anomaly detection at the only grain the fused path has (the
        # epoch-mean loss); no per-step norms here — --device_metrics is
        # refused with --fused_epoch at construction
        self._observe_health(epoch, None, 0, m)
        if preemption.requested():
            # the fused epoch has no step grain — the epoch boundary is the
            # first cooperative point a SIGTERM can be honored at. The epoch
            # IS complete here (metrics fetched above block on it), so
            # publish that before raising: _emergency_save must file the
            # state under THIS epoch, not discard it as "0 steps done"
            self._progress = (self.state, epoch, 0, True)
            raise PreemptedError(
                f"SIGTERM observed during fused epoch {epoch} — shutting "
                f"down at the epoch boundary"
            )
        return m

    def _lr(self, epoch: int) -> float:
        """Scheduled LR times the auto-recovery backoff scale."""
        return self.lr_schedule(epoch) * self._lr_scale

    def _capture_step_cost(self, *args, runner=None, loop_trips=None) -> None:
        """ONE XLA cost-analysis capture per Trainer (obs/costmodel.py):
        an abstract host-side re-trace of the already-compiled step —
        no device dispatch, no second compile — published as the
        ``device.flops_per_step``/``device.bytes_per_step`` gauges and
        held for the per-epoch MFU. ``{}`` marks a failed capture so it
        is never retried in the hot loop."""
        if self._step_cost is not None:
            return
        cost = costmodel_lib.analyze_jitted(
            runner if runner is not None else self.train_step,
            *args,
            loop_trips=(
                loop_trips if loop_trips is not None
                else self.cfg.grad_accu_steps
            ),
        )
        self._step_cost = cost or {}
        costmodel_lib.publish(cost)
        self._capture_memory_ledger(
            runner if runner is not None else self.train_step, args
        )

    def _capture_memory_ledger(self, jitted, args) -> None:
        """ONE HBM-ledger snapshot per Trainer, at first dispatch beside
        the flops capture (obs/memory.py): the live-buffer census
        reconciled against the allocator (attributed + unattributed ==
        bytes_in_use, exact), the construction-time static ledger, and —
        when telemetry consumers exist — the ``memory_analysis()``
        waterfall of the step, which costs one extra host-side AOT
        compile (booked into ``compile.seconds`` by the monitoring
        listener) and is therefore skipped on telemetry-less runs.
        Published as ``mem.*`` gauges and one ``memory`` history record
        (schema v11)."""
        if self._mem_record is not None:
            return
        from tpu_dist.obs import memory as memory_lib  # noqa: PLC0415

        xla = None
        if self._history is not None or self._exporter is not None:
            xla = costmodel_lib.memory_analysis_jitted(jitted, *args)
        rec = memory_lib.ledger(static=self._mem_static, xla=xla)
        if self._mem_feasibility:
            rec["feasibility"] = self._mem_feasibility
        memory_lib.publish_ledger(rec)
        self._mem_record = rec
        if self._history is not None:
            self._history.log("memory", **rec)
        rank0_print("=> " + memory_lib.summary_line(rec))

    def _publish_memory_gauges(self) -> None:
        """Epoch-grain peak-HBM gauges from the runtime allocator's own
        counters (the true device numbers on TPU/GPU, now across ALL
        local devices — the scalar keys are the WORST chip, with min/
        skew gauges beside them; nothing is published on CPU, where the
        backend keeps no stats). ``mem.headroom_frac`` — the free
        fraction of the worst chip's limit — feeds the built-in
        ``memory_headroom_low`` alert rule."""
        mem = costmodel_lib.device_memory_stats()
        if mem:
            for key, value in mem.items():
                counters_lib.set_gauge(f"mem.{key}", value)
            lim = mem.get("bytes_limit")
            use = mem.get("bytes_in_use")
            if lim and isinstance(use, (int, float)):
                counters_lib.set_gauge(
                    "mem.headroom_frac", round(1.0 - use / lim, 4)
                )

    def _observe_health(self, epoch: int, step, nb: int, m: dict) -> None:
        """Per-fetch health layer over the metrics the loop already holds
        on the host — zero additional device traffic (TD107's fetch-count
        half). Writes the ``device_stats`` history record, per-step
        TensorBoard scalars, and feeds the anomaly detector; findings
        become rank-0 warnings + ``anomaly`` records, and
        ``--anomaly_action snapshot`` writes an exact mid-epoch
        checkpoint (emergency-snapshot discipline) while the state is
        still finite. The detector state is deterministic and the fed
        values are replica-identical (post-pmean), so every process takes
        the same snapshot branch — the collective save stays aligned."""
        cfg = self.cfg
        history = self._history
        if history is not None and "grad_norm" in m:
            history.log(
                "device_stats", epoch=epoch, step=step,
                **{
                    k: m[k]
                    for k in (
                        "grad_norm", "param_norm", "update_ratio",
                        "nonfinite_grads",
                    )
                    if k in m
                },
            )
        if self._tb is not None and step is not None:
            gs = epoch * nb + step
            self._tb.add_scalar("step/loss", m["loss"], gs)
            for k in ("grad_norm", "update_ratio"):
                if k in m:
                    self._tb.add_scalar(f"step/{k}", m[k], gs)
        # live layer at the fetch cadence: the health norms land in the
        # next exposition, and the step-grain alert rules (grad-norm
        # ceiling) see the SAME host copy — zero additional device traffic
        if self._exporter is not None:
            for k in ("grad_norm", "param_norm", "update_ratio"):
                if k in m:
                    self._export_rollup[f"device.{k}"] = m[k]
        if self._alerts is not None:
            fired = self._alerts.observe(m)
            if fired:
                self._fire_alerts(fired, epoch, step)
        if self._anomaly is None:
            return
        findings = self._anomaly.observe(
            epoch=epoch, step=step, loss=m.get("loss"),
            grad_norm=m.get("grad_norm"), nonfinite=m.get("nonfinite_grads"),
        )
        for f in findings:
            rank0_print(
                f"WARNING: anomaly {f['anomaly']} at epoch {epoch} step "
                f"{step}: value {f.get('value')}"
                + (
                    f" = {f['ratio']}x the rolling median {f['median']}"
                    if f.get("ratio") is not None else ""
                )
            )
            if history is not None:
                history.log("anomaly", **f)
            if self._flight is not None:
                self._flight.record(
                    "anomaly", anomaly=f["anomaly"], epoch=epoch, step=step,
                )
            counters_lib.inc("anomaly.findings")
            if (
                self._profiler is not None
                and "anomaly" in self._profile_triggers
                and mesh_lib.is_primary()
            ):
                # arm a bounded device capture: the NEXT steps — the ones
                # that explain whether the spike was data or numerics —
                # land on an XLA timeline (obs/profile.py caps apply)
                self._profiler.arm(f"anomaly_{f['anomaly']}")
            if (
                cfg.anomaly_action == "snapshot"
                and cfg.ckpt_dir
                and f["anomaly"] in ("loss_spike", "grad_norm_explosion")
            ):
                # pre-divergence forensic snapshot: the spike kinds fire
                # on FINITE values only, so the state is still safe to
                # publish. Written OFF the ckpt_{N} namespace (no "ckpt_"
                # substring — the discovery regexes cannot match it), so
                # the next periodic/end-of-epoch save can never overwrite
                # it, prune never removes it, and resume never silently
                # picks it — the pre-divergence bits stay on disk for as
                # long as the operator wants them. Stamped with the
                # finding + the exact position (mid_epoch_* for the
                # streaming path; the fused path's only grain is the
                # epoch boundary), so a manual rollback knows where it
                # re-enters. Synchronous plain write even under
                # --async_ckpt: a rare forensic event, not hot-path I/O.
                extra = {**self._ckpt_meta(), "anomaly": f["anomaly"]}
                if step is not None:
                    extra.update(self._mid_epoch_position(step + 1))
                stem = f"anomaly_{epoch}" + (
                    f"_s{step + 1}" if step is not None else ""
                )
                with self._goodput.timed("ckpt"):
                    if cfg.sharded_ckpt:
                        ckpt_lib.save_sharded(
                            cfg.ckpt_dir, self.state, epoch,
                            extra_meta=extra, stem=stem,
                        )
                    else:
                        ckpt_lib.save(
                            cfg.ckpt_dir, self.state, epoch,
                            extra_meta=extra, name=f"{stem}.npz",
                        )
                counters_lib.inc("anomaly.snapshots")
                rank0_print(
                    f"=> anomaly snapshot written ({stem}, epoch {epoch}"
                    + (f" step {step + 1}" if step is not None else "")
                    + ") — pre-divergence state preserved off the resume "
                    "namespace"
                )

    def _export_live(self, force: bool = False) -> None:
        """Publish one OpenMetrics exposition (``obs/export.py``): the
        counter registry, the latest epoch rollup + health norms, the
        goodput totals so far, the heartbeat age, and the per-rule
        ``alert_active`` gauges. Throttled HERE (not just in the writer)
        so the per-step cost inside the window is one clock read — the
        render/snapshot work only happens when something will publish."""
        if self._exporter is None:
            return
        now = time.monotonic()
        if not force and now - self._export_t < self._exporter.min_interval:
            return
        self._export_t = now
        values = dict(counters_lib.snapshot())
        values.update(self._export_rollup)
        # run-level goodput totals over the closed windows so far — the
        # same numbers the ledger's final record will carry
        totals = self._goodput.run_totals()
        for b in goodput_lib.ALL_BUCKETS:
            values[f"goodput.{b}_s"] = totals[f"{b}_s"]
        values["goodput.goodput_frac"] = totals["goodput_frac"]
        if self._heartbeat is not None:
            age = self._heartbeat.age()
            if age != float("inf"):
                values["heartbeat.age_s"] = round(age, 3)
        labeled = (
            {"alert_active": self._alerts.active()}
            if self._alerts is not None else None
        )
        self._exporter.update(values, labeled, force=True)

    def _epoch_live_update(self, epoch: int, last: dict) -> None:
        """Close of an epoch for the live layer: refresh the exporter's
        rollup (throughput, percentiles, stall, MFU, eval top-1), run the
        epoch-grain alert rules over the rollup + goodput fraction +
        counter snapshot (the delta rules — mid-run retraces — read the
        monotonic counters), and force an exposition so a scraper sees
        the epoch boundary immediately."""
        rollup = self._export_rollup
        rollup["train.epoch"] = epoch
        for key in ("images_per_sec", "loss", "mfu", "data_stall_frac",
                    "epoch_time"):
            if isinstance(last.get(key), (int, float)):
                rollup[f"train.{key}"] = last[key]
        for key in ("step_time_p50", "step_time_p95", "step_time_p99"):
            if isinstance(last.get(key), (int, float)):
                rollup[f"train.{key}_s"] = last[key]
        if isinstance(last.get("val_top1"), (int, float)):
            rollup["eval.top1"] = last["val_top1"]
        if self._alerts is not None:
            window = {
                k: v for k, v in last.items() if isinstance(v, (int, float))
            }
            window["goodput_frac"] = self._goodput.run_totals()["goodput_frac"]
            window.update(counters_lib.snapshot())
            fired = self._alerts.observe(window)
            if fired:
                self._fire_alerts(fired, epoch, None)
        self._export_live(force=True)

    def _fire_alerts(self, fired: list, epoch: int, step) -> None:
        """A rule fired: rank-0 warning + ``alert`` history record
        (schema v5) + counter + exporter gauge flip (the next exposition
        carries ``alert_active{rule=...} 1``) + — for ``profile = true``
        rules — an armed triggered-profiler capture, so the steps that
        explain the breach land on an XLA timeline."""
        for a in fired:
            counters_lib.inc("alerts.fired")
            rank0_print(
                f"WARNING: ALERT {a['rule']}: {a['metric']} = {a['value']} "
                f"{a['op']} threshold {a['threshold']} (sustained "
                f"{a['sustained']} window(s))"
            )
            if self._flight is not None:
                self._flight.record(
                    "alert", rule=a["rule"], epoch=epoch,
                    **({"step": step} if step is not None else {}),
                )
            if self._history is not None:
                extra = {"epoch": epoch}
                if step is not None:
                    extra["step"] = step
                self._history.log("alert", **extra, **a)
            if (
                a.get("profile")
                and self._profiler is not None
                and mesh_lib.is_primary()
            ):
                self._profiler.arm(f"alert_{a['rule']}")
        if self._exporter is not None:
            self._export_live(force=True)

    def _note_profile_event(self, ev: dict, epoch: int, step) -> None:
        """A triggered-profiler window opened/closed/failed: rank-0 line +
        a ``profile`` history record (schema v4), so ``obs summarize`` and
        the pod report can say WHEN and WHY each capture ran. A stop
        event carrying the auto-analysis (obs/profile.py hook) peels it
        off into its own ``profile_analysis`` record + summary line +
        calibration gauges — the ``profile`` record stays the small
        when/why stamp it always was."""
        ev = dict(ev)
        analysis = ev.pop("analysis", None)
        analysis_error = ev.pop("analysis_error", None)
        if ev.get("event") == "start":
            rank0_print(
                f"=> profiler capture started ({ev.get('reason')}) at "
                f"epoch {epoch} step {step} — {ev.get('window_steps')} "
                f"step window → {ev.get('dir')}"
            )
        elif ev.get("event") == "stop":
            rank0_print(
                f"=> profiler capture done ({ev.get('reason')}, "
                f"{ev.get('steps')} step(s)) → {ev.get('dir')}"
            )
        else:
            rank0_print(
                f"WARNING: profiler capture failed ({ev.get('reason')}): "
                f"{ev.get('error')} — triggered profiling disabled for "
                "this run"
            )
        if self._history is not None:
            self._history.log("profile", epoch=epoch, **ev)
        if ev.get("event") == "stop":
            self._note_capture_analysis(
                analysis, analysis_error, epoch=epoch,
                reason=ev.get("reason"), capture_dir=ev.get("dir"),
                steps=ev.get("steps"),
            )

    def _note_capture_analysis(
        self, analysis, error, *, epoch: int, reason, capture_dir, steps,
    ) -> None:
        """The read-back half of a capture (``obs/xprof.py``): rank-0
        attribution line, ``profile_analysis`` history record (schema
        v6), and cost-model calibration gauges (``cost.calibration_*`` —
        measured category seconds divided into the predicted per-step
        FLOPs/bytes, the drift signal a later ``--auto_shard`` planner
        prices layouts with). Analysis failures were counted by the hook
        already; here they surface as a warning + an error-stamped
        record, never an exception — forensics must not kill training."""
        if analysis is None:
            if error:
                rank0_print(
                    f"WARNING: capture analysis failed ({reason}): {error}"
                )
                if self._history is not None:
                    self._history.log(
                        "profile_analysis", epoch=epoch, reason=reason,
                        dir=capture_dir, error=error,
                    )
            return
        from tpu_dist.obs import xprof as xprof_lib  # noqa: PLC0415

        cal = costmodel_lib.calibration(
            self._step_cost, analysis,
            steps=steps, n_devices=jax.local_device_count(),
        )
        if cal:
            costmodel_lib.publish_calibration(cal)
        rank0_print(
            f"=> capture analysis ({reason}): "
            + xprof_lib.summary_line(analysis)
        )
        if self._history is not None:
            rec = dict(analysis)
            if cal:
                rec["calibration"] = cal
            if steps is not None:
                rec["steps"] = steps
            self._history.log(
                "profile_analysis", epoch=epoch, reason=reason,
                dir=capture_dir, **rec,
            )
        # TD119 planner-error-tracked: every profiled run closes the
        # planner's loop — the capture's achieved per-step wall time
        # against the priced one. An --auto_shard plan is held to the
        # step time it promised; without one, this run's own compiled
        # cost is priced with the calibration just published, so the
        # drift gauge exists for every profiled run, planned or not.
        busy = analysis.get("device_busy_s")
        if steps and isinstance(busy, (int, float)) and busy > 0:
            n_dev = max(jax.local_device_count(), 1)
            achieved = busy / steps / n_dev
            predicted = (self._plan or {}).get("predicted_step_s")
            src = "plan"
            if predicted is None and self._step_cost:
                pred = costmodel_lib.predicted_step_time(
                    self._step_cost, n_devices=n_dev,
                )
                predicted = pred.get("predicted_step_s") if pred else None
                src = "step_cost"
            err = costmodel_lib.planner_error_frac(predicted, achieved)
            if err is not None:
                counters_lib.set_gauge("plan.planner_error_frac", err)
                rank0_print(
                    f"=> planner drift (TD119): predicted {predicted:g}s "
                    f"vs achieved {achieved:g}s per step — "
                    f"planner_error_frac={err:.4f} [{src}]"
                )
                if self._history is not None:
                    self._history.log(
                        "plan", epoch=epoch,
                        family=(self._plan or {}).get("family"),
                        mode=(self._plan or {}).get("mode"),
                        predicted_step_s=predicted,
                        achieved_step_s=float(f"{achieved:.4g}"),
                        planner_error_frac=err,
                        prediction_source=src,
                    )

    def _apply_step_faults(self, epoch: int, step: int, lr: float) -> None:
        """Host-side --fault_plan actions at the step grain. A matching
        ``sigterm`` clause delivered a real signal inside ``on_step`` (the
        loop's preemption check picks it up); ``nan_loss`` reports a
        divergence through the SAME error type the NaN guard uses, so the
        existing auto-recover machinery runs unmodified."""
        acts = faults.on_step(epoch, step, rank=mesh_lib.process_index())
        if faults.NAN_LOSS in acts:
            if self.cfg.nan_guard:
                raise TrainingDivergedError(
                    f"non-finite loss nan at epoch {epoch} step {step} "
                    f"(lr={lr}) [fault-injected]; restore from ckpt_dir to "
                    f"recover"
                )
            rank0_print(
                f"[faults] nan_loss injected at epoch {epoch} step {step} "
                "but --no_nan_guard is set — ignored"
            )

    def _quarantine_ckpt(self, path: str, err: Exception) -> None:
        """Rank-0 renames a failed checkpoint to ``*.corrupt`` (kept for
        forensics, invisible to every discovery function). Other processes
        only log — they will stop seeing the file once the rename lands."""
        if jax.process_index() == 0:
            try:
                dst = ckpt_lib.quarantine(path)
            except OSError:
                dst = path + ".corrupt (rename failed)"
        else:
            dst = path + ".corrupt"
        rank0_print(
            f"WARNING: checkpoint {path} failed integrity verification "
            f"({err}) — quarantined to {dst}; falling back to the next "
            "older checkpoint"
        )

    def _check_ladder_agreement(self, picked_epoch: int) -> None:
        """Multi-process resumes must agree on WHICH checkpoint the ladder
        picked: the walk runs per-process (reads and transient errors are
        local), and resuming different epochs on different processes is
        silent divergence — the one unacceptable outcome. Every process
        reaches this exact point once per _restore_latest (picked_epoch is
        -1 when nothing usable was found), so the allgather is safe."""
        if jax.process_count() <= 1:
            return
        from jax.experimental import multihost_utils  # noqa: PLC0415

        got = np.asarray(
            multihost_utils.process_allgather(np.int32(picked_epoch))
        ).ravel()
        if int(got.min()) != int(got.max()):
            raise RuntimeError(
                "processes disagree on the resume checkpoint (per-process "
                f"ladder picks: {sorted(set(int(x) for x in got))}) — a "
                "transient read error or racing quarantine made the "
                "newest-intact walk diverge; inspect ckpt_dir (quarantined "
                "*.corrupt files) and relaunch"
            )

    def _restore_latest(self):
        """Restore the newest INTACT checkpoint in the configured format.

        The retry ladder: walk newest→oldest; a candidate that is
        unreadable or fails its CRC32 stamps (``--ckpt_verify``, default
        on) is quarantined to ``*.corrupt`` with a rank-0 warning and the
        next older checkpoint is tried — a torn/bit-flipped newest file
        degrades the resume by one snapshot instead of bricking it.
        Config mismatches (pipeline layout, AdamW mask, mid-epoch
        batch/seed stamps, shape mismatches) still RAISE: those are
        operator errors, not corruption, and falling past them would
        silently resume the wrong run.

        Returns the restored epoch, or None when the dir holds nothing
        usable; raises when the dir holds only the OTHER format (a silent
        restart-from-scratch is the one unacceptable outcome)."""
        cfg = self.cfg
        if not cfg.ckpt_dir:
            return None
        if cfg.sharded_ckpt:
            list_, read_meta_, restore_ = (
                ckpt_lib.all_sharded_checkpoints,
                ckpt_lib.read_sharded_meta,
                ckpt_lib.restore_sharded,
            )
            # multi-process: deep (full-CRC) verification would have EVERY
            # process decompress the WHOLE checkpoint — n× the bytes the
            # sharded format exists to avoid. Shallow verify checks the
            # manifest/shard-set/zip directories; restore's own overlap
            # reads still surface piece-level corruption to the ladder.
            verify_ = functools.partial(
                ckpt_lib.verify_sharded, deep=jax.process_count() == 1
            )
            other = ckpt_lib.latest_checkpoint
        else:
            # plain format: verification is FUSED into restore's single
            # decompression pass (verify=True) — a standalone verify_npz
            # here would read the whole archive twice per resume
            list_, read_meta_, restore_, verify_ = (
                ckpt_lib.all_checkpoints,
                ckpt_lib.read_meta,
                functools.partial(ckpt_lib.restore, verify=cfg.ckpt_verify),
                None,
            )
            other = ckpt_lib.latest_sharded_checkpoint
        if jax.process_index() == 0:
            # a crash between open(tmp) and the atomic rename leaks a *.tmp
            # forever (plain npz, shard piece, or manifest alike); resume
            # startup is a no-write-in-flight point, so sweep here
            # (single-writer-per-file discipline)
            ckpt_lib.sweep_stale_tmp(cfg.ckpt_dir)
        candidates = list_(cfg.ckpt_dir)
        if not candidates:
            if other(cfg.ckpt_dir):
                raise ValueError(
                    f"ckpt_dir {cfg.ckpt_dir} holds checkpoints in the "
                    f"{'plain' if cfg.sharded_ckpt else 'sharded'} format "
                    f"but this run asked for the "
                    f"{'sharded' if cfg.sharded_ckpt else 'plain'} one — "
                    "flip --sharded_ckpt to match (the formats do not "
                    "auto-convert)"
                )
            self._check_ladder_agreement(-1)
            return None
        from tpu_dist.elastic import remap as elastic_remap  # noqa: PLC0415

        self._last_reshard_s = 0.0
        self._elastic_resume = None
        chosen = None
        for path, epoch in candidates:
            try:
                if cfg.ckpt_verify and verify_ is not None:
                    verify_(path)
                meta = read_meta_(path)
            except (ckpt_lib.CheckpointCorruptError,) + ckpt_lib.CKPT_READ_ERRORS as e:
                self._quarantine_ckpt(path, e)
                continue
            # config-mismatch checks on the (readable) meta: a valid-but-
            # wrong checkpoint must raise (ConfigMismatchError), not be
            # quarantined as corrupt
            self._check_ckpt_meta(meta, path)
            # mesh-shape portability: restore WITH the elastic remapper —
            # world-size-independent leaves load verbatim; the dp-extent-
            # dependent flat layouts (ZeRO-1 opt vectors, EF residuals)
            # are remapped onto THIS run's extent (elastic/remap.py).
            # A model-shape mismatch still raises (ConfigMismatchError,
            # from make_remapper's params_len check or the restore).
            remapper = elastic_remap.make_remapper(
                self.state, meta, self.n_data
            )
            t_restore = time.monotonic()
            try:
                with spans_lib.span("ckpt/restore_ladder", file=path):
                    restored = restore_(path, self.state, remap=remapper)
            except (ckpt_lib.CheckpointCorruptError,) + ckpt_lib.CKPT_READ_ERRORS as e:
                # plain format verifies CRCs HERE (fused into restore's
                # read); sharded piece-level corruption also lands here
                self._quarantine_ckpt(path, e)
                continue
            if remapper.used:
                # this restore WAS the reshard: charge its wall time to the
                # goodput recovery bucket (the __init__ caller splits it
                # out of the ckpt bucket) and count it
                self._last_reshard_s = time.monotonic() - t_restore
                counters_lib.inc("resume.resharded")
                prev_dp = (meta.get("elastic") or {}).get("dp")
                rank0_print(
                    f"=> elastic resume: remapped {len(remapper.used)} "
                    f"dp-extent-dependent leaf(s) from dp={prev_dp} onto "
                    f"dp={self.n_data} (ZeRO-1/EF flat layouts re-laid — "
                    "docs/resilience.md 'Elastic training')"
                )
            chosen = (path, epoch, meta, restored, bool(remapper.used))
            break
        self._check_ladder_agreement(chosen[1] if chosen is not None else -1)
        if chosen is None:
            rank0_print(
                f"WARNING: every checkpoint in {cfg.ckpt_dir} was corrupt "
                "and has been quarantined — starting from scratch"
            )
            return None
        path, epoch, meta, restored, resharded = chosen
        stamped_dp = (meta.get("elastic") or {}).get("dp")
        if isinstance(stamped_dp, int) and stamped_dp < self.n_data:
            # the scale-up half: a resume onto a LARGER extent is a grow
            # (probe-triggered or fleet-granted) — counted whether or not
            # the remapper had leaves to re-lay (a run without ZeRO-1/EF
            # state grows with zero remapped leaves but it still grew)
            counters_lib.inc("elastic.grows")
        self.state = self._place_state(restored)
        # pick the recovery backoff up from the checkpoint (see _ckpt_meta)
        self._lr_scale = float(meta.get("lr_scale", 1.0))
        # exact mid-epoch snapshot (emergency save): re-enter THIS epoch at
        # this step instead of starting the next epoch
        self._resume_step = int(meta.get("mid_epoch_step", 0))
        self._resume_examples = 0
        # the snapshot's final-step metrics (when stamped): replayed by
        # train_epoch iff the resumed epoch has zero steps left to run
        self._resume_metrics = (
            meta.get("mid_epoch_metrics") if self._resume_step else None
        )
        if self._resume_step:
            from tpu_dist.elastic.errors import (  # noqa: PLC0415
                ConfigMismatchError,
            )

            # the GLOBAL batch size and shuffle seed pin the data position
            # under ANY world size — refuse silent drift (same contract as
            # the pp/adamw layout stamps above)
            for key, current in (
                ("mid_epoch_batch_size", cfg.batch_size),
                ("mid_epoch_seed", cfg.seed or 0),
            ):
                saved = meta.get(key)
                if saved is not None and saved != current:
                    raise ConfigMismatchError(
                        f"checkpoint {path} is a mid-epoch snapshot taken "
                        f"with {key.removeprefix('mid_epoch_')}={saved}; "
                        f"this run uses {current} — the step offset would "
                        f"re-enter the epoch at the wrong data position "
                        f"(silently skipping/repeating examples). Resume "
                        f"with the matching value, or from the last clean "
                        f"epoch checkpoint."
                    )
            # world-portable re-entry: the per-shard step offset replays
            # bit-identically only when the shard count is unchanged AND
            # the snapshot itself entered its epoch at offset 0. Otherwise
            # switch to the consumed-example offset: skip the globally
            # consumed prefix and re-partition the remainder over THIS
            # world's shards (sampler.set_offset — nothing dropped or
            # double-seen; augmentation streams re-key, so the continued
            # trajectory is parity, not bit-identity).
            nproc = mesh_lib.process_count()
            saved_procs = meta.get("mid_epoch_procs")
            saved_ex = meta.get("mid_epoch_examples")
            same_world = saved_procs is None or int(saved_procs) == nproc
            offset_free = (
                saved_ex is None
                or int(saved_ex) == self._resume_step * cfg.batch_size
            )
            if not (same_world and offset_free):
                # clamp defensively too (pre-clamp or foreign stamps): an
                # offset at N is a legally-empty resumed epoch, past N is
                # not a position in this dataset
                self._resume_examples = min(
                    int(
                        saved_ex
                        if saved_ex is not None
                        else self._resume_step * cfg.batch_size
                    ),
                    len(self.train_data[0]),
                )
                self._resume_step = 0
        self._state_poisoned = False
        self._elastic_resume = {
            "epoch": epoch,
            "world": mesh_lib.process_count(),
            "dp": self.n_data,
            "resharded": resharded,
            "prev_dp": (meta.get("elastic") or {}).get("dp"),
            "prev_procs": (meta.get("elastic") or {}).get("procs"),
            "mid_epoch_step": self._resume_step,
            "examples_offset": self._resume_examples,
        }
        if self._resume_step:
            self._progress = (self.state, epoch, self._resume_step, False)
            rank0_print(
                f"=> resumed from {path} (mid-epoch {epoch}, "
                f"continuing at step {self._resume_step})"
            )
        elif self._resume_examples:
            self._progress = (self.state, epoch, 0, False)
            rank0_print(
                f"=> resumed from {path} (mid-epoch {epoch}, elastic: "
                f"continuing at example offset {self._resume_examples}, "
                f"remainder re-partitioned over {mesh_lib.process_count()} "
                "process(es))"
            )
        else:
            self._progress = (self.state, epoch, 0, True)
            rank0_print(f"=> resumed from {path} (epoch {epoch})")
        return epoch

    def _auto_recover(self, err: TrainingDivergedError) -> None:
        """Divergence response (--auto_recover): reload the last good
        checkpoint and back the LR schedule off by cfg.recover_lr_factor —
        a bare retry would diverge identically (epoch-seeded data order is
        deterministic by design). Raises the original error when there is
        no checkpoint to fall back to."""
        cfg = self.cfg
        self._ckpt_close(suppress=True)  # drain in-flight async writes
        epoch = self._restore_latest()
        if epoch is None:
            raise err
        # a mid-fit recovery is not a new segment: fit's resume-record
        # block already ran, and leaving this set would leak a stale
        # 'resume' boundary into a LATER fit() on this instance (the
        # auto_recover history record documents this restore instead)
        self._elastic_resume = None
        self.start_epoch = (
            epoch if (self._resume_step or self._resume_examples) else epoch + 1
        )
        self._seed_global_step()  # the --profile_steps grid follows the
        #                           restored (replayed) training position
        self._lr_scale *= cfg.recover_lr_factor
        rank0_print(
            f"=> AUTO-RECOVER: {err}; resumed from epoch {epoch}, LR scale "
            f"now {self._lr_scale:g} (factor {cfg.recover_lr_factor})"
        )

    def fit(self, epochs: Optional[int] = None) -> dict:
        cfg = self.cfg
        epochs = epochs if epochs is not None else cfg.epochs
        from tpu_dist.metrics.history import MetricsHistory  # noqa: PLC0415

        run_id = self._run_id  # stamped at construction (one id per run)
        # rel_s shares the construction-time clock origin with the span
        # recorder — one timeline for epoch bars and host spans.
        # --per_host_log: EVERY process writes its own history (rank 0
        # keeps the bare path, rank k appends .h<k>) so `obs pod` can
        # merge per-host goodput ledgers and skew timelines later.
        log_path = cfg.log_file
        if cfg.per_host_log and cfg.log_file:
            from tpu_dist.obs.heartbeat import per_rank_path  # noqa: PLC0415

            log_path = per_rank_path(cfg.log_file, jax.process_index())
        history = MetricsHistory(
            log_path, run_id=run_id, t0=self._telemetry_t0,
            all_processes=cfg.per_host_log,
        )
        # the step loop's health records (device_stats / anomaly) write
        # through this handle; cleared in the finally below so a direct
        # train_epoch() call outside fit() never logs to a closed file
        self._history = history
        # crash forensics (docs/observability.md "Crash forensics"): a
        # per-rank SIGKILL-surviving flight ring + faulthandler stack
        # capture, armed on EVERY process — unlike the rank-0 telemetry,
        # forensics is per-rank by definition (any rank can wedge)
        self._flight = None
        self._fault_handle = None
        if cfg.crash_dir:
            from tpu_dist.obs import flight as flight_lib  # noqa: PLC0415
            from tpu_dist.obs.heartbeat import per_rank_path  # noqa: PLC0415

            import os as _fos  # noqa: PLC0415

            rank = jax.process_index()
            self._flight = flight_lib.FlightRecorder(
                per_rank_path(
                    _fos.path.join(cfg.crash_dir, flight_lib.RING_NAME), rank
                ),
                run_id=run_id, rank=rank,
            )
            # last-words discipline: an UNHANDLED exception anywhere (main
            # thread or a worker like the loader producer) stamps a fatal
            # slot before the interpreter dies; previous hooks still run
            self._flight.install_excepthooks()
            # every host span OPEN (ckpt write/restore, loader produce,
            # eval) taps one slot — the ring shows which host operation
            # was in flight at death, on every rank, buffering none
            spans_lib.set_open_listener(self._flight.span_open)
            self._flight.record(
                "open", epoch=self.start_epoch,
                world=mesh_lib.process_count(), dp=self.n_data,
            )
            # hard-fault tracebacks land in the per-rank crash file, and
            # SIGUSR1 dumps all threads on demand — the launcher watchdog
            # signals a live-but-frozen rank and reads back WHERE it is
            # stuck before escalating SIGTERM→SIGKILL
            self._fault_handle = flight_lib.arm_faulthandler(
                per_rank_path(
                    _fos.path.join(cfg.crash_dir, flight_lib.STACKS_NAME),
                    rank,
                )
            )
        # elastic observability (docs/resilience.md "Elastic training"):
        # the current world size is a first-class gauge (segment
        # boundaries in summarize/tail/pod key off it) and a supervisor-
        # relaunched child reports WHICH restart it is (the launcher
        # injects TPU_DIST_ELASTIC_RESTARTS into every relaunched round)
        import os as _os  # noqa: PLC0415

        counters_lib.set_gauge("elastic.world_size", self.n_data)
        try:
            _restarts = int(
                _os.environ.get("TPU_DIST_ELASTIC_RESTARTS", "0") or 0
            )
        except ValueError:
            _restarts = 0
        if _restarts:
            counters_lib.set_gauge("elastic.restarts", _restarts)
        # causal arbitration tracing (schema v15): a relaunch that
        # actuates a fleet decision carries the scheduler's id/cause in
        # env (launcher reads them off the allocation file) — stamped
        # into the resume record, the flight-ring slot, and the
        # fleet.decision_id gauge, so every artifact layer names WHICH
        # arbitration moved this run (a chip-loss relaunch has none)
        _decision_id: "Optional[int]" = None
        _decision_cause = _os.environ.get("TPU_DIST_FLEET_DECISION_CAUSE") or None
        try:
            _raw_did = _os.environ.get("TPU_DIST_FLEET_DECISION_ID", "")
            _decision_id = int(_raw_did) if _raw_did else None
        except ValueError:
            _decision_id = None
        if _decision_id is not None:
            counters_lib.set_gauge("fleet.decision_id", _decision_id)
        if self._elastic_resume is not None:
            # one 'resume' record per resumed segment (schema v7): world
            # size, reshard flag, re-entry position — the segment-boundary
            # line obs summarize/tail/pod render
            _trace = {}
            if _decision_id is not None:
                _trace["decision_id"] = _decision_id
                if _decision_cause:
                    _trace["decision_cause"] = _decision_cause
            history.log(
                "resume", restarts=_restarts, **_trace,
                **self._elastic_resume,
            )
            if self._flight is not None:
                self._flight.record(
                    "resume",
                    epoch=self._elastic_resume.get("epoch"),
                    world=self._elastic_resume.get("world"),
                    dp=self._elastic_resume.get("dp"),
                    resharded=self._elastic_resume.get("resharded"),
                    **_trace,
                )
            self._elastic_resume = None
        # re-arm host-span tracing (construction armed it before the
        # resume-path restore; a second fit() on this Trainer re-arms after
        # _export_telemetry disarmed) WITHOUT clearing or re-zeroing — the
        # restore ladder's spans are still in the buffer and the clock
        # origin must stay the construction instant. Counters are always
        # live — they are plain host ints.
        telemetry = self._telemetry
        if telemetry:
            spans_lib.enable(fresh=False)
            counters_lib.set_gauge("run.id", run_id)
            counters_lib.set_gauge("run.grad_compression", cfg.grad_compression)
            if not cfg.fsdp:  # under fsdp the wire format is inert (GSPMD)
                # static ring-model estimate, pure host arithmetic from the
                # param SHAPES (no device touch): RS+AG = 2 payload legs ×
                # bytes/elem of the wire format. The exact per-eqn account
                # is TD104's job; this gauge puts the mode's wire cost next
                # to the throughput numbers in every history record.
                import math  # noqa: PLC0415

                n_params = sum(
                    math.prod(l.shape) if l.shape else 1
                    for l in jax.tree_util.tree_leaves(self.state.params)
                )
                bpe = {"none": 4, "bf16": 2, "int8": 1, "int8_ef": 1}[
                    cfg.grad_compression
                ]
                counters_lib.set_gauge(
                    "comm.grad_wire_bytes_per_step", 2 * bpe * n_params
                )
        if self._plan is not None:
            # the --auto_shard announcement record (schema v12): what the
            # planner chose and what step time it promised. TD119's drift
            # record lands later, from _note_capture_analysis, once a
            # profiled run produces an achieved step time to compare
            if telemetry:
                counters_lib.set_gauge("plan.family", self._plan["family"])
                if self._plan.get("predicted_step_s") is not None:
                    counters_lib.set_gauge(
                        "plan.predicted_step_s", self._plan["predicted_step_s"]
                    )
            history.log("plan", epoch=self.start_epoch, **self._plan)
        if self._tune is not None:
            # the --tune_report announcement (satellite of the overlap
            # autotuner): which schedule knobs the run actually trains
            # with, as gauges (history + compare can pin a regression to
            # a knob flip) plus one 'tune' history record
            if telemetry:
                counters_lib.set_gauge(
                    "tune.family", self._tune.get("family") or "none"
                )
                for knob, value in sorted(
                    (self._tune.get("applied") or {}).items()
                ):
                    counters_lib.set_gauge(f"tune.{knob}", value)
            history.log("tune", epoch=self.start_epoch, **self._tune)
        if cfg.heartbeat_file:
            from tpu_dist.obs.heartbeat import (  # noqa: PLC0415
                Heartbeat, per_rank_path,
            )

            # EVERY process beats its own file (per_rank_path: rank 0 the
            # bare path, rank k .h<k> — the --per_host_log naming):
            # liveness is per-host, and a watchdog that only sees rank 0
            # would kill healthy workers / miss a wedged rank 3. The
            # launcher's --heartbeat_dir watchdog reads the same scheme.
            self._heartbeat = Heartbeat(
                per_rank_path(cfg.heartbeat_file, jax.process_index())
            )
            self._heartbeat.beat(
                epoch=self.start_epoch, phase="start", force=True
            )
        # live export + alerting (docs/observability.md "Live export"):
        # the exporter publishes the counter registry + the latest epoch
        # rollup as OpenMetrics (textfile at the heartbeat's step-grain
        # throttle, rank-0 HTTP endpoint serving the last snapshot); the
        # alert engine evaluates the declarative rules at the epoch grain
        # (stall/MFU/goodput/retraces) and the step-fetch grain (norms).
        # Host-side only — TD109 pins the traced step byte-identical.
        self._exporter = None
        self._alerts = None
        self._export_rollup = {}
        self._export_t = float("-inf")
        if cfg.metrics_file or cfg.metrics_port:
            from tpu_dist.obs.export import MetricsExporter  # noqa: PLC0415
            from tpu_dist.obs.heartbeat import per_rank_path  # noqa: PLC0415

            rank = jax.process_index()
            textfile = (
                per_rank_path(cfg.metrics_file, rank)
                if cfg.metrics_file else None
            )
            # the HTTP endpoint is rank-0-only (MetricsExporter refuses a
            # port on rank >= 1); other ranks export via their derived
            # textfile only — and with --metrics_port alone, rank >= 1 has
            # NO output surface, so it skips the exporter entirely rather
            # than render expositions nothing can read
            port = (cfg.metrics_port or None) if rank == 0 else None
            if textfile or port:
                self._exporter = MetricsExporter(
                    textfile=textfile, port=port, rank=rank
                )
        if self._alert_rule_list:
            from tpu_dist.obs.alerts import AlertEngine  # noqa: PLC0415

            # fresh streak/cooldown state per fit(); runs on EVERY process
            # (like the anomaly detector) — its actions are rank-scoped
            # (rank-0 history/warning, per-process exporter gauges), never
            # collective, so per-host metric divergence is harmless
            self._alerts = AlertEngine(self._alert_rule_list)
            # delta rules (mid-run retraces) fire on change SINCE FIT
            # START — a counter born mid-run must alert on its first
            # increment, not spend it establishing a baseline
            self._alerts.seed_deltas(counters_lib.snapshot())
        last = {}
        self._last_epoch = self.start_epoch
        self._in_epoch = False
        self._tb = None
        if cfg.tensorboard_dir and mesh_lib.is_primary():
            from tpu_dist.metrics.tensorboard import SummaryWriter  # noqa: PLC0415

            self._tb = SummaryWriter(cfg.tensorboard_dir)
        attempts = cfg.auto_recover
        self._best_top1 = -1.0  # survives recovery retries of _fit_loop
        # preemption-graceful shutdown: SIGTERM sets a flag; the loops honor
        # it at the step/epoch grain and raise PreemptedError (restored to
        # the previous disposition on every exit path below)
        sig_token = preemption.install()
        preemption.clear()
        try:
            while True:
                try:
                    result = self._fit_loop(epochs, history, last)
                    with self._goodput.timed("ckpt"):
                        # success path: writer errors RAISE; the blocking
                        # drain of background writes is ckpt time (the
                        # ledger's sum-to-wall partition stays exact)
                        self._ckpt_close()
                    if self._heartbeat is not None:
                        # clean exit: the heartbeat's ABSENCE is the signal
                        self._heartbeat.sweep()
                    return result
                except TrainingDivergedError as e:
                    # from here until the restore completes, self.state is
                    # NaN-poisoned — _emergency_save must not snapshot it
                    self._state_poisoned = True
                    if attempts <= 0:
                        raise
                    attempts -= 1
                    with self._goodput.timed("recovery"):
                        self._auto_recover(e)  # raises e when no ckpt to load
                    history.log(
                        "auto_recover", epoch=self._last_epoch,
                        lr_scale=self._lr_scale,
                    )
                    if self._flight is not None:
                        self._flight.record(
                            "auto_recover", epoch=self._last_epoch,
                        )
        except (KeyboardInterrupt, PreemptedError) as e:
            # Ctrl-C and SIGTERM share one snapshot discipline; the caller
            # (cli/train.py) maps PreemptedError to the distinct
            # PREEMPTION_EXIT_CODE so the launcher/orchestrator can requeue
            if isinstance(e, PreemptedError):
                counters_lib.inc("preemption.observed")
            # preemption/interrupt-loss accounting: the shutdown tail this
            # process spends honoring the signal (position beat + emergency
            # snapshot), measured from HERE — time between the SIGTERM and
            # the cooperative boundary stays in the bucket that actually
            # used it (finishing the step/eval, or unattributed for a
            # partial epoch), so the ledger's sum-equals-wall-clock
            # invariant holds with no double count. The restart gap is the
            # offline half (obs/goodput.py run_ledger).
            t_pre = time.monotonic()
            if self._heartbeat is not None:
                # last beat marks the position; the file is deliberately
                # NOT swept — a watchdog seeing it + the exit code knows
                # the run ended preempted/interrupted, not hung
                self._heartbeat.beat(
                    epoch=self._last_epoch, phase="preempted", force=True
                )
            self._emergency_save()
            self._goodput.add("preempt", time.monotonic() - t_pre)
            raise
        finally:
            # error exits (divergence, interrupt): still drain in-flight
            # writes, but log writer failures rather than mask the
            # propagating exception
            preemption.restore(sig_token)
            with self._goodput.timed("ckpt"):  # async-writer drain is ckpt time
                self._ckpt_close(suppress=True)
            if self._profiler is not None:
                # an in-flight capture window must not outlive the run
                ev = self._profiler.close()
                if ev is not None:
                    self._note_profile_event(ev, self._last_epoch, None)
            if self._tb is not None:
                self._tb.close()
            self._close_goodput(history)
            if self._exporter is not None:
                # one final forced exposition — the closing totals stay
                # scrapeable in the textfile (deliberately not deleted:
                # the last exposition documents how the run ended) — then
                # stop the HTTP thread
                try:
                    self._export_live(force=True)
                finally:
                    self._exporter.close()
                    self._exporter = None
            self._alerts = None
            if telemetry:
                self._export_telemetry(history)
            # OOM forensics (obs/memory.py): a propagating
            # RESOURCE_EXHAUSTED is parsed into a typed allocation
            # report, logged as a 'memory' OOM event (schema v11) while
            # the history is still open, stamped into the flight ring,
            # and written as oom.json — with the ledger snapshot that
            # was live at the time — next to the ring, so `obs
            # postmortem` classifies this rank's verdict as 'oom'
            # instead of an opaque 'fatal'.
            import sys as _esys  # noqa: PLC0415

            _oom_et, _oom_ev, _ = _esys.exc_info()
            if _oom_et is not None:
                from tpu_dist.obs import memory as memory_lib  # noqa: PLC0415

                _oom = memory_lib.parse_resource_exhausted(str(_oom_ev))
                if _oom is not None:
                    counters_lib.inc("mem.oom_events")
                    _snap = self._mem_record or {"static": self._mem_static}
                    rank0_print(
                        "FATAL: device "
                        + memory_lib.oom_summary_line(_oom)
                        + " — " + memory_lib.summary_line(_snap)
                    )
                    history.log(
                        "memory", event="oom", epoch=self._last_epoch,
                        oom=_oom, ledger=_snap,
                    )
                    if self._flight is not None:
                        self._flight.record(
                            "oom", epoch=self._last_epoch,
                            requested=_oom.get("requested_bytes"),
                            used=_oom.get("used_bytes"),
                            limit=_oom.get("limit_bytes"),
                        )
                    if cfg.crash_dir:
                        from tpu_dist.obs.heartbeat import (  # noqa: PLC0415
                            per_rank_path,
                        )

                        import os as _oos  # noqa: PLC0415

                        memory_lib.write_oom_report(
                            per_rank_path(
                                _oos.path.join(
                                    cfg.crash_dir, memory_lib.OOM_NAME
                                ),
                                jax.process_index(),
                            ),
                            _oom, _snap,
                        )
            self._history = None
            history.close()
            self._heartbeat = None
            if self._flight is not None:
                # LAST teardown step so the drain/save spans above still
                # tapped the ring. Classify the exit: a propagating
                # failure stamps its fatal slot HERE (the excepthooks are
                # being unwound), preemption/interrupt stamp their own
                # terminal kind, a clean return stamps `exit` — a ring
                # that ends with none of these was a hard kill.
                import sys as _sys  # noqa: PLC0415

                spans_lib.clear_open_listener()
                self._flight.uninstall_excepthooks()
                et, ev, tb = _sys.exc_info()
                if et is None:
                    self._flight.close("exit", clean=True)
                elif issubclass(et, PreemptedError):
                    self._flight.close("preempt", epoch=self._last_epoch)
                elif issubclass(et, KeyboardInterrupt):
                    self._flight.close("interrupt", epoch=self._last_epoch)
                else:
                    self._flight.fatal(et, ev, tb)
                    self._flight.close("exit", clean=False)
                self._flight = None
                from tpu_dist.obs import flight as flight_lib  # noqa: PLC0415

                flight_lib.disarm_faulthandler(self._fault_handle)
                self._fault_handle = None

    def _emergency_save(self) -> None:
        """Ctrl-C / SIGTERM snapshot discipline (one path for both: the
        preemption handler raises PreemptedError at the step grain, so by
        the time this runs the in-flight step is finished and published).

        The ONLY source of truth is ``self._progress = (state, epoch,
        steps_done, epoch_complete)`` — published atomically at every
        position change (init/restore, each train step, epoch completion),
        so there is no interrupt window in which the pieces disagree
        (including the preamble right after a mid-epoch restore, where a
        flag-based scheme would misfile k already-trained steps as a clean
        epoch boundary).

        - Cross-process-sharded state (multi-host ZeRO-1/TP) is NOT saved:
          the gather in ckpt save is collective, and Ctrl-C lands at
          unsynchronized points per process — attempting it would deadlock
          the job. Skipped with a message instead.
        - Position "complete through epoch e": save the clean epoch-e state
          under ``e`` (kept as-is when ``ckpt_e`` already exists); nothing
          to save when no epoch has completed (e < 0).
        - Position "epoch e, k>0 steps done": EXACT snapshot under ``e``
          stamped ``mid_epoch_step=k`` (+ batch_size/seed, which pin the
          data position) — ``--resume`` continues epoch e at batch k.
        - Position "epoch e, 0 steps done" (incl. the fused epoch, which
          has no step grain): fall back to the previous clean boundary
          ``e-1`` — kept when already on disk, nothing saved when e == 0.
        """
        cfg = self.cfg
        if not cfg.ckpt_dir:
            return
        if getattr(self, "_state_poisoned", False):
            rank0_print(
                "=> interrupted while the live state was NaN-poisoned "
                "(divergence handling in flight) — emergency snapshot "
                "skipped; the last periodic checkpoint stays the newest"
            )
            return
        # drain any in-flight async write FIRST (host-local, not collective —
        # safe before the sharded-state guard): the emergency snapshot must be
        # the LAST file published, and a writer error must not abort the
        # snapshot or mask the interrupt
        self._ckpt_close(suppress=True)
        state, epoch, steps_done, complete = self._progress
        if jax.process_count() > 1 and (
            cfg.sharded_ckpt  # manifest commit needs a cross-process barrier
            or any(
                isinstance(l, jax.Array) and not l.is_fully_addressable
                for l in jax.tree_util.tree_leaves(state._asdict())
            )
        ):
            rank0_print(
                "=> interrupted; state (or the sharded-ckpt commit barrier) "
                "is cross-process — emergency snapshot skipped (collectives "
                "cannot run from a signal handler); resume from the last "
                "periodic checkpoint"
            )
            return
        io = ckpt_lib.ShardedCheckpointer if cfg.sharded_ckpt else ckpt_lib
        done_marker = (
            "ckpt_{e}.manifest.json" if cfg.sharded_ckpt else "ckpt_{e}.npz"
        )
        import os  # noqa: PLC0415

        def clean_exists(e: int) -> bool:
            return os.path.exists(
                os.path.join(cfg.ckpt_dir, done_marker.format(e=e))
            )

        def save(ckpt_epoch: int, extra_meta: dict, msg: str) -> None:
            # Donation hazard: when the interrupt lands while a train step
            # is dispatching, the published state's buffers may be (or
            # become, racing the aborted dispatch's cleanup) donated to the
            # in-flight step — serialization then raises "Array has been
            # deleted".  The save is atomic (tmp + rename), so the failed
            # attempt leaves nothing partial; skip gracefully rather than
            # crash the interrupt handler.
            try:
                io.save(cfg.ckpt_dir, state, ckpt_epoch, cfg.keep_last_ckpts,
                        extra_meta=extra_meta)
            except RuntimeError as e:
                if "deleted" not in str(e):
                    raise
                rank0_print(
                    "=> interrupted while a step held the donated state "
                    "buffers — emergency snapshot skipped; resume from the "
                    "last periodic checkpoint"
                )
                return
            rank0_print(msg)

        if complete:
            if epoch < 0:
                return  # nothing trained yet
            if clean_exists(epoch):
                rank0_print(
                    f"=> interrupted after epoch {epoch} completed; clean "
                    f"ckpt_{epoch} already on disk — kept as-is"
                )
                return
            save(epoch, self._ckpt_meta(),
                 f"=> interrupted after epoch {epoch} completed; "
                 f"saved as epoch {epoch}")
            return
        if steps_done > 0:
            # Exact mid-epoch snapshot: state after steps_done steps of
            # epoch, stamped with the step offset plus the two config
            # values the data position depends on (the epoch-seeded
            # permutation makes (seed, epoch, batch_size, step) pin it
            # exactly); _restore_latest refuses a mismatched resume.
            save(epoch,
                 {**self._ckpt_meta(),
                  **self._mid_epoch_position(int(steps_done))},
                 f"=> interrupted mid-epoch {epoch} after step "
                 f"{steps_done - 1}; exact snapshot saved — resume continues "
                 f"epoch {epoch} at step {steps_done}")
            return
        if epoch <= 0:
            return
        prev = epoch - 1
        if clean_exists(prev):
            rank0_print(
                f"=> interrupted mid-epoch {epoch}; clean ckpt_{prev} "
                f"already on disk — kept as-is, resume re-runs epoch {epoch}"
            )
            return
        save(prev, self._ckpt_meta(),
             f"=> interrupted mid-epoch {epoch}; state saved to "
             f"{cfg.ckpt_dir} as epoch {prev} — resume re-runs epoch "
             f"{epoch}")

    def _close_goodput(self, history) -> None:
        """Run-end ledger bookkeeping: fold the tail window (final save,
        drain, teardown preamble), write the ``final`` totals record, and
        print the rank-0 ledger line. Best-effort like the rest of the
        telemetry teardown — the books must never mask a propagating
        training error."""
        try:
            tail = self._goodput.window_record()
            totals = self._goodput.run_totals()
            if history.path:
                # tail=True distinguishes this teardown window from the
                # per-epoch window logged under the same epoch number
                history.log("goodput", epoch=self._last_epoch, tail=True,
                            **tail)
                history.log("goodput", final=True, **totals)
            if history.path or self.cfg.trace_file:
                rank0_print("=> " + goodput_lib.ledger_line(totals))
        except OSError as e:
            rank0_print(f"WARNING: goodput ledger close failed: {e}")

    def _export_telemetry(self, history) -> None:
        """End-of-run span disposal (rank 0 — fit() arms telemetry there
        only): drain the tail into the JSONL history, write --trace_file,
        disarm the recorder. Best-effort: telemetry must never mask a
        propagating training error."""
        cfg = self.cfg
        try:
            # one drain path (history record + capped accumulator with
            # counted drops) for the tail too — no silent overflow here
            self._drain_spans(history, self._last_epoch)
            if cfg.trace_file:
                spans_lib.export_chrome_trace(
                    cfg.trace_file, extra_events=self._trace_events
                )
                rank0_print(
                    f"=> wrote host-span Chrome trace to {cfg.trace_file} "
                    f"({len(self._trace_events)} events; load in Perfetto)"
                )
        except OSError as e:
            rank0_print(f"WARNING: telemetry export failed: {e}")
        finally:
            spans_lib.disable()
            self._trace_events = []

    def _drain_spans(self, history, epoch: int) -> None:
        """Move this epoch's host spans out of the in-memory buffer: into
        the JSONL history (a ``spans`` record, streamed to disk) and/or
        the --trace_file accumulator, which is capped at the same
        MAX_EVENTS budget as the live buffer — a week-long run keeps its
        earliest events and counts the overflow, never grows unbounded."""
        if not spans_lib.enabled():
            return
        ev = spans_lib.drain()
        if not ev:
            return
        if self.cfg.log_file:
            history.log("spans", epoch=epoch, events=ev)
        if self.cfg.trace_file:
            room = spans_lib.MAX_EVENTS - len(self._trace_events)
            if room > 0:
                self._trace_events.extend(ev[:room])
            if len(ev) > max(room, 0):
                counters_lib.inc(
                    "spans.trace_export_dropped", len(ev) - max(room, 0)
                )

    def _fit_loop(self, epochs: int, history, last: dict) -> dict:
        cfg = self.cfg
        for epoch in range(self.start_epoch, epochs):
            self._last_epoch = epoch
            self._in_epoch = True
            # a restored mid-epoch snapshot applies to its own epoch only.
            # _progress stays whatever was last published (the restore point
            # or the previous epoch's completion) until train_epoch's own
            # publish — every interrupt window reads a consistent position.
            start_step, self._resume_step = self._resume_step, 0
            start_examples, self._resume_examples = self._resume_examples, 0
            # the epoch-0 blanket trace only when triggered/manual capture
            # does NOT own --profile_dir (two live jax.profiler traces
            # cannot nest)
            if (
                cfg.profile_dir and epoch == self.start_epoch
                and self._profiler is None
            ):
                from tpu_dist.obs.profile import (  # noqa: PLC0415
                    analyze_capture_quietly,
                    trace,
                )

                with trace(cfg.profile_dir):
                    last = self.train_epoch(
                        epoch, start_step=start_step,
                        start_examples=start_examples,
                    )
                if mesh_lib.is_primary():
                    # the blanket capture gets the same read-back as a
                    # triggered one: attribution record + summary line +
                    # calibration gauges (obs/xprof.py)
                    analysis, a_err = analyze_capture_quietly(cfg.profile_dir)
                    self._note_capture_analysis(
                        analysis, a_err, epoch=epoch, reason="profile_dir",
                        capture_dir=cfg.profile_dir,
                        steps=last.get("steps"),
                    )
            else:
                last = self.train_epoch(
                    epoch, start_step=start_step,
                    start_examples=start_examples,
                )
            self._in_epoch = False
            # epoch fully trained: one atomic publish flips the position to
            # "complete through epoch" for the eval/save window below
            self._progress = (self.state, epoch, 0, True)
            history.log("train_epoch", epoch=epoch, **last)
            self._drain_spans(history, epoch)
            if cfg.straggler_threshold > 0:
                # COLLECTIVE (allgather of two floats per process): every
                # process reaches this once per epoch — same contract as
                # the restore ladder's agreement check
                from tpu_dist.obs import straggler as straggler_lib  # noqa: PLC0415

                srec = straggler_lib.epoch_skew(
                    float(last.get("epoch_time", 0.0)),
                    float(last.get("data_stall_frac", 0.0)),
                    epoch=epoch, threshold=cfg.straggler_threshold,
                )
                if srec["straggler"]:
                    history.log("straggler", epoch=epoch, **srec)
                    if (
                        self._profiler is not None
                        and "straggler" in self._profile_triggers
                        and mesh_lib.process_index() == srec["worst_rank"]
                    ):
                        # the FLAGGED host arms: its next-epoch steps are
                        # the timeline that explains the skew (rank 0's
                        # would just show it waiting at the collective)
                        self._profiler.arm("straggler")
            if self._tb is not None:
                for k in ("loss", "acc1", "acc5", "images_per_sec", "mfu"):
                    if k in last:
                        self._tb.add_scalar(f"train/{k}", last[k], epoch)
                self._tb.add_scalar("train/lr", self._lr(epoch), epoch)
            if cfg.eval_every and (epoch + 1) % cfg.eval_every == 0:
                with self._goodput.timed("eval"):
                    if self._fused_runner is not None:
                        t_ev = time.perf_counter()
                        sums = _fetch_metrics(
                            self._fused_eval(self.state, *self._fused_test_data)
                        )
                        spans_lib.add_event(
                            "eval/fused", t_ev, time.perf_counter() - t_ev,
                            epoch=epoch,
                        )
                        n = max(sums["count"], 1.0)
                        t1 = sums["top1"] / n * 100.0
                        t5 = sums["top5"] / n * 100.0
                        vloss = sums["loss"] / n
                        rank0_print(f" * Acc@1 {t1:.3f} Acc@5 {t5:.3f} (epoch {epoch}, fused)")
                    else:
                        t1, t5, vloss = validate(
                            self.test_loader, self.state, self.eval_step, epoch=epoch
                        )
                last.update(val_top1=t1, val_top5=t5, val_loss=vloss)
                history.log("eval", epoch=epoch, top1=t1, top5=t5, loss=vloss)
                if self._tb is not None:
                    self._tb.add_scalar("eval/top1", t1, epoch)
                    self._tb.add_scalar("eval/top5", t5, epoch)
                    self._tb.add_scalar("eval/loss", vloss, epoch)
                if cfg.ckpt_dir and t1 > self._best_top1:
                    self._best_top1 = t1
                    with self._goodput.timed("ckpt"):
                        self._ckpt_io().save_best(
                            cfg.ckpt_dir, self.state, epoch, t1,
                            extra_meta=self._ckpt_meta(),
                        )
            if cfg.ckpt_dir and (
                (epoch + 1) % cfg.save_every == 0
                # with periodic mid-epoch snapshots on, EVERY epoch end
                # writes the clean checkpoint — otherwise a stale
                # mid-epoch ckpt_e would stay newest across the boundary
                # and the "at most N steps lost" guarantee breaks
                or cfg.mid_epoch_save_every > 0
            ):
                with self._goodput.timed("ckpt"):
                    self._ckpt_io().save(
                        cfg.ckpt_dir, self.state, epoch, cfg.keep_last_ckpts,
                        extra_meta=self._ckpt_meta(),
                    )
            # close this epoch's goodput window (train + eval + save):
            # one v4 record per epoch; the records chain, partitioning the
            # run's wall-clock exactly (obs/goodput.py)
            live = self._exporter is not None or self._alerts is not None
            if history.path or live:
                # the live layer needs the window CLOSED too (run totals
                # feed the goodput gauges and the goodput-floor rule)
                gp_rec = self._goodput.window_record()
                if history.path:
                    history.log("goodput", epoch=epoch, **gp_rec)
            if live:
                self._epoch_live_update(epoch, last)
            if preemption.requested():
                # SIGTERM during eval/save lands here: the epoch is complete
                # and published — the emergency path keeps/writes ckpt_epoch
                raise PreemptedError(
                    f"SIGTERM observed after epoch {epoch} completed — "
                    f"shutting down at the epoch boundary"
                )
        if cfg.ckpt_dir:
            with self._goodput.timed("ckpt"):
                self._ckpt_io().save(
                    cfg.ckpt_dir, self.state, epochs - 1, cfg.keep_last_ckpts,
                    extra_meta=self._ckpt_meta(),
                )
        return last  # fit() drains the async writer before returning
