"""SGD + momentum + weight decay and the MultiStepLR schedule.

Exact semantics of the reference optimizer line
(``torch.optim.SGD(params, lr, momentum=0.9, weight_decay=1e-4)``,
``distributed.py:63``) and scheduler
(``MultiStepLR(milestones=[60,120,160], gamma=0.2)``, ``distributed.py:64``):

* weight decay is added to the gradient (L2, not decoupled),
* momentum buffer ``b ← μ·b + g`` (no dampening, no Nesterov),
* update ``p ← p − lr·b``,
* LR is a pure function of the epoch: ``base_lr · γ^(#milestones ≤ epoch)``.

Written as a tiny pure-pytree optimizer rather than optax so the whole
update stays one fused XLA computation inside the sharded train step and
the momentum state is a plain pytree the checkpoint layer can serialize.
"""

from __future__ import annotations

import math
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp


class SGD:
    def __init__(
        self,
        momentum: float = 0.9,
        weight_decay: float = 1e-4,
        nesterov: bool = False,
        fused: bool = False,
    ):
        """``fused=True`` routes the update through the Pallas fused kernel
        (``tpu_dist.ops.fused_sgd``, the apex fused-optimizer equivalent);
        numerically identical to the plain path."""
        if fused and nesterov:
            raise ValueError("fused SGD does not implement nesterov")
        self.momentum = momentum
        self.weight_decay = weight_decay
        self.nesterov = nesterov
        self.fused = fused

    def init(self, params):
        return jax.tree_util.tree_map(jnp.zeros_like, params)

    def state_specs(self, param_specs):
        """Optimizer-state partition specs given per-leaf param specs (used
        by the TP/EP/PP and FSDP placements): SGD's momentum mirrors the
        param tree exactly."""
        return param_specs

    def update(self, grads, opt_state, params, lr):
        """Returns ``(new_params, new_opt_state)``. ``lr`` may be traced."""
        if self.fused:
            return self._update_fused(grads, opt_state, params, lr)
        mu, wd = self.momentum, self.weight_decay
        tm = jax.tree_util.tree_map

        new_state = tm(lambda p, g, b: mu * b + (g + wd * p), params, grads, opt_state)
        if self.nesterov:
            new_params = tm(
                lambda p, g, b: p - lr * ((g + wd * p) + mu * b), params, grads, new_state
            )
        else:
            new_params = tm(lambda p, b: p - lr * b, params, new_state)
        return new_params, new_state

    def _update_fused(self, grads, opt_state, params, lr):
        from tpu_dist.ops.fused_sgd import fused_sgd_leaf  # noqa: PLC0415

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_b = treedef.flatten_up_to(opt_state)
        out = [
            fused_sgd_leaf(
                p, g, b, lr, momentum=self.momentum, weight_decay=self.weight_decay
            )
            for p, g, b in zip(flat_p, flat_g, flat_b)
        ]
        new_params = jax.tree_util.tree_unflatten(treedef, [o[0] for o in out])
        new_state = jax.tree_util.tree_unflatten(treedef, [o[1] for o in out])
        return new_params, new_state


def multistep_lr(
    base_lr: float,
    milestones: Sequence[int] = (60, 120, 160),
    gamma: float = 0.2,
    warmup_epochs: int = 0,
):
    """Returns ``lr(epoch)`` (host-side float — the LR enters the compiled
    step as a scalar argument, so no recompilation on LR drops).
    ``warmup_epochs`` prepends a linear ramp to ``base_lr`` — required by
    the large-batch LARS/LAMB recipes, a no-op by default (the reference's
    MultiStepLR has no warmup)."""
    ms: Tuple[int, ...] = tuple(sorted(milestones))

    def schedule(epoch: int) -> float:
        if warmup_epochs > 0 and epoch < warmup_epochs:
            return float(base_lr * (epoch + 1) / warmup_epochs)
        k = sum(1 for m in ms if epoch >= m)
        return float(base_lr * (gamma ** k))

    return schedule


def linear_scaled_lr(base_lr: float, base_batch: int, global_batch: int) -> float:
    """The Goyal et al. linear-scaling rule: ``lr = base_lr · B/B₀``. The
    large-batch recipe's first half (the second half is warmup — pass
    ``warmup_epochs`` to the schedule); LARS/LAMB exist precisely because
    this rule alone stops working past ~8k images/batch."""
    if base_batch <= 0:
        raise ValueError(f"base_batch must be positive, got {base_batch}")
    if global_batch <= 0:
        raise ValueError(f"global_batch must be positive, got {global_batch}")
    return float(base_lr * global_batch / base_batch)


def cosine_lr(base_lr: float, total_epochs: int, warmup_epochs: int = 0, min_lr: float = 0.0):
    """Linear warmup then cosine decay to ``min_lr`` — the standard
    transformer/ViT schedule (no reference counterpart; the reference only
    ships MultiStepLR, ``distributed.py:64``). Epoch-granular like the
    reference's scheduler."""
    def schedule(epoch: int) -> float:
        if warmup_epochs > 0 and epoch < warmup_epochs:
            return float(base_lr * (epoch + 1) / warmup_epochs)
        t = (epoch - warmup_epochs) / max(1, total_epochs - warmup_epochs)
        t = min(max(t, 0.0), 1.0)
        return float(min_lr + 0.5 * (base_lr - min_lr) * (1.0 + math.cos(math.pi * t)))

    return schedule


class AdamW:
    """Decoupled-weight-decay Adam (Loshchilov & Hutter) — the standard
    transformer/ViT optimizer the reference never needed for its conv nets
    (``distributed.py:63`` ships only SGD). Same pure-pytree contract as
    :class:`SGD`: state is a plain dict pytree (first/second moments + step
    count) that the checkpoint layer serializes and the FSDP engine shards
    leaf-by-leaf. Verified step-for-step against ``optax.adamw``
    (``tests/test_optim.py``).
    """

    def __init__(
        self,
        b1: float = 0.9,
        b2: float = 0.999,
        eps: float = 1e-8,
        weight_decay: float = 0.01,
        decay_mask: str = "auto",
    ):
        """``decay_mask``: which leaves get decoupled weight decay.
        ``"auto"`` (default) follows standard transformer practice — skip
        rank ≤ 1 leaves (biases, LayerNorm/BN scales, 1-D tables), decay
        matrices/conv kernels. ``"all"`` decays every leaf (optax.adamw's
        unmasked behavior)."""
        if decay_mask not in ("auto", "all"):
            raise ValueError(f"decay_mask must be 'auto' or 'all', got {decay_mask!r}")
        self.b1 = b1
        self.b2 = b2
        self.eps = eps
        self.weight_decay = weight_decay
        self.decay_mask = decay_mask

    def _wd_tree(self, params):
        """Per-leaf effective weight decay (0.0 for masked-out leaves)."""
        wd = self.weight_decay
        if self.decay_mask == "all":
            return jax.tree_util.tree_map(lambda p: wd, params)
        return jax.tree_util.tree_map(
            lambda p: wd if jnp.ndim(p) > 1 else 0.0, params
        )

    def init(self, params):
        zeros = lambda: jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p, dtype=jnp.float32), params
        )
        return {"mu": zeros(), "nu": zeros(), "count": jnp.zeros((), jnp.int32)}

    def state_specs(self, param_specs):
        """mu/nu mirror the param tree's specs; the step count replicates."""
        from jax.sharding import PartitionSpec as P  # noqa: PLC0415

        return {"mu": param_specs, "nu": param_specs, "count": P()}

    # -- ZeRO-1 flat layout (shard_weight_update) ----------------------------

    def flat_state_specs(self, axis: str):
        """Partition specs for the ZeRO-1 flat layout: mu/nu are 1/n-sharded
        flat vectors, the step count replicates."""
        from jax.sharding import PartitionSpec as P  # noqa: PLC0415

        return {"mu": P(axis), "nu": P(axis), "count": P()}

    def init_flat_state(self, length: int):
        """Fresh ZeRO-1 state: flat f32 mu/nu of the padded raveled-param
        length (sharding applied by the caller)."""
        return {
            "mu": jnp.zeros((length,), jnp.float32),
            "nu": jnp.zeros((length,), jnp.float32),
            "count": jnp.zeros((), jnp.int32),
        }

    def leaf_wd_intervals(self, params):
        """The ``auto`` decay mask in flat coordinates: [start, end) ranges
        of the raveled param vector that receive weight decay, derived from
        ``_wd_tree`` (single source of truth for the mask rule) and the
        ravel order (= ``tree_leaves`` order, which ``ravel_pytree``
        concatenates). The ZeRO-1 update rebuilds its shard's per-element
        decay from these static intervals with iota comparisons — no
        model-length constant vector ever materializes."""
        wd_leaves = jax.tree_util.tree_leaves(self._wd_tree(params))
        out, off = [], 0
        for p, w in zip(jax.tree_util.tree_leaves(params), wd_leaves):
            n = int(math.prod(p.shape))
            if w:
                out.append((off, off + n, float(w)))
            off += n
        return out

    def update(self, grads, opt_state, params, lr, wd_tree=None):
        """Returns ``(new_params, new_opt_state)``; ``lr`` may be traced.
        ``wd_tree`` overrides the per-leaf decay (the ZeRO-1 flat path
        passes a positional per-element vector)."""
        b1, b2, eps = self.b1, self.b2, self.eps
        tm = jax.tree_util.tree_map
        count = opt_state["count"] + 1
        cf = count.astype(jnp.float32)
        bc1 = 1.0 - b1 ** cf
        bc2 = 1.0 - b2 ** cf

        mu = tm(lambda m, g: b1 * m + (1.0 - b1) * g, opt_state["mu"], grads)
        nu = tm(lambda v, g: b2 * v + (1.0 - b2) * jnp.square(g), opt_state["nu"], grads)
        if wd_tree is None:
            wd_tree = self._wd_tree(params)
        new_params = tm(
            lambda p, m, v, wd: p - lr * ((m / bc1) / (jnp.sqrt(v / bc2) + eps) + wd * p),
            params, mu, nu, wd_tree,
        )
        return new_params, {"mu": mu, "nu": nu, "count": count}


def _trust_ratio(p, u, eps: float):
    """The layer-wise trust ratio ``‖p‖/‖u‖`` shared by LARS and LAMB:
    falls back to 1.0 whenever either norm vanishes (fresh zero-init
    leaves, dead gradients) so the update degrades to the base optimizer
    instead of exploding or freezing."""
    pn = jnp.linalg.norm(p.reshape(-1))
    un = jnp.linalg.norm(u.reshape(-1))
    return jnp.where(
        (pn > 0.0) & (un > 0.0), pn / (un + eps), jnp.ones_like(pn)
    )


class LARS:
    """Layer-wise Adaptive Rate Scaling (You, Gitman & Ginsburg, 2017) —
    SGD-momentum where each layer's step is rescaled by the trust ratio
    ``η·‖p‖ / (‖g‖ + wd·‖p‖)``, the large-batch conv-net recipe (ResNet-50
    at 32k batch). Pair with :func:`linear_scaled_lr` and a warmup
    schedule. Same pure-pytree contract as :class:`SGD`; momentum state
    mirrors the param tree, so ``state_specs`` is the identity.

    Rank ≤ 1 leaves (biases, BN scales) skip both the adaptation and the
    weight decay — the standard exclusion, matching :class:`AdamW`'s
    ``auto`` decay mask.
    """

    def __init__(
        self,
        momentum: float = 0.9,
        weight_decay: float = 1e-4,
        trust_coefficient: float = 1e-3,
        eps: float = 1e-9,
    ):
        self.momentum = momentum
        self.weight_decay = weight_decay
        self.trust_coefficient = trust_coefficient
        self.eps = eps

    def init(self, params):
        return jax.tree_util.tree_map(jnp.zeros_like, params)

    def state_specs(self, param_specs):
        return param_specs

    def _leaf(self, p, g, b, lr):
        mu, wd, eta, eps = (
            self.momentum, self.weight_decay, self.trust_coefficient, self.eps,
        )
        if jnp.ndim(p) > 1:
            pn = jnp.linalg.norm(p.reshape(-1))
            gn = jnp.linalg.norm(g.reshape(-1))
            local = jnp.where(
                (pn > 0.0) & (gn > 0.0),
                eta * pn / (gn + wd * pn + eps),
                jnp.ones_like(pn),
            )
            gg = g + wd * p
        else:
            local = 1.0
            gg = g
        new_b = mu * b + local * gg
        return p - lr * new_b, new_b

    def update(self, grads, opt_state, params, lr):
        """Returns ``(new_params, new_opt_state)``; ``lr`` may be traced."""
        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_b = treedef.flatten_up_to(opt_state)
        out = [self._leaf(p, g, b, lr) for p, g, b in zip(flat_p, flat_g, flat_b)]
        new_params = jax.tree_util.tree_unflatten(treedef, [o[0] for o in out])
        new_state = jax.tree_util.tree_unflatten(treedef, [o[1] for o in out])
        return new_params, new_state


class LAMB:
    """Layer-wise Adaptive Moments (You et al., 2020) — AdamW's
    bias-corrected direction rescaled per layer by the trust ratio
    ``‖p‖/‖u‖``, the large-batch transformer recipe (BERT in 76 minutes).
    State layout is identical to :class:`AdamW` (mu/nu/count dict), so the
    checkpoint layer and ``state_specs`` sharding carry over unchanged.

    Rank ≤ 1 leaves skip the trust ratio and the decoupled weight decay
    (the ``auto`` mask, shared with :class:`AdamW`).
    """

    def __init__(
        self,
        b1: float = 0.9,
        b2: float = 0.999,
        eps: float = 1e-6,
        weight_decay: float = 0.01,
    ):
        self.b1 = b1
        self.b2 = b2
        self.eps = eps
        self.weight_decay = weight_decay

    def init(self, params):
        zeros = lambda: jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p, dtype=jnp.float32), params
        )
        return {"mu": zeros(), "nu": zeros(), "count": jnp.zeros((), jnp.int32)}

    def state_specs(self, param_specs):
        from jax.sharding import PartitionSpec as P  # noqa: PLC0415

        return {"mu": param_specs, "nu": param_specs, "count": P()}

    def update(self, grads, opt_state, params, lr):
        """Returns ``(new_params, new_opt_state)``; ``lr`` may be traced."""
        b1, b2, eps, wd = self.b1, self.b2, self.eps, self.weight_decay
        tm = jax.tree_util.tree_map
        count = opt_state["count"] + 1
        cf = count.astype(jnp.float32)
        bc1 = 1.0 - b1 ** cf
        bc2 = 1.0 - b2 ** cf

        mu = tm(lambda m, g: b1 * m + (1.0 - b1) * g, opt_state["mu"], grads)
        nu = tm(lambda v, g: b2 * v + (1.0 - b2) * jnp.square(g), opt_state["nu"], grads)

        def leaf(p, m, v):
            u = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if jnp.ndim(p) > 1:
                u = u + wd * p
                r = _trust_ratio(p, u, eps)
            else:
                r = 1.0
            return p - lr * r * u

        new_params = tm(leaf, params, mu, nu)
        return new_params, {"mu": mu, "nu": nu, "count": count}

