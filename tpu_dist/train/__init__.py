from tpu_dist.train.optim import SGD, multistep_lr  # noqa: F401
from tpu_dist.train.state import TrainState  # noqa: F401
from tpu_dist.train.step import make_eval_step, make_train_step  # noqa: F401
from tpu_dist.train.trainer import Trainer  # noqa: F401
