"""Fused-epoch runner: the whole training epoch as ONE compiled program.

The most TPU-native answer to the reference's epoch loop. CIFAR-100 is
~150 MB as uint8 — it fits in HBM many times over, so instead of streaming
batches from the host (reference: DataLoader worker processes + H2D copies
every step, ``distributed.py:71,88-89``), this path:

* keeps the dataset **device-resident**, uint8, sharded over the ``data``
  axis (each chip owns N/n examples);
* shuffles **on device** each epoch (per-shard permutation from a seeded
  key — the ``set_epoch`` semantics, folded per-device);
* augments **on device**: batch pad + per-image random crop offsets via
  ``jax.random``, normalize into the compute dtype — fused by XLA into the
  first conv's input pipeline;
* runs the epoch as ``lax.scan`` over steps inside one ``jit`` call: ONE
  host dispatch per epoch, zero host↔device traffic, no Python in the loop.

Per-step semantics (grads pmean, SyncBN, optimizer, metrics) are exactly
``tpu_dist.train.step``'s. The trade against the streaming path: shuffling
is within each device's shard rather than global (documented deviation —
equivalent in expectation after the initial global shuffle; reshard
periodically if exact torch semantics matter).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.comm.compat import shard_map
from tpu_dist.data.transforms import CIFAR100_MEAN, CIFAR100_STD
from tpu_dist.nn import functional as F
from tpu_dist.train.state import TrainState


def put_dataset_on_device(mesh: Mesh, images_u8: np.ndarray, labels: np.ndarray):
    """Shard the uint8 dataset over the data axis (one global shuffle first
    so per-shard shuffling stays representative).

    Multi-host: every process passes the SAME full dataset arrays (CIFAR
    scale — ~150 MB host RAM); each process places only its slice of the
    globally shuffled order onto its local devices.
    """
    n = (len(images_u8) // mesh.devices.size) * mesh.devices.size
    perm = np.random.default_rng(0).permutation(len(images_u8))[:n]
    sharding = NamedSharding(mesh, P(mesh_lib.DATA_AXIS))
    if jax.process_count() == 1:
        return (
            jax.device_put(np.ascontiguousarray(images_u8[perm]), sharding),
            jax.device_put(np.ascontiguousarray(labels[perm]), sharding),
        )
    # this process's contiguous slice of the global order
    per_proc = n // jax.process_count()
    lo = jax.process_index() * per_proc
    sel = perm[lo : lo + per_proc]
    return (
        jax.make_array_from_process_local_data(
            sharding, np.ascontiguousarray(images_u8[sel])
        ),
        jax.make_array_from_process_local_data(
            sharding, np.ascontiguousarray(labels[sel])
        ),
    )


def fused_steps_per_epoch(dataset_len: int, global_batch: int) -> int:
    """Scan trips one fused-epoch call runs (floor division — the runner
    drops the ragged tail batch). This is the ``loop_trips`` the cost
    model needs to normalize the fused program's numbers to one step:
    XLA's cost analysis counts the scan body ONCE, so flops/bytes of the
    whole-epoch program are body × trips (``obs/costmodel.py``)."""
    return max(1, int(dataset_len) // int(global_batch))


def make_fused_epoch(
    model_apply: Callable,
    optimizer,
    mesh: Mesh,
    *,
    batch_per_device: int,
    sync_bn: bool = True,
    compute_dtype=jnp.bfloat16,
    pad: int = 4,
    axis: str = mesh_lib.DATA_AXIS,
    mean: np.ndarray = CIFAR100_MEAN,
    std: np.ndarray = CIFAR100_STD,
    moe_aux_coef: float = 0.01,
    grad_compression: str = "none",
    quant_chunk: int | None = None,
    model_kwargs: dict | None = None,
):
    """Build ``epoch(state, images_u8, labels, lr, epoch_idx) ->
    (state, metrics)`` running every step of the epoch on device.

    ``images_u8``/``labels`` from :func:`put_dataset_on_device`.
    ``grad_compression``: same contract as ``make_train_step`` (bf16 cast
    or int8/int8_ef quantized two-stage wire for the grad reduce — the
    shared helpers in ``train/step.py`` define it ONCE for both paths).
    Under ``int8_ef`` the error-feedback residuals ride the ``lax.scan``
    carry inside ``TrainState.ef`` (build with ``step.init_ef_state``),
    so every step of the fused epoch compensates the previous step's
    quantization error exactly like the streaming path.
    """
    from tpu_dist.comm.quantize import DEFAULT_CHUNK  # noqa: PLC0415
    from tpu_dist.train.step import (  # noqa: PLC0415
        _QUANT_KEY_SEED,
        compressed_pmean,
        ef_state_spec,
        validate_grad_compression,
    )

    validate_grad_compression(grad_compression)
    q_chunk = int(quant_chunk) if quant_chunk else DEFAULT_CHUNK
    bn_axis = axis if sync_bn else None
    mean_c = jnp.asarray(mean, jnp.float32)
    std_inv_c = jnp.asarray(1.0 / std, jnp.float32)

    def augment(imgs_u8, key):
        """[B,H,W,C] uint8 → normalized compute_dtype, random crop pad=4."""
        b, h, w, c = imgs_u8.shape
        xp = jnp.pad(imgs_u8, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
        offs = jax.random.randint(key, (b, 2), 0, 2 * pad + 1)

        def crop(img, off):
            return lax.dynamic_slice(img, (off[0], off[1], 0), (h, w, c))

        cropped = jax.vmap(crop)(xp, offs)
        x = (cropped.astype(jnp.float32) / 255.0 - mean_c) * std_inv_c
        return x.astype(compute_dtype)

    def epoch_local(state: TrainState, images_u8, labels, lr, epoch_idx):
        n_loc = images_u8.shape[0]
        steps = n_loc // batch_per_device
        dev = lax.axis_index(axis)
        base = jax.random.fold_in(jax.random.fold_in(jax.random.PRNGKey(0), epoch_idx), dev)
        perm = jax.random.permutation(base, n_loc)

        from tpu_dist.train.step import extract_aux_loss  # noqa: PLC0415

        def loss_fn(params, bn_state, x, y):
            p = jax.tree_util.tree_map(lambda t: t.astype(compute_dtype), params)
            logits, new_bn = model_apply(
                p, bn_state, x, train=True, axis_name=bn_axis,
                **(model_kwargs or {})
            )
            new_bn, aux = extract_aux_loss(new_bn)
            loss = F.cross_entropy(logits, y)
            if aux is not None:
                loss = loss + moe_aux_coef * aux.astype(loss.dtype)
            return loss, (new_bn, logits)

        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

        def body(state, i):
            idx = lax.dynamic_slice_in_dim(perm, i * batch_per_device, batch_per_device)
            imgs = jnp.take(images_u8, idx, axis=0)
            ys = jnp.take(labels, idx, axis=0)
            x = augment(imgs, jax.random.fold_in(base, i + 1))

            (loss, (new_bn, logits)), grads = grad_fn(state.params, state.bn_state, x, ys)
            # same per-step/per-replica stochastic-rounding stream as the
            # streaming path (step.py::quant_key); no-op for none/bf16
            qkey = jax.random.fold_in(
                jax.random.fold_in(
                    jax.random.PRNGKey(_QUANT_KEY_SEED), state.step
                ),
                dev,
            )
            grads, new_ef = compressed_pmean(
                grads, axis, grad_compression,
                key=qkey, ef=state.ef, chunk=q_chunk,
            )
            if not sync_bn:
                new_bn = lax.pmean(new_bn, axis)
            new_params, new_opt = optimizer.update(grads, state.opt_state, state.params, lr)
            c1, c5 = F.topk_correct(logits.astype(jnp.float32), ys, (1, 5))
            metrics = {
                "loss": lax.pmean(loss, axis),
                "acc1": lax.psum(c1, axis) / (batch_per_device * lax.psum(1, axis)) * 100.0,
                "acc5": lax.psum(c5, axis) / (batch_per_device * lax.psum(1, axis)) * 100.0,
            }
            return TrainState(
                new_params, new_bn, new_opt, state.step + 1, new_ef
            ), metrics

        state, ms = lax.scan(body, state, jnp.arange(steps))
        return state, jax.tree_util.tree_map(lambda t: t.mean(), ms)

    # the state is replicated except the (per-replica, data-axis-sharded)
    # error-feedback residuals of the int8_ef wire format
    state_spec = TrainState(
        params=P(), bn_state=P(), opt_state=P(), step=P(),
        ef=ef_state_spec(grad_compression, axis=axis),
    )
    sharded = shard_map(
        epoch_local,
        mesh=mesh,
        in_specs=(state_spec, P(axis), P(axis), P(), P()),
        out_specs=(state_spec, P()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,))


def make_fused_eval(
    model_apply: Callable,
    mesh: Mesh,
    *,
    batch_per_device: int,
    compute_dtype=jnp.bfloat16,
    axis: str = mesh_lib.DATA_AXIS,
    mean: np.ndarray = CIFAR100_MEAN,
    std: np.ndarray = CIFAR100_STD,
    ef_specs=(),
    model_kwargs: dict | None = None,
):
    """Whole-test-set evaluation as ONE jit call over device-resident data.

    ``eval(state, images_u8, labels) -> {loss, top1, top5, count}`` global
    sums — the fused counterpart of ``make_eval_step``: the uint8 test set
    lives sharded in HBM (see :func:`put_dataset_on_device`), a ``lax.scan``
    sweeps it in per-device batches, normalization happens on device, and
    padding slots are masked (exact counts, no double-count — same
    guarantee as the streaming evaluator). Padding convention: label < 0
    marks a padding example (use it to round the dataset up to a multiple
    of the device count before :func:`put_dataset_on_device`); the
    per-device scan tail is padded the same way internally.
    """
    mean_c = jnp.asarray(mean, jnp.float32)
    std_inv_c = jnp.asarray(1.0 / std, jnp.float32)

    def eval_local(state: TrainState, images_u8, labels):
        n_loc = images_u8.shape[0]
        steps = -(-n_loc // batch_per_device)
        pad = steps * batch_per_device - n_loc
        imgs = jnp.pad(images_u8, ((0, pad), (0, 0), (0, 0), (0, 0)))
        lbls = jnp.pad(labels, (0, pad), constant_values=-1)
        p = jax.tree_util.tree_map(lambda t: t.astype(compute_dtype), state.params)

        def body(acc, i):
            sl = lambda t: lax.dynamic_slice_in_dim(t, i * batch_per_device, batch_per_device)
            x = (sl(imgs).astype(jnp.float32) / 255.0 - mean_c) * std_inv_c
            logits, _ = model_apply(
                p, state.bn_state, x.astype(compute_dtype), train=False,
                axis_name=None, **(model_kwargs or {})
            )
            y = sl(lbls)
            m = (y >= 0).astype(jnp.float32)
            y = jnp.maximum(y, 0)  # safe index for the masked loss
            nll = F.cross_entropy(logits, y, reduction="none")
            maxk = min(5, logits.shape[-1])
            _, pred = lax.top_k(logits.astype(jnp.float32), maxk)
            hits = (pred == y[:, None]).astype(jnp.float32) * m[:, None]
            acc = {
                "loss": acc["loss"] + jnp.sum(nll * m),
                "top1": acc["top1"] + jnp.sum(hits[:, :1]),
                "top5": acc["top5"] + jnp.sum(hits[:, :maxk]),
                "count": acc["count"] + jnp.sum(m),
            }
            return acc, None

        zero = {k: jnp.zeros((), jnp.float32) for k in ("loss", "top1", "top5", "count")}
        sums, _ = lax.scan(body, zero, jnp.arange(steps))
        return jax.tree_util.tree_map(lambda t: lax.psum(t, axis), sums)

    # ``ef_specs``: layout of the int8_ef residuals when the training state
    # carries them (eval never reads them; the in_specs must still match)
    state_spec = TrainState(
        params=P(), bn_state=P(), opt_state=P(), step=P(), ef=ef_specs
    )
    sharded = shard_map(
        eval_local,
        mesh=mesh,
        in_specs=(state_spec, P(axis), P(axis)),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(sharded)
