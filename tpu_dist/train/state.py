"""TrainState — the one pytree that flows through the compiled step.

Bundles what the reference scatters across mutable objects (module params,
BN running stats buffers, ``optimizer.state``, the epoch counter) into a
single immutable pytree, replicated over the mesh. This is what the
checkpoint layer serializes (params + opt state + epoch — the rank-0 save
pattern of reference ``tutorials/2:§7``, plus BN stats which torch keeps
inside ``state_dict`` buffers).

``ef`` carries the error-feedback residuals of the quantized gradient
wire format (``grad_compression='int8_ef'``, train/step.py): flat f32
vectors laid over the data axis — per-REPLICA state, the one part of the
TrainState that is deliberately NOT replicated. Empty (``()``, zero
pytree leaves) for every other compression mode, so existing
4-argument constructions, checkpoints, and shard specs are unchanged.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax.numpy as jnp


class TrainState(NamedTuple):
    params: Any      # model parameters (pytree)
    bn_state: Any    # BatchNorm running mean/var (pytree)
    opt_state: Any   # momentum buffers (pytree, same structure as params)
    step: jnp.ndarray  # global step counter, int32 scalar
    ef: Any = ()     # error-feedback residuals (int8_ef wire format only)

    @classmethod
    def create(cls, params, bn_state, optimizer) -> "TrainState":
        return cls(
            params=params,
            bn_state=bn_state,
            opt_state=optimizer.init(params),
            step=jnp.zeros((), jnp.int32),
        )
