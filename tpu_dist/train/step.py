"""Compiled train/eval steps over the device mesh.

This module is where the reference's four native engines collapse into one
TPU program (SURVEY §7 design stance):

* **DDP gradient allreduce** (``reducer.cpp`` behind ``distributed.py:60``)
  → ``lax.pmean(grads, 'data')`` inside the step; XLA's latency-hiding
  scheduler overlaps the collective with the backward, which is exactly the
  bucketed-overlap service the DDP reducer provides.
* **DataParallel scatter/replicate/gather** (``dataparallel.py:47``)
  → the batch arrives sharded on the ``data`` axis, params arrive
  replicated; nothing to scatter.
* **apex AMP** (``distributed_apex.py:86,119-120``) → a bf16 compute policy:
  master params stay f32, the forward/backward runs in bf16. TPUs have
  hardware bf16 with f32 accumulation in the MXU, so there is NO loss
  scaling — the reason apex needs it (fp16 underflow) does not exist here.
* **grad accumulation + no_sync** (``distributed_gradient_accumulation.py:
  90-111``) → a ``lax.scan`` over sub-batches accumulating LOCAL grads, with
  the single ``pmean`` after the scan. Suppressing cross-rank traffic on
  non-boundary sub-steps is precisely torch's ``model.no_sync()`` (``:106``);
  the 1/K loss scaling (``:103,110``) appears here as the mean over chunk
  grads.
* **per-step barrier + reduce_mean of metrics** (``distributed.py:95,109``)
  → the metric ``pmean`` rides the same compiled step; the barrier is
  deleted (XLA dataflow already orders collectives — SURVEY §5).

Everything is wrapped in ``jax.jit`` over a ``shard_map``, so one Python
call runs the whole step on every chip with static shapes and no host sync.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.comm import compat
from tpu_dist.comm.compat import shard_map
from tpu_dist.nn import functional as F
from tpu_dist.train.state import TrainState


def extract_aux_loss(new_bn):
    """Split a model's auxiliary training loss out of its returned state.

    MoE models report the router load-balancing loss by returning
    ``{"moe_aux_loss": scalar}`` in the state dict during training
    (``vit_moe.py``); it must be POPPED before the state is stored so the
    TrainState pytree structure stays identical step to step (and matches
    the eval-time state). Returns ``(clean_state, aux_or_None)``."""
    if isinstance(new_bn, dict) and "moe_aux_loss" in new_bn:
        new_bn = dict(new_bn)
        return new_bn, new_bn.pop("moe_aux_loss")
    return new_bn, None


GRAD_COMPRESSION_MODES = ("none", "bf16", "int8", "int8_ef")

# ONE registry of the shard-auditable parallelism config families: name →
# the :func:`make_train_step` kwargs that select the family. This is the
# enumeration the static analyzers walk (the jaxpr audit's budget cases,
# the shardlint HLO audit — tpu_dist/analysis) and the search space the
# measurement-calibrated ``--auto_shard`` planner ranks over
# (``analysis/planner.py``): every entry lowers to a distinct collective
# inventory, and each gets its own verified entry in ``shard_report.json``
# (docs/shard_report.md). Families that need a model/mesh beyond the flag
# combo (fsdp's per-leaf specs, tp's param_specs, sp's ring-attention
# model) carry the axis flags here and get their builders in
# ``analysis/shardlint.py``. The planner's TRAINER-flag projection of
# these step kwargs lives in ``planner.FAMILY_TRAIN_OVERRIDES`` — a new
# family here that --auto_shard apply should reach needs an entry there
# too (test_planner pins the two registries against each other).
SHARD_CONFIG_FAMILIES: dict = {
    "dp_sgd": {},
    "dp_sgd_accum4": {"grad_accum_steps": 4},
    "dp_bf16": {"compute_dtype": "bfloat16"},  # compute policy, f32 wire
    "dp_wire_bf16": {"grad_compression": "bf16"},
    "dp_int8": {"grad_compression": "int8"},
    "dp_int8_ef": {"grad_compression": "int8_ef"},
    "zero1_sgd": {"shard_weight_update": True},
    "zero1_int8": {"shard_weight_update": True, "grad_compression": "int8"},
    "dp_device_metrics": {"device_metrics": True},
    "tp": {"tp_axis": "model"},    # + param_specs from the model
    "sp": {"seq_axis": "seq"},     # + a ring-attention model
    "fsdp": {},                    # the GSPMD engine (parallel/fsdp.py)
}


def family_step_kwargs(name: str) -> dict:
    """Resolve a :data:`SHARD_CONFIG_FAMILIES` entry to real
    :func:`make_train_step` kwargs (the registry stores dtypes by NAME so
    it stays a plain-data enumeration planners can serialize)."""
    kw = dict(SHARD_CONFIG_FAMILIES[name])
    if isinstance(kw.get("compute_dtype"), str):
        kw["compute_dtype"] = jnp.dtype(kw["compute_dtype"]).type
    return kw

# Modes that use the quantized two-stage reduce below. They are scoped to
# the plain data-parallel reduce (per-step and fused-epoch) and the ZeRO-1
# reduce-scatter; the model-parallel reduces (tp/ep/pp/sp) keep the cast
# wire formats — see make_train_step's composition wall.
QUANTIZED_MODES = ("int8", "int8_ef")

_QUANT_KEY_SEED = 0x1D8  # stochastic-rounding PRNG stream, folded per step


def validate_grad_compression(mode: str) -> None:
    if mode not in GRAD_COMPRESSION_MODES:
        raise ValueError(
            f"grad_compression must be one of {GRAD_COMPRESSION_MODES}, "
            f"got {mode!r}"
        )


def grad_wire(g, mode: str):
    """Gradient wire format for cross-replica reduces — ONE definition of
    the compression contract, shared by the per-step path here and the
    fused-epoch path (``train/epoch.py``) so the semantics cannot drift.
    ``'bf16'`` halves gradient ICI/DCN traffic (full f32 exponent range,
    so the pre-reduce 1/n scaling cannot underflow). The int8 modes do not
    go through this per-leaf cast — they reduce on the flat quantized
    two-stage path (:func:`quantized_pmean_flat`)."""
    return g.astype(jnp.bfloat16) if mode == "bf16" else g


def grad_unwire(g, like, mode: str):
    """Restore the update dtype after a compressed reduce."""
    return g.astype(like.dtype) if mode == "bf16" else g


def ef_state_spec(mode: str, *, zero1: bool = False, axis: str = mesh_lib.DATA_AXIS):
    """PartitionSpec tree for ``TrainState.ef`` under ``mode``.

    The residuals are flat f32 vectors laid over the data axis (per-replica
    state — each replica compensates ITS OWN quantization error): ``r1``
    covers the leg-1 (send-side) error over the full padded gradient,
    ``r2`` the leg-2 error on the owned reduced shard. ZeRO-1 has no
    quantized second leg (the param all-gather stays in the param dtype),
    so only ``r1`` exists there. Every other mode carries ``()``.
    """
    if mode != "int8_ef":
        return ()
    spec = {"r1": P(axis)}
    if not zero1:
        spec["r2"] = P(axis)
    return spec


def ef_state_host_zeros(params, n: int, *, zero1: bool = False):
    """Host (numpy) zero residuals matching :func:`ef_state_spec`'s layout
    for an ``n``-way data axis — the placement-free half of
    :func:`init_ef_state` (the Trainer places these with
    ``mesh.place_host_tree``, which also covers multi-host meshes)."""
    import numpy as np  # noqa: PLC0415
    from jax.flatten_util import ravel_pytree  # noqa: PLC0415

    from tpu_dist.comm.quantize import padded_len  # noqa: PLC0415

    L = ravel_pytree(params)[0].shape[0]
    P_len = padded_len(L, n)
    ef = {"r1": np.zeros((n * P_len,), np.float32)}
    if not zero1:
        ef["r2"] = np.zeros((P_len,), np.float32)
    return ef


def init_ef_state(
    params, mesh: Mesh, *, zero1: bool = False, axis: str = mesh_lib.DATA_AXIS,
):
    """Zero error-feedback residuals, placed on the mesh (the ``int8_ef``
    counterpart of :func:`init_sharded_opt_state`): ``r1`` is one padded
    gradient-length vector PER replica (global ``(n*P,)``, sharded over
    ``axis``), ``r2`` one reduced-shard vector per replica (global
    ``(P,)``)."""
    ef = ef_state_host_zeros(params, int(mesh.shape[axis]), zero1=zero1)
    return mesh_lib.place_host_tree(
        mesh, ef, ef_state_spec("int8_ef", zero1=zero1, axis=axis)
    )


def _chunk_bounds(total: int, k: int) -> list:
    """Split ``[0, total)`` into at most ``k`` contiguous column groups of
    near-equal width (remainder spread over the first groups, NO padding —
    the pieces repartition the original extent exactly, so chunking the
    ZeRO-1 RS+AG pair moves the collective *schedule* without adding a
    single wire byte; that invariance is what TD121 pins)."""
    k = max(1, min(k, total))
    base, rem = divmod(total, k)
    bounds, lo = [], 0
    for i in range(k):
        hi = lo + base + (1 if i < rem else 0)
        bounds.append((lo, hi))
        lo = hi
    return bounds


def _quantized_reduce_scatter_rows(rows, axis: str, key, chunk: int):
    """EQuARX-style quantized reduce-scatter of ``rows`` ``(n, m)`` over
    ``axis``: quantize → int8 ``all_to_all`` (+ tiny f32 scale sideband) →
    local dequantize-sum. Returns ``(reduced_shard (m,), sent)`` where
    ``sent`` is this replica's dequantized transmission (for the
    error-feedback residual).

    This is the software spelling of a quantized ``psum_scatter``: the
    transpose leg carries int8 instead of f32 (4× fewer wire bytes), the
    reduction itself runs locally in f32 — no int overflow, same
    schedule-shape as the ring reduce-scatter XLA emits for ``psum``.
    """
    from tpu_dist.comm.quantize import dequantize_int8, quantize_int8  # noqa: PLC0415

    q, s = quantize_int8(rows, chunk, key)
    qt = lax.all_to_all(q, axis, split_axis=0, concat_axis=0, tiled=True)
    st = lax.all_to_all(s, axis, split_axis=0, concat_axis=0, tiled=True)
    reduced = jnp.sum(dequantize_int8(qt, st, chunk), axis=0)
    return reduced, dequantize_int8(q, s, chunk)


def quantized_pmean_flat(grads, axis: str, *, key, ef, chunk: int):
    """Two-stage quantized mean of a grad pytree over ``axis`` — the int8
    replacement for ``lax.pmean(grads)`` (EQuARX, arXiv:2506.17615): BOTH
    wire legs are compressed, not just the input.

    1. Flatten + pad to a multiple of n, pre-scale by 1/n (so the
       dequantize-sum lands on the MEAN; bf16-wire precedent: the f32
       exponent range of the scales makes this safe).
    2. Leg 1: per-chunk int8 quantize, ``all_to_all`` the rows — each
       replica reduces its own shard locally in f32 (quantized
       reduce-scatter).
    3. Leg 2: re-quantize the reduced shard, int8 ``all_gather`` (+ scale
       sideband), dequantize, unravel.

    ``ef``: ``()`` for plain ``int8`` (stochastic rounding alone keeps the
    estimate unbiased); the ``{"r1", "r2"}`` residual dict for
    ``int8_ef`` — the residual is added BEFORE quantization and the
    realized error carried to the next step (error feedback, per replica,
    for each leg independently). Returns ``(mean_grads, new_ef)``.
    """
    from jax.flatten_util import ravel_pytree  # noqa: PLC0415

    from tpu_dist.comm.quantize import (  # noqa: PLC0415
        dequantize_int8,
        padded_len,
        quantize_int8,
    )

    n = compat.axis_size(axis)
    flat, unravel = ravel_pytree(grads)
    L = flat.shape[0]
    P_len = padded_len(L, n)
    m = P_len // n
    x = jnp.pad(flat, (0, P_len - L)) / n
    if ef:
        x = x + ef["r1"]
    k1 = jax.random.fold_in(key, 1)
    k2 = jax.random.fold_in(key, 2)
    reduced, sent = _quantized_reduce_scatter_rows(
        x.reshape(n, m), axis, k1, chunk
    )
    new_ef = ()
    if ef:
        new_ef = {"r1": x - sent.reshape(P_len)}
        reduced = reduced + ef["r2"]
    q2, s2 = quantize_int8(reduced, chunk, k2)
    if ef:
        new_ef["r2"] = reduced - dequantize_int8(q2, s2, chunk)
    qg = lax.all_gather(q2, axis, tiled=True)
    sg = lax.all_gather(s2, axis, tiled=True)
    full = dequantize_int8(
        qg.reshape(n, m), sg.reshape(n, -1), chunk
    ).reshape(P_len)[:L]
    return unravel(full), new_ef


def compressed_pmean(grads, axes, mode: str, *, key=None, ef=(), chunk=None):
    """Cross-replica grad mean on the compressed wire format — the shared
    entry point of the per-step and fused-epoch paths. Returns
    ``(mean_grads, new_ef)``; ``new_ef`` is ``()`` except under
    ``int8_ef`` (pass the state's residuals in as ``ef``). ``key`` seeds
    the stochastic rounding for the quantized modes (required there)."""
    if mode in QUANTIZED_MODES:
        if isinstance(axes, (tuple, list)):
            raise ValueError(
                "int8 grad compression reduces over a single mesh axis "
                f"(got {axes!r}) — see make_train_step's composition wall"
            )
        from tpu_dist.comm.quantize import DEFAULT_CHUNK  # noqa: PLC0415

        return quantized_pmean_flat(
            grads, axes, key=key, ef=ef if mode == "int8_ef" else (),
            chunk=chunk or DEFAULT_CHUNK,
        )
    if mode == "none":
        return lax.pmean(grads, axes), ef
    # one multi-operand psum for the whole tree (same eqn shape as the
    # per-step path, so the TD101 budgets match across both consumers)
    wired = lax.pmean(
        jax.tree_util.tree_map(lambda g: grad_wire(g, mode), grads), axes
    )
    return jax.tree_util.tree_map(
        lambda g, like: grad_unwire(g, like, mode), wired, grads
    ), ef


def make_train_step(
    model_apply: Callable,
    optimizer,
    mesh: Mesh,
    *,
    grad_accum_steps: int = 1,
    sync_bn: bool = True,
    compute_dtype=jnp.float32,
    axis: str = mesh_lib.DATA_AXIS,
    donate: bool = True,
    shard_weight_update: bool = False,
    label_smoothing: float = 0.0,
    grad_clip_norm: float = 0.0,
    moe_aux_coef: float = 0.01,
    seq_axis: str | None = None,
    tp_axis: str | None = None,
    ep_axis: str | None = None,
    pp_axis: str | None = None,
    param_specs=None,
    remat: bool = False,
    grad_compression: str = "none",
    quant_chunk: int | None = None,
    pmean_fusion: str = "fused",
    rs_ag_chunks: int = 1,
    device_metrics: bool = False,
    model_kwargs: dict | None = None,
):
    """Build ``step(state, images, labels, lr) -> (state, metrics)``.

    ``model_apply(params, bn_state, x, train=, axis_name=)`` is the
    functional model (e.g. ``ResNetDef.apply``). ``metrics`` is a dict of
    replica-averaged scalars: loss, top-1/top-5 accuracy (the reference's
    per-step ``reduce_mean(loss)`` + ``accuracy`` line,
    ``distributed.py:104-111``).

    ``shard_weight_update=True`` enables cross-replica weight-update
    sharding (Xu et al. 2020, arXiv:2004.13336 — ZeRO-1 on TPU): the grad
    allreduce becomes reduce-scatter, each replica updates only its 1/n
    shard of the (flattened) parameters with a SHARDED momentum state, and
    an all-gather rebuilds the replicated params. Same numerics, 1/n the
    optimizer-state memory, and 2x less collective traffic than
    allreduce+full-update at large scale. The optimizer state becomes one
    flat f32 array per replica — build it with
    :func:`init_sharded_opt_state`.

    ``seq_axis``: sequence-parallel training over a 2-D mesh (DP×SP). The
    batch stays sharded on ``axis`` and replicated over ``seq_axis``; the
    model (e.g. ViT) slices its own token chunk and runs ring attention
    over the axis. Parameter gradients are ``pmean``-ed over ``seq_axis``
    on top of the ``pmean`` over the data axis (each shard differentiates a
    full loss replica). Composes with ``shard_weight_update`` (the seq
    pmean happens before the data-axis reduce-scatter).

    ``grad_compression='bf16'``: cast gradients to bf16 for the
    cross-replica reduce and back to f32 for the update — halves gradient
    ICI/DCN traffic, the TPU equivalent of torch DDP's
    ``bf16_compress_hook`` communication hook (quantized-allreduce family,
    cf. EQuARX, arXiv:2506.17615). Local accumulation (grad_accum scan)
    stays f32; only the wire format changes. Applies to the DP/EP/SP
    reduces and the ZeRO-1 reduce-scatter; the FSDP engine's collectives
    are GSPMD-inserted and are not hooked.

    ``grad_compression='int8'`` / ``'int8_ef'``: per-chunk scaled int8
    with stochastic rounding, reduced as a two-stage quantized
    reduce-scatter + all-gather (EQuARX-style — BOTH wire legs are int8,
    ~4× less gradient traffic than f32, 2× less than bf16; see
    docs/compression.md). ``int8_ef`` adds per-replica error-feedback
    residuals carried in ``TrainState.ef`` (build with
    :func:`init_ef_state`), so the realized quantization error is
    compensated on the next step rather than discarded. Scoped to the
    plain data-parallel reduce and the ZeRO-1 reduce-scatter (the ZeRO-1
    param all-gather stays in the param dtype — it carries weights, not
    gradients); the model-parallel reduces (tp/ep/pp/sp) are refused, and
    the FSDP engine's GSPMD collectives remain unhookable.

    ``pmean_fusion`` / ``rs_ag_chunks``: collective-*scheduling* knobs for
    the overlap autotuner (``python -m tpu_dist.analysis tune-overlap``).
    ``pmean_fusion='per_leaf'`` reduces each gradient leaf with its own
    ``pmean`` instead of the single fused multi-operand reduce;
    ``rs_ag_chunks=k`` splits the ZeRO-1 reduce-scatter / all-gather pair
    into ``k`` pipelined column-group collectives. Both move the HLO
    collective *schedule* only — the payload-byte inventory is identical
    by construction (no repacking, no extra padding) and TD121 pins
    exactly that invariant.

    ``device_metrics=True``: fuse the training-health scalars
    (``obs/device_stats.py`` — global grad norm, param norm, update
    ratio, nonfinite-leaf count) into the step's metrics dict. Computed
    on the POST-reduce gradients, so everything is local arithmetic:
    zero extra collectives, zero extra host fetches (the scalars ride the
    metrics tree the trainer already fetches once per logged step) — the
    TD107 jaxpr rule pins both halves, and flag-off is byte-identical.
    Scoped to the replicated-param paths (plain DP/SP, any
    ``grad_compression``, grad accumulation): under ZeRO-1/tp/ep/pp the
    reduced gradient exists only as shards, so the global norms would
    need extra collectives — refused rather than silently costed.
    """
    K = int(grad_accum_steps)
    n_axis = int(mesh.shape[axis])
    validate_grad_compression(grad_compression)
    quantized = grad_compression in QUANTIZED_MODES
    from tpu_dist.comm.quantize import DEFAULT_CHUNK  # noqa: PLC0415

    q_chunk = int(quant_chunk) if quant_chunk else DEFAULT_CHUNK
    if quantized and any(
        a is not None for a in (tp_axis, ep_axis, pp_axis, seq_axis)
    ):
        # the flat two-stage reduce assumes a replicated param tree and one
        # reduce axis; the model-parallel engines reduce per leaf over
        # other axes with their own layouts — cast compression (bf16)
        # composes there, the quantized transpose does not
        raise ValueError(
            f"grad_compression={grad_compression!r} is scoped to the plain "
            "data-parallel and ZeRO-1 paths; it cannot combine with "
            "sp/tp/ep/pp (use grad_compression='bf16' there)"
        )
    if device_metrics and (
        shard_weight_update
        or any(a is not None for a in (tp_axis, ep_axis, pp_axis))
    ):
        # the health scalars are free only where the reduced grad tree and
        # the params are replica-identical; under ZeRO-1/tp/ep/pp they
        # exist as shards and the global norms would need collectives the
        # TD107 zero-cost contract forbids
        raise ValueError(
            "device_metrics is scoped to the replicated-param paths "
            "(plain DP/SP, any grad_compression) — it cannot combine "
            "with shard_weight_update/tp/ep/pp"
        )
    if pmean_fusion not in ("fused", "per_leaf"):
        raise ValueError(
            f"pmean_fusion={pmean_fusion!r}: expected 'fused' or 'per_leaf'"
        )
    if pmean_fusion == "per_leaf" and (
        quantized or shard_weight_update or ep_axis is not None
    ):
        # the knob only exists where the fused multi-operand pmean exists:
        # the plain data-parallel reduce. The quantized path reduces one
        # flat vector (nothing to split), ZeRO-1 reduce-scatters, and the
        # MoE engine owns its own per-group reduces — accepting the knob
        # there would be a silent no-op, which TD121 tooling forbids
        raise ValueError(
            "pmean_fusion='per_leaf' is scoped to the non-quantized "
            "data-parallel reduce; it cannot combine with "
            "grad_compression int8/ep/shard_weight_update"
        )
    rs_ag_chunks = int(rs_ag_chunks)
    if rs_ag_chunks < 1:
        raise ValueError(f"rs_ag_chunks={rs_ag_chunks}: must be >= 1")
    if rs_ag_chunks > 1 and not (shard_weight_update and not quantized):
        # pipelining the RS+AG pair only means something where that pair
        # exists: the non-quantized ZeRO-1 update (the quantized variant
        # already chunks on the int8 wire via quant_chunk)
        raise ValueError(
            "rs_ag_chunks > 1 is scoped to the non-quantized ZeRO-1 path "
            "(shard_weight_update=True, grad_compression none/bf16)"
        )
    if device_metrics:
        from tpu_dist.obs.device_stats import compute_device_stats  # noqa: PLC0415

    def wire(g):
        return grad_wire(g, grad_compression)

    def unwire(g, like):
        return grad_unwire(g, like, grad_compression)

    def quant_key(step):
        """Per-step, per-replica stochastic-rounding stream (deterministic
        replay: folds the step counter, then this replica's position)."""
        return jax.random.fold_in(
            jax.random.fold_in(jax.random.PRNGKey(_QUANT_KEY_SEED), step),
            lax.axis_index(axis),
        )
    # Composition walls. grad_clip_norm composes with EVERY axis (the clip
    # computes a shard-aware global norm — see clip_grads). The remaining
    # exclusions are genuinely structural, not deferred work:
    if tp_axis is not None:
        if param_specs is None:
            raise ValueError("tp_axis requires param_specs (per-leaf shardings)")
        if shard_weight_update:
            # ZeRO-1 is BY DESIGN the data-parallel fast path (SGD or
            # AdamW): it ravels the (replicated) param tree into flat
            # vectors and reduce-scatters over the data axis. Under TP the
            # local tree is a per-shard slice, so the flat layout no longer
            # lines up — and rather than grow a second sharding engine,
            # that territory belongs to FSDP (parallel/fsdp.py), which
            # shards per-leaf via GSPMD and composes by specs. Final
            # scoping decision, not deferred work (VERDICT r2 #6).
            raise ValueError(
                "tp_axis + shard_weight_update is out of ZeRO-1's scope "
                "(DP-only fast path by design) — use --fsdp for "
                "sharded weight updates beyond plain DP"
            )
        # tp_axis + seq_axis composes (3-D DPxTPxSP): the conjugate VJP ops
        # absorb the model axis, grads pmean over data+seq — verified exact
        # (tests/test_3d_mesh_training.py)
    if ep_axis is not None:
        if param_specs is None:
            raise ValueError("ep_axis requires param_specs (per-leaf shardings)")
        if shard_weight_update or seq_axis or tp_axis:
            # ZeRO-1: same flat-layout conflict as under TP. seq/tp: the MoE
            # model's dispatch all_to_all and the ring-attention / Megatron
            # sharding would have to thread the same token dimension through
            # two conflicting layouts — a model-architecture change, not a
            # step-function flag.
            raise ValueError(
                "ep_axis is incompatible with shard_weight_update / "
                "seq_axis / tp_axis (structural; see docstring)"
            )
    if pp_axis is not None:
        if param_specs is None:
            raise ValueError("pp_axis requires param_specs (per-leaf shardings)")
        if shard_weight_update or seq_axis or ep_axis:
            # ZeRO-1: flat-layout conflict (stage-sharded leaves). seq/ep
            # inside a pipeline stage would thread the token dim through two
            # conflicting layouts (ring/all_to_all under the stage ring).
            # tp COMPOSES (Megatron PP×TP): the per-block psum pair runs
            # over the model axis inside each stage, orthogonal to the pipe
            # ring's ppermute — tests/test_pp_tp_training.py pins it.
            raise ValueError(
                "pp_axis is incompatible with shard_weight_update / "
                "seq_axis / ep_axis (structural; see docstring)"
            )
    # the expert axis doubles as a data axis outside the MoE: batch shards
    # over both, metrics/loss reduce over both
    batch_axes = (axis, ep_axis) if ep_axis is not None else axis
    bn_axis = batch_axes if sync_bn else None

    def loss_fn(params, bn_state, images, labels):
        x = images.astype(compute_dtype)
        p = jax.tree_util.tree_map(lambda t: t.astype(compute_dtype), params)
        kw = {}
        if seq_axis is not None:
            kw["seq_axis"] = seq_axis
        if tp_axis is not None:
            kw["tp_axis"] = tp_axis
        if ep_axis is not None:
            kw["ep_axis"] = ep_axis
        if pp_axis is not None:
            kw["pp_axis"] = pp_axis
        if model_kwargs:
            kw.update(model_kwargs)
        logits, new_bn = model_apply(p, bn_state, x, train=True, axis_name=bn_axis, **kw)
        new_bn, aux = extract_aux_loss(new_bn)
        loss = F.cross_entropy(logits, labels, label_smoothing=label_smoothing)
        if aux is not None:
            loss = loss + moe_aux_coef * aux.astype(loss.dtype)
        return loss, (new_bn, logits)

    def clip_grads(grads):
        """Global-norm clip on the ALREADY-REDUCED grads (so the norm is the
        true global-batch gradient norm, identical on every replica).

        Under model parallelism (tp/ep/pp) some leaves are SHARDED across a
        model axis — their local sum-of-squares is only this shard's slice of
        the leaf's norm. Leaves are grouped by the model axes in their spec:
        each sharded group's sum gets one ``psum`` over those axes
        (shard-norm pattern, same as the ZeRO-1 path below); replicated
        leaves' grads are identical on every model shard (the model's VJP
        collectives guarantee it) and contribute locally. A final ``pmean``
        keeps the scale bit-identical on every shard."""
        if grad_clip_norm <= 0.0:
            return grads
        model_axes = tuple(a for a in (tp_axis, ep_axis, pp_axis) if a is not None)
        if not model_axes or param_specs is None:
            sq = sum(jnp.sum(jnp.square(g)) for g in jax.tree_util.tree_leaves(grads))
        else:
            def leaf_model_axes(spec):
                names = set()
                for entry in spec:
                    for name in (entry if isinstance(entry, tuple) else (entry,)):
                        if name is not None:
                            names.add(name)
                return tuple(a for a in model_axes if a in names)

            groups: dict = {}

            def accumulate(g, spec):
                groups.setdefault(leaf_model_axes(spec), []).append(
                    jnp.sum(jnp.square(g))
                )
                return g

            jax.tree_util.tree_map(accumulate, grads, param_specs)
            sq = 0.0
            for axes, sums in groups.items():
                group_sq = sum(sums)
                if axes:
                    group_sq = lax.psum(group_sq, axes)
                sq = sq + group_sq
            sq = lax.pmean(sq, model_axes)
        norm = jnp.sqrt(sq)
        scale = jnp.minimum(1.0, grad_clip_norm / jnp.maximum(norm, 1e-12))
        return jax.tree_util.tree_map(lambda g: g * scale, grads)

    if remat:
        # rematerialize the forward during the backward: activations are
        # recomputed instead of stored, trading ~33% extra FLOPs for O(depth)
        # less activation memory — the standard TPU lever for bigger batches.
        # Numerics identical (tests/test_remat.py).
        loss_fn = jax.checkpoint(loss_fn)
    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def local_grads(params, bn_state, images, labels):
        """Local (pre-allreduce) grads; grad-accum via scan when K > 1."""
        if K == 1:
            (loss, (bn, logits)), grads = grad_fn(params, bn_state, images, labels)
            return loss, grads, bn, logits
        # [B, ...] -> [K, B/K, ...]; BN state threads through the scan so
        # running stats update every sub-step, like torch.
        chunked = jax.tree_util.tree_map(
            lambda t: t.reshape((K, t.shape[0] // K) + t.shape[1:]), (images, labels)
        )

        def body(carry, chunk):
            bn, acc = carry
            imgs, lbls = chunk
            (loss, (bn, logits)), g = grad_fn(params, bn, imgs, lbls)
            acc = jax.tree_util.tree_map(jnp.add, acc, g)
            return (bn, acc), (loss, logits)

        zero = jax.tree_util.tree_map(jnp.zeros_like, params)
        (bn, acc), (losses, logits) = lax.scan(body, (bn_state, zero), chunked)
        grads = jax.tree_util.tree_map(lambda g: g / K, acc)  # tutorials/1 mean math
        logits = logits.reshape((-1,) + logits.shape[2:])
        return losses.mean(), grads, bn, logits

    def step_local(state: TrainState, images, labels, lr):
        loss, grads, new_bn, logits = local_grads(state.params, state.bn_state, images, labels)

        if not sync_bn:
            # Local-BN replicas hold diverged running stats; average them so
            # the replicated state stays consistent (torch instead keeps
            # per-rank stats and saves rank 0's — documented deviation).
            new_bn = lax.pmean(new_bn, axis)

        new_ef = state.ef
        if shard_weight_update:
            new_params, new_opt, new_ef = _sharded_update(state, grads, lr)
        else:
            if ep_axis is not None:
                grads = _ep_grad_reduce(grads)
            elif quantized:
                # THE data-parallel reduce on the int8 wire: two-stage
                # quantized reduce-scatter + all-gather, residuals carried
                # in the state under int8_ef
                grads, new_ef = quantized_pmean_flat(
                    grads, axis, key=quant_key(state.step),
                    ef=state.ef if grad_compression == "int8_ef" else (),
                    chunk=q_chunk,
                )
            else:
                # THE data-parallel step: average grads over the mesh (DDP),
                # on the (optionally bf16-compressed) wire format; one cast
                # round-trip covers both axes.
                local = grads
                if pmean_fusion == "per_leaf":
                    # one pmean PER GRADIENT LEAF instead of one fused
                    # multi-operand reduce: identical payload bytes on the
                    # wire, but many small collectives the scheduler can
                    # launch as each leaf's backward finishes — the overlap
                    # autotuner's schedule knob (analysis/overlap.py, TD121)
                    grads = jax.tree_util.tree_map(
                        lambda g: lax.pmean(wire(g), axis), grads
                    )
                else:
                    grads = lax.pmean(jax.tree_util.tree_map(wire, grads), axis)
                if seq_axis is not None:
                    # every seq shard differentiates a full replica of the
                    # loss, so local grads sum to n× the true gradient —
                    # MEAN over the axis recovers it (verified empirically,
                    # tests/test_seq_parallel_training.py)
                    grads = lax.pmean(grads, seq_axis)
                grads = jax.tree_util.tree_map(unwire, grads, local)
            grads = clip_grads(grads)
            new_params, new_opt = optimizer.update(
                grads, state.opt_state, state.params, lr
            )
        new_state = TrainState(new_params, new_bn, new_opt, state.step + 1, new_ef)

        # Replica-averaged metrics, fused into the same program
        labels_all = labels
        c1, c5 = F.topk_correct(logits.astype(jnp.float32), labels_all, (1, 5))
        b = labels_all.shape[0]
        metrics = {
            "loss": lax.pmean(loss, batch_axes),
            "acc1": lax.psum(c1, batch_axes) / (b * lax.psum(1, batch_axes)) * 100.0,
            "acc5": lax.psum(c5, batch_axes) / (b * lax.psum(1, batch_axes)) * 100.0,
        }
        if device_metrics:
            # grads is the post-reduce (post-clip) tree here — the ZeRO-1
            # branch (where it would be a shard) is refused above — so
            # every stat is local arithmetic riding the same fetch
            metrics.update(
                compute_device_stats(grads, state.params, new_params)
            )
        return new_state, metrics

    def _ep_grad_reduce(grads):
        """Per-leaf reduction under expert parallelism (rule verified
        empirically, tests/test_expert_parallel_training.py): expert-sharded
        leaves already aggregate the whole expert group's token
        contributions (n_ep× scaled) → pmean over data, divide by n_ep;
        replicated leaves are plain per-shard grads → pmean over both axes.
        """
        n_ep = compat.axis_size(ep_axis)

        def has_ep(spec):
            return any(
                ep_axis in (e if isinstance(e, tuple) else (e,))
                for e in spec
                if e is not None
            )

        def red(g, spec):
            if has_ep(spec):
                return unwire(lax.pmean(wire(g), axis), g) / n_ep
            return unwire(lax.pmean(wire(g), batch_axes), g)

        return jax.tree_util.tree_map(red, grads, param_specs)

    def _sharded_update(state: TrainState, grads, lr):
        """reduce-scatter grads → update own param shard with sharded
        optimizer state → all-gather params (arXiv:2004.13336). Works for
        any optimizer whose update is elementwise over its buffers: SGD's
        momentum rides as one flat vector, AdamW's mu/nu as two (with the
        ``auto`` decay mask converted to a positional per-element vector —
        leaf ranks are invisible in the flat layout).

        Under the int8 modes the reduce-scatter leg carries the quantized
        wire (the one gradient collective in this engine); the param
        all-gather below stays in the param dtype — it moves weights, and
        quantizing weights would drift the replicated copies, a different
        trade than compressing a gradient that feeds a smooth update.
        Returns ``(params, opt_state, ef)``."""
        from jax.flatten_util import ravel_pytree  # noqa: PLC0415

        if seq_axis is not None:
            # same correction as the plain path: each seq shard holds a
            # full-loss-replica gradient, mean over the axis recovers truth
            grads = jax.tree_util.tree_map(
                lambda g: unwire(lax.pmean(wire(g), seq_axis), g), grads
            )
        flat_g, _ = ravel_pytree(grads)
        flat_p, unravel = ravel_pytree(state.params)
        L = flat_g.shape[0]
        chunk = -(-L // n_axis)
        pad = chunk * n_axis - L
        new_ef = state.ef
        if quantized:
            x = jnp.pad(flat_g / n_axis, (0, pad))
            if grad_compression == "int8_ef":
                x = x + state.ef["r1"]
            g_shard, sent = _quantized_reduce_scatter_rows(
                x.reshape(n_axis, chunk), axis,
                quant_key(state.step), q_chunk,
            )
            if grad_compression == "int8_ef":
                new_ef = {"r1": x - sent.reshape(chunk * n_axis)}
        elif rs_ag_chunks > 1:
            # pipelined reduce-scatter: split the padded flat vector into
            # column groups of the per-replica extent and reduce-scatter
            # each independently — same total payload (the groups tile the
            # extent exactly, no extra padding), but k smaller collectives
            # the scheduler can interleave with the shard update below.
            # Shard p of group [c0:c1) is exactly rows[p, c0:c1], so the
            # concatenation rebuilds this replica's contiguous g_shard.
            rows = wire(jnp.pad(flat_g / n_axis, (0, pad))).reshape(n_axis, chunk)
            g_shard = jnp.concatenate([
                lax.psum_scatter(
                    rows[:, c0:c1].reshape(-1), axis,
                    scatter_dimension=0, tiled=True,
                )
                for c0, c1 in _chunk_bounds(chunk, rs_ag_chunks)
            ]).astype(flat_g.dtype)
        else:
            g_shard = lax.psum_scatter(
                wire(jnp.pad(flat_g / n_axis, (0, pad))), axis,
                scatter_dimension=0, tiled=True,
            ).astype(flat_g.dtype)
        if grad_clip_norm > 0.0:  # global norm from shard norms (one psum)
            sq = lax.psum(jnp.sum(jnp.square(g_shard)), axis)
            scale = jnp.minimum(1.0, grad_clip_norm / jnp.maximum(jnp.sqrt(sq), 1e-12))
            g_shard = g_shard * scale
        idx = lax.axis_index(axis)
        p_shard = lax.dynamic_slice_in_dim(jnp.pad(flat_p, (0, pad)), idx * chunk, chunk)
        kw = {}
        if hasattr(optimizer, "leaf_wd_intervals"):
            # AdamW: the rank-based decay mask in flat coordinates — this
            # shard's per-element decay built from static leaf intervals
            # (iota comparisons; never a model-length constant in HBM)
            pos = idx * chunk + jnp.arange(chunk)
            wd_shard = jnp.zeros((chunk,), jnp.float32)
            for start, end, w in optimizer.leaf_wd_intervals(state.params):
                wd_shard = wd_shard + w * (
                    (pos >= start) & (pos < end)
                ).astype(jnp.float32)
            kw["wd_tree"] = wd_shard
        new_p_shard, new_b_shard = optimizer.update(
            g_shard, state.opt_state, p_shard, lr, **kw
        )
        if rs_ag_chunks > 1:
            # mirrored pipelined all-gather: gather each column group and
            # reassemble columnwise — tiled gather of piece [c0:c1) yields
            # (n*(c1-c0),) = rows (n, c1-c0), so concat on axis=1 restores
            # the (n, chunk) row layout the flat vector linearizes
            parts = [
                lax.all_gather(
                    new_p_shard[c0:c1], axis, tiled=True
                ).reshape(n_axis, c1 - c0)
                for c0, c1 in _chunk_bounds(chunk, rs_ag_chunks)
            ]
            flat_new = jnp.concatenate(parts, axis=1).reshape(-1)[:L]
        else:
            flat_new = lax.all_gather(new_p_shard, axis, tiled=True)[:L]
        return unravel(flat_new), new_b_shard, new_ef

    p_spec = param_specs if param_specs is not None else P()
    if shard_weight_update:
        # ZeRO-1 flat layout: one sharded vector per optimizer buffer
        # (SGD momentum, or AdamW mu/nu + replicated count)
        opt_spec = (
            optimizer.flat_state_specs(axis)
            if hasattr(optimizer, "flat_state_specs")
            else P(axis)
        )
    elif hasattr(optimizer, "state_specs"):
        # optimizer state may not mirror the param tree (AdamW's
        # {mu, nu, count}) — ask the optimizer for its layout
        opt_spec = optimizer.state_specs(p_spec)
    else:
        opt_spec = p_spec
    state_spec = TrainState(
        params=p_spec,
        bn_state=P(),
        opt_state=opt_spec,
        step=P(),
        ef=ef_state_spec(
            grad_compression, zero1=shard_weight_update, axis=axis
        ),
    )
    batch_spec = P(batch_axes)
    sharded = shard_map(
        step_local,
        mesh=mesh,
        in_specs=(state_spec, batch_spec, batch_spec, P()),
        out_specs=(state_spec, P()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def init_sharded_opt_state(
    params, mesh: Mesh, axis: str = mesh_lib.DATA_AXIS, optimizer=None,
):
    """Flat, axis-sharded optimizer state for ``shard_weight_update`` steps:
    f32 vectors of ceil(L/n)*n zeros laid over the axis (each replica holds
    its 1/n shard). Default (``optimizer=None``): SGD's single momentum
    vector. An optimizer exposing ``init_flat_state``/``flat_state_specs``
    (AdamW) gets its own flat layout — mu/nu sharded, count replicated."""
    from jax.flatten_util import ravel_pytree  # noqa: PLC0415
    from jax.sharding import NamedSharding  # noqa: PLC0415

    L = ravel_pytree(params)[0].shape[0]
    n = int(mesh.shape[axis])
    chunk = -(-L // n)
    if optimizer is not None and hasattr(optimizer, "init_flat_state"):
        state = optimizer.init_flat_state(chunk * n)
        specs = optimizer.flat_state_specs(axis)
        return jax.tree_util.tree_map(
            lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
            state, specs,
        )
    return jax.device_put(
        jnp.zeros((chunk * n,), jnp.float32), NamedSharding(mesh, P(axis))
    )


def make_eval_step(
    model_apply: Callable,
    mesh: Mesh,
    *,
    compute_dtype=jnp.float32,
    axis=mesh_lib.DATA_AXIS,
    tp_axis: str | None = None,
    ep_axis: str | None = None,
    pp_axis: str | None = None,
    param_specs=None,
    opt_specs=None,
    ef_specs=(),
    model_kwargs: dict | None = None,
):
    """Build ``eval_step(state, images, labels, mask) -> sums``.

    ``opt_specs``: partition specs for the optimizer state when its TREE
    differs from the param tree (AdamW under TP/EP/PP) — eval never reads
    it, but the shard_map in_specs must still match its structure.
    ``ef_specs``: same story for the error-feedback residuals of the
    ``int8_ef`` wire format (:func:`ef_state_spec`) — eval ignores them,
    the in_specs must still describe their data-axis layout.

    Returns GLOBAL sums (loss·mask, top1, top5, count) so the host can
    divide once at the end — unlike the reference's ``validate()``, which
    averages per-batch averages over padded shards (the double-count noted
    in SURVEY §3.4). ``mask`` is 1.0 for real examples, 0.0 for sampler
    padding.

    ``axis`` may be a tuple of mesh axes: on a 2-D DP×SP mesh pass
    ``("data", "seq")`` so the eval batch shards over EVERY device (eval
    needs no sequence parallelism — different devices just hold different
    examples).
    """

    def eval_local(state: TrainState, images, labels, mask):
        x = images.astype(compute_dtype)
        p = jax.tree_util.tree_map(lambda t: t.astype(compute_dtype), state.params)
        kw = {}
        if tp_axis is not None:
            kw["tp_axis"] = tp_axis
        if ep_axis is not None:
            kw["ep_axis"] = ep_axis
        if pp_axis is not None:
            kw["pp_axis"] = pp_axis
        if model_kwargs:
            kw.update(model_kwargs)
        logits, _ = model_apply(p, state.bn_state, x, train=False, axis_name=None, **kw)
        nll = F.cross_entropy(logits, labels, reduction="none")
        maxk_hits = _masked_topk(logits, labels, mask)
        sums = {
            "loss": lax.psum(jnp.sum(nll * mask), axis),
            "top1": lax.psum(maxk_hits[0], axis),
            "top5": lax.psum(maxk_hits[1], axis),
            "count": lax.psum(jnp.sum(mask), axis),
        }
        return sums

    def _masked_topk(logits, labels, mask):
        maxk = min(5, logits.shape[-1])  # clamp: num_classes may be < 5
        _, pred = lax.top_k(logits.astype(jnp.float32), maxk)
        hits = (pred == labels[:, None]).astype(jnp.float32) * mask[:, None]
        return jnp.sum(hits[:, :1]), jnp.sum(hits[:, :maxk])

    p_spec = param_specs if param_specs is not None else P()
    state_spec = TrainState(
        params=p_spec,
        bn_state=P(),
        opt_state=opt_specs if opt_specs is not None else p_spec,
        step=P(),
        ef=ef_specs,
    )
    sharded = shard_map(
        eval_local,
        mesh=mesh,
        in_specs=(state_spec, P(axis), P(axis), P(axis)),
        out_specs=P(),
        check_vma=False,
    )
    # eval reads the TrainState without replacing it — donating would free
    # buffers the training loop still owns
    return jax.jit(sharded)  # tpu-dist: ignore[TD003]
