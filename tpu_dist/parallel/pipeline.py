"""Pipeline parallelism: GPipe-style microbatched stage execution over a
mesh axis, with ``ppermute`` stage-to-stage transfers.

Beyond the reference's scope (SURVEY §2.3: no PP anywhere). The classic
SPMD formulation: every device holds ONE stage's parameters; microbatches
flow through the pipeline as a ``lax.scan`` over n_micro + n_stages - 1
ticks. At each tick a device runs its stage on the activation it holds and
passes the result to the next stage via ``lax.ppermute`` (nearest-neighbor
ICI). Bubble fraction is the usual (S-1)/(M+S-1).

Differentiation: stage handoffs (``ppermute``) transpose exactly; the
microbatch ingestion and final result broadcast are wrapped in the
conjugate custom-VJP ops from :func:`tpu_dist.parallel.tensor.tp_ops`
(identity-fwd/psum-bwd on the input, psum-fwd/identity-bwd on the output).
GRADIENT CONVENTION: correctness is defined for PER-DEVICE loss-replica
differentiation — ``jax.grad`` taken INSIDE ``shard_map``, each device
differentiating its own copy of the replicated loss. That is what
``make_train_step`` does, and what the equivalence tests pin. Cotangents
arriving from OUTSIDE the ``shard_map`` are scaled 1/n by the out-spec
machinery — scale by the stage count if you differentiate that way.
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp
from jax import lax


def pipeline_apply(
    stage_fn: Callable,
    stage_params_local,
    x_micro,
    axis: str,
    n_stages: int,
):
    """Run microbatches through the stage pipeline.

    Inside ``shard_map`` over ``axis`` (size == n_stages):

    * ``stage_fn(params, h) -> h`` — one stage's computation (same shape in
      and out, the homogeneous-stage case),
    * ``stage_params_local`` — THIS device's stage parameters,
    * ``x_micro`` — [M, B_micro, ...] microbatches, replicated; stage 0
      feeds them in, the last stage's outputs come back replicated via a
      final broadcast.

    Returns [M, B_micro, ...] outputs (valid on every device).
    """
    from tpu_dist.parallel.tensor import tp_ops  # noqa: PLC0415

    copy_to_pipe, reduce_from_pipe = tp_ops(axis)
    x_micro = copy_to_pipe(x_micro)
    M = x_micro.shape[0]
    my = lax.axis_index(axis)
    n = n_stages
    total = M + n - 1

    def tick(carry, t):
        h, outs = carry
        # stage 0 ingests microbatch t (when in range), others use incoming h
        feed = jnp.where(t < M, t, 0)
        h = jnp.where(my == 0, x_micro[feed], h)
        y = stage_fn(stage_params_local, h)
        # last stage records its result into the output slot for micro t-n+1
        out_idx = t - (n - 1)
        write = (my == n - 1) & (out_idx >= 0)
        outs = lax.cond(
            write,
            lambda o: lax.dynamic_update_index_in_dim(o, y, jnp.maximum(out_idx, 0), 0),
            lambda o: o,
            outs,
        )
        # shift activations to the next stage
        perm = [(i, (i + 1) % n) for i in range(n)]
        h = lax.ppermute(y, axis, perm)
        return (h, outs), None

    h0 = jnp.zeros_like(x_micro[0])
    outs0 = jnp.zeros_like(x_micro)
    (_, outs), _ = lax.scan(tick, (h0, outs0), jnp.arange(total))
    # outs is only valid on the last stage; broadcast it to every device
    outs = reduce_from_pipe(jnp.where(my == n - 1, outs, jnp.zeros_like(outs)))
    return outs


def bubble_fraction(n_stages: int, n_micro: int, interleave: int = 1) -> float:
    """Idle fraction of the pipeline's step-count accounting.

    GPipe (``interleave=1``): ``(S-1)/(M+S-1)``. Interleaved virtual stages
    (Megatron-style, ``interleave=v``): each device holds ``v`` 1/v-sized
    chunks, the warmup/drain ramp costs the same ``S-1`` CHUNK-ticks but a
    chunk-tick is ``1/v`` of a stage-tick, so the fraction drops to
    ``(S-1)/(v*M+S-1)`` — the v-fold bubble reduction."""
    s, m, v = n_stages, n_micro, interleave
    return (s - 1) / (v * m + s - 1)


def pipeline_apply_interleaved(
    stage_fn: Callable,
    chunk_params_local,
    x_micro,
    axis: str,
    n_stages: int,
    interleave: int,
):
    """Interleaved-schedule pipeline (Megatron's virtual stages, the
    1F1B-family schedule that actually shrinks the bubble).

    Device ``d`` holds ``v = interleave`` non-adjacent chunks — virtual
    stages ``d, d+S, ..., d+(v-1)S`` — as stacked leading-dim-``v`` arrays
    in ``chunk_params_local``. A microbatch laps the ring ``v`` times.

    Schedule: device ``d`` is busy ticks ``[d, d+vM)``; at relative tick
    ``r = t-d`` it runs chunk ``k = r // M`` on microbatch ``m = r % M``.
    For devices ``d > 0`` every handoff is just-in-time: the producing
    virtual stage ``(d-1, k)`` emitted that activation on the previous
    tick, one nearest-neighbor ``ppermute`` away. The only early arrival
    is the LAP boundary ``(S-1, k-1) → (0, k)``: it lands ``M - S`` ticks
    before consumption, so a circular buffer of depth ``Q = M - S + 1``
    rides the scan carry and absorbs it — ``M == S`` degenerates to
    ``Q = 1``, the zero-buffer schedule. Per-device activation memory is
    therefore ``Q`` microbatches (the buffered-handoff analogue of 1F1B's
    in-flight window), while the tick count stays ``vM + S - 1`` against
    GPipe's ``v(M + S - 1)``: bubble ``(S-1)/(vM+S-1)`` keeps SHRINKING
    as M grows (see :func:`bubble_fraction`) instead of being pinned at
    the ``M == S`` corner.

    Differentiation follows :func:`pipeline_apply`'s convention (per-device
    loss-replica grads inside ``shard_map``; conjugate ``tp_ops`` wrap
    ingestion/extraction).
    """
    import jax  # noqa: PLC0415

    from tpu_dist.parallel.tensor import tp_ops  # noqa: PLC0415

    M = x_micro.shape[0]
    n, v = n_stages, interleave
    if M < n:
        raise ValueError(
            f"interleaved schedule requires n_microbatches >= n_stages "
            f"(a microbatch laps the ring {v}x; fewer than S in flight "
            f"starves the warmup ramp); got M={M}, S={n}"
        )
    Q = M - n + 1  # lap-boundary buffer depth (1 == zero-buffer M==S case)
    copy_to_pipe, reduce_from_pipe = tp_ops(axis)
    x_micro = copy_to_pipe(x_micro)
    my = lax.axis_index(axis)
    total = v * M + n - 1

    def tick(carry, t):
        h, buf, outs = carry
        # ``h`` arrived over the ring at this tick: record it. Slots cycle
        # every Q ticks; the wrap activation read Q-1 pushes later is still
        # intact (its slot is untouched until exactly tick t + Q).
        buf = lax.dynamic_update_index_in_dim(buf, h, jnp.mod(t, Q), 0)
        rel = t - my
        active = (rel >= 0) & (rel < v * M)
        relc = jnp.clip(rel, 0, v * M - 1)
        k = relc // M
        m = relc % M
        # devices d>0 consume this tick's arrival (delay 0 == the slot just
        # written); device 0 consumes the lap-boundary arrival from M-S
        # ticks ago
        delay = jnp.where(my == 0, M - n, 0)
        h_cons = lax.dynamic_index_in_dim(
            buf, jnp.mod(t - delay, Q), 0, keepdims=False
        )
        # virtual stage 0 (device 0, chunk 0) ingests microbatch m instead
        h_in = jnp.where((my == 0) & (k == 0), x_micro[m], h_cons)
        chunk = jax.tree_util.tree_map(
            lambda p: lax.dynamic_index_in_dim(p, k, 0, keepdims=False),
            chunk_params_local,
        )
        y = stage_fn(chunk, h_in)
        y = jnp.where(active, y, h)
        # last virtual stage (device S-1, chunk v-1) records microbatch m
        write = (my == n - 1) & (k == v - 1) & active
        outs = lax.cond(
            write,
            lambda o: lax.dynamic_update_index_in_dim(o, y, m, 0),
            lambda o: o,
            outs,
        )
        perm = [(i, (i + 1) % n) for i in range(n)]
        h = lax.ppermute(y, axis, perm)
        return (h, buf, outs), None

    h0 = jnp.zeros_like(x_micro[0])
    buf0 = jnp.zeros((Q,) + x_micro.shape[1:], x_micro.dtype)
    outs0 = jnp.zeros_like(x_micro)
    (_, _, outs), _ = lax.scan(tick, (h0, buf0, outs0), jnp.arange(total))
    outs = reduce_from_pipe(jnp.where(my == n - 1, outs, jnp.zeros_like(outs)))
    return outs
