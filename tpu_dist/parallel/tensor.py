"""Tensor (model) parallelism primitives over a named mesh axis.

Megatron-style column/row parallel linear layers, TPU-idiomatic: the weight
lives SHARDED on the ``model`` axis (each device holds a slice), activations
flow through with at most one ``psum`` per pair. Out of the reference's
scope (SURVEY §2.3: model parallelism is theory-only there, ``tutorials/
0:3-6``) but first-class here so the mesh design doesn't preclude it.

Pair them the standard way for an MLP / attention projection:

    h = column_parallel_dense(x, W1_local, axis)   # [.., d_ff/n] local
    h = activation(h)                              # elementwise, stays local
    y = row_parallel_dense(h, W2_local, axis)      # psum -> replicated

so the wide hidden dimension is never materialized on one chip and the
only communication is the single output-side ``psum``.

All functions run inside ``shard_map`` with the weight's shard dim mapped
to ``axis``.
"""

from __future__ import annotations

import functools

import jax
from jax import lax


@functools.lru_cache(maxsize=None)
def tp_ops(axis: str):
    """The Megatron conjugate pair for ``axis``:

    * ``copy_to_tp``  — forward identity, backward ``psum`` (the "f"
      operator: feeds a replicated activation into column-parallel layers,
      collecting each shard's partial cotangent on the way back).
    * ``reduce_from_tp`` — forward ``psum``, backward identity (the "g"
      operator: merges row-parallel partial outputs; the cotangent is
      already replicated).

    Explicit custom-VJP pairs are REQUIRED under ``shard_map``: the raw
    ``lax.psum`` transposes as ``psum``, which double-counts when each
    device differentiates its own replica of the loss.
    """

    @jax.custom_vjp
    def copy_to_tp(x):
        return x

    copy_to_tp.defvjp(lambda x: (x, None), lambda _, g: (lax.psum(g, axis),))

    @jax.custom_vjp
    def reduce_from_tp(x):
        return lax.psum(x, axis)

    reduce_from_tp.defvjp(lambda x: (lax.psum(x, axis), None), lambda _, g: (g,))

    return copy_to_tp, reduce_from_tp


def shard_columns(w, axis_size: int, index: int):
    """Host-side helper: slice [din, dout] → this device's [din, dout/n]."""
    step = w.shape[1] // axis_size
    return w[:, index * step : (index + 1) * step]


def shard_rows(w, axis_size: int, index: int):
    """Host-side helper: slice [din, dout] → this device's [din/n, dout]."""
    step = w.shape[0] // axis_size
    return w[index * step : (index + 1) * step]


def column_parallel_dense(x, w_local, axis: str, b_local=None):
    """``x @ W`` with W column-sharded over ``axis``.

    Input ``x`` replicated over ``axis``; output is the LOCAL slice of the
    activations (sharded hidden dim). No communication.
    """
    del axis  # no collective needed; kept for signature symmetry
    y = x @ w_local.astype(x.dtype)
    if b_local is not None:
        y = y + b_local.astype(x.dtype)
    return y


def row_parallel_dense(x_local, w_local, axis: str, b=None):
    """``x @ W`` with W row-sharded over ``axis`` and ``x`` carrying the
    matching sharded feature dim. One ``psum`` makes the output replicated.

    Bias (replicated) is added AFTER the psum so it isn't multiplied by the
    axis size.
    """
    partial = x_local @ w_local.astype(x_local.dtype)
    y = lax.psum(partial, axis)
    if b is not None:
        y = y + b.astype(y.dtype)
    return y
