"""Expert parallelism: top-k Mixture-of-Experts over a mesh axis, with
capacity-based dispatch/combine through ``lax.all_to_all``.

Beyond the reference's scope (SURVEY §2.3: no EP anywhere), built so the
``expert`` mesh axis is exercised for real:

* every device holds ``E/n`` experts' weights (expert-sharded params),
* tokens are routed top-k (k=1 is Switch, k=2 is GShard-style) with a
  capacity limit ``C`` per expert; first choices of every token claim
  slots before any second choice does (choice-major priority, the GShard
  rule),
* dispatch: one-hot einsum packs tokens into ``[E, C, d]`` slots, then ONE
  ``all_to_all`` over the axis moves each expert's slots to its owner,
* experts run their FFN on their ``[n_local_tokens... , C, d]`` slab,
* combine: the reverse ``all_to_all`` + gate-weighted einsum restores
  token order (for k>1 the k gates are renormalized to sum to one).

Tokens that overflow an expert's capacity are dropped (standard Switch
behavior) — that choice contributes 0 and the residual connection carries
the token.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax

from tpu_dist.comm import compat


@dataclass(frozen=True)
class MoE:
    """Top-k MoE FFN. ``n_experts`` must be a multiple of the axis size.

    ``init(key, d_model, d_ff)`` → params with leading expert dim E.
    Shard params over the axis with ``P('expert')`` on that dim (or slice
    manually per device inside shard_map via ``params_local``).

    ``top_k=1`` gates by the raw softmax probability (Switch); ``top_k>1``
    renormalizes the chosen probabilities to sum to one (GShard).
    """

    n_experts: int
    capacity_factor: float = 1.25
    top_k: int = 1

    def init(self, key, d_model: int, d_ff: int):
        k1, k2, k3 = jax.random.split(key, 3)
        E = self.n_experts
        s1 = d_model ** -0.5
        s2 = d_ff ** -0.5
        return {
            "router": jax.random.normal(k1, (d_model, E)) * s1,
            "w_in": jax.random.normal(k2, (E, d_model, d_ff)) * s1,
            "w_out": jax.random.normal(k3, (E, d_ff, d_model)) * s2,
        }

    # -- dense reference (single device, no sharding) -----------------------

    def apply_dense(self, params, x, *, with_aux: bool = False):
        """[T, d] → [T, d]; ground truth for the EP path. ``with_aux=True``
        also returns the load-balancing loss (see :meth:`aux_loss`)."""
        T, d = x.shape
        C = self._capacity(T)
        pack, combine, aux = self._route(params, x, C)
        slots = jnp.einsum("tec,td->ecd", pack, x)            # [E, C, d]
        h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", slots, params["w_in"]))
        out = jnp.einsum("ecf,efd->ecd", h, params["w_out"])  # [E, C, d]
        y = jnp.einsum("tec,ecd->td", combine, out)
        return (y, aux) if with_aux else y

    # -- expert-parallel (inside shard_map over `axis`) ---------------------

    def apply_ep(self, params_repl_router, w_in_local, w_out_local, x, axis: str,
                 *, with_aux: bool = False):
        """Expert-parallel forward for THIS device's token shard ``x``
        [T_loc, d]. ``w_in_local``/``w_out_local``: [E/n, d, f] local expert
        slabs; router weights replicated.

        Every device dispatches its tokens into per-expert capacity slots,
        one ``all_to_all`` exchanges slots so each device receives all
        devices' slots for ITS experts, the local experts run, and the
        reverse ``all_to_all`` + combine restores token order.
        """
        n = compat.axis_size(axis)
        T_loc, d = x.shape
        E = self.n_experts
        e_loc = E // n
        C = self._capacity(T_loc)

        pack, combine, aux = self._route({"router": params_repl_router}, x, C)
        slots = jnp.einsum("tec,td->ecd", pack, x)             # [E, C, d]
        # group by owner device: [n, e_loc, C, d] → all_to_all over axis
        slots = slots.reshape(n, e_loc, C, d)
        recv = lax.all_to_all(slots, axis, split_axis=0, concat_axis=0, tiled=False)
        # recv: [n, e_loc, C, d] — slot blocks from every peer for MY experts
        h = jax.nn.gelu(jnp.einsum("necd,edf->necf", recv, w_in_local))
        out = jnp.einsum("necf,efd->necd", h, w_out_local)
        # send results back to the token owners
        back = lax.all_to_all(out, axis, split_axis=0, concat_axis=0, tiled=False)
        back = back.reshape(E, C, d)
        y = jnp.einsum("tec,ecd->td", combine, back)
        return (y, aux) if with_aux else y

    # -- shared routing ------------------------------------------------------

    def _capacity(self, T: int) -> int:
        return max(1, int(self.capacity_factor * self.top_k * T / self.n_experts))

    def _route(self, params, x, C: int):
        """Top-k routing with capacity. Returns two [T, E, C] dispatch
        tensors — ``pack`` (binary: which slot each token occupies, up to k
        of them) and ``combine`` (gate-weighted: how expert outputs sum
        back per token) — plus the scalar load-balancing auxiliary loss
        (Switch Transformer §2.2): ``E · Σ_e f_e · P_e`` with ``f_e`` the
        fraction of tokens whose TOP choice is expert e (non-differentiable
        count) and ``P_e`` the mean router probability for e
        (differentiable). Minimized (→ 1) by a uniform router; the
        coefficient is the caller's (``--moe_aux_coef``)."""
        T = x.shape[0]
        E, k = self.n_experts, self.top_k
        logits = x.astype(jnp.float32) @ params["router"].astype(jnp.float32)
        probs = jax.nn.softmax(logits, axis=-1)
        topk_probs, topk_idx = lax.top_k(probs, k)            # [T, k]
        if k == 1:
            gates = topk_probs                                # Switch: raw prob
        else:
            gates = topk_probs / jnp.maximum(
                topk_probs.sum(-1, keepdims=True), 1e-9
            )                                                 # GShard: renorm

        # CHOICE-MAJOR slot assignment: every token's 1st choice outranks
        # any token's 2nd choice for the capacity budget
        oh = jax.nn.one_hot(topk_idx, E, dtype=jnp.int32)     # [T, k, E]
        oh_cm = oh.transpose(1, 0, 2).reshape(k * T, E)       # [k*T, E]
        pos = jnp.cumsum(oh_cm, axis=0) * oh_cm - 1           # slot per entry
        keep = (pos < C) & (pos >= 0)
        slot = jnp.where(keep, pos, -1).max(-1)               # [k*T]; -1 = drop
        pos_oh = jax.nn.one_hot(slot, C, dtype=x.dtype)       # [k*T, C]
        disp_cm = oh_cm.astype(x.dtype)[:, :, None] * pos_oh[:, None, :]
        disp_k = disp_cm.reshape(k, T, E, C)                  # per-choice

        pack = disp_k.sum(0)                                  # binary [T, E, C]
        combine = jnp.einsum("ktec,tk->tec", disp_k, gates.astype(x.dtype))

        f_e = oh[:, 0, :].astype(jnp.float32).mean(0)         # top-choice freq
        P_e = probs.mean(0)                                   # mean router prob
        aux = E * jnp.sum(f_e * P_e)
        return pack, combine, aux.astype(x.dtype)
