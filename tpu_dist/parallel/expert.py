"""Expert parallelism: top-1 (Switch-style) Mixture-of-Experts over a mesh
axis, with capacity-based dispatch/combine through ``lax.all_to_all``.

Beyond the reference's scope (SURVEY §2.3: no EP anywhere), built so the
``expert`` mesh axis is exercised for real:

* every device holds ``E/n`` experts' weights (expert-sharded params),
* tokens are routed top-1 with a capacity limit ``C`` per expert,
* dispatch: one-hot einsum packs tokens into ``[E, C, d]`` slots, then ONE
  ``all_to_all`` over the axis moves each expert's slots to its owner,
* experts run their FFN on their ``[n_local_tokens... , C, d]`` slab,
* combine: the reverse ``all_to_all`` + weighted einsum restores token
  order, scaled by the router gate.

Tokens that overflow an expert's capacity are dropped (standard Switch
behavior) — their output is 0 and the residual connection carries them.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax


@dataclass(frozen=True)
class MoE:
    """Top-1 MoE FFN. ``n_experts`` must be a multiple of the axis size.

    ``init(key, d_model, d_ff)`` → params with leading expert dim E.
    Shard params over the axis with ``P('expert')`` on that dim (or slice
    manually per device inside shard_map via ``params_local``).
    """

    n_experts: int
    capacity_factor: float = 1.25

    def init(self, key, d_model: int, d_ff: int):
        k1, k2, k3 = jax.random.split(key, 3)
        E = self.n_experts
        s1 = d_model ** -0.5
        s2 = d_ff ** -0.5
        return {
            "router": jax.random.normal(k1, (d_model, E)) * s1,
            "w_in": jax.random.normal(k2, (E, d_model, d_ff)) * s1,
            "w_out": jax.random.normal(k3, (E, d_ff, d_model)) * s2,
        }

    # -- dense reference (single device, no sharding) -----------------------

    def apply_dense(self, params, x):
        """[T, d] → [T, d]; ground truth for the EP path."""
        T, d = x.shape
        E = self.n_experts
        C = self._capacity(T)
        gates, idx, disp = self._route(params, x, C)
        slots = jnp.einsum("tec,td->ecd", disp, x)            # [E, C, d]
        h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", slots, params["w_in"]))
        out = jnp.einsum("ecf,efd->ecd", h, params["w_out"])  # [E, C, d]
        return jnp.einsum("tec,ecd->td", disp, out) * gates[:, None]

    # -- expert-parallel (inside shard_map over `axis`) ---------------------

    def apply_ep(self, params_repl_router, w_in_local, w_out_local, x, axis: str):
        """Expert-parallel forward for THIS device's token shard ``x``
        [T_loc, d]. ``w_in_local``/``w_out_local``: [E/n, d, f] local expert
        slabs; router weights replicated.

        Every device dispatches its tokens into per-expert capacity slots,
        one ``all_to_all`` exchanges slots so each device receives all
        devices' slots for ITS experts, the local experts run, and the
        reverse ``all_to_all`` + combine restores token order.
        """
        n = lax.axis_size(axis)
        T_loc, d = x.shape
        E = self.n_experts
        e_loc = E // n
        C = self._capacity(T_loc)

        gates, idx, disp = self._route({"router": params_repl_router}, x, C)
        slots = jnp.einsum("tec,td->ecd", disp, x)             # [E, C, d]
        # group by owner device: [n, e_loc, C, d] → all_to_all over axis
        slots = slots.reshape(n, e_loc, C, d)
        recv = lax.all_to_all(slots, axis, split_axis=0, concat_axis=0, tiled=False)
        # recv: [n, e_loc, C, d] — slot blocks from every peer for MY experts
        h = jax.nn.gelu(jnp.einsum("necd,edf->necf", recv, w_in_local))
        out = jnp.einsum("necf,efd->necd", h, w_out_local)
        # send results back to the token owners
        back = lax.all_to_all(out, axis, split_axis=0, concat_axis=0, tiled=False)
        back = back.reshape(E, C, d)
        return jnp.einsum("tec,ecd->td", disp, back) * gates[:, None]

    # -- shared routing ------------------------------------------------------

    def _capacity(self, T: int) -> int:
        return max(1, int(self.capacity_factor * T / self.n_experts))

    def _route(self, params, x, C: int):
        """Top-1 routing with capacity: returns (gates [T], idx [T],
        dispatch one-hot [T, E, C])."""
        logits = x.astype(jnp.float32) @ params["router"].astype(jnp.float32)
        probs = jax.nn.softmax(logits, axis=-1)
        idx = jnp.argmax(probs, axis=-1)                      # [T]
        gates = jnp.take_along_axis(probs, idx[:, None], axis=-1)[:, 0]
        onehot = jax.nn.one_hot(idx, self.n_experts, dtype=jnp.int32)  # [T, E]
        pos = jnp.cumsum(onehot, axis=0) * onehot - 1         # slot per token
        keep = (pos < C) & (pos >= 0)
        # slot of the routed expert (-1 when dropped); one_hot(-1) is all-zero
        slot = jnp.where(keep, pos, -1).max(-1)
        pos_oh = jax.nn.one_hot(slot, C, dtype=x.dtype)       # [T, C]
        disp = onehot.astype(x.dtype)[:, :, None] * pos_oh[:, None, :]
        return gates.astype(x.dtype), idx, disp
