from tpu_dist.parallel.tensor import (  # noqa: F401
    column_parallel_dense,
    row_parallel_dense,
    shard_columns,
    shard_rows,
)
from tpu_dist.parallel.expert import MoE  # noqa: F401
from tpu_dist.parallel.pipeline import pipeline_apply  # noqa: F401
from tpu_dist.parallel.fsdp import (  # noqa: F401
    fsdp_specs,
    make_fsdp_eval_step,
    make_fsdp_train_step,
)
