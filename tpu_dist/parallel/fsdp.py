"""Fully-sharded data parallelism (FSDP / ZeRO-3) via GSPMD auto-sharding.

The engines in ``train/step.py`` write their collectives BY HAND inside a
``shard_map`` — the per-device view. This module is the other TPU idiom,
and the one torch-FSDP users should map onto: annotate how parameters and
optimizer state are SHARDED over the data axis, write the training step as
if every array were global, and let XLA's GSPMD partitioner insert the
all-gathers (parameters, just before use) and reduce-scatters (gradients)
that ``torch.distributed.fsdp.FullyShardedDataParallel`` implements
manually with hooks around each wrapped submodule.

Memory story vs the reference's DDP engine (``distributed.py:60``, which
keeps a FULL replica of params + momentum on every device): here each
device stores 1/n of every large tensor — parameters, momentum, and the
gradient accumulator — trading it for an all-gather of each weight at use
time, which XLA overlaps with compute the same way its latency-hiding
scheduler overlaps the DDP grad allreduce.

Numerics are IDENTICAL to the plain data-parallel step (asserted leaf by
leaf in ``tests/test_fsdp.py``): GSPMD preserves full-value semantics, so
sharding annotations change the schedule, never the math.

Notes on the semantics under GSPMD's global view:

* BatchNorm: batch statistics are computed over the GLOBAL batch — i.e.
  SyncBN (``distributed.py:59``) holds by construction; there is no
  local-stats mode in this engine (the Trainer refuses ``sync_bn=False``
  with ``fsdp=True`` rather than silently synchronizing anyway).
* Gradient clipping: ``jnp.linalg`` style global norm of the global
  gradient — no shard-norm ``psum`` choreography needed; the partitioner
  derives it.
* Grad accumulation: the ``lax.scan`` accumulator carries the SHARDED
  layout (constrained to the param specs), so large-model accumulation
  costs 1/n memory too — the ``no_sync`` semantics of
  ``distributed_gradient_accumulation.py:106`` fall out of summing local
  chunk grads before the (single, scheduler-placed) reduction.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_dist.comm import mesh as mesh_lib
from tpu_dist.nn import functional as F
from tpu_dist.train.state import TrainState


def fsdp_specs(params, mesh: Mesh, axis: str = mesh_lib.DATA_AXIS, min_size: int = 1024):
    """Per-leaf :class:`PartitionSpec` sharding each large tensor over ``axis``.

    The largest dimension divisible by the axis size is sharded (ties break
    toward the leading dim); leaves smaller than ``min_size`` elements, or
    with no divisible dim, stay replicated — sharding a 64-element BN scale
    buys nothing and costs an all-gather.
    """
    n = int(mesh.shape[axis])

    def spec(x):
        shape = getattr(x, "shape", ())
        size = 1
        for d in shape:
            size *= int(d)
        if n <= 1 or not shape or size < min_size:
            return P()
        order = sorted(range(len(shape)), key=lambda d: (-int(shape[d]), d))
        for d in order:
            if int(shape[d]) % n == 0:
                entry = [None] * len(shape)
                entry[d] = axis
                return P(*entry)
        return P()

    return jax.tree_util.tree_map(spec, params)


def compose_fsdp_specs(
    params,
    mesh: Mesh,
    model_specs,
    *,
    data_axis: str = mesh_lib.DATA_AXIS,
    min_size: int = 1024,
):
    """FSDP × TP spec composition (VERDICT r2 #5): overlay data-axis
    (weight/optimizer-state) sharding onto existing MODEL-axis specs.

    ``model_specs`` is the per-leaf pytree of Megatron-style specs (e.g.
    ``ViTDef.tp_param_specs("model")``: qkv/mlp1 column-sharded, proj/mlp2
    row-sharded). For each leaf, the largest dimension NOT already claimed
    by a model axis and divisible by the data-axis size additionally shards
    over ``data_axis`` — so a ``[D, 4D]`` mlp1 kernel on a (data=4, model=2)
    mesh lands as ``P('data', 'model')``: each device holds 1/8 of it, the
    GSPMD partitioner all-gathers over ``data`` at use time (FSDP) and
    psums the row-parallel matmuls over ``model`` (TP). Leaves below
    ``min_size`` or with no free divisible dim keep their model spec
    unchanged — on the (replicated-over-data) model axis they behave like
    plain TP params.

    This is the GSPMD half of the framework's scaling story: no engine
    change, only specs — compare ``train/step.py``'s hand-written
    shard_map TP, which composes with ZeRO-style sharding only by explicit
    per-shard layouts (scoped out; see the ZeRO-1 design note there).
    """
    n = int(mesh.shape[data_axis])

    def compose(x, mspec):
        shape = tuple(getattr(x, "shape", ()))
        entries = list(tuple(mspec)) if mspec is not None else []
        entries += [None] * (len(shape) - len(entries))
        size = 1
        for d in shape:
            size *= int(d)
        if n > 1 and shape and size >= min_size:
            order = sorted(range(len(shape)), key=lambda d: (-int(shape[d]), d))
            for d in order:
                if entries[d] is None and int(shape[d]) % n == 0:
                    entries[d] = data_axis
                    break
        while entries and entries[-1] is None:
            entries.pop()
        return P(*entries)

    return jax.tree_util.tree_map(compose, params, model_specs)


def _shardings(mesh: Mesh, specs):
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        specs,
        is_leaf=lambda s: isinstance(s, P),
    )


def state_shardings(mesh: Mesh, specs, opt_specs=None) -> TrainState:
    """Shardings for a :class:`TrainState` under FSDP: params follow
    ``specs``; optimizer state follows ``opt_specs`` when its tree differs
    from the params tree (AdamW's {mu, nu, count} — build it with
    ``fsdp_specs(optimizer.init(params), mesh)``), else ``specs``; BN stats
    and the step counter replicate (small)."""
    rep = NamedSharding(mesh, P())
    return TrainState(
        params=_shardings(mesh, specs),
        bn_state=rep,
        opt_state=_shardings(mesh, specs if opt_specs is None else opt_specs),
        step=rep,
    )


def make_fsdp_train_step(
    model_apply: Callable,
    optimizer,
    mesh: Mesh,
    specs,
    *,
    opt_specs=None,
    grad_accum_steps: int = 1,
    compute_dtype=jnp.float32,
    axis: str = mesh_lib.DATA_AXIS,
    donate: bool = True,
    label_smoothing: float = 0.0,
    grad_clip_norm: float = 0.0,
    moe_aux_coef: float = 0.01,
    remat: bool = False,
    grad_compression: str = "none",
    model_kwargs: dict | None = None,
):
    """Build ``step(state, images, labels, lr) -> (state, metrics)``, the
    FSDP twin of :func:`tpu_dist.train.step.make_train_step`.

    ``model_kwargs``: extra keywords pinned into the model apply at build
    time (e.g. ``attn_impl`` — the process-global attention default must
    not leak into this trace).

    ``specs`` is the per-leaf param pytree from :func:`fsdp_specs`. The body
    is written entirely in the global view — no ``pmean``/``psum`` anywhere;
    compare it with the ``shard_map`` version to see what GSPMD buys.

    ``grad_compression`` exists only to make the engine's boundary
    explicit: this engine accepts ``'none'`` and refuses everything else.
    The bf16/int8 wire formats (``train/step.py``, docs/compression.md)
    hook the hand-written collectives of the shard_map engines; here the
    gradient reduce-scatters are *inserted by the GSPMD partitioner* from
    sharding annotations — there is no per-tensor seam to quantize at
    short of rewriting the engine as a shard_map program, which is exactly
    the other engine. (EQuARX does it INSIDE XLA for this reason.)
    """
    if grad_compression != "none":
        raise ValueError(
            f"grad_compression={grad_compression!r} cannot apply under the "
            "GSPMD/FSDP engine (collectives are partitioner-inserted, not "
            "hookable) — use the shard_map engines (plain DP / --zero1) "
            "for compressed gradient wire formats"
        )
    K = int(grad_accum_steps)
    st_sh = state_shardings(mesh, specs, opt_specs)
    param_sh = st_sh.params
    batch_sh = NamedSharding(mesh, P(axis))
    rep = NamedSharding(mesh, P())

    def loss_fn(params, bn_state, images, labels):
        x = images.astype(compute_dtype)
        p = jax.tree_util.tree_map(lambda t: t.astype(compute_dtype), params)
        # axis_name=None: the mean/var in BN run over the global batch —
        # under GSPMD that IS cross-replica SyncBN (module docstring).
        logits, new_bn = model_apply(
            p, bn_state, x, train=True, axis_name=None, **(model_kwargs or {})
        )
        from tpu_dist.train.step import extract_aux_loss  # noqa: PLC0415

        new_bn, aux = extract_aux_loss(new_bn)
        loss = F.cross_entropy(logits, labels, label_smoothing=label_smoothing)
        if aux is not None:
            loss = loss + moe_aux_coef * aux.astype(loss.dtype)
        return loss, (new_bn, logits)

    if remat:
        loss_fn = jax.checkpoint(loss_fn)
    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    n_axis = int(mesh.shape[axis])

    def chunk(t):
        """[B, ...] -> [K, B/K, ...] in the same PER-DEVICE order the
        shard_map engine uses (``step.py::local_grads``): chunk k holds each
        device's k-th local sub-batch, NOT global rows [kB/K, (k+1)B/K).
        Matters twice — BatchNorm statistics per chunk must match the other
        engine's bit for bit, and the per-chunk rows stay on their home
        devices (no cross-device resharding every accumulation step)."""
        b = t.shape[0]
        t = t.reshape((n_axis, K, b // (n_axis * K)) + t.shape[1:])
        t = jnp.swapaxes(t, 0, 1)
        t = t.reshape((K, b // K) + t.shape[3:])
        return lax.with_sharding_constraint(t, NamedSharding(mesh, P(None, axis)))

    def unchunk(t):
        """Invert :func:`chunk` on scan-stacked outputs ([K, B/K, ...] ->
        [B, ...] in the original global row order)."""
        b = K * t.shape[1]
        t = t.reshape((K, n_axis, b // (n_axis * K)) + t.shape[2:])
        t = jnp.swapaxes(t, 0, 1)
        return t.reshape((b,) + t.shape[3:])

    def local_grads(params, bn_state, images, labels):
        if K == 1:
            (loss, (bn, logits)), grads = grad_fn(params, bn_state, images, labels)
            return loss, grads, bn, logits
        chunked = jax.tree_util.tree_map(chunk, (images, labels))

        def body(carry, chunk):
            bn, acc = carry
            imgs, lbls = chunk
            (loss, (bn, logits)), g = grad_fn(params, bn, imgs, lbls)
            # keep the accumulator in the sharded layout: 1/n grad memory
            acc = lax.with_sharding_constraint(
                jax.tree_util.tree_map(jnp.add, acc, g), param_sh
            )
            return (bn, acc), (loss, logits)

        zero = lax.with_sharding_constraint(
            jax.tree_util.tree_map(jnp.zeros_like, params), param_sh
        )
        (bn, acc), (losses, logits) = lax.scan(body, (bn_state, zero), chunked)
        grads = jax.tree_util.tree_map(lambda g: g / K, acc)
        logits = unchunk(logits)  # back to the global row order of ``labels``
        return losses.mean(), grads, bn, logits

    def step(state: TrainState, images, labels, lr):
        loss, grads, new_bn, logits = local_grads(
            state.params, state.bn_state, images, labels
        )
        if grad_clip_norm > 0.0:
            # global norm of the global gradient — one line, no psum
            sq = sum(jnp.sum(jnp.square(g)) for g in jax.tree_util.tree_leaves(grads))
            scale = jnp.minimum(
                1.0, grad_clip_norm / jnp.maximum(jnp.sqrt(sq), 1e-12)
            )
            grads = jax.tree_util.tree_map(lambda g: g * scale, grads)
        grads = lax.with_sharding_constraint(grads, param_sh)
        new_params, new_opt = optimizer.update(grads, state.opt_state, state.params, lr)
        # ef rides through untouched (always () here — no quantized wire
        # under GSPMD; see the grad_compression refusal above)
        new_state = TrainState(new_params, new_bn, new_opt, state.step + 1, state.ef)

        b = labels.shape[0]
        c1, c5 = F.topk_correct(logits.astype(jnp.float32), labels, (1, 5))
        metrics = {
            "loss": loss,
            "acc1": c1 / b * 100.0,
            "acc5": c5 / b * 100.0,
        }
        return new_state, metrics

    return jax.jit(
        step,
        in_shardings=(st_sh, batch_sh, batch_sh, None),
        out_shardings=(st_sh, rep),
        donate_argnums=(0,) if donate else (),
    )


def make_fsdp_eval_step(
    model_apply: Callable,
    mesh: Mesh,
    specs,
    *,
    opt_specs=None,
    compute_dtype=jnp.float32,
    axis: str = mesh_lib.DATA_AXIS,
    model_kwargs: dict | None = None,
):
    """FSDP twin of :func:`tpu_dist.train.step.make_eval_step` — identical
    contract (masked GLOBAL sums of loss/top1/top5/count, so the streaming
    evaluator divides once at the end)."""
    st_sh = state_shardings(mesh, specs, opt_specs)
    batch_sh = NamedSharding(mesh, P(axis))
    rep = NamedSharding(mesh, P())

    def eval_step(state: TrainState, images, labels, mask):
        x = images.astype(compute_dtype)
        p = jax.tree_util.tree_map(
            lambda t: t.astype(compute_dtype), state.params
        )
        logits, _ = model_apply(
            p, state.bn_state, x, train=False, axis_name=None,
            **(model_kwargs or {})
        )
        nll = F.cross_entropy(logits, labels, reduction="none")
        maxk = min(5, logits.shape[-1])
        _, pred = lax.top_k(logits.astype(jnp.float32), maxk)
        hits = (pred == labels[:, None]).astype(jnp.float32) * mask[:, None]
        return {
            "loss": jnp.sum(nll * mask),
            "top1": jnp.sum(hits[:, :1]),
            "top5": jnp.sum(hits[:, :maxk]),
            "count": jnp.sum(mask),
        }

    # eval reads the TrainState without replacing it — donating would free
    # buffers the training loop still owns
    return jax.jit(  # tpu-dist: ignore[TD003]
        eval_step,
        in_shardings=(st_sh, batch_sh, batch_sh, batch_sh),
        out_shardings=rep,
    )
