"""Typed restore-ladder errors for elastic training (docs/resilience.md).

Before elastic resume existed, every mismatch between a checkpoint and the
live run raised a generic ``ValueError`` — so a world-size change (which
only reshapes the dp-extent-dependent leaves: ZeRO-1 flat optimizer
vectors, error-feedback residuals) pattern-matched to config drift and
bricked the resume. The split:

* :class:`ElasticShapeMismatch` — **benign**: the shape difference is
  exactly the one a different data-parallel extent produces on an
  elastic-remappable leaf. The restore ladder handles it by re-running the
  restore with a :class:`tpu_dist.elastic.remap.Remapper`.
* :class:`ConfigMismatchError` — **operator error**: a layout stamp
  (pipeline interleave, AdamW decay mask, mid-epoch data-position pins) or
  a parameter-shape mismatch that no world-size change explains. Still
  raises — falling past it would silently resume the wrong run.

Both subclass ``ValueError`` so pre-elastic callers (and tests) that catch
the generic type keep working. This module imports nothing — it sits below
both ``tpu_dist.ckpt`` and ``tpu_dist.elastic.remap`` in the import graph.
"""

from __future__ import annotations


class ConfigMismatchError(ValueError):
    """The checkpoint disagrees with the live run in a way that is NOT a
    world-size change (model shape drift, layout stamps, data-position
    pins). The restore ladder re-raises: resuming past it would silently
    train the wrong run."""


class ElasticShapeMismatch(ValueError):
    """A leaf's checkpointed shape differs from the template only because
    the run's data-parallel extent changed — the elastic remapper
    (``tpu_dist/elastic/remap.py``) can rebuild it exactly. Raised by the
    checkpoint layer when no remap hook was supplied; the trainer's
    restore ladder catches the *class* of problem up front by always
    restoring with a remapper."""

    def __init__(self, key: str, ckpt_shape, want_shape):
        self.key = key
        self.ckpt_shape = tuple(ckpt_shape)
        self.want_shape = tuple(want_shape)
        super().__init__(
            f"elastic shape mismatch for {key}: ckpt {self.ckpt_shape} vs "
            f"state {self.want_shape} — a dp-extent-dependent leaf saved at "
            "a different world size; restore with an elastic remapper "
            "(docs/resilience.md 'Elastic training')"
        )
