"""Elastic training: mesh-shape-portable checkpoints and mid-run shrink.

Three pieces (docs/resilience.md "Elastic training"):

* ``elastic/remap.py`` — restore a checkpoint onto a different
  data-parallel extent: world-size-independent leaves re-slice, the
  dp-dependent flat layouts (ZeRO-1 optimizer vectors, error-feedback
  residuals) are remapped bit-exactly where dtype allows.
* ``elastic/supervisor.py`` — the launcher's relaunch policy: when a
  preemption or rank death ends a round, relaunch ``--resume`` at the
  largest feasible reduced world size instead of failing the run.
* ``elastic/drill.py`` — the local proof: preempt a run mid-epoch,
  resume shrunken, assert state bit-identity and loss-trajectory parity
  (``make elastic-drill``).
"""

from tpu_dist.elastic.errors import ConfigMismatchError, ElasticShapeMismatch
from tpu_dist.elastic.remap import (
    Remapper,
    classify,
    elastic_stamp,
    make_remapper,
    params_len,
)
from tpu_dist.elastic.supervisor import (
    RoundResult,
    next_world_size,
    supervise,
)

__all__ = [
    "ConfigMismatchError",
    "ElasticShapeMismatch",
    "Remapper",
    "RoundResult",
    "classify",
    "elastic_stamp",
    "make_remapper",
    "next_world_size",
    "params_len",
    "supervise",
]
