"""Mesh-shape-portable checkpoint remapping (docs/resilience.md).

A checkpoint's parameters, BN statistics and per-leaf momentum are
world-size-independent: their global shapes never mention the mesh, so a
resume onto a different data-parallel extent only needs a re-slice (which
``restore_sharded`` already does from the manifest's shard-piece origins).
Three leaf families are NOT: their *global* shapes bake in the dp extent
``n`` because they are flat vectors padded to an ``n``-divisible length
(``comm/quantize.py::padded_len``):

========================  =====================  ==========================
leaf                      global shape at dp=n   logical content
========================  =====================  ==========================
ZeRO-1 flat opt state     ``(ceil(L/n)*n,)``     first ``L`` entries (the
(SGD momentum, AdamW                             raveled param order); the
mu/nu)                                           pad tail is provably zero
                                                 (pad grads are zero, decay
                                                 intervals stop at ``L``)
``ef['r1']`` residuals    ``(n*P,)``,            row ``i`` = replica i's
                          ``P=padded_len(L,n)``  send-side quantization
                                                 error over the padded
                                                 gradient
``ef['r2']`` residuals    ``(P,)``               per-coordinate leg-2 error
                                                 of the reduced gradient
========================  =====================  ==========================

Remap contract (what is bit-exact vs parity-only):

* **ZeRO-1 flat opt state — bit-exact.** The logical ``[:L]`` prefix is
  copied verbatim (dtype preserved); both tails are zeros. A nonzero
  source tail means the layout assumption broke and raises loudly.
* **``r2`` — bit-exact per coordinate.** It is positional over the reduced
  gradient: crop to ``L``, re-pad with zeros. Residuals beyond ``L`` chase
  pad coordinates that are sliced off before they ever touch a parameter.
* **``r1`` — aggregate-exact, per-replica parity.** What matters to the
  next update is the SUM over replicas (each replica adds its row to its
  gradient contribution before the reduce), so the remap folds the old
  rows' ``[:L]`` columns into new replica 0's row and zeroes the rest:
  the total compensated error is preserved to the bit, while the
  per-replica split (which only shapes the next step's quantization
  ranges) is not — error feedback re-balances itself within one step.

The remapper is a host-side hook the checkpoint layer calls on a shape
mismatch (``restore(..., remap=...)`` / ``restore_sharded(..., remap=...)``)
— nothing here touches jax, so an elastic-resumed trainer's traced step is
byte-identical to a fresh start at the new world size (jaxpr rule TD111).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from tpu_dist.elastic.errors import ConfigMismatchError, ElasticShapeMismatch

__all__ = [
    "ConfigMismatchError",
    "ElasticShapeMismatch",
    "Remapper",
    "classify",
    "elastic_stamp",
    "make_remapper",
    "params_len",
]

_EF_R1_PREFIX = "['ef']['r1']"
_EF_R2_PREFIX = "['ef']['r2']"
_OPT_PREFIX = "['opt_state']"


def params_len(params) -> int:
    """Logical length ``L`` of the raveled parameter vector — the one
    world-size-independent coordinate every elastic flat layout is padded
    from. Pure shape arithmetic (no device math, works on numpy and
    jax.Array leaves alike)."""
    import jax  # noqa: PLC0415 — shape walking only

    total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        shape = np.shape(leaf)
        total += int(np.prod(shape)) if shape else 1
    return total


def elastic_stamp(n_data: int, procs: int, L: int) -> dict:
    """The ``elastic`` checkpoint-meta stamp: the dp extent the state was
    laid out for, the process count (the sampler's shard count), and the
    logical param length — everything a restore at a different world size
    needs to remap deterministically."""
    return {"dp": int(n_data), "procs": int(procs), "params_len": int(L)}


def classify(
    key: str, ckpt_shape: Tuple[int, ...], want_shape: Tuple[int, ...], L: int
) -> Optional[str]:
    """Which elastic family (if any) explains a ``ckpt_shape`` vs
    ``want_shape`` mismatch on ``key``: ``'zero1_flat'`` / ``'ef_r1'`` /
    ``'ef_r2'``, or None (a real config mismatch)."""
    if key.startswith(_EF_R1_PREFIX):
        return "ef_r1"
    if key.startswith(_EF_R2_PREFIX):
        return "ef_r2"
    if (
        key.startswith(_OPT_PREFIX)
        and len(ckpt_shape) == 1
        and len(want_shape) == 1
        and ckpt_shape[0] >= L
        and want_shape[0] >= L
    ):
        return "zero1_flat"
    return None


class Remapper:
    """Shape-mismatch hook for ``ckpt.restore``/``restore_sharded``:
    rebuilds the dp-extent-dependent leaves at the new extent (module
    docstring for the exactness contract). ``used`` records every
    ``(key, kind)`` it actually remapped, so the trainer can tell a
    resharded resume (counter + rank-0 line) from a same-shape one — and
    the TD111 probe can prove it fired."""

    def __init__(self, L: int, n_new: int, n_old: Optional[int] = None):
        if L <= 0:
            raise ValueError(f"params_len must be positive, got {L}")
        if n_new <= 0:
            raise ValueError(f"n_new must be positive, got {n_new}")
        self.L = int(L)
        self.n_new = int(n_new)
        self.n_old = int(n_old) if n_old is not None else None
        self.used: list = []

    def __call__(self, key: str, arr: np.ndarray, leaf) -> Optional[np.ndarray]:
        want = tuple(np.shape(leaf))
        arr = np.asarray(arr)
        kind = classify(key, tuple(arr.shape), want, self.L)
        if kind is None:
            return None
        out = getattr(self, f"_remap_{kind}")(key, arr.ravel(), int(np.prod(want)))
        self.used.append((key, kind))
        return out.reshape(want)

    # -- families ----------------------------------------------------------

    def _remap_zero1_flat(self, key: str, arr: np.ndarray, want: int) -> np.ndarray:
        L = self.L
        if want < L:
            raise ConfigMismatchError(
                f"{key}: target flat length {want} is shorter than the "
                f"logical param length {L} — not a world-size change"
            )
        if arr[L:].any():
            # the pad tail of a ZeRO-1 flat vector is zero by construction
            # (pad gradients are zero, decay intervals stop at L); nonzero
            # means this is NOT the layout we think it is — refuse rather
            # than silently drop optimizer state
            raise ConfigMismatchError(
                f"{key}: flat optimizer vector has nonzero entries past the "
                f"logical param length {L} — the checkpoint's layout does "
                "not match the ZeRO-1 elastic contract; refusing to remap"
            )
        out = np.zeros((want,), arr.dtype)
        out[:L] = arr[:L]  # bit-exact: verbatim copy, dtype preserved
        return out

    def _remap_ef_r1(self, key: str, arr: np.ndarray, want: int) -> np.ndarray:
        if self.n_old is None:
            raise ConfigMismatchError(
                f"{key}: checkpoint predates the elastic 'dp' stamp, so the "
                "per-replica row count of the r1 residuals is unknown — "
                "resume at the original world size once (re-stamping), or "
                "drop to a clean-epoch checkpoint"
            )
        if arr.size % self.n_old:
            raise ConfigMismatchError(
                f"{key}: r1 length {arr.size} does not divide into "
                f"{self.n_old} replica rows — stamp/layout disagreement"
            )
        p_old = arr.size // self.n_old
        if want % self.n_new:
            raise ConfigMismatchError(
                f"{key}: target r1 length {want} does not divide into "
                f"{self.n_new} replica rows"
            )
        p_new = want // self.n_new
        crop = min(self.L, p_old, p_new)
        # aggregate-exact: the reduce sums every replica's compensated
        # contribution, so folding all rows into new replica 0 preserves
        # the total error to the bit; pad-coordinate residuals (past L)
        # chase phantom parameters and are dropped
        total = arr.reshape(self.n_old, p_old)[:, :crop].sum(
            axis=0, dtype=arr.dtype
        )
        out = np.zeros((want,), arr.dtype)
        out[:crop] = total
        return out

    def _remap_ef_r2(self, key: str, arr: np.ndarray, want: int) -> np.ndarray:
        # positional over the reduced gradient: bit-exact crop + zero re-pad
        keep = min(self.L, arr.size, want)
        out = np.zeros((want,), arr.dtype)
        out[:keep] = arr[:keep]
        return out


def make_remapper(template_state, meta: Optional[dict], n_new: int) -> Remapper:
    """Build the restore-ladder remapper for one checkpoint candidate:
    ``L`` comes from the live template (the param tree is world-size-
    independent, so it equals the checkpoint's), ``n_old`` from the
    checkpoint's ``elastic`` stamp (None for pre-stamp checkpoints — only
    ``r1`` needs it and raises a pointed error without it). A stamped
    ``params_len`` that disagrees with the template is a different MODEL,
    not a world-size change — :class:`ConfigMismatchError`."""
    L = params_len(template_state.params)
    el = (meta or {}).get("elastic") or {}
    stamped = el.get("params_len")
    if stamped is not None and int(stamped) != L:
        raise ConfigMismatchError(
            f"checkpoint was written with params_len={stamped} but the live "
            f"model ravels to {L} parameters — a different model, not a "
            "world-size change; elastic remap refused"
        )
    return Remapper(L, n_new, n_old=el.get("dp"))
