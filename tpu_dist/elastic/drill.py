"""The elastic drill — ``make elastic-drill`` / ``python -m
tpu_dist.elastic.drill``.

A self-contained local proof of the elastic contract
(docs/resilience.md "Elastic training"), on CPU-emulated devices:

1. **Golden** — an uninterrupted run at ``--devices`` emulated devices
   (ZeRO-1 + error-feedback state, so the dp-dependent layouts are real).
2. **Preempt** — the same run with a deterministic
   ``sigterm@epoch=E:step=S`` fault: the trainer finishes the in-flight
   step, writes the exact mid-epoch emergency snapshot, and exits 75.
3. **Shrink + resume** — the same command relaunched at ``--shrink_to``
   devices with ``--resume``: the restore ladder remaps the checkpoint
   onto the smaller dp extent (ZeRO-1 flat vectors and EF residuals
   re-laid) and training continues mid-epoch.
4. **Verify** — exit codes (75 then 0), the ``resume`` record's
   ``resharded`` flag in the JSONL, and the continued loss trajectory
   against the golden run within the golden-trajectory tolerance.

Each phase is a subprocess with its own
``--xla_force_host_platform_device_count``, because a process cannot
change its device count after the backend initializes. The bit-identity
half of the proof (restored state vs emergency save) lives in
``tests/test_elastic.py``, where the restored arrays are inspectable
in-process.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from typing import List, Optional, Sequence

from tpu_dist.resilience.preemption import PREEMPTION_EXIT_CODE

#: Relative loss tolerance — the golden-trajectory bound the test suite
#: uses (tests/test_golden_trajectory.py): the shrunk run reduces over a
#: different device count, so float reduction order differs while the
#: math is the same.
LOSS_RTOL = 2e-3


def _say(msg: str) -> None:
    # tpu-dist: ignore[TD002,TD007] — single-process CLI; stdout is the report
    print(f"elastic-drill: {msg}", flush=True)


def _run_phase(
    name: str, devices: int, train_args: List[str], extra_env: dict
) -> int:
    import re  # noqa: PLC0415

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""  # skip TPU plugin registration
    # replace (not append) any inherited device-count flag: each phase
    # owns its own emulated device count
    inherited = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "",
        env.get("XLA_FLAGS", ""),
    )
    env["XLA_FLAGS"] = (
        inherited + f" --xla_force_host_platform_device_count={devices}"
    ).strip()
    env.update(extra_env)
    cmd = [sys.executable, "-m", "tpu_dist.cli.train"] + train_args
    _say(f"phase {name}: {devices} device(s): {' '.join(train_args)}")
    rc = subprocess.call(cmd, env=env)
    _say(f"phase {name}: exit {rc}")
    return rc


def _load(log_path: str) -> List[dict]:
    from tpu_dist.obs.summarize import load_records  # one JSONL reader

    records, _bad = load_records(log_path)
    return records


def _epoch_losses(records: List[dict]) -> dict:
    return {
        rec.get("epoch"): rec["loss"]  # last segment wins
        for rec in records
        if rec.get("kind") == "train_epoch"
        and isinstance(rec.get("loss"), (int, float))
    }


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tpu_dist.elastic.drill",
        description="preempt-at-step-k -> shrink -> parity drill (CPU)",
    )
    p.add_argument("--workdir", required=True, help="scratch dir for ckpts/logs")
    p.add_argument("--devices", type=int, default=8)
    p.add_argument("--shrink_to", type=int, default=4)
    p.add_argument("--model", default="vit_tiny")
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--steps_per_epoch", type=int, default=3)
    p.add_argument("--batch_size", type=int, default=64)
    p.add_argument("--kill_epoch", type=int, default=1)
    p.add_argument("--kill_step", type=int, default=1)
    p.add_argument(
        "--grad_compression", default="none",
        choices=("none", "bf16", "int8", "int8_ef"),
        help="wire format for the drilled run; 'none' (default) keeps the "
             "shrunk trajectory inside the tight golden tolerance (the "
             "int8 modes re-chunk quantization at the new extent — "
             "parity, but noisier); int8_ef additionally drills the EF "
             "residual remap, which tests/test_elastic.py covers "
             "bit-exactly in-process",
    )
    args = p.parse_args(argv)

    os.makedirs(args.workdir, exist_ok=True)
    golden_log = os.path.join(args.workdir, "golden.jsonl")
    elastic_log = os.path.join(args.workdir, "elastic.jsonl")
    base = [
        "--dataset", "synthetic", "--model", args.model,
        "--num_classes", "10", "--synthetic_n", "256",
        "--batch_size", str(args.batch_size),
        "--epochs", str(args.epochs),
        "--steps_per_epoch", str(args.steps_per_epoch),
        "--eval_every", "0", "--save_every", "1", "--log_every", "50",
        "--seed", "0", "--shard_weight_update",
        "--grad_compression", args.grad_compression,
    ]

    rc = _run_phase(
        "golden", args.devices,
        base + ["--ckpt_dir", os.path.join(args.workdir, "ck_golden"),
                "--log_file", golden_log],
        {},
    )
    if rc != 0:
        _say(f"FAIL: golden run exited {rc}")
        return 1

    elastic_ck = os.path.join(args.workdir, "ck_elastic")
    rc = _run_phase(
        "preempt", args.devices,
        base + ["--ckpt_dir", elastic_ck, "--log_file", elastic_log,
                "--fault_plan",
                f"sigterm@epoch={args.kill_epoch}:step={args.kill_step}"],
        {},
    )
    if rc != PREEMPTION_EXIT_CODE:
        _say(f"FAIL: preempted run exited {rc}, wanted {PREEMPTION_EXIT_CODE}")
        return 1

    rc = _run_phase(
        "shrink-resume", args.shrink_to,
        base + ["--ckpt_dir", elastic_ck, "--log_file", elastic_log,
                "--resume"],
        {"TPU_DIST_ELASTIC_RESTARTS": "1"},
    )
    if rc != 0:
        _say(f"FAIL: shrunk resume exited {rc}")
        return 1

    elastic_recs = _load(elastic_log)
    resumes = [r for r in elastic_recs if r.get("kind") == "resume"]
    if not resumes:
        _say("FAIL: no 'resume' record in the elastic log")
        return 1
    last = resumes[-1]
    if not last.get("resharded"):
        _say(f"FAIL: resume record not resharded: {last}")
        return 1
    _say(
        f"resume record: epoch {last.get('epoch')} dp {last.get('prev_dp')}"
        f" -> {last.get('dp')}, resharded"
    )

    golden = _epoch_losses(_load(golden_log))
    elastic = _epoch_losses(elastic_recs)
    for epoch, want in sorted(golden.items()):
        got = elastic.get(epoch)
        if got is None:
            _say(f"FAIL: elastic run has no epoch {epoch}")
            return 1
        rel = abs(got - want) / max(abs(want), 1e-12)
        _say(
            f"epoch {epoch}: golden loss {want:.6f}, elastic {got:.6f} "
            f"(rel {rel:.2e})"
        )
        if rel > LOSS_RTOL:
            _say(f"FAIL: loss diverged past rtol {LOSS_RTOL}")
            return 1
    _say(
        f"PASS: preempted at epoch {args.kill_epoch} step {args.kill_step} "
        f"on {args.devices} devices, resumed on {args.shrink_to}, state "
        "resharded, trajectory within golden tolerance"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
