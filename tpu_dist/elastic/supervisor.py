"""Elastic relaunch policy for the local launcher (``cli/launch.py``).

On a shared/preemptible pool, losing part of the pod is routine, not
fatal: pod-scale practice treats worker loss as a reschedule, and a run
that can continue at a reduced world size survives the night where an
"identical size or nothing" run waits in the queue. This module is the
policy half — pure, deterministic, jax-free — the launcher supplies the
mechanism (spawn a round of workers, collect per-rank exits).

The loop:

1. Run a round at world size ``n``.
2. Clean exit → done. Otherwise classify each rank's exit: a rank that
   ended 0, with the preemption code (75), or on the launcher's own
   forwarded SIGTERM is a **survivor** (it can be rescheduled); anything
   else — a hard kill, a crash, a watchdog SIGKILL — is **lost**.
3. Whole-pod preemption (nothing lost) relaunches at the same size;
   lost ranks shrink the next round to the largest divisor of the
   ORIGINAL world size that fits the survivors (divisors keep the global
   batch's divisibility story intact) and stays >= ``min_procs``.
4. Each relaunch waits the deterministic exponential backoff of
   ``resilience/retry.py`` (injectable sleep, no jitter) and is bounded
   by ``max_restarts`` — a deterministic crash loop burns its budget and
   surfaces the real exit code instead of cycling forever.

The mid-run *state* story (checkpoint remap onto the new dp extent,
sampler re-partitioning) lives in ``tpu_dist/elastic/remap.py`` and the
trainer's restore ladder; the relaunched children just run ``--resume``.
"""

from __future__ import annotations

import dataclasses
import signal
import time
from typing import Callable, Dict, Optional

from tpu_dist.resilience.preemption import PREEMPTION_EXIT_CODE
from tpu_dist.resilience.retry import backoff_delays

#: Exit statuses that mark a rank as reschedulable: clean, the cooperative
#: preemption code, and death by the launcher's own forwarded SIGTERM
#: (a child preempted before its handler was installed).
SURVIVOR_EXITS = frozenset({0, PREEMPTION_EXIT_CODE, -int(signal.SIGTERM)})


@dataclasses.dataclass
class RoundResult:
    """One launcher round's outcome: the aggregate exit code the launcher
    would have returned, and each rank's raw exit status."""

    rc: int
    rank_exits: Dict[int, int]

    def survivors(self) -> int:
        return sum(
            1 for code in self.rank_exits.values() if code in SURVIVOR_EXITS
        )

    def lost(self) -> int:
        return len(self.rank_exits) - self.survivors()


def feasible_sizes(original: int) -> list:
    """Candidate world sizes, largest first: the divisors of the original
    launch size. A divisor keeps every 'global value divides over the
    world' property (batch, dataset sharding) that held at full size."""
    return [n for n in range(original, 0, -1) if original % n == 0]


def next_world_size(
    original: int, survivors: int, min_procs: int
) -> Optional[int]:
    """Largest feasible world size that the surviving ranks can staff and
    that honors the ``--elastic_min_procs`` floor; None when no such size
    exists (the run must fail rather than limp below the floor)."""
    for n in feasible_sizes(original):
        if n <= survivors and n >= max(1, min_procs):
            return n
    return None


def supervise(
    run_round: Callable[[int, int], RoundResult],
    *,
    nproc: int,
    min_procs: int,
    max_restarts: int,
    backoff_base: float = 0.5,
    backoff_max: float = 30.0,
    sleep: Optional[Callable[[float], None]] = None,
    announce: Optional[Callable[[str], None]] = None,
    should_continue: Optional[Callable[[], bool]] = None,
) -> int:
    """Drive ``run_round(world_size, restart_index)`` until the run
    completes, the restart budget is spent, or the pod shrinks below the
    floor. Returns the exit code of the final round (0 on success).

    ``should_continue`` is consulted before every relaunch: the launcher
    passes "I was not myself SIGTERMed" — when the ORCHESTRATOR preempts
    the whole job (signal to the launcher), elastic must surface the
    requeue code upward, not fight the scheduler by relaunching locally."""
    do_sleep = sleep if sleep is not None else time.sleep
    say = announce if announce is not None else (lambda _msg: None)
    keep_going = should_continue if should_continue is not None else (lambda: True)
    delays = backoff_delays(max(1, max_restarts), backoff_base, backoff_max)
    n = nproc
    res = run_round(n, 0)
    for restart in range(max_restarts):
        if res.rc == 0:
            return 0
        if not keep_going():
            say(
                "elastic: the launcher itself was asked to stop — "
                f"surfacing exit {res.rc} instead of relaunching"
            )
            return res.rc
        lost = res.lost()
        survivors = res.survivors()  # the census is the single source
        if lost == 0:
            # whole-pod preemption: every rank is reschedulable — retry at
            # the same size (the orchestrator-requeue case, done locally)
            target = n
        else:
            target = next_world_size(nproc, survivors, min_procs)
            if target is None:
                say(
                    f"elastic: only {survivors} of {n} rank(s) survived — "
                    f"no feasible world size >= min_procs={min_procs}; "
                    f"giving up with exit {res.rc}"
                )
                return res.rc
        delay = delays[min(restart, len(delays) - 1)]
        say(
            f"elastic: relaunching at world size {target} (was {n}, "
            f"{lost} rank(s) lost; restart {restart + 1}/{max_restarts}, "
            f"backoff {delay:g}s)"
        )
        do_sleep(delay)
        if not keep_going():
            # the stop request can land DURING the backoff window — a
            # relaunch after it would fight the scheduler with a whole
            # fresh world; surface the last round's code instead
            say(
                "elastic: stop requested during backoff — surfacing exit "
                f"{res.rc} instead of relaunching"
            )
            return res.rc
        n = target
        res = run_round(n, restart + 1)
    if res.rc != 0:
        say(
            f"elastic: restart budget ({max_restarts}) spent; surfacing "
            f"exit {res.rc}"
        )
    return res.rc
