"""Elastic relaunch policy for the local launcher (``cli/launch.py``).

On a shared/preemptible pool, losing part of the pod is routine, not
fatal: pod-scale practice treats worker loss as a reschedule, and a run
that can continue at a reduced world size survives the night where an
"identical size or nothing" run waits in the queue. This module is the
policy half — pure, deterministic, jax-free — the launcher supplies the
mechanism (spawn a round of workers, collect per-rank exits).

The loop:

1. Run a round at world size ``n``.
2. Clean exit → done. Otherwise classify each rank's exit: a rank that
   ended 0, with the preemption code (75), or on the launcher's own
   forwarded SIGTERM is a **survivor** (it can be rescheduled); anything
   else — a hard kill, a crash, a watchdog SIGKILL — is **lost**.
3. Whole-pod preemption (nothing lost) relaunches at the same size;
   lost ranks shrink the next round to the largest divisor of the
   ORIGINAL world size that fits the survivors (divisors keep the global
   batch's divisibility story intact) and stays >= ``min_procs``.
4. Each relaunch waits the deterministic exponential backoff of
   ``resilience/retry.py`` (injectable sleep, no jitter) and is bounded
   by ``max_restarts`` — a deterministic crash loop burns its budget and
   surfaces the real exit code instead of cycling forever.

Scale-UP closes the other half of the loop (docs/resilience.md
"Scale-up & fleet scheduling"): a shrunken run stays small forever
unless somebody notices the preempted chips came back. The supervisor
owns that too — a :class:`CapacityProbe` (injectable census + clock, a
fixed probe interval, and a deterministic ``resilience/retry.py``
cooldown between grow decisions) is polled by the RUNNING round; when
the census staffs a larger feasible divisor the round checkpoints its
world (graceful SIGTERM → exit 75) and reports ``resize_to``, and the
loop relaunches ``--resume`` at the new size. Resizes are voluntary:
they consume no restart budget and wait no failure backoff. The same
probe drives scheduler-initiated *donations* (the census shrank below
the current size — ``tpu_dist/fleet/scheduler.py`` moved this run's
chips to a sibling), and on a FAILURE relaunch the census caps the
survivor-derived target, so a round never respawns onto chips an
external scheduler already took away.

The mid-run *state* story (checkpoint remap onto the new dp extent,
sampler re-partitioning) lives in ``tpu_dist/elastic/remap.py`` and the
trainer's restore ladder; the relaunched children just run ``--resume``.
"""

from __future__ import annotations

import dataclasses
import signal
import time
from typing import Callable, Dict, Optional

from tpu_dist.resilience.preemption import PREEMPTION_EXIT_CODE
from tpu_dist.resilience.retry import backoff_delays

#: Exit statuses that mark a rank as reschedulable: clean, the cooperative
#: preemption code, and death by the launcher's own forwarded SIGTERM
#: (a child preempted before its handler was installed).
SURVIVOR_EXITS = frozenset({0, PREEMPTION_EXIT_CODE, -int(signal.SIGTERM)})

#: Relaunch-env names for causal arbitration tracing: when a resize was
#: fleet-initiated, the allocation file carries the scheduler's
#: ``decision_id``/``cause`` metadata tokens and the launcher stamps
#: them into every relaunched child — the trainer's resume record,
#: flight-ring slot, and goodput window then name WHICH arbitration
#: moved the run. A chip-loss resize (no scheduler involved) leaves the
#: env unset, so the two causes are finally distinguishable downstream.
DECISION_ID_ENV = "TPU_DIST_FLEET_DECISION_ID"
DECISION_CAUSE_ENV = "TPU_DIST_FLEET_DECISION_CAUSE"


def read_decision(capacity_file: Optional[str]) -> dict:
    """The active arbitration metadata in the run's allocation file:
    ``{"decision_id": int|None, "cause": str|None}`` — all-None when no
    capacity file is configured, the file is absent/torn, or its writer
    predates causal tracing. Never raises (the probe discipline)."""
    if not capacity_file:
        return {"decision_id": None, "cause": None}
    from tpu_dist.fleet import capacity as capacity_lib

    return capacity_lib.read_allocation_meta(capacity_file)


def stamp_decision_env(env: dict, capacity_file: Optional[str]) -> dict:
    """Stamp the active ``decision_id``/``cause`` (when any) into a
    relaunch environment IN PLACE, clearing stale values otherwise — a
    child relaunched after the arbitration window closed must not
    inherit a dead id from the launcher's own environment. Returns the
    metadata that was read, for the caller's round log."""
    meta = read_decision(capacity_file)
    for key, val in (
        (DECISION_ID_ENV, meta["decision_id"]),
        (DECISION_CAUSE_ENV, meta["cause"]),
    ):
        if val is not None:
            env[key] = str(val)
        else:
            env.pop(key, None)
    return meta


@dataclasses.dataclass
class RoundResult:
    """One launcher round's outcome: the aggregate exit code the launcher
    would have returned, and each rank's raw exit status.

    ``resize_to`` is set when the round ended because the CAPACITY PROBE
    asked it to (a grow when chips returned, a shrink when the fleet
    scheduler donated this run's chips away): the round SIGTERMed its own
    world — every rank checkpointed and exited 75 — and the supervisor
    should relaunch ``--resume`` at that size without touching the
    failure budget."""

    rc: int
    rank_exits: Dict[int, int]
    resize_to: Optional[int] = None

    def survivors(self) -> int:
        return sum(
            1 for code in self.rank_exits.values() if code in SURVIVOR_EXITS
        )

    def lost(self) -> int:
        return len(self.rank_exits) - self.survivors()


def feasible_sizes(original: int) -> list:
    """Candidate world sizes, largest first: the divisors of the original
    launch size. A divisor keeps every 'global value divides over the
    world' property (batch, dataset sharding) that held at full size."""
    return [n for n in range(original, 0, -1) if original % n == 0]


def next_world_size(
    original: int, survivors: int, min_procs: int
) -> Optional[int]:
    """Largest feasible world size that the surviving ranks can staff and
    that honors the ``--elastic_min_procs`` floor; None when no such size
    exists (the run must fail rather than limp below the floor)."""
    for n in feasible_sizes(original):
        if n <= survivors and n >= max(1, min_procs):
            return n
    return None


def grow_target(
    original: int, current: int, available: int, max_procs: int = 0
) -> Optional[int]:
    """Largest feasible world size the AVAILABLE capacity staffs that is
    strictly larger than ``current`` and within ``max_procs`` (0 = the
    original launch size — elastic never grows a run past what it was
    asked for); None when capacity doesn't reach the next divisor up."""
    bound = min(max_procs, original) if max_procs > 0 else original
    for n in feasible_sizes(original):
        if current < n <= min(available, bound):
            return n
    return None


def shrink_target(
    original: int, current: int, available: int, min_procs: int
) -> Optional[int]:
    """Largest feasible world size at or below ``available`` and strictly
    below ``current``, honoring the floor — the donation half of a
    capacity change (the census says this run's chips were taken). None
    when no feasible smaller size exists (the run keeps its chips rather
    than dying: a donation must never do what a preemption couldn't)."""
    for n in feasible_sizes(original):
        if n < current and n <= available and n >= max(1, min_procs):
            return n
    return None


class CapacityProbe:
    """Deterministic capacity-probe state machine (docs/resilience.md
    "Scale-up & fleet scheduling").

    ``census`` is the injectable capacity source — how many processes'
    worth of chips this run may use *right now* (the launcher backs it
    with ``tpu_dist/fleet/capacity.py``: an allocation file the fleet
    scheduler owns, an env override, or "the original size" when nothing
    external constrains the run). :meth:`poll` is called from the running
    round's wait loop and returns a resize target — a GROW when the
    census staffs a larger feasible divisor, a SHRINK when it dropped
    below the current size — or None.

    Determinism: probes fire on a fixed ``interval`` of the injectable
    ``clock`` (tests pass ``now`` explicitly), and each grow decision
    arms the ``resilience/retry.py`` exponential cooldown
    (``cooldown_base * 2**k`` capped at ``cooldown_max``) before the next
    one, so a flapping census cannot thrash the run through
    checkpoint/relaunch cycles. Shrinks are NOT cooled down — the chips
    are already gone; delaying the handover only burns the donor's and
    recipient's time (the fleet scheduler has its own per-run move
    cooldown at decision grain).
    """

    def __init__(
        self,
        census: Callable[[], Optional[int]],
        *,
        original: int,
        min_procs: int = 1,
        max_procs: int = 0,
        interval: float = 30.0,
        cooldown_base: Optional[float] = None,
        cooldown_max: float = 600.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if original <= 0:
            raise ValueError(f"original world size must be positive, got {original}")
        if interval <= 0:
            raise ValueError(f"probe interval must be positive, got {interval}")
        self.census = census
        self.original = int(original)
        self.min_procs = int(min_procs)
        self.max_procs = int(max_procs)
        self.interval = float(interval)
        self.cooldown_base = (
            float(cooldown_base) if cooldown_base is not None
            else 2.0 * self.interval
        )
        self.cooldown_max = float(cooldown_max)
        self.clock = clock
        self.grows = 0  # grow decisions issued (drives the cooldown index)
        self._next_probe: Optional[float] = None  # first poll arms it
        self._grow_cooldown_until = float("-inf")  # grows only — a shrink
        #                            (the chips are gone) is never delayed

    def available(self) -> Optional[int]:
        """One raw census read (no pacing) — the supervisor consults this
        on a FAILURE relaunch to cap the survivor-derived target; None
        means the census cannot answer (treat as unconstrained)."""
        try:
            avail = self.census()
        except OSError:
            return None  # an unreadable census must never kill the policy
        return int(avail) if avail is not None else None

    def poll(self, current: int, now: Optional[float] = None) -> Optional[int]:
        """Consult the census at probe grain; returns the resize target
        (grow or shrink) or None. The first call only arms the timer —
        a freshly (re)launched round gets a full interval to settle
        before any census reading can bounce it again."""
        now = self.clock() if now is None else now
        if self._next_probe is None:
            self._next_probe = now + self.interval
            return None
        if now < self._next_probe:
            return None
        self._next_probe = now + self.interval
        avail = self.available()
        if avail is None:
            return None
        if avail < current:
            # a shrink ends the current shrink→grow cycle: the NEXT cycle
            # starts its grow-cooldown ladder from the base again (a
            # long-lived fleet run legitimately donates and receives many
            # times — an ever-growing streak would eventually park freed
            # chips for the max cooldown). The cooldown ARMED by the last
            # grow still stands, so a census flapping up-down-up is still
            # paced to at most one full cycle per grow cooldown.
            self.grows = 0
            return shrink_target(
                self.original, current, avail, self.min_procs
            )
        if now < self._grow_cooldown_until:
            return None
        target = grow_target(self.original, current, avail, self.max_procs)
        if target is None:
            return None
        # arm the deterministic grow cooldown: the k-th grow waits the
        # k-th retry.py backoff delay before the NEXT grow may fire
        self.grows += 1
        self._grow_cooldown_until = now + backoff_delays(
            self.grows, self.cooldown_base, self.cooldown_max
        )[self.grows - 1]
        return target

    def reset_timer(self, now: Optional[float] = None) -> None:
        """Re-arm the probe interval from ``now`` — the launcher calls
        this when a new round spawns so the fresh world always gets one
        full interval of peace (the grow cooldown is separate state and
        survives untouched)."""
        now = self.clock() if now is None else now
        self._next_probe = max(self._next_probe or 0.0, now + self.interval)


def supervise(
    run_round: Callable[[int, int], RoundResult],
    *,
    nproc: int,
    min_procs: int,
    max_restarts: int,
    backoff_base: float = 0.5,
    backoff_max: float = 30.0,
    sleep: Optional[Callable[[float], None]] = None,
    announce: Optional[Callable[[str], None]] = None,
    should_continue: Optional[Callable[[], bool]] = None,
    probe: Optional[CapacityProbe] = None,
    same_size_retries: int = 2,
    start_procs: Optional[int] = None,
) -> int:
    """Drive ``run_round(world_size, round_index)`` until the run
    completes, the restart budget is spent, or the pod shrinks below the
    floor. Returns the exit code of the final round (0 on success).

    ``should_continue`` is consulted before every relaunch: the launcher
    passes "I was not myself SIGTERMed" — when the ORCHESTRATOR preempts
    the whole job (signal to the launcher), elastic must surface the
    requeue code upward, not fight the scheduler by relaunching locally.
    That stand-down outranks every other branch here, resizes included.

    ``probe`` arms the scale-up/donation half: a round that ends with
    ``resize_to`` set (the running round polled the probe and SIGTERMed
    itself) is relaunched at that size immediately — no failure backoff,
    no restart-budget charge (resizes are voluntary and self-bounding:
    grows strictly increase through the divisor chain and are paced by
    the probe's own cooldown). On a FAILURE relaunch the probe's census
    additionally caps the survivor-derived target — exit codes say who
    died, the census says whose chips exist at all.

    ``start_procs`` launches the FIRST round at a smaller feasible size
    than ``nproc`` (the launcher passes the census-granted allocation —
    a run whose chips are currently lent out must not spawn round 0 on
    top of another run); every feasibility computation still derives
    from the original ``nproc``, so the run grows back to full size
    when the probe says the chips returned.

    ``same_size_retries`` bounds the whole-pod-loss retry: a round where
    every rank was reschedulable retries at the SAME size at most that
    many consecutive times, then steps down one feasible divisor (floor
    permitting) instead of burning the entire restart budget waiting for
    capacity that isn't coming back — while the first flaky round still
    never shrinks the run permanently (scale-up grows it back anyway)."""
    do_sleep = sleep if sleep is not None else time.sleep
    say = announce if announce is not None else (lambda _msg: None)
    keep_going = should_continue if should_continue is not None else (lambda: True)
    delays = backoff_delays(max(1, max_restarts), backoff_base, backoff_max)
    n = start_procs if start_procs is not None else nproc
    round_idx = 0
    restarts_used = 0
    same_size_used = 0
    res = run_round(n, round_idx)
    while True:
        if res.rc == 0:
            return 0
        if not keep_going():
            say(
                "elastic: the launcher itself was asked to stop — "
                f"surfacing exit {res.rc} instead of relaunching"
            )
            return res.rc
        if res.resize_to is not None and res.resize_to != n:
            # voluntary resize (probe-driven): the round already
            # checkpointed and stood its world down — relaunch --resume
            # at the new size now; no failure backoff, no budget charge
            target = res.resize_to
            say(
                "elastic: "
                + ("capacity returned — growing" if target > n
                   else "chips donated — shrinking")
                + f" from world size {n} to {target} (round "
                f"{round_idx + 1}, restart budget untouched at "
                f"{restarts_used}/{max_restarts})"
            )
            same_size_used = 0
            n = target
            round_idx += 1
            res = run_round(n, round_idx)
            continue
        if restarts_used >= max_restarts:
            say(
                f"elastic: restart budget ({max_restarts}) spent; "
                f"surfacing exit {res.rc}"
            )
            return res.rc
        lost = res.lost()
        survivors = res.survivors()  # the exit-code census
        if lost == 0:
            # whole-pod preemption: every rank is reschedulable — retry
            # at the same size, but only ``same_size_retries`` times in a
            # row before stepping down a divisor (a pod that keeps
            # preempting whole is not coming back this backoff window)
            if same_size_used < same_size_retries:
                same_size_used += 1
                target = n
            else:
                smaller = [
                    s for s in feasible_sizes(nproc)
                    if s < n and s >= max(1, min_procs)
                ]
                if smaller:
                    target = smaller[0]
                    say(
                        f"elastic: {same_size_used} same-size retries at "
                        f"world size {n} all lost the whole pod — "
                        f"stepping down to {target}"
                    )
                    same_size_used = 0
                else:
                    target = n  # already at the floor: keep trying
        else:
            same_size_used = 0
            target = next_world_size(nproc, survivors, min_procs)
            if target is None:
                say(
                    f"elastic: only {survivors} of {n} rank(s) survived — "
                    f"no feasible world size >= min_procs={min_procs}; "
                    f"giving up with exit {res.rc}"
                )
                return res.rc
        if probe is not None:
            # the external census caps a failure relaunch: survivors'
            # exit codes prove who CAN reschedule, the capacity census
            # says how many chips still belong to this run at all
            avail = probe.available()
            if avail is not None and avail < target:
                capped = next_world_size(nproc, int(avail), min_procs)
                if capped is None:
                    say(
                        f"elastic: capacity census reports {avail} "
                        f"proc(s) available — no feasible world size >= "
                        f"min_procs={min_procs}; giving up with exit "
                        f"{res.rc}"
                    )
                    return res.rc
                if capped != target:
                    say(
                        f"elastic: capacity census caps the relaunch at "
                        f"{capped} (survivors allowed {target}, census "
                        f"reports {avail} available)"
                    )
                    target = capped
        if target != n:
            # any size change starts a fresh same-size streak — a
            # census-capped relaunch must not inherit the old size's
            # spent retries (the step-down/loss branches reset above)
            same_size_used = 0
        delay = delays[min(restarts_used, len(delays) - 1)]
        say(
            f"elastic: relaunching at world size {target} (was {n}, "
            f"{lost} rank(s) lost; restart {restarts_used + 1}/"
            f"{max_restarts}, backoff {delay:g}s)"
        )
        do_sleep(delay)
        if not keep_going():
            # the stop request can land DURING the backoff window — a
            # relaunch after it would fight the scheduler with a whole
            # fresh world; surface the last round's code instead
            say(
                "elastic: stop requested during backoff — surfacing exit "
                f"{res.rc} instead of relaunching"
            )
            return res.rc
        restarts_used += 1
        round_idx += 1
        n = target
        res = run_round(n, round_idx)
