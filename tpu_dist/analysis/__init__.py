"""Static + trace-time + compile-time analysis of the training system.

Three layers (see ``docs/analysis.md``):

* **Layer 1 — AST lint** (:mod:`tpu_dist.analysis.lint`): walks the package
  source with ``ast`` and flags TPU-hostile idioms — host syncs in jitted
  step functions, unguarded non-rank-0 I/O, hot-path ``jax.jit`` without
  donation, version-fragile JAX imports, trace-time nondeterminism,
  rank-guarded collective call sites. Rules TD001-TD008. No jax import
  needed; runs in milliseconds.
* **Layer 2 — jaxpr audit** (:mod:`tpu_dist.analysis.jaxpr_audit`):
  abstractly traces the registered train-step builders on an emulated CPU
  mesh and inspects the closed jaxpr — collective counts asserted against
  the parallelism config's budget, unexpected transfer ops, bf16→f32
  promotion creep, quantized wire-byte ratios, the armed-vs-off no-op
  contracts. Rules TD101-TD115.
* **Layer 3 — HLO shard audit** (:mod:`tpu_dist.analysis.shardlint`):
  lowers and compiles every config family and parses the OPTIMIZED HLO —
  the program GSPMD actually emitted — into a structured collective
  inventory; the compiled accounting must agree with the jaxpr ring model
  (TD116) and carry no unpredicted reshard (TD117). Emits
  ``shard_report.json``, the ``--auto_shard`` planner input
  (docs/shard_report.md).

CLI: ``python -m tpu_dist.analysis [--format text|json] [--baseline F]``
for Layers 1+2; ``python -m tpu_dist.analysis shard [--out F]`` for
Layer 3. Exit 0 = clean (after suppressions + baseline), 1 = violations,
2 = error.

Keep this ``__init__`` import-light: the CLI must be able to configure the
emulated mesh before anything touches a jax backend.
"""

from tpu_dist.analysis.rules import RULES, Rule, Violation  # noqa: F401


def lint_paths(*args, **kwargs):
    from tpu_dist.analysis.lint import lint_paths as _impl

    return _impl(*args, **kwargs)


def audit_all(*args, **kwargs):
    from tpu_dist.analysis.jaxpr_audit import audit_all as _impl

    return _impl(*args, **kwargs)


def shard_all(*args, **kwargs):
    from tpu_dist.analysis.shardlint import shard_all as _impl

    return _impl(*args, **kwargs)
