"""Checked-in baseline: accepted violations the CI gate tolerates.

The gate's contract is *no NEW violations*: findings whose
``(rule, file, line-text)`` key matches a baseline entry are filtered out
before the exit code is computed, so the count can only ratchet down.
Matching ignores line numbers (they drift on every edit) and is multiset —
two identical prints in one file need two entries. Stale entries (baseline
lines the code no longer produces) are reported so the file shrinks as
debt is paid.

Regenerate with ``python -m tpu_dist.analysis --write-baseline`` after a
deliberate accept; prefer inline ``# tpu-dist: ignore[TDxxx]`` with a
reason for anything permanent.
"""

from __future__ import annotations

import json
import os
from collections import Counter

from tpu_dist.analysis.rules import Violation


def load(path: str) -> list[dict]:
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return data.get("accepted", []) if isinstance(data, dict) else data


def _entry_key(entry: dict) -> tuple:
    return (entry.get("rule"), entry.get("path"), (entry.get("snippet") or "").strip())


def apply(
    violations: list[Violation], baseline: list[dict]
) -> tuple[list[Violation], list[dict]]:
    """Returns ``(new_violations, stale_entries)``."""
    budget = Counter(_entry_key(e) for e in baseline)
    new: list[Violation] = []
    for v in violations:
        key = v.baseline_key()
        if budget.get(key, 0) > 0:
            budget[key] -= 1
        else:
            new.append(v)
    stale = []
    for e in baseline:
        key = _entry_key(e)
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            stale.append(e)
    return new, stale


def write(violations: list[Violation], path: str) -> None:
    entries = [
        {"rule": v.rule, "path": v.path, "snippet": v.snippet.strip()}
        for v in violations
    ]
    payload = {
        "comment": "accepted analysis findings — see docs/analysis.md; "
        "prefer inline '# tpu-dist: ignore[TDxxx]' suppressions",
        "accepted": entries,
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
