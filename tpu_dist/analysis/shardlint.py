"""Layer 3 — static HLO sharding & collective audit (``shardlint``).

The jaxpr layers (TD101-TD115) audit the program the *tracer* saw; this
layer audits the program the *compiler emitted*. Every config family is
lowered through the real ``jax.jit(...).lower(...).compile()`` pipeline
and the post-optimization HLO text is parsed into a structured collective
inventory — op kind, operand/result shapes+dtypes, replica groups,
estimated wire bytes per op under the same ring model TD104 uses — which
is where GSPMD-inserted implicit reshards, surprise all-gathers, and
backend dtype rewrites live, none of which the jaxpr can show.

Two rules ride on the inventory:

* **TD116** ``compiled-collectives-match-predicted`` — the HLO-derived
  wire accounting must agree with the jaxpr-level ring model: total
  elements exactly, integer/quantized legs byte-for-byte, float legs
  exactly in one of the two declared dtype regimes (``native``, or
  ``widened_to_f32`` on backends whose float-normalization pass rewrites
  narrow-float collectives — CPU emulation does exactly this to bf16).
  Anything else means one of the two accountings is lying.
* **TD117** ``unintended-reshard-in-compiled-step`` — any collective the
  prediction did not budget (an unpredicted op *kind*, or per-kind wire
  bytes beyond the prediction) is flagged with op, shape, bytes, and
  replica groups. The canonical trigger is a bad ``in_shardings`` making
  GSPMD gather state the step expected resident
  (:func:`injected_bad_zero1` demonstrates it on the ZeRO-1 step).

Config families come from the ONE registry the planner will search
(``train/step.py::SHARD_CONFIG_FAMILIES``): the dp/zero1/compression
families reuse the jaxpr-audit model zoo; fsdp (GSPMD engine), tp
(Megatron ViT), sp (ring attention), and the serve forward step get
builders here. Each analyzed family lands in ``shard_report.json``
(:func:`build_shard_report` / :func:`load_shard_report`,
docs/shard_report.md) — the machine-readable planner input: verified
collective inventory + HLO wire bytes + static HBM ledger + calibrated
step-time prediction per family.

Everything is host-side: lowering and compiling for *text* never touches
a device buffer, and on CPU emulation the whole matrix runs in seconds —
a CPU-valid static perf signal while the TPU tunnel is down (ROADMAP
re-anchor note).
"""

from __future__ import annotations

import dataclasses
import json
from collections import Counter
from typing import Callable, Optional

from tpu_dist.analysis.rules import Violation

SCHEMA = "shard_report_v1"

#: HLO collective opcodes the inventory tracks (async ``-start`` halves
#: are folded into their base kind; ``-done`` halves are skipped).
HLO_COLLECTIVE_KINDS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "all-to-all",
    "collective-permute",
)

#: jaxpr collective primitive → the HLO opcode it lowers to.
PRIM_TO_HLO_KIND = {
    "psum": "all-reduce",
    "pmin": "all-reduce",
    "pmax": "all-reduce",
    "psum_scatter": "reduce-scatter",
    "reduce_scatter": "reduce-scatter",
    "all_gather": "all-gather",
    "pgather": "all-gather",
    "all_to_all": "all-to-all",
    "ppermute": "collective-permute",
}

#: Per-replica wire legs per HLO kind — the SAME ring model TD104 prices
#: the jaxpr with (``jaxpr_audit._WIRE_LEGS``): an all-reduce is a
#: reduce-scatter + all-gather of its operand (2 legs); the scatter/
#: gather/exchange ops move their costed side once. all-gather is costed
#: on its OUTPUT (the operand is the local shard).
KIND_LEGS = {
    "all-reduce": 2,
    "all-gather": 1,
    "reduce-scatter": 1,
    "all-to-all": 1,
    "collective-permute": 1,
}

_DTYPE_BYTES = {
    "pred": 1, "s2": 1, "s4": 1, "s8": 1, "u2": 1, "u4": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
    "f8e4m3": 1, "f8e5m2": 1, "f8e4m3fn": 1, "f8e4m3b11fnuz": 1,
    "f8e5m2fnuz": 1, "f8e4m3fnuz": 1,
}
_FLOAT_DTYPES = frozenset(
    d for d in _DTYPE_BYTES if d.startswith(("f", "bf", "c"))
)


class HLOParseError(ValueError):
    """The text is not a parseable post-optimization HLO module (empty,
    truncated mid-computation, or a different dialect entirely)."""


class ShardReportError(ValueError):
    """A shard_report.json failed schema validation on load."""


# --------------------------------------------------------------------------
# The HLO text parser
# --------------------------------------------------------------------------


@dataclasses.dataclass
class HLOCollective:
    """One collective op from the optimized HLO, priced with the ring
    model. ``elems``/``wire_bytes`` already include the loop multiplier
    (``loop_trips`` > 1 for ops living inside a ``while`` body)."""

    kind: str
    shape: str               # costed-side type string, e.g. "f32[12,16]"
    dtype: str
    elems: int               # leg-free element count × loop trips
    wire_bytes: int          # legs × bytes × loop trips
    int_bytes: int           # the integer-dtype share of wire_bytes
    float_bytes: int         # the float-dtype share of wire_bytes
    replica_groups: Optional[str]
    channel_id: Optional[int]
    op_name: str             # metadata op_name (the jax source op)
    source: str              # metadata "file:line" of the jax call site
    computation: str
    in_loop: bool
    loop_trips: int

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


import re  # noqa: E402  (grouped with the parser it serves)

_COMP_NAME_RE = re.compile(r"^(?:ENTRY\s+)?%?([\w.\-]+)")
_WHILE_RE = re.compile(r"\bwhile\(")
_CALLED_RE = re.compile(
    r"(?:body|condition|to_apply|calls|branch_computations)=\{?%?([\w.\-,% ]+)\}?"
)
_SHAPE_RE = re.compile(r"\b([a-z]+[0-9]+(?:[a-z0-9]*)|pred)\[([0-9,]*)\]")
_KIND_RE = re.compile(
    r"=\s*(.*?)\s+(all-reduce|all-gather|reduce-scatter|all-to-all|"
    r"collective-permute)(-start|-done)?\("
)
_CHANNEL_RE = re.compile(r"channel_id=(\d+)")
_GROUPS_RE = re.compile(
    r"replica_groups=(\{\{[0-9,{} ]*\}\}|\[[0-9,]*\]<=\[[0-9,]*\])"
)
_PAIRS_RE = re.compile(r"source_target_pairs=(\{[0-9,{} ]*\})")
_OP_NAME_RE = re.compile(r'op_name="([^"]*)"')
_SOURCE_RE = re.compile(r'source_file="([^"]*)"(?:.*?source_line=(\d+))?')


def _shapes_in(text: str):
    """``(dtype, elems)`` for every type token in ``text`` (unknown
    dtypes are kept with a 4-byte default so a renamed float type drifts
    the bytes instead of vanishing)."""
    out = []
    for dt, dims in _SHAPE_RE.findall(text):
        elems = 1
        if dims:
            for d in dims.split(","):
                elems *= int(d)
        out.append((dt, elems))
    return out


def _balanced_operands(line: str, open_idx: int) -> str:
    """The operand text between the paren at ``open_idx`` and its match
    (TPU tiled layouts like ``{1,0:T(8,128)}`` nest parens)."""
    depth = 0
    for i in range(open_idx, len(line)):
        if line[i] == "(":
            depth += 1
        elif line[i] == ")":
            depth -= 1
            if depth == 0:
                return line[open_idx + 1:i]
    return line[open_idx + 1:]


def _split_computations(text: str) -> dict:
    """Module text → ``{computation_name: [body lines]}``; raises
    :class:`HLOParseError` on empty/foreign/truncated input."""
    if not text or not text.strip():
        raise HLOParseError("empty HLO text")
    head = text.lstrip()[:4096]
    if head.startswith("module @") or "stablehlo." in head or "mhlo." in head:
        raise HLOParseError(
            "StableHLO/MLIR dialect — shardlint parses the post-"
            "optimization HLO text (Compiled.as_text()), not the lowered "
            "StableHLO module"
        )
    if "HloModule" not in head:
        raise HLOParseError("no HloModule header — not HLO text")
    comps: dict = {}
    cur: Optional[str] = None
    for raw in text.splitlines():
        line = raw.rstrip()
        if cur is None:
            # computation headers are the only non-indented lines ending
            # in "{" (the HloModule header is a single self-closed line)
            if (
                line
                and not line[0].isspace()
                and line.endswith("{")
                and not line.startswith("HloModule")
            ):
                m = _COMP_NAME_RE.match(line)
                if m:
                    cur = m.group(1)
                    comps[cur] = []
        elif line == "}":
            cur = None
        else:
            comps[cur].append(line)
    if cur is not None:
        raise HLOParseError(
            f"truncated HLO text: computation {cur!r} never closed"
        )
    if not comps:
        raise HLOParseError("no computations found in HLO text")
    return comps


def _loop_computations(comps: dict) -> set:
    """Names of computations that execute once per loop trip: direct
    ``while`` bodies/conditions plus everything they call, to a fixpoint."""
    called: dict = {}
    loop_roots: set = set()
    for name, lines in comps.items():
        refs: set = set()
        for line in lines:
            for m in _CALLED_RE.finditer(line):
                for part in m.group(1).split(","):
                    refs.add(part.strip().lstrip("%"))
            if _WHILE_RE.search(line):
                wm = re.search(r"body=%?([\w.\-]+)", line)
                cm = re.search(r"condition=%?([\w.\-]+)", line)
                for g in (wm, cm):
                    if g:
                        loop_roots.add(g.group(1))
        called[name] = refs
    loop: set = set()
    frontier = list(loop_roots)
    while frontier:
        name = frontier.pop()
        if name in loop:
            continue
        loop.add(name)
        frontier.extend(called.get(name, ()))
    return loop


def parse_hlo_collectives(
    text: str, *, loop_trips: int = 1
) -> list[HLOCollective]:
    """Parse optimized HLO text into the collective inventory.

    ``loop_trips``: static multiplicity for collectives living inside a
    ``while`` body (XLA's text does not carry trip counts, so the config
    family declares them — a ring-attention family declares its sequence
    extent, a fused-epoch program its steps per epoch). Ops outside loops
    always count once. Raises :class:`HLOParseError` on non-HLO input;
    unknown op names are simply not collectives (a renamed future opcode
    degrades to a smaller inventory, never a crash)."""
    comps = _split_computations(text)
    loop_comps = _loop_computations(comps)
    out: list[HLOCollective] = []
    for comp, lines in comps.items():
        in_loop = comp in loop_comps
        trips = loop_trips if in_loop else 1
        for line in lines:
            m = _KIND_RE.search(line)
            if not m or m.group(3) == "-done":
                continue
            result_part, kind = m.group(1), m.group(2)
            open_idx = m.end(0) - 1
            operand_part = _balanced_operands(line, open_idx)
            attrs = line[open_idx + 1 + len(operand_part):]
            op_shapes = _shapes_in(operand_part)
            res_shapes = _shapes_in(result_part)
            if kind == "all-gather":
                # costed on the gathered OUTPUT; async -start results
                # alias the operand in front — drop that prefix
                shapes = res_shapes
                if m.group(3) == "-start" and len(shapes) > len(op_shapes):
                    shapes = shapes[len(op_shapes):]
                shapes = shapes or op_shapes
            else:
                shapes = op_shapes or res_shapes
            elems = sum(n for _, n in shapes)
            legs = KIND_LEGS[kind]
            byts = ints = flts = 0
            for dt, n in shapes:
                b = legs * n * _DTYPE_BYTES.get(dt, 4)
                byts += b
                if dt in _FLOAT_DTYPES or (
                    dt not in _DTYPE_BYTES and dt.startswith("f")
                ):
                    flts += b
                else:
                    ints += b
            groups = _GROUPS_RE.search(attrs)
            pairs = _PAIRS_RE.search(attrs)
            chan = _CHANNEL_RE.search(attrs)
            opn = _OP_NAME_RE.search(attrs)
            src = _SOURCE_RE.search(attrs)
            dom = max(shapes, key=lambda s: s[1])[0] if shapes else "?"
            shape_str = (
                f"{shapes[0][0]}[{shapes[0][1]}]" if len(shapes) == 1
                else "(" + ",".join(f"{d}[{n}]" for d, n in shapes) + ")"
            )
            out.append(
                HLOCollective(
                    kind=kind,
                    shape=shape_str,
                    dtype=dom,
                    elems=elems * trips,
                    wire_bytes=byts * trips,
                    int_bytes=ints * trips,
                    float_bytes=flts * trips,
                    replica_groups=(
                        groups.group(1) if groups
                        else pairs.group(1) if pairs else None
                    ),
                    channel_id=int(chan.group(1)) if chan else None,
                    op_name=(opn.group(1) if opn else "")[:160],
                    source=(
                        f"{src.group(1)}:{src.group(2) or '?'}" if src else ""
                    ),
                    computation=comp,
                    in_loop=in_loop,
                    loop_trips=trips,
                )
            )
    return out


def count_sharding_annotations(stablehlo_text: str) -> int:
    """``custom_call @Sharding`` / ``mhlo.sharding`` annotation count in
    the LOWERED (StableHLO) module — the sharding constraints jax handed
    GSPMD, reported so a family that silently lost its annotations is
    visible in the report."""
    return stablehlo_text.count("@Sharding") + stablehlo_text.count(
        "sdy.sharding_constraint"
    )


# --------------------------------------------------------------------------
# The jaxpr-side prediction (the TD104 ring model, per HLO kind)
# --------------------------------------------------------------------------


def predicted_inventory(fn, *args) -> dict:
    """Abstractly trace ``fn`` and price its collectives with the TD104
    ring model, keyed by the HLO kind each primitive lowers to. Two byte
    flavors per kind: ``bytes`` (the eqn dtypes as traced) and
    ``bytes_f32norm`` (narrow-float legs priced at 4 B/elem — what a
    backend without native narrow-float collectives emits after float
    normalization). Elements are leg-free and dtype-independent — the
    invariant the compiler cannot legally change."""
    import jax
    import numpy as np

    from tpu_dist.analysis.jaxpr_audit import (
        COLLECTIVE_PRIMS,
        _WIRE_LEGS,
        _walk_eqns,
    )

    closed = jax.make_jaxpr(fn)(*args)
    by_kind: dict = {}
    for eqn, mult in _walk_eqns(closed.jaxpr):
        name = eqn.primitive.name
        if name not in COLLECTIVE_PRIMS:
            continue
        kind = PRIM_TO_HLO_KIND.get(name, name)
        legs = _WIRE_LEGS.get(name, 1)
        vars_ = (
            eqn.outvars if name in ("all_gather", "pgather") else eqn.invars
        )
        entry = by_kind.setdefault(
            kind,
            {"eqns": 0, "elems": 0, "bytes": 0, "bytes_f32norm": 0,
             "int_bytes": 0, "float_bytes": 0, "float_bytes_f32norm": 0},
        )
        entry["eqns"] += mult
        for v in vars_:
            aval = getattr(v, "aval", None)
            shape = getattr(aval, "shape", ())
            dt = np.dtype(getattr(aval, "dtype", np.float32))
            n = int(np.prod(shape)) if shape else 1
            isz = dt.itemsize
            is_float = dt.kind in ("f", "c") or dt.name == "bfloat16"
            b = legs * n * isz * mult
            b_norm = legs * n * (max(isz, 4) if is_float else isz) * mult
            entry["elems"] += n * mult
            entry["bytes"] += b
            entry["bytes_f32norm"] += b_norm
            if is_float:
                entry["float_bytes"] += b
                entry["float_bytes_f32norm"] += b_norm
            else:
                entry["int_bytes"] += b
    totals = {
        k: sum(e[k] for e in by_kind.values())
        for k in ("elems", "bytes", "bytes_f32norm", "int_bytes",
                  "float_bytes", "float_bytes_f32norm")
    }
    return {"by_kind": by_kind, "totals": totals, "source": "jaxpr-ring-model"}


def hlo_wire_buckets(ops: list[HLOCollective]) -> dict:
    """Payload/sideband bucketing of the HLO inventory under the SAME
    rule the jaxpr model uses (``jaxpr_audit._wire_buckets``): integer
    legs are always quantized payload, float legs are payload when within
    a factor 8 of the step's largest message, sideband below.

    One normalization first: XLA splits a multi-operand jaxpr eqn (the
    grad-tree pmean) into per-leaf ops, whose small leaves (bias vectors)
    would individually fall under the payload cut the aggregated eqn
    clears — so ops are re-aggregated by their jax call site
    (``kind + metadata op_name + source file:line + dtype``) back to eqn
    granularity, then fed through the one shared bucketing function. The
    two accountings therefore bucket identically by construction."""
    from tpu_dist.analysis.jaxpr_audit import _wire_buckets

    grouped: dict = {}
    for i, op in enumerate(ops):
        is_q = (
            op.int_bytes > 0 and op.float_bytes == 0
            and op.dtype not in ("s32", "u32", "s64", "u64", "pred")
        )
        key = (
            (op.kind, op.op_name, op.source, op.dtype, op.loop_trips)
            if op.op_name or op.source else (op.kind, "anon", i)
        )
        g = grouped.setdefault(key, [op.kind, 0, 0, is_q, op.loop_trips])
        g[1] += op.elems // max(op.loop_trips, 1)
        g[2] += op.wire_bytes // max(op.loop_trips, 1)
        g[3] = g[3] and is_q
    return _wire_buckets([tuple(g) for g in grouped.values()])


# --------------------------------------------------------------------------
# TD116 / TD117 comparison
# --------------------------------------------------------------------------


def _hlo_totals(ops: list[HLOCollective]) -> dict:
    by_kind: dict = {}
    for op in ops:
        e = by_kind.setdefault(
            op.kind, {"ops": 0, "elems": 0, "bytes": 0, "int_bytes": 0,
                      "float_bytes": 0},
        )
        e["ops"] += 1
        e["elems"] += op.elems
        e["bytes"] += op.wire_bytes
        e["int_bytes"] += op.int_bytes
        e["float_bytes"] += op.float_bytes
    totals = {
        k: sum(e[k] for e in by_kind.values())
        for k in ("ops", "elems", "bytes", "int_bytes", "float_bytes")
    }
    return {"by_kind": by_kind, "totals": totals}


def _within(actual: float, expected: float, tol: float) -> bool:
    return abs(actual - expected) <= tol * max(abs(expected), 1.0)


def compare_compiled_vs_predicted(
    name: str,
    ops: list[HLOCollective],
    predicted: dict,
    *,
    tolerance: float = 0.0,
) -> tuple[dict, list[Violation]]:
    """TD116 + TD117 over one family. Returns ``(verdict, violations)``;
    ``verdict`` carries the resolved ``float_wire`` regime and the totals
    both sides agreed (or disagreed) on."""
    path = f"<hlo:{name}>"
    out: list[Violation] = []
    hlo = _hlo_totals(ops)
    pt = predicted["totals"]
    ht = hlo["totals"]

    # -- TD116: elements are dtype-independent and must match exactly ----
    if not _within(ht["elems"], pt["elems"], tolerance):
        out.append(
            Violation(
                "TD116", path, 0,
                f"compiled wire ELEMENTS {ht['elems']} != predicted "
                f"{pt['elems']} (ring model over the jaxpr) — the "
                "compiler moved a different amount of data than the "
                "model budgeted; per-kind: hlo="
                f"{ {k: v['elems'] for k, v in hlo['by_kind'].items()} } "
                f"predicted="
                f"{ {k: v['elems'] for k, v in predicted['by_kind'].items()} }",
                snippet=f"elems:{ht['elems']}!={pt['elems']}",
            )
        )
    # -- TD116: integer (quantized) legs may NEVER change size -----------
    if not _within(ht["int_bytes"], pt["int_bytes"], tolerance):
        out.append(
            Violation(
                "TD116", path, 0,
                f"compiled integer-leg wire bytes {ht['int_bytes']} != "
                f"predicted {pt['int_bytes']} — a quantized leg widened "
                "or leaked (the compiler must not rewrite int8 payload)",
                snippet=f"int_bytes:{ht['int_bytes']}!={pt['int_bytes']}",
            )
        )
    # -- TD116: float legs match in exactly one declared dtype regime ----
    float_wire = None
    if _within(ht["float_bytes"], pt["float_bytes"], tolerance):
        float_wire = "native"
    elif _within(ht["float_bytes"], pt["float_bytes_f32norm"], tolerance):
        float_wire = (
            "widened_to_f32"
            if pt["float_bytes_f32norm"] != pt["float_bytes"]
            else "native"
        )
    else:
        out.append(
            Violation(
                "TD116", path, 0,
                f"compiled float-leg wire bytes {ht['float_bytes']} match "
                f"neither the native prediction {pt['float_bytes']} nor "
                f"the f32-normalized prediction "
                f"{pt['float_bytes_f32norm']} — an undeclared dtype "
                "rewrite on the wire",
                snippet=f"float_bytes:{ht['float_bytes']}",
            )
        )

    # -- TD117: unpredicted kinds / per-kind byte excess ------------------
    for kind, he in sorted(hlo["by_kind"].items()):
        pe = predicted["by_kind"].get(kind)
        if pe is None or pe["elems"] == 0:
            for op in ops:
                if op.kind != kind:
                    continue
                out.append(
                    Violation(
                        "TD117", path, 0,
                        f"unpredicted {op.kind} {op.shape} "
                        f"({op.wire_bytes} wire B, replica_groups="
                        f"{op.replica_groups}, from "
                        f"{op.op_name or '<no metadata>'}) — the jaxpr "
                        "inventory budgets no "
                        f"{kind} here; GSPMD inserted a reshard "
                        "(check in_shardings/out_shardings)",
                        snippet=f"{kind}:{op.shape}",
                    )
                )
            continue
        allowed = max(pe["bytes"], pe["bytes_f32norm"])
        if he["bytes"] > allowed * (1.0 + tolerance) + 0.5:
            excess = he["bytes"] - allowed
            culprits: list[HLOCollective] = []
            acc = 0
            for op in sorted(
                (o for o in ops if o.kind == kind),
                key=lambda o: o.wire_bytes,
            ):
                culprits.append(op)
                acc += op.wire_bytes
                if acc >= excess:
                    break
            desc = ", ".join(
                f"{o.shape}@{o.replica_groups}" for o in culprits[:4]
            )
            out.append(
                Violation(
                    "TD117", path, 0,
                    f"{kind} wire bytes {he['bytes']} exceed the "
                    f"predicted {allowed} by {excess} B — an unintended "
                    f"reshard rides a predicted kind (smallest ops "
                    f"covering the excess: {desc})",
                    snippet=f"{kind}:{he['bytes']}>{allowed}",
                )
            )

    verdict = {
        "float_wire": float_wire,
        "hlo": ht,
        "predicted": pt,
        "agree": not out,
    }
    return verdict, out


def check_expected_kinds(
    name: str, ops: list[HLOCollective], expected_kinds
) -> list[Violation]:
    """TD117 for GSPMD-engine families (no jaxpr prediction exists — the
    partitioner inserts every collective): the emitted kinds must stay
    inside the family's declared set."""
    allowed = set(expected_kinds)
    out: list[Violation] = []
    for op in ops:
        if op.kind in allowed:
            continue
        out.append(
            Violation(
                "TD117", f"<hlo:{name}>", 0,
                f"unexpected {op.kind} {op.shape} ({op.wire_bytes} wire "
                f"B, replica_groups={op.replica_groups}, from "
                f"{op.op_name or '<no metadata>'}) — outside this GSPMD "
                f"family's declared kind set {sorted(allowed)}",
                snippet=f"{op.kind}:{op.shape}",
            )
        )
    return out


# --------------------------------------------------------------------------
# Config families
# --------------------------------------------------------------------------


@dataclasses.dataclass
class ConfigFamily:
    """One shard-auditable configuration: ``build(mesh)`` returns
    ``(step_callable, example_args)`` where ``args[0]`` is the state the
    HBM ledger prices. ``gspmd`` families have no jaxpr prediction (the
    partitioner owns the collectives) and declare ``expected_kinds``
    instead. ``loop_trips`` prices ``while``-resident collectives
    (ring-attention scans); 1 means "collectives must live outside loops"
    — a collective leaking INTO a loop then breaks TD116 by the trip
    factor, which is exactly the no_sync discipline at the HLO level."""

    name: str
    build: Callable
    kind: str = "train"
    gspmd: bool = False
    expected_kinds: tuple = ()
    loop_trips: int = 1
    tolerance: float = 0.0
    min_devices: int = 1
    note: str = ""


_FAMILIES: dict = {}


def register_family(fam: ConfigFamily) -> None:
    _FAMILIES[fam.name] = fam


def registered_families() -> list:
    return sorted(_FAMILIES)


def _mlp_family_builder(family: str):
    def build(mesh):
        from tpu_dist.analysis.jaxpr_audit import _dp_setup
        from tpu_dist.train.step import family_step_kwargs

        return _dp_setup(mesh, **family_step_kwargs(family))

    return build


def _build_fsdp(mesh):
    import jax
    import jax.numpy as jnp

    from tpu_dist.analysis.jaxpr_audit import _AuditMLP
    from tpu_dist.parallel.fsdp import fsdp_specs, make_fsdp_train_step
    from tpu_dist.train.optim import SGD
    from tpu_dist.train.state import TrainState

    model = _AuditMLP()
    params, bn = model.init(jax.random.PRNGKey(0))
    # min_size=64 so the audit MLP's matrices genuinely shard (its leaves
    # sit under the production default threshold)
    specs = fsdp_specs(params, mesh, min_size=64)
    opt = SGD(momentum=0.9, weight_decay=1e-4)
    state = TrainState(params, bn, opt.init(params), jnp.zeros((), jnp.int32))
    step = make_fsdp_train_step(model.apply, opt, mesh, specs, donate=False)
    n = mesh.devices.size
    images = jax.ShapeDtypeStruct((8 * n, 2, 2, 3), jnp.float32)
    labels = jax.ShapeDtypeStruct((8 * n,), jnp.int32)
    return step, (state, images, labels, 0.1)


def _build_tp(mesh):
    import jax
    import jax.numpy as jnp

    from tpu_dist.comm import mesh as mesh_lib
    from tpu_dist.nn.vit import ViTDef
    from tpu_dist.train.optim import SGD
    from tpu_dist.train.state import TrainState
    from tpu_dist.train.step import family_step_kwargs, make_train_step

    devs = list(mesh.devices.ravel())
    n = len(devs)
    m2 = mesh_lib.device_mesh([n // 2, 2], ["data", "model"], devices=devs)
    vit = ViTDef(
        image_size=8, patch_size=4, dim=16, depth=1, heads=2, num_classes=8
    )
    specs = vit.tp_param_specs("model")
    opt = SGD()
    params, s = vit.init(jax.random.PRNGKey(0))
    state = TrainState(params, s, opt.init(params), jnp.zeros((), jnp.int32))
    step = make_train_step(
        vit.apply, opt, m2, sync_bn=False, donate=False,
        param_specs=specs, **family_step_kwargs("tp"),
    )
    b = 4 * (n // 2)
    images = jax.ShapeDtypeStruct((b, 8, 8, 3), jnp.float32)
    labels = jax.ShapeDtypeStruct((b,), jnp.int32)
    return step, (state, images, labels, 0.1)


def _build_sp(mesh):
    import jax
    import jax.numpy as jnp

    from tpu_dist.comm import mesh as mesh_lib
    from tpu_dist.nn.vit import ViTDef
    from tpu_dist.train.optim import SGD
    from tpu_dist.train.state import TrainState
    from tpu_dist.train.step import family_step_kwargs, make_train_step

    devs = list(mesh.devices.ravel())
    n = len(devs)
    m2 = mesh_lib.device_mesh([n // 4, 4], ["data", "seq"], devices=devs)
    vit = ViTDef(
        image_size=8, patch_size=2, dim=16, depth=1, heads=2, num_classes=8
    )
    opt = SGD()
    params, s = vit.init(jax.random.PRNGKey(0))
    state = TrainState(params, s, opt.init(params), jnp.zeros((), jnp.int32))
    step = make_train_step(
        vit.apply, opt, m2, sync_bn=False, donate=False,
        **family_step_kwargs("sp"),
    )
    b = 4 * (n // 4)
    images = jax.ShapeDtypeStruct((b, 8, 8, 3), jnp.float32)
    labels = jax.ShapeDtypeStruct((b,), jnp.int32)
    return step, (state, images, labels, 0.1)


def _build_serve(mesh):
    import jax
    import jax.numpy as jnp

    from tpu_dist.analysis.jaxpr_audit import _AuditMLP
    from tpu_dist.train.optim import SGD
    from tpu_dist.train.state import TrainState
    from tpu_dist.train.step import make_eval_step

    model = _AuditMLP()
    params, bn = model.init(jax.random.PRNGKey(0))
    opt = SGD()
    state = TrainState(params, bn, opt.init(params), jnp.zeros((), jnp.int32))
    step = make_eval_step(model.apply, mesh)
    n = mesh.devices.size
    images = jax.ShapeDtypeStruct((8 * n, 2, 2, 3), jnp.float32)
    labels = jax.ShapeDtypeStruct((8 * n,), jnp.int32)
    mask = jax.ShapeDtypeStruct((8 * n,), jnp.float32)
    return step, (state, images, labels, mask)


for _name in (
    "dp_sgd", "dp_sgd_accum4", "dp_bf16", "dp_wire_bf16",
    "dp_int8", "dp_int8_ef", "zero1_sgd", "zero1_int8",
):
    register_family(ConfigFamily(_name, _mlp_family_builder(_name)))
register_family(ConfigFamily(
    "fsdp", _build_fsdp, gspmd=True,
    expected_kinds=("all-reduce", "all-gather", "reduce-scatter"),
    note="GSPMD engine: collectives are partitioner-inserted; kinds "
         "gated, bytes reported",
))
register_family(ConfigFamily(
    "tp_vit", _build_tp, min_devices=2,
    note="Megatron-TP ViT on [data, model=2]",
))
register_family(ConfigFamily(
    "sp_vit", _build_sp, min_devices=4, loop_trips=4,
    note="ring-attention ViT on [data, seq=4]; ppermutes live in the "
         "ring scan (loop_trips = seq extent)",
))
register_family(ConfigFamily(
    "serve_eval", _build_serve, kind="serve",
    note="the inference/eval forward step (metric psums only)",
))


def injected_bad_zero1(mesh):
    """The TD117 acceptance probe: the ZeRO-1 step re-jitted with a
    deliberately WRONG ``in_shardings`` — params (which the shard_map
    expects replicated) declared sharded over the data axis — so GSPMD
    must insert all-gathers to rebuild them before every step. Returns
    ``(jitted, args)`` for :func:`shard_case`-style analysis; the
    resulting report MUST carry TD117 violations (a clean report here
    means the analyzer stopped seeing reshards)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_dist.analysis.jaxpr_audit import _dp_setup

    fn, args = _dp_setup(mesh, shard_weight_update=True)
    n = mesh.devices.size

    def bad(x):
        shape = getattr(x, "shape", None)
        if shape and len(shape) >= 1 and shape[0] % n == 0:
            return NamedSharding(mesh, P("data"))
        return NamedSharding(mesh, P())

    state_sh = jax.tree_util.tree_map(bad, args[0])
    batch_sh = NamedSharding(mesh, P("data"))
    jitted = jax.jit(fn, in_shardings=(state_sh, batch_sh, batch_sh, None))
    return jitted, args


# --------------------------------------------------------------------------
# Driving one family / the whole matrix
# --------------------------------------------------------------------------


def _as_jitted(fn):
    import jax

    return fn if hasattr(fn, "lower") else jax.jit(fn)


def shard_case(
    name: str, mesh=None, *, step_override=None
) -> tuple[dict, list[Violation]]:
    """Lower + compile one family, parse the optimized HLO, run
    TD116/TD117, and assemble its shard-report entry.
    ``step_override=(jitted, args)`` swaps in a pre-built step (the
    injected-reshard probe) while keeping the family's prediction."""
    import jax

    from tpu_dist.comm import mesh as mesh_lib
    from tpu_dist.obs import costmodel

    if name not in _FAMILIES:
        raise ValueError(
            f"unknown config family {name!r}; registered: "
            f"{registered_families()}"
        )
    fam = _FAMILIES[name]
    m = mesh if mesh is not None else mesh_lib.data_parallel_mesh()
    if m.devices.size < fam.min_devices:
        raise ValueError(
            f"family {name!r} needs >= {fam.min_devices} devices "
            f"(got {m.devices.size})"
        )
    fn, args = fam.build(m)
    if step_override is not None:
        jit_fn, args = step_override
    else:
        jit_fn = _as_jitted(fn)
    lowered, compiled = costmodel.lower_and_compile(jit_fn, *args)
    ops = parse_hlo_collectives(
        compiled.as_text(), loop_trips=fam.loop_trips
    )
    hlo = _hlo_totals(ops)
    try:
        annotations = count_sharding_annotations(lowered.as_text())
    except Exception:
        annotations = None

    violations: list[Violation] = []
    predicted = None
    verdict: dict = {}
    if fam.gspmd:
        violations.extend(check_expected_kinds(name, ops, fam.expected_kinds))
        verdict = {
            "float_wire": None,
            "hlo": hlo["totals"],
            "predicted": None,
            "agree": not violations,
            "skipped_td116": "gspmd-engine family: collectives are "
                             "partitioner-inserted, no jaxpr ring model",
        }
    else:
        predicted = predicted_inventory(fn, *args)
        verdict, vs = compare_compiled_vs_predicted(
            name, ops, predicted, tolerance=fam.tolerance
        )
        violations.extend(vs)

    # -- static HBM (the PR 13 ledger) + XLA's executable waterfall ------
    state = args[0]
    hbm: dict = {}
    try:
        from tpu_dist.obs import memory as memory_lib

        led = memory_lib.static_ledger(
            params=getattr(state, "params", None),
            opt_state=getattr(state, "opt_state", None),
            ef=getattr(state, "ef", ()),
            bn_state=getattr(state, "bn_state", None),
        )
        hbm["static_bytes_per_device"] = led["bytes_per_device"]
        hbm["static_sections"] = {
            k: v["bytes_per_device"] for k, v in led["sections"].items()
        }
    except Exception as e:  # pragma: no cover - ledger must never block
        hbm["ledger_error"] = f"{type(e).__name__}: {e}"
    ma = costmodel.memory_analysis_bytes(compiled)
    if ma:
        hbm["memory_analysis"] = ma

    cost = costmodel.step_cost(compiled)
    predicted_step = costmodel.predicted_step_time(
        cost,
        wire_bytes=hlo["totals"]["bytes"],
        n_devices=m.devices.size,
    )

    report = {
        "family": name,
        "kind": fam.kind,
        "config": dict(_family_config(name)),
        "mesh": {ax: int(s) for ax, s in zip(m.axis_names, m.devices.shape)},
        "note": fam.note,
        "collectives": [op.to_json() for op in ops],
        "hlo": {
            **hlo["totals"],
            "by_kind": hlo["by_kind"],
            "wire": hlo_wire_buckets(ops),
            "float_wire": verdict.get("float_wire"),
            "sharding_annotations": annotations,
        },
        "predicted": predicted,
        "verdict": verdict,
        "hbm": hbm,
        "cost": cost,
        "predicted_step": predicted_step,
        "violations": [v.to_json() for v in violations],
    }
    return report, violations


def _family_config(name: str) -> dict:
    from tpu_dist.train.step import SHARD_CONFIG_FAMILIES

    key = {"tp_vit": "tp", "sp_vit": "sp", "serve_eval": None}.get(name, name)
    if key is None:
        return {}
    return SHARD_CONFIG_FAMILIES.get(key, {})


def shard_all(
    mesh=None, names=None
) -> tuple[dict, list[Violation]]:
    """Run the whole family matrix (or ``names``). A family whose build/
    lower/parse fails is recorded under ``skips`` with its typed error —
    never a crash — so a jax upgrade that renames an op degrades the
    report instead of killing the gate; the skip COUNT is loud in the
    report and the CLI output."""
    report: dict = {"families": {}, "skips": {}}
    violations: list[Violation] = []
    for name in names if names is not None else registered_families():
        try:
            fam_report, vs = shard_case(name, mesh)
        except Exception as e:
            report["skips"][name] = f"{type(e).__name__}: {e}"
            continue
        report["families"][name] = fam_report
        violations.extend(vs)
    report["counts"] = {
        "families": len(report["families"]),
        "skipped": len(report["skips"]),
        "violations": len(violations),
    }
    return report, violations


# --------------------------------------------------------------------------
# shard_report.json — the --auto_shard planner input
# --------------------------------------------------------------------------


def build_shard_report(mesh=None, names=None) -> tuple[dict, list[Violation]]:
    """The persisted artifact: :func:`shard_all` plus environment stamps
    (backend, device kind/count, jax version) and the schema pin."""
    import jax

    report, violations = shard_all(mesh, names)
    dev = jax.devices()[0]
    report = {
        "schema": SCHEMA,
        "backend": dev.platform,
        "device_kind": dev.device_kind,
        "n_devices": jax.device_count(),
        "jax_version": jax.__version__,
        **report,
    }
    return report, violations


def save_shard_report(report: dict, path: str) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=2, sort_keys=True)
        f.write("\n")
    import os

    os.replace(tmp, path)


_REQUIRED_FAMILY_KEYS = (
    "collectives", "hlo", "verdict", "hbm", "cost", "predicted_step",
    "violations",
)

SCHEMA_VERSION = 1
_SCHEMA_TAG_RE = re.compile(r"^shard_report_v(\d+)$")


def load_shard_report(path: str) -> dict:
    """Schema-pinned loader — the contract the ``--auto_shard`` planner
    reads through — with the summarize ``KNOWN_KINDS`` forward-compat
    discipline: a NEWER ``shard_report_v<N>`` tag is tolerated (every
    schema bump is additive) — its extra fields are ignored and any
    family entry missing the v1 pricing keys is skipped with a count
    into ``load_notes`` rather than read half-blind. A foreign tag, an
    older-than-supported version, or a SAME-version entry missing
    required keys (that is corruption, not forward compat) still raises
    the typed :class:`ShardReportError` — never a silent partial dict."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    tag = data.get("schema") if isinstance(data, dict) else None
    m = _SCHEMA_TAG_RE.match(tag) if isinstance(tag, str) else None
    if not isinstance(data, dict) or not m:
        got = tag if isinstance(data, dict) else type(data).__name__
        raise ShardReportError(
            f"{path}: schema {got!r} is not a shard_report tag — "
            "regenerate with `make shard-report`"
        )
    ver = int(m.group(1))
    if ver < SCHEMA_VERSION:
        raise ShardReportError(
            f"{path}: schema {tag!r} predates v{SCHEMA_VERSION} — "
            "regenerate with `make shard-report`"
        )
    newer = ver > SCHEMA_VERSION
    fams = data.get("families")
    if not isinstance(fams, dict):
        raise ShardReportError(f"{path}: no 'families' map")
    skipped: dict = {}
    for name, entry in list(fams.items()):
        missing = [k for k in _REQUIRED_FAMILY_KEYS if k not in entry]
        if not missing:
            continue
        if not newer:
            raise ShardReportError(
                f"{path}: family {name!r} is missing {missing}"
            )
        skipped[name] = missing
        del fams[name]
    if newer:
        data["load_notes"] = {
            "newer_schema": tag,
            "reader_version": SCHEMA_VERSION,
            "skipped_families": skipped,
            "skipped_count": len(skipped),
        }
    return data


def format_text(report: dict) -> str:
    """Terminal rendering of a shard report (one line per family)."""
    lines = [
        f"shardlint: {report['counts']['families']} famil(ies) analyzed"
        + (
            f", {report['counts']['skipped']} SKIPPED"
            if report["counts"]["skipped"] else ""
        )
        + f", {report['counts']['violations']} violation(s)"
    ]
    for name, fam in sorted(report.get("families", {}).items()):
        h = fam["hlo"]
        kinds = ", ".join(
            f"{k}x{v['ops']}" for k, v in sorted(h["by_kind"].items())
        ) or "collective-free"
        step = fam.get("predicted_step") or {}
        pred = step.get("predicted_step_s")
        lines.append(
            f"  {name:<16} {kinds:<52} wire {h['bytes']:>8} B"
            + (f"  float_wire={h['float_wire']}" if h.get("float_wire") else "")
            + (f"  pred_step {pred * 1e3:.3f} ms" if pred else "")
        )
    for name, why in sorted(report.get("skips", {}).items()):
        lines.append(f"  {name:<16} SKIPPED: {why}")
    return "\n".join(lines)
